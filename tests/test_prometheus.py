"""Prometheus text-format conformance of the metrics exposition.

`obs/metrics.py:render_prometheus` claims text format 0.0.4; this file
holds it to the grammar instead of eyeballing: a strict parser (metric
and label name charsets, label-value escaping, float values, HELP/TYPE
comment shape, TYPE-before-samples and TYPE-at-most-once) plus the
histogram invariants a Prometheus server relies on (`_bucket` counts
cumulative and non-decreasing over sorted `le` bounds, the `+Inf`
bucket present and equal to `_count`, `_sum`/`_count` series present)
and the gauge naming of the windowed-quantile series (obs/windows.py).

The fixtures deliberately include label values with quotes, backslashes
and newlines — the escaping class the conformance pass caught in the
original renderer (values were interpolated raw).
"""

import math
import re

import numpy as np
import pytest

from mpi_k_selection_tpu import obs as obs_lib

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (-?[0-9.eE+-]+|\+Inf|-Inf|NaN)$"
)
COMMENT_RE = re.compile(r"^# (HELP|TYPE) ([^ ]+) (.+)$")

HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_label_block(block: str) -> dict:
    """``{k="v",...}`` -> dict, validating names and escape sequences
    (the only legal escapes in a label value are ``\\\\``, ``\\"`` and
    ``\\n``)."""
    assert block.startswith("{") and block.endswith("}"), block
    body = block[1:-1]
    out = {}
    i = 0
    while i < len(body):
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', body[i:])
        assert m, f"bad label name at {body[i:]!r}"
        name = m.group(1)
        assert LABEL_NAME_RE.match(name), name
        i += m.end()
        val = []
        while True:
            assert i < len(body), f"unterminated label value for {name}"
            c = body[i]
            if c == "\\":
                assert i + 1 < len(body), "dangling backslash"
                esc = body[i + 1]
                assert esc in ('\\', '"', "n"), f"illegal escape \\{esc}"
                val.append("\n" if esc == "n" else esc)
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                assert c != "\n", "raw newline inside a label value"
                val.append(c)
                i += 1
        out[name] = "".join(val)
        if i < len(body):
            assert body[i] == ",", f"expected ',' at {body[i:]!r}"
            i += 1
    return out


def parse_exposition(text: str):
    """Strict text-format 0.0.4 parse. Returns ``(types, helps,
    samples)`` with samples as ``(name, labels, value)`` triples; raises
    AssertionError on any grammar violation."""
    types: dict = {}
    helps: dict = {}
    samples: list = []
    sampled: set = set()
    assert text == "" or text.endswith("\n"), "exposition must end in \\n"
    for line in text.split("\n"):
        if line == "":
            continue
        if line.startswith("#"):
            m = COMMENT_RE.match(line)
            assert m, f"malformed comment line: {line!r}"
            kind, name, rest = m.groups()
            assert NAME_RE.match(name), name
            if kind == "TYPE":
                assert name not in types, f"duplicate TYPE for {name}"
                assert name not in sampled and not any(
                    name + s in sampled for s in HISTOGRAM_SUFFIXES
                ), f"TYPE for {name} after its samples"
                assert rest in (
                    "counter", "gauge", "histogram", "summary", "untyped"
                ), rest
                types[name] = rest
            else:
                assert name not in helps, f"duplicate HELP for {name}"
                helps[name] = rest
            continue
        m = SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, block, value = m.groups()
        assert NAME_RE.match(name), name
        labels = _parse_label_block(block) if block else {}
        if value == "+Inf":
            v = math.inf
        elif value == "-Inf":
            v = -math.inf
        else:
            v = float(value)  # raises on malformed numbers
        sampled.add(name)
        samples.append((name, labels, v))
    # every sample belongs to a declared family (a name with its own
    # TYPE wins; histogram sub-series attach via suffix otherwise)
    for name, _, _ in samples:
        base = name
        if name not in types:
            for suf in HISTOGRAM_SUFFIXES:
                cand = name[: -len(suf)] if name.endswith(suf) else None
                if cand and types.get(cand) == "histogram":
                    base = cand
                    break
        assert base in types, f"sample {name} has no TYPE declaration"
        if base != name:
            assert types[base] == "histogram", name
    _check_histograms(types, samples)
    return types, helps, samples


def _check_histograms(types, samples):
    """Per (histogram, label set minus le): buckets cumulative and
    non-decreasing over sorted le, +Inf present and equal to _count,
    _sum present."""
    for base, t in types.items():
        if t != "histogram":
            continue
        buckets: dict = {}
        counts: dict = {}
        sums: dict = {}
        for name, labels, v in samples:
            if name == base + "_bucket":
                le = labels["le"]
                key = tuple(sorted((k, x) for k, x in labels.items() if k != "le"))
                bound = math.inf if le == "+Inf" else float(le)
                buckets.setdefault(key, []).append((bound, v))
            elif name == base + "_count":
                counts[tuple(sorted(labels.items()))] = v
            elif name == base + "_sum":
                sums[tuple(sorted(labels.items()))] = v
        assert buckets, f"histogram {base} exposes no _bucket series"
        for key, bs in buckets.items():
            assert key in counts, f"{base}{dict(key)} missing _count"
            assert key in sums, f"{base}{dict(key)} missing _sum"
            bs = sorted(bs)
            bounds = [b for b, _ in bs]
            assert bounds[-1] == math.inf, f"{base}{dict(key)} missing +Inf"
            assert len(set(bounds)) == len(bounds), "duplicate le bounds"
            vals = [v for _, v in bs]
            assert all(
                a <= b for a, b in zip(vals, vals[1:])
            ), f"{base}{dict(key)} buckets not cumulative: {vals}"
            assert vals[-1] == counts[key], (
                f"{base}{dict(key)} +Inf bucket {vals[-1]} != _count "
                f"{counts[key]}"
            )


# ---------------------------------------------------------------------------


def test_empty_registry_renders_empty():
    assert obs_lib.MetricsRegistry().render_prometheus() == ""


def test_basic_families_conform():
    reg = obs_lib.MetricsRegistry()
    reg.counter("ingest.chunks", labels={"device": "0"}).inc(3)
    reg.counter("ingest.chunks", labels={"device": "host"}).inc()
    reg.gauge("staging_pool.resident_bytes").set(12345)
    h = reg.histogram("serve.queue_depth")
    for v in (0, 1, 1, 5, 40):
        h.observe(v)
    types, helps, samples = parse_exposition(reg.render_prometheus())
    assert types["ksel_ingest_chunks"] == "counter"
    assert types["ksel_staging_pool_resident_bytes"] == "gauge"
    assert types["ksel_serve_queue_depth"] == "histogram"
    # HELP emitted for cataloged names, before samples, well-formed
    assert "ksel_ingest_chunks" in helps
    by = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    assert by[("ksel_ingest_chunks", (("device", "0"),))] == 3


def test_label_escaping_roundtrips():
    evil = 'a"b\\c\nd'
    reg = obs_lib.MetricsRegistry()
    reg.counter("ingest.chunks", labels={"device": evil}).inc()
    text = reg.render_prometheus()
    _, _, samples = parse_exposition(text)
    (name, labels, v), = [s for s in samples if s[0] == "ksel_ingest_chunks"]
    assert labels["device"] == evil
    assert v == 1


def test_float_values_conform():
    reg = obs_lib.MetricsRegistry()
    reg.gauge("phase.seconds", labels={"phase": "solve"}).set(1.25e-05)
    reg.gauge("phase.seconds", labels={"phase": "inf"}).set(math.inf)
    _, _, samples = parse_exposition(reg.render_prometheus())
    vals = {l["phase"]: v for _, l, v in samples}
    assert vals["solve"] == 1.25e-05
    assert vals["inf"] == math.inf


def test_windowed_histogram_series_are_conformant_gauges():
    reg = obs_lib.MetricsRegistry()
    reg.enable_windowed("serve.latency_seconds", window=4, advance_every=8)
    rng = np.random.default_rng(3)
    for tier in ("sketch", "exact"):
        h = reg.histogram("serve.latency_seconds", labels={"tier": tier})
        for v in rng.exponential(0.005, size=64):
            h.observe(float(v))
    types, helps, samples = parse_exposition(reg.render_prometheus())
    base = "ksel_serve_latency_seconds"
    assert types[base] == "histogram"
    assert types[base + "_windowed"] == "gauge"
    assert types[base + "_windowed_rank_error"] == "gauge"
    assert types[base + "_windowed_count"] == "gauge"
    assert base + "_windowed" in helps
    wq = [
        (l, v) for n, l, v in samples if n == base + "_windowed"
    ]
    # one series per (tier, quantile)
    assert {(l["tier"], l["quantile"]) for l, _ in wq} == {
        (t, q)
        for t in ("sketch", "exact")
        for q in ("0.5", "0.9", "0.99")
    }
    for l, v in wq:
        assert 0.0 <= float(l["quantile"]) <= 1.0
        assert v >= 0.0
    # the plain histogram series of the SAME metric still parse + verify
    assert any(n == base + "_bucket" for n, _, _ in samples)


def test_streaming_run_exposition_conformant():
    """The real thing: every metric a pipelined spill descent records
    renders to a conformant exposition."""
    from mpi_k_selection_tpu.streaming.chunked import streaming_kselect

    rng = np.random.default_rng(11)
    chunks = [
        rng.integers(-(2**31), 2**31 - 1, size=m, dtype=np.int32)
        for m in (3000, 1024, 2048)
    ]
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    streaming_kselect(
        chunks, n // 2, pipeline_depth=2, spill="force",
        radix_bits=4, collect_budget=64, obs=o,
    )
    types, _, samples = parse_exposition(o.metrics.render_prometheus())
    assert any(t == "histogram" for t in types.values())
    assert any(n_ == "ksel_spill_passes" for n_, _, _ in samples)


def test_enable_windowed_after_creation_raises():
    reg = obs_lib.MetricsRegistry()
    reg.histogram("serve.latency_seconds", labels={"tier": "exact"})
    with pytest.raises(TypeError, match="before the first observation"):
        reg.enable_windowed("serve.latency_seconds")


def test_live_serve_scrape_with_ledger_gauges_conformant():
    """ISSUE 14 satellite: a LIVE HTTP scrape of a query server — the
    runtime ledger's compile counters and device-byte gauges riding it —
    passes the strict grammar parser, and the ledger families are
    actually present in the scraped text."""
    import http.client

    from mpi_k_selection_tpu.serve import KSelectServer, start_http_server

    rng = np.random.default_rng(7)
    x = rng.integers(-(2**31), 2**31 - 1, size=40_000, dtype=np.int32)
    o = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(window=0.0, obs=o) as srv:
        srv.add_dataset("scrape", x)
        for k in (5, 5, 1234):  # compile + repeat hits at serve.programs
            srv.kselect("scrape", k, tier="exact")
        with start_http_server(srv) as h:
            c = http.client.HTTPConnection("127.0.0.1", h.port, timeout=30)
            try:
                c.request("GET", "/metrics")
                r = c.getresponse()
                assert r.status == 200
                text = r.read().decode()
            finally:
                c.close()
    types, _, samples = parse_exposition(text)
    names = {n for n, _, _ in samples}
    assert "ksel_ledger_compiles" in names
    assert "ksel_ledger_cache_hits" in names
    assert "ksel_ledger_recompiles" in names
    assert "ksel_ledger_compile_seconds" in names
    assert "ksel_ledger_device_bytes" in names
    assert "ksel_ledger_device_bytes_peak" in names
    # the site label rides each program-book sample; the resident pool
    # gauge carries this server's registered bytes
    sites = {
        labels.get("site")
        for n, labels, _ in samples
        if n == "ksel_ledger_compiles"
    }
    assert "serve.programs" in sites
    resident = [
        v
        for n, labels, v in samples
        if n == "ksel_ledger_device_bytes" and labels.get("pool") == "resident"
    ]
    assert resident and max(resident) >= x.nbytes
