"""kselect-lint — static analysis gating this codebase's recurring bug classes.

Every review round of this repository has caught the same families of
latent bugs by hand: silent int64->int32 truncation when x64 is off,
f64-on-TPU paths that bypass the ~49-bit key-space warning, host syncs
hiding inside jitted hot paths, and test files silently falling out of the
tier-1 gate. This package encodes those reviewers' checklists as
machine-enforced rules, in two engines:

1. **AST lint rules** (analysis/ast_rules.py, ids ``KSLxxx``) over the
   package source — pure syntax-tree pattern rules with per-line
   ``# ksel: noqa[KSLxxx]`` suppressions.
2. **jaxpr contract checks** (analysis/jaxpr_checks.py, ids ``KSCxxx``)
   that abstractly trace the public kernels over a shape/dtype grid and
   assert dtype preservation, counter-width discipline, and jaxpr
   stability across batch sizes (the recompile-hazard detector).

Run it::

    kselect-lint mpi_k_selection_tpu/            # console script
    python -m mpi_k_selection_tpu.analysis .     # same thing

The tier-1 test suite runs the analyzer over the whole repository and
fails on any unsuppressed finding (tests/test_analysis.py), so a PR cannot
reintroduce a gated bug class without carrying a written justification.
Rule catalog: docs/ANALYSIS.md.
"""

from mpi_k_selection_tpu.analysis.core import (
    Finding,
    Report,
    Rule,
    iter_python_files,
    load_module,
    run_analysis,
)
from mpi_k_selection_tpu.analysis import ast_rules as _ast_rules  # registers KSL rules
from mpi_k_selection_tpu.analysis import concurrency as _concurrency  # KSL015-017
from mpi_k_selection_tpu.analysis import lifecycle as _lifecycle  # KSL019-021
from mpi_k_selection_tpu.analysis import placement as _placement  # KSL022-024
from mpi_k_selection_tpu.analysis.concurrency import build_concurrency_report
from mpi_k_selection_tpu.analysis.core import all_rules
from mpi_k_selection_tpu.analysis.jaxpr_checks import CONTRACT_CHECKS
from mpi_k_selection_tpu.analysis.lifecycle import build_lifecycle_report
from mpi_k_selection_tpu.analysis.modcache import shared_modules
from mpi_k_selection_tpu.analysis.placement import build_placement_report
from mpi_k_selection_tpu.analysis.lockorder import LockOrderSanitizer
from mpi_k_selection_tpu.analysis.reporters import render_json, render_text

__all__ = [
    "Finding",
    "Report",
    "Rule",
    "run_analysis",
    "all_rules",
    "iter_python_files",
    "load_module",
    "CONTRACT_CHECKS",
    "LockOrderSanitizer",
    "build_concurrency_report",
    "build_lifecycle_report",
    "build_placement_report",
    "shared_modules",
    "render_json",
    "render_text",
]
