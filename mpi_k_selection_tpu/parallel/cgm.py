"""Distributed CGM weighted-median k-selection — reference-parity algorithm.

This is the reference's main artifact (``TODO-kth-problem-cgm.c:35-296``)
rebuilt TPU-first. Protocol correspondence, step by step:

==============================================  ===============================
reference (MPI, physical discards)              this module (XLA, logical window)
==============================================  ===============================
``MPI_Scatterv`` root->all ``:103``             block sharding annotation
local ``qsort`` of the shard ``:115``           one ``lax.sort`` per shard
local median of live elements ``:125-132``      sorted-window middle element
two ``MPI_Gather`` of (median, count)           one ``lax.all_gather`` pair
``:135-136`` (author's TODO ``:107-112``        (the fusion the author left
wanted them fused)                              as TODO)
rank-0 weighted median ``:139-165`` +           replicated weighted median —
``MPI_Bcast(M)`` ``:168``                       Bcast implicit under SPMD
linear L/E/G count sweep ``:175-185``           two binary searches
                                                (``searchsorted``) per round
``MPI_Allreduce(leg,3,SUM)`` ``:190``           ``lax.psum`` of the 3-vector
exact-hit test ``L < k <= L+E`` ``:194-201``    identical, in the while_loop
``VecErase`` physical discard sweeps            logical window shrink
``:204-225`` (scrambles order, SURVEY §2.3)     ``[lo, hi) -> [lo, lb)`` or
                                                ``[rb, hi)`` — order preserved
final Gatherv + sequential finish ``:236-280``  not needed: the loop always
                                                terminates on the exact test
==============================================  ===============================

Two deliberate repairs over the reference (same capability, better math):

1. **True medians every round.** The reference sorts once but its swap-delete
   discard scrambles order, so from round 2 its "local median" is an
   arbitrary element and convergence degrades to random-pivot quickselect
   (SURVEY.md §2.3). Here the shard stays sorted and the active set is a
   contiguous window of it, so the window middle is the *exact* local median
   every round — the >= 1/4-discard-per-round CGM guarantee actually holds.
2. **No sequential finish.** The reference cuts over to gather-and-sort on
   rank 0 when the live set is small (``:122``, ``:236-280``). Since the
   exact-hit test is guaranteed to fire (the pivot is always a live element,
   so E >= 1 and every round discards >= 1 element), the collective loop
   simply runs to termination — no data movement at all.

All comparisons run in order-preserving key space (utils/dtypes.py), so
duplicates, -0.0/0.0 and the full int range behave exactly.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_k_selection_tpu.ops.radix import select_count_dtype
from mpi_k_selection_tpu.parallel import mesh as mesh_lib
from mpi_k_selection_tpu.utils import compat, debug as _debug, dtypes as _dt

_pvary = compat.pvary  # varying-manual-axes marking across jax versions


@functools.lru_cache(maxsize=64)
def _jitted_cgm(mesh, n, cdt, max_rounds):
    """Cached jitted CGM program per (mesh, config) — avoids a retrace per call
    (jit caches are per jit object; see parallel/radix.py)."""
    axis = mesh.axis_names[0]

    def shard_fn(xs, kk0):
        keys = _dt.to_sortable_bits(xs.ravel())
        s = jax.lax.sort(keys)  # local pre-sort, once (TODO-…:115)
        m = s.shape[0]
        kk0 = jnp.clip(kk0.astype(cdt), 1, n)

        def cond(state):
            lo, hi, kk, found, ans, r = state
            return jnp.logical_and(~found, r < max_rounds)

        def body(state):
            lo, hi, kk, found, ans, r = state
            w = (hi - lo).astype(cdt)
            mid = jnp.clip((lo + hi) // 2, 0, m - 1)
            med = s[mid]  # exact local median of the live window
            meds = jax.lax.all_gather(med, axis)  # (P,) — the :135-136 gathers
            ws = jax.lax.all_gather(w, axis)
            # weighted median, replicated on every shard (:139-165 + :168)
            order = jnp.argsort(meds)
            wsort = ws[order]
            cumw = jnp.cumsum(wsort)
            total = cumw[-1]
            # first live candidate past half: cumw >= ceil(total/2); written
            # without the 2*cumw form, which would overflow int32 for n > 2^30
            idx = jnp.argmax(cumw >= (total + 1) // 2)
            pivot = meds[order][idx]
            # local L/E/G: binary searches replace the linear sweep :175-185
            pl_ = jnp.searchsorted(s, pivot, side="left").astype(cdt)
            pr_ = jnp.searchsorted(s, pivot, side="right").astype(cdt)
            lb = jnp.clip(pl_, lo, hi)
            rb = jnp.clip(pr_, lo, hi)
            leg = jnp.stack([lb - lo, rb - lb, hi - rb])
            leg = jax.lax.psum(leg, axis)  # the one Allreduce (:190)
            L, E = leg[0], leg[1]
            hit = jnp.logical_and(L < kk, kk <= L + E)  # exact test (:194)
            go_low = kk <= L  # discard >= pivot (:204-213)
            lo2 = jnp.where(hit | go_low, lo, rb)
            hi2 = jnp.where(hit, hi, jnp.where(go_low, lb, hi))
            kk2 = jnp.where(hit | go_low, kk, kk - (L + E))  # k shift (:224)
            ans2 = jnp.where(hit, pivot, ans)
            return lo2, hi2, kk2, found | hit, ans2, r + 1

        # lo/hi are per-shard state (each shard's live window differs), so the
        # initial values must be marked varying over the mesh axis.
        lo0 = _pvary(jnp.zeros((), cdt), axis)
        hi0 = _pvary(jnp.full((), m, cdt), axis)
        init = (lo0, hi0, kk0, jnp.zeros((), bool), s[0], jnp.zeros((), jnp.int32))
        _, _, _, found, ans, rounds = jax.lax.while_loop(cond, body, init)
        return _dt.from_sortable_bits(ans, xs.dtype), rounds, found

    # check_vma=False: the answer/rounds are replicated by construction (they
    # derive only from psum/all_gather results), but the while_loop's mixed
    # varying/invariant carry defeats static replication inference.
    fn = compat.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def distributed_cgm_select(
    x: jax.Array,
    k,
    *,
    mesh=None,
    max_rounds: int | None = None,
    return_rounds: bool = False,
):
    """Exact k-th smallest (1-indexed) of sharded ``x`` via CGM weighted-median.

    Returns a replicated scalar (and the round count if ``return_rounds``).
    """
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)

    x = jnp.ravel(jnp.asarray(x))
    _debug.check_concrete_k(k, x.shape[0])
    x, n = mesh_lib.pad_to_multiple(x, mesh.size)
    # counts sized for the padded total (sentinels are counted too)
    cdt = select_count_dtype(x.shape[0])
    if max_rounds is None:
        # true-median pivots discard >= 1/4 of the live set per round; the
        # slack covers duplicate-heavy ties and the int range.
        max_rounds = 64 + 8 * int(math.ceil(math.log2(n + 1)))

    fn = _jitted_cgm(mesh, n, cdt, max_rounds)
    xs = jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))
    value, rounds, found = fn(xs, jnp.asarray(k, cdt))
    if not bool(found):
        raise RuntimeError(
            f"CGM selection did not converge within {max_rounds} rounds — "
            "this indicates a bug (the exact-hit test is guaranteed to fire); "
            "please report with the input configuration"
        )
    if return_rounds:
        return value, rounds
    return value
