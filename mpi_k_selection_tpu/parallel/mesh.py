"""Device mesh construction — the process-group layer.

Replaces the reference's MPI process-group bootstrap
(``MPI_Init/Comm_size/Comm_rank``, ``TODO-kth-problem-cgm.c:53-61``) with a
1-D ``jax.sharding.Mesh`` over all visible devices. The reference's
``world_size >= 2`` guard (``MPI_Abort`` at ``TODO-…:56-59``) is mirrored as
a clean error in :func:`require_distributed`.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_k_selection_tpu import config

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, axis_name: str = DATA_AXIS) -> Mesh:
    """1-D mesh over the first `n_devices` devices (all by default)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def require_distributed(mesh: Mesh) -> None:
    """Mirror of the reference's world_size >= 2 guard (TODO-…:56-59)."""
    if mesh.size < config.MIN_DEVICES_DISTRIBUTED:
        raise ValueError(
            f"distributed selection needs >= {config.MIN_DEVICES_DISTRIBUTED} "
            f"devices, got {mesh.size} (reference aborts the same way: "
            "TODO-kth-problem-cgm.c:56-59)"
        )


def shard_1d(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a 1-D array block-sharded over the mesh (the Scatterv analogue,
    TODO-kth-problem-cgm.c:103 — here a zero-copy sharding annotation)."""
    return jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))


def pad_to_multiple(x, multiple: int):
    """Pad 1-D `x` to a multiple of `multiple` with order-maximal sentinels.

    Balanced block distribution analogue of ``TODO-…:81-100``: XLA needs equal
    shards, so instead of first-(N%p)-ranks-get-one-extra we pad with values
    whose radix keys are all-ones (the dtype's order-maximum). Safe for
    selection as long as 1 <= k <= len(x): the sentinels occupy only the top
    ranks, and cumulative counts reach k within real elements first (see
    ops/radix.py docstring).
    """
    import jax.numpy as jnp

    from mpi_k_selection_tpu.utils import dtypes as _dt

    n = x.shape[0]
    rem = n % multiple
    if rem == 0:
        return x, n
    pad = multiple - rem
    kdt = _dt.key_dtype(x.dtype)
    ones = np.array(~np.uint64(0), dtype=np.uint64).astype(kdt)
    sentinel = _dt.from_sortable_bits(jnp.full((pad,), ones, dtype=kdt), x.dtype)
    return jnp.concatenate([x, sentinel]), n
