"""Test configuration: force an 8-device virtual CPU mesh.

The JAX analogue of running the reference under local ``mpirun -np P``
(SURVEY.md §4, "Multi-node without a cluster"): the collective/sharded paths
run on 8 virtual CPU devices so the full multi-chip code path executes
without TPU hardware. Must run before the first ``import jax``.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The machine's site customization (PYTHONPATH sitecustomize) pins
# jax_platforms to the real TPU; tests must run on the 8-device virtual CPU
# mesh regardless, so override the config directly as well.
jax.config.update("jax_platforms", "cpu")

import threading  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(autouse=True)
def _no_leaked_pipeline_threads():
    """Every package-owned thread must be joined by the time its owner
    returns/closes — normally AND on every raise/injected-fault path.
    All such threads carry the ``ksel-`` name prefix (``ksel-pipeline-*``
    producers/pullers, ``ksel-ingest-*``: the parallel data plane's
    encode/pack/stage workers and the spill read side's
    ``ksel-ingest-decode-*`` pool, ``ksel-serve-*``: the per-device dispatch-lane threads
    (``ksel-serve-lane-<key>-dispatch-*``, serve/lanes.py) and the
    standalone batcher's SUPERVISED dispatch thread — restarts reuse the
    same thread, so its name survives a crash-recover cycle — the HTTP
    serve loop, per-request handlers, ``ksel-monitor-*`` exporters, and
    any future faults/-layer worker),
    so the fixture matches the prefix family rather than an allowlist a
    new subsystem could silently fall out of. A thread surviving a test
    is a shutdown bug in streaming/pipeline.py, serve/, monitor/ or
    faults/, not test noise. The prefix vocabulary is the SAME registry
    the static lifecycle pass (KSL021) enforces against —
    mpi_k_selection_tpu/resource_protocols.py — so a resource kind
    cannot be tracked at runtime yet invisible statically."""
    yield
    from mpi_k_selection_tpu import resource_protocols as _rp
    # the owning modules re-export the registry's prefixes; assert the
    # canonical family stays ksel- so a renamed subsystem cannot dodge
    # the generic match, and that the live constants ARE the registry's
    from mpi_k_selection_tpu.monitor.monitor import MONITOR_THREAD_PREFIX
    from mpi_k_selection_tpu.serve.batcher import SERVE_THREAD_PREFIX
    from mpi_k_selection_tpu.streaming.pipeline import (
        INGEST_THREAD_PREFIX,
        THREAD_NAME_PREFIX,
    )

    assert set(_rp.THREAD_PREFIXES) == {
        THREAD_NAME_PREFIX, INGEST_THREAD_PREFIX, SERVE_THREAD_PREFIX,
        MONITOR_THREAD_PREFIX,
    }
    for prefix in _rp.RESOURCE_PREFIXES:
        assert prefix.startswith(_rp.KSEL_PREFIX)
    stragglers = [
        t for t in threading.enumerate()
        if t.name.startswith(_rp.KSEL_PREFIX)
    ]
    for t in stragglers:  # grace for a close() racing the fixture
        t.join(timeout=5.0)
    alive = [t.name for t in stragglers if t.is_alive()]
    assert not alive, f"leaked streaming-pipeline/serve threads: {alive}"


@pytest.fixture(autouse=True)
def _no_leaked_staged_buffers():
    """Every staged key buffer (``pipeline.stage_keys``) must be
    ``release()``d by the time its pass returns — on success AND on every
    raise path, including a consumer raise with executor bundles in
    flight (StreamExecutor.abort) and a pipeline close with staged chunks
    still queued. A nonzero delta is a leaked ring slot in
    streaming/executor.py or streaming/pipeline.py, not test noise."""
    from mpi_k_selection_tpu.streaming.pipeline import live_staged_keys

    before = live_staged_keys()
    yield
    after = live_staged_keys()
    assert after <= before, (
        f"leaked staged key buffers: {after - before} never release()d"
    )


@pytest.fixture(autouse=True)
def _flight_bundles_valid_and_reaped():
    """Every debug bundle the test dumped (obs/flight.py registers each
    one, auto and on-demand alike) must parse as JSON carrying all five
    always-present sections, and no ``ksel-flight-*`` file may outlive
    its test under the system temp dir — the spill-dir discipline
    applied to the postmortem artifacts. Bundles written to explicit
    paths (tmp_path) are validated too; only temp-dir ones are reaped
    here (pytest owns tmp_path cleanup)."""
    import glob
    import json
    import tempfile

    from mpi_k_selection_tpu.obs.flight import (
        BUNDLE_SECTIONS,
        FLIGHT_FILE_PREFIX,
        drain_dumped,
    )

    tmp = tempfile.gettempdir()
    pattern = os.path.join(tmp, FLIGHT_FILE_PREFIX + "*")
    before = set(glob.glob(pattern))
    drain_dumped()  # a prior test's stragglers are not this test's
    yield
    for path in drain_dumped():
        if not os.path.exists(path):
            continue
        with open(path) as f:
            bundle = json.load(f)  # must parse — a torn dump fails here
        missing = [s for s in BUNDLE_SECTIONS if s not in bundle]
        assert not missing, (
            f"debug bundle {path} is missing sections {missing} "
            f"(every bundle carries {BUNDLE_SECTIONS})"
        )
        if os.path.dirname(path) == tmp and os.path.basename(
            path
        ).startswith(FLIGHT_FILE_PREFIX):
            os.unlink(path)
    leaked = sorted(set(glob.glob(pattern)) - before)
    assert not leaked, f"leaked flight-recorder bundles: {leaked}"


@pytest.fixture(autouse=True)
def _no_leaked_spill_dirs():
    """Every internally-created spill store (streaming/spill.py) must be
    removed by the time its descent returns — on success AND on every
    raise path (consumer, producer, corrupt record). A ``ksel-spill-*``
    temp dir surviving a test is a cleanup bug in streaming/chunked.py's
    spill lifecycle, not test noise. (Pre-existing dirs from an earlier
    crashed process are tolerated: only NEW leaks fail the test.)"""
    import glob
    import tempfile

    from mpi_k_selection_tpu.resource_protocols import SPILL_DIR_PREFIX

    pattern = os.path.join(tempfile.gettempdir(), SPILL_DIR_PREFIX + "*")
    before = set(glob.glob(pattern))
    yield
    leaked = sorted(set(glob.glob(pattern)) - before)
    assert not leaked, f"leaked spill temp dirs: {leaked}"
