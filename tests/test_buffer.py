"""DeviceVector ADT vs the reference IntVector semantics (vector.c)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_k_selection_tpu.buffer import DeviceVector


def test_new_add_get_size():
    v = DeviceVector.new(8)
    assert v.capacity == 8 and int(v.size) == 0
    for i in [5, 3, 9]:
        v = v.add(i)
    assert int(v.size) == 3
    assert [int(v.get(i)) for i in range(3)] == [5, 3, 9]


def test_add_grows_like_realloc():
    v = DeviceVector.new(2)
    for i in range(5):
        v = v.add(i)
    assert int(v.size) == 5 and v.capacity >= 5
    assert [int(v.get(i)) for i in range(5)] == list(range(5))


def test_get_set_bounds_checked():
    v = DeviceVector.from_array(np.array([1, 2, 3], np.int32))
    v2 = v.set(1, 42)
    assert int(v2.get(1)) == 42 and int(v.get(1)) == 2  # immutable
    with pytest.raises(IndexError):
        v.get(3)
    with pytest.raises(IndexError):
        v.set(-1, 0)


def test_erase_swap_with_last():
    # faithful VecErase semantics (vector.c:108-121): O(1), order-destroying
    v = DeviceVector.from_array(np.array([10, 20, 30, 40], np.int32))
    v = v.erase(1)
    assert int(v.size) == 3
    assert sorted(int(v.get(i)) for i in range(3)) == [10, 30, 40]
    assert int(v.get(1)) == 40  # last element swapped into the hole


def test_erase_out_of_range_is_noop():
    v = DeviceVector.from_array(np.array([1, 2], np.int32))
    v = v.erase(5)
    assert int(v.size) == 2


def test_compact_preserves_order():
    x = np.array([7, 1, 8, 2, 9, 3], np.int32)
    v = DeviceVector.from_array(x)
    v = v.compact(x > 5)
    assert int(v.size) == 3
    assert [int(v.get(i)) for i in range(3)] == [7, 8, 9]


def test_min_max_sum_mean():
    x = np.array([4, -2, 7, 1], np.int32)
    v = DeviceVector.from_array(x)
    assert int(v.min()) == -2 and int(v.max()) == 7
    assert int(v.sum()) == 10  # AverageFind's actual behavior (vector.c:162)
    assert float(v.mean()) == pytest.approx(2.5)


def test_reductions_ignore_dead_slots():
    v = DeviceVector.new(8).add(5).add(3)
    assert int(v.min()) == 3 and int(v.max()) == 5 and int(v.sum()) == 8


def test_search():
    v = DeviceVector.from_array(np.array([5, 3, 5, 1], np.int32))
    assert int(v.search(5)) == 0
    assert int(v.search(5, start_pos=1)) == 2
    assert int(v.search(99)) == -1


def test_sort_and_binary_search():
    rng = np.random.default_rng(0)
    x = rng.integers(-100, 100, size=37, dtype=np.int32)
    v = DeviceVector.new(64, jnp.int32)
    for e in x:
        v = v.add(int(e))
    v = v.sort()
    got = [int(v.get(i)) for i in range(37)]
    assert got == sorted(int(e) for e in x)
    probe = int(x[7])
    assert got[int(v.binary_search(probe))] == probe
    assert int(v.binary_search(101)) == -1


def test_sort_float_negatives():
    x = np.array([0.5, -1.5, -0.0, 2.5, 0.0], np.float32)
    v = DeviceVector.from_array(x).sort()
    assert [float(v.get(i)) for i in range(5)] == sorted(x.tolist())


def test_jittable_pipeline():
    # the ADT flows through jit: mask-discard then reduce, all traced
    @jax.jit
    def pipeline(v: DeviceVector, pivot):
        kept = v.compact(v.data < pivot)
        return kept.size, kept.sum()

    x = np.arange(16, dtype=np.int32)
    n, s = pipeline(DeviceVector.from_array(x), 10)
    assert int(n) == 10 and int(s) == 45


def test_traced_append_under_scan():
    # VecAdd usable inside lax control flow (the generation loop analogue,
    # kth-problem-seq.c:26-28)
    def body(v, e):
        return v.add(e), None

    v0 = DeviceVector.new(8)
    xs = jnp.arange(5, dtype=jnp.int32)
    v, _ = jax.lax.scan(body, v0, xs)
    assert int(v.size) == 5 and [int(v.get(i)) for i in range(5)] == list(range(5))
