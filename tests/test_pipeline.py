"""Pipelined streaming ingest (streaming/pipeline.py).

The acceptance contract: every answer the pipelined path
(``pipeline_depth >= 1``) produces is BIT-identical to the synchronous
oracle (``pipeline_depth=0``) — host chunks, device chunks, ragged final
chunks, staged pow2 padding, the host-exact 64-bit and float64 routes —
and every error the synchronous path raises (dtype drift, replay
instability, oversized chunks) still raises with chunks in flight, with
the producer thread joined on every exit path (the autouse conftest
fixture asserts no ``ksel-pipeline`` thread survives any test here).
"""

import threading
import time

import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.streaming import (
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.streaming import pipeline as pl
from mpi_k_selection_tpu.streaming.chunked import _chunk_histograms
from mpi_k_selection_tpu.utils.profiling import PhaseTimer


def _chunks(x, nchunks):
    return [np.ascontiguousarray(c) for c in np.array_split(x, nchunks)]


def _ints(rng, n, dtype=np.int32):
    return rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(dtype)


# -- bit-equality with the synchronous oracle --------------------------------


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_pipeline_bit_equal_host_chunks(depth, rng):
    x = _ints(rng, 1 << 14)
    chunks = _chunks(x, 8)
    ks = [1, 137, x.size // 2, x.size]
    sync = streaming_kselect_many(chunks, ks, pipeline_depth=0)
    assert sync == [seq.kselect_sort(x, k) for k in ks]
    assert streaming_kselect_many(chunks, ks, pipeline_depth=depth) == sync


def test_pipeline_bit_equal_device_chunks(rng):
    import jax.numpy as jnp

    x = _ints(rng, 1 << 14)
    dchunks = [jnp.asarray(c) for c in _chunks(x, 8)]
    k = 4321
    sync = streaming_kselect(dchunks, k, pipeline_depth=0)
    assert streaming_kselect(dchunks, k, pipeline_depth=2) == sync == seq.kselect_sort(x, k)


def test_pipeline_slow_source_bit_equal(rng):
    """An artificially slow producer (sleep per chunk) exercises real
    consumer-side waiting; answers stay bit-equal to the synchronous
    path for host AND device chunk streams."""
    import jax.numpy as jnp

    x = _ints(rng, 1 << 13)
    host = _chunks(x, 6)
    dev = [jnp.asarray(c) for c in host]

    def slow(parts):
        def source():
            for c in parts:
                time.sleep(0.002)
                yield c

        return source

    k = x.size // 3
    want = seq.kselect_sort(x, k)
    for parts in (host, dev):
        sync = streaming_kselect(slow(parts), k, pipeline_depth=0)
        assert streaming_kselect(slow(parts), k, pipeline_depth=3) == sync == want


def test_pipeline_ragged_final_chunk_staged_padding(rng):
    """Non-pow2 chunk sizes force the staged pow2 padding + host-side pad
    correction; a ragged final chunk exercises a second bucket size. Forced
    device method so staging actually engages on the CPU backend."""
    x = _ints(rng, 3 * 1000 + 537)  # chunks of 1000,1000,1000,537
    chunks = [x[:1000], x[1000:2000], x[2000:3000], x[3000:]]
    for k in (1, 1700, x.size):
        sync = streaming_kselect(chunks, k, hist_method="scatter", pipeline_depth=0)
        got = streaming_kselect(chunks, k, hist_method="scatter", pipeline_depth=2)
        assert got == sync == seq.kselect_sort(x, k)


def test_pipeline_empty_chunks_skipped(rng):
    x = _ints(rng, 257)
    chunks = [x[:100], np.empty(0, np.int32), x[100:], np.empty(0, np.int32)]
    assert streaming_kselect(chunks, 19, pipeline_depth=2) == seq.kselect_sort(x, 19)
    with pytest.raises(ValueError, match="non-empty"):
        streaming_kselect([np.empty(0, np.int32)], 1, pipeline_depth=2)


def test_pipeline_64bit_host_exact_route_no_x64(rng):
    import jax

    assert not jax.config.jax_enable_x64
    x = rng.integers(-(2**62), 2**62, size=1 << 13, dtype=np.int64)
    k = x.size // 2
    sync = streaming_kselect(_chunks(x, 8), k, pipeline_depth=0)
    got = streaming_kselect(_chunks(x, 8), k, pipeline_depth=2)
    assert got == sync == seq.kselect_sort(x, k)


def test_pipeline_64bit_device_chunks_under_x64(rng):
    """jax's enable_x64 context is thread-local: the producer thread must
    inherit the consumer's mode, or encoding 64-bit DEVICE chunks in the
    worker raises where the synchronous path succeeds."""
    from mpi_k_selection_tpu.utils import x64

    x = rng.integers(-(2**62), 2**62, size=1 << 12, dtype=np.int64)
    k = x.size // 2
    with x64.enable_x64():
        import jax.numpy as jnp

        dchunks = [jnp.asarray(c) for c in _chunks(x, 8)]
        sync = streaming_kselect(dchunks, k, pipeline_depth=0)
        got = streaming_kselect(dchunks, k, pipeline_depth=2)
    assert got == sync == seq.kselect_sort(x, k)


def test_pipeline_f64_host_exact_route(rng):
    x = rng.standard_normal(1 << 13)  # float64
    k = x.size // 2
    sync = streaming_kselect(_chunks(x, 8), k, pipeline_depth=0)
    got = streaming_kselect(_chunks(x, 8), k, pipeline_depth=2)
    assert got == sync == seq.kselect_sort(x, k)


def test_pipeline_tiny_budget_multi_prefix(rng):
    # a tiny collect budget drives deep multi-prefix passes — the staged
    # shared-sweep path — through several pipeline generations
    x = _ints(rng, 1 << 14)
    chunks = _chunks(x, 8)
    ks = [7, x.size // 4, x.size // 2, x.size - 3]
    sync = streaming_kselect_many(chunks, ks, collect_budget=64, pipeline_depth=0)
    got = streaming_kselect_many(chunks, ks, collect_budget=64, pipeline_depth=2)
    assert got == sync == [seq.kselect_sort(x, k) for k in ks]


def test_pipeline_certificate_matches_sync(rng):
    x = _ints(rng, 1 << 13)
    chunks = _chunks(x, 8)
    v = int(np.sort(x)[x.size // 2])
    sync = streaming_rank_certificate(chunks, v, pipeline_depth=0)
    assert streaming_rank_certificate(chunks, v, pipeline_depth=2) == sync


# -- error propagation + shutdown -------------------------------------------


def test_pipeline_dtype_mismatch_raises(rng):
    x = _ints(rng, 64)
    with pytest.raises(TypeError, match="one dtype"):
        streaming_kselect([x, x.astype(np.float32)], 1, pipeline_depth=2)


def test_pipeline_drifting_source_raises_and_joins(rng):
    calls = [0]

    def source():
        calls[0] += 1
        r = np.random.default_rng(calls[0])
        for _ in range(4):  # several chunks keep the producer busy/ahead
            yield r.integers(-(2**31), 2**31, size=1 << 11, dtype=np.int64).astype(
                np.int32
            )

    with pytest.raises(RuntimeError, match="not replay-stable"):
        streaming_kselect(source, 1 << 12, collect_budget=4, pipeline_depth=3)
    # deterministic shutdown: the consumer-side raise unwound through the
    # stream context manager, which joined the producer thread
    assert not [
        t for t in threading.enumerate()
        if t.name.startswith(pl.THREAD_NAME_PREFIX)
    ]


def test_pipeline_source_exception_propagates(rng):
    x = _ints(rng, 256)

    def source():
        yield x
        raise OSError("disk gone")

    with pytest.raises(OSError, match="disk gone"):
        streaming_kselect(source, 5, pipeline_depth=2)


def test_pipeline_depth_validation(rng):
    x = _ints(rng, 64)
    for bad in (-1, 1.5, "2", True, pl.MAX_PIPELINE_DEPTH + 1):
        with pytest.raises(ValueError, match="pipeline_depth"):
            streaming_kselect([x], 1, pipeline_depth=bad)
    with pytest.raises(ValueError, match="pipeline_depth"):
        pl.ChunkPipeline(lambda: iter([x]), depth=0)


def test_pipeline_inherits_default_device(rng):
    """jax.default_device is thread-local like enable_x64: staged buffers
    must land on the CALLER's device, not wherever the fresh producer
    thread defaults to."""
    import jax

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs the multi-device virtual mesh")
    target = devs[-1]
    x = _ints(rng, 1 << 12)
    chunks = _chunks(x, 4)  # 1024-element pow2 chunks: staged unpadded
    with jax.default_device(target):
        pipe = pl.ChunkPipeline(
            lambda: iter(chunks), depth=2, hist_method="scatter"
        )
        try:
            n = 0
            for keys, _ in pipe:
                assert isinstance(keys, pl.StagedKeys)
                assert next(iter(keys.data.devices())) == target
                n += keys.size
                keys.release()  # the consumer contract: every slot freed
        finally:
            pipe.close()
    assert n == x.size


def test_pipeline_depth_zero_spawns_no_thread(rng):
    x = _ints(rng, 1 << 10)
    before = {t.ident for t in threading.enumerate()}
    streaming_kselect(_chunks(x, 4), 17, pipeline_depth=0)
    new = [
        t for t in threading.enumerate()
        if t.ident not in before and t.name.startswith(pl.THREAD_NAME_PREFIX)
    ]
    assert not new


# -- staged padding machinery ------------------------------------------------


def test_bucket_elems_pow2_ceiling():
    assert [pl._bucket_elems(n) for n in (1, 2, 3, 4, 5, 1000, 1024)] == [
        1, 2, 4, 4, 8, 1024, 1024,
    ]
    # past 2^30 the pow2 ceiling would cross the 2^31 counter bound: unpadded
    assert pl._bucket_elems((1 << 30) + 1) == (1 << 30) + 1


def test_staged_histogram_pad_correction_exact(rng):
    """Histogram a padded staged buffer and the raw keys: identical counts,
    including the all-zero prefix (where pad keys land) and real zero keys
    in the stream (the correction must not over-subtract)."""
    from mpi_k_selection_tpu.utils import dtypes as _dt

    kdt = np.dtype(np.uint32)
    keys = _dt.np_to_sortable_bits(_ints(rng, 1000))  # non-pow2 -> pad 24
    keys[:5] = 0  # real zero keys alongside the pad zeros
    staged = pl.stage_keys(keys)
    assert staged.pad == 24 and staged.size == 1000
    staged.release()

    ups = sorted({int(u) for u in (keys >> np.uint32(16))} | {0})[:4]

    def hists(mk):
        # a fresh staging per call: _chunk_histograms releases (donates)
        # a staged buffer once its counts are host-side
        one = _chunk_histograms(mk(), 24, 8, [None], "scatter", kdt)[None]
        # multi-prefix at a deeper level, INCLUDING prefix 0 (pad-sensitive)
        many = _chunk_histograms(mk(), 8, 8, ups, "scatter", kdt)
        return one, many

    got_one, got_many = hists(lambda: pl.stage_keys(keys))
    want_one, want_many = hists(lambda: keys)
    np.testing.assert_array_equal(got_one, want_one)
    assert set(got_many) == set(want_many)
    for p in want_many:
        np.testing.assert_array_equal(got_many[p], want_many[p])


def test_staged_keys_valid_slice_roundtrip(rng):
    keys = np.arange(100, dtype=np.uint32) + 7
    staged = pl.stage_keys(keys)
    np.testing.assert_array_equal(np.asarray(staged.valid()), keys)
    staged.release()  # idempotent / safe post-use


# -- instrumentation ---------------------------------------------------------


def test_ingest_hidden_frac_recorded_and_bounded(rng):
    x = _ints(rng, 1 << 13)
    timer = PhaseTimer()
    got = streaming_kselect(
        _chunks(x, 8), x.size // 2, pipeline_depth=2, timer=timer
    )
    assert got == seq.kselect_sort(x, x.size // 2)
    frac = pl.ingest_hidden_frac(timer)
    assert frac is not None and 0.0 <= frac <= 1.0
    assert any(p in timer.phases for p in pl.INGEST_PHASES)
    assert pl.STALL_PHASE in timer.phases


def test_ingest_hidden_frac_none_for_sync_run(rng):
    x = _ints(rng, 1 << 10)
    timer = PhaseTimer()
    streaming_kselect(_chunks(x, 4), 5, pipeline_depth=0, timer=timer)
    assert pl.ingest_hidden_frac(timer) is None


# -- sketch / quantile surfaces ----------------------------------------------


def test_sketch_update_stream_matches_sequential(rng):
    from mpi_k_selection_tpu.streaming import RadixSketch

    x = _ints(rng, 1 << 13)
    chunks = _chunks(x, 7)
    want = RadixSketch(np.int32)
    for c in chunks:
        want.update(c)
    assert RadixSketch(np.int32).update_stream(chunks, pipeline_depth=2) == want
    assert RadixSketch(np.int32).update_stream(chunks, pipeline_depth=0) == want


def test_streaming_quantiles_pipeline_surface(rng):
    from mpi_k_selection_tpu import StreamingQuantiles
    from mpi_k_selection_tpu.api import quantile_ranks

    x = _ints(rng, 1 << 13)
    chunks = _chunks(x, 8)
    t = StreamingQuantiles(np.int32, pipeline_depth=2).update_stream(chunks)
    t0 = StreamingQuantiles(np.int32, pipeline_depth=0)
    for c in chunks:
        t0.update(c)
    assert t.sketch == t0.sketch
    qs = [0.5, 0.99]
    s = np.sort(x, kind="stable")
    want = [s[k - 1] for k in quantile_ranks(qs, x.size)]
    assert t.refine_quantiles(qs, chunks) == want
    assert t0.refine_quantiles(qs, chunks) == want
    with pytest.raises(ValueError, match="pipeline_depth"):
        StreamingQuantiles(np.int32, pipeline_depth=-2)


def test_cli_pipeline_depth_flag(capsys):
    import json

    from mpi_k_selection_tpu import cli

    args = [
        "--backend", "tpu", "--streaming", "--n", "60000",
        "--chunk-elems", "9973", "--verify", "--check", "--json",
    ]
    rc = cli.main(args + ["--pipeline-depth", "2"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["extra"]["pipeline_depth"] == 2
    assert rec["extra"]["exact_match"] is True
    rc = cli.main(args + ["--pipeline-depth", "0"])
    assert rc == 0
    rec0 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec0["extra"]["pipeline_depth"] == 0
    assert rec0["answer"] == rec["answer"]
    with pytest.raises(SystemExit):
        cli.main(args + ["--pipeline-depth", "-3"])
    capsys.readouterr()
