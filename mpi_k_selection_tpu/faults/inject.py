"""Fault injector — executes a :class:`~mpi_k_selection_tpu.faults.plan.
FaultPlan` at the real failure surfaces.

The streaming and serving layers carry cheap hook points
(``maybe_fault(site, ...)`` — one module-global ``is None`` check when no
harness is active) at exactly the places real faults strike: the chunk
pull, the staging ``device_put``, spill record writes and reads, and the
batcher's dispatch loop. Activating a plan (:func:`inject`, a context
manager) arms those hooks process-wide; the injector counts occurrences
and attempts per site under a lock (producer threads, request threads and
the consumer all hit it), fires the scheduled fault kinds — transient
raises, sleeper-backed stalls, on-disk corruption/truncation so the REAL
CRC/size validation trips, ENOSPC — and logs every firing (``fired``,
plus a :class:`~mpi_k_selection_tpu.obs.events.FaultEvent` per firing
when an obs bundle is attached).

Only ONE injector can be active at a time (nesting raises): the plan's
occurrence counters are process-global state, and two overlapping plans
would see interleaved counts neither seeded for.
"""

from __future__ import annotations

import contextlib
import errno as _errno
import os
import threading

from mpi_k_selection_tpu.errors import SpillRecordError, TransientError
from mpi_k_selection_tpu.faults.plan import FaultPlan, FaultSpec
from mpi_k_selection_tpu.faults.sleeper import resolve_sleeper
from mpi_k_selection_tpu.obs.wiring import fault_event


class FaultInjector:
    """Runtime executor for one plan. ``check``/``maybe_fault`` are the
    hook-point API; ``wrap_chunk_source`` arms a chunk source with the
    plan's ``"source"`` specs. ``fired`` is the chronological injection
    log (dicts: site/kind/index/attempt) the chaos tests and the CLI
    ``--chaos`` report read back."""

    def __init__(self, plan: FaultPlan, *, sleeper=None, obs=None):
        if not isinstance(plan, FaultPlan):
            raise ValueError(f"expected a FaultPlan, got {plan!r}")
        self.plan = plan
        self.sleeper = resolve_sleeper(sleeper)
        self.obs = obs
        self._lock = threading.Lock()
        self._site_calls: dict[str, int] = {}  # auto-index per site
        self._attempts: dict[tuple, int] = {}  # (site, index) -> tries
        self.fired: list[dict] = []  # ksel: guarded-by[_lock]
        self._by_key = {}
        for s in plan.specs:
            # later specs for the same (site, index) extend the earlier
            # ones' attempt set rather than silently shadowing them
            self._by_key.setdefault((s.site, s.index), []).append(s)

    # -- bookkeeping -------------------------------------------------------

    def _next_index(self, site: str) -> int:
        i = self._site_calls.get(site, 0)
        self._site_calls[site] = i + 1
        return i

    def check(self, site: str, index: int | None = None) -> FaultSpec | None:
        """Advance the (site, index) attempt counter and return the spec
        scheduled for this attempt, if any. ``index=None`` auto-indexes
        by site call order (the ``stage``/``spill.write``/
        ``serve.dispatch`` sites, where "occurrence i" means the i-th
        call)."""
        with self._lock:
            if index is None:
                index = self._next_index(site)
            key = (site, int(index))
            attempt = self._attempts.get(key, 0)
            self._attempts[key] = attempt + 1
            for spec in self._by_key.get(key, ()):
                if attempt in spec.attempts:
                    self.fired.append(
                        {
                            "site": site,
                            "kind": spec.kind,
                            "index": int(index),
                            "attempt": attempt,
                        }
                    )
                    self._emit(spec, int(index), attempt)
                    return spec
        return None

    def _emit(self, spec: FaultSpec, index: int, attempt: int) -> None:
        fault_event(
            self.obs, spec.site, "inject",
            fault_kind=spec.kind, index=index, attempt=attempt,
            counter="faults.injected", labels={"site": spec.site},
        )

    # -- execution ---------------------------------------------------------

    def maybe_fault(self, site: str, index: int | None = None, path=None):
        """Hook-point entry: fire the scheduled fault for this call, if
        any. Raising kinds raise here (``"raise"`` ->
        :class:`TransientError`; ``"enospc"`` -> ``OSError(ENOSPC)``;
        ``"corrupt"`` -> :class:`SpillRecordError`, the transient bad
        read). ``"stall"`` sleeps through the injectable sleeper and
        proceeds. The persistent disk kinds (``"corrupt_disk"``,
        ``"truncate"``) damage ``path`` on disk and proceed — the caller's
        own CRC/size validation then fails exactly as it would for real
        corruption."""
        spec = self.check(site, index)
        if spec is None:
            return None
        if spec.kind == "stall":
            self.sleeper.sleep(spec.arg)
            return spec
        if spec.kind == "raise":
            raise TransientError(
                f"injected transient fault at {site}[{spec.index}]"
            )
        if spec.kind == "enospc":
            raise OSError(
                _errno.ENOSPC,
                f"injected ENOSPC at {site}[{spec.index}]",
            )
        if spec.kind == "corrupt":
            raise SpillRecordError(
                f"injected transient checksum mismatch at {site}[{spec.index}]"
            )
        # persistent disk damage: the real validation machinery trips
        if path is not None:
            apply_disk_fault(path, spec.kind)
        return spec

    def wrap_chunk_source(self, src):
        """Arm a replayable chunk-source callable with this injector's
        ``"source"`` specs: pulling chunk *i* consults
        ``maybe_fault("source", i)`` first, so scheduled raises/stalls
        strike before the chunk exists — the upstream-hiccup shape. The
        wrapped source stays replayable (each invocation re-iterates the
        inner source; the per-chunk attempt counters persist across
        invocations, which is exactly what lets retries and later passes
        see the chunk recover)."""
        injector = self

        def wrapped():
            it = iter(src())
            def gen():
                i = 0
                while True:
                    injector.maybe_fault("source", i)
                    try:
                        chunk = next(it)
                    except StopIteration:
                        return
                    yield chunk
                    i += 1
            return gen()

        return wrapped


def apply_disk_fault(path: str, kind: str) -> None:
    """Persist one fault into a spill record file: ``"corrupt_disk"``
    XORs the file's last byte (payload territory — the header is
    fixed-size at the front, and records are validated header-first, so
    the flip lands in checksummed payload); ``"truncate"`` cuts the file
    in half. Both make the record's own validation
    (:class:`~mpi_k_selection_tpu.errors.SpillRecordError`) fire on
    every subsequent read — real, persistent damage."""
    size = os.path.getsize(path)
    if kind == "truncate":
        os.truncate(path, size // 2)
        return
    if kind == "corrupt_disk":
        if size == 0:  # pragma: no cover - records always carry a header
            return
        with open(path, "r+b") as f:
            f.seek(size - 1)
            b = f.read(1)
            f.seek(size - 1)
            f.write(bytes([b[0] ^ 0xFF]))
        return
    raise ValueError(f"not a disk fault kind: {kind!r}")  # pragma: no cover


# -- the process-wide active injector ---------------------------------------

_ACTIVE: FaultInjector | None = None  # ksel: guarded-by[_ACTIVE_LOCK] (writes; the hook-point read is a deliberate bare is-None probe)
_ACTIVE_LOCK = threading.Lock()


def active_injector() -> FaultInjector | None:
    """The currently armed injector (None = no harness active — the
    production state; every hook point is one ``is None`` check then)."""
    return _ACTIVE


def maybe_fault(site: str, index: int | None = None, path=None):
    """The hook-point helper library code calls: no-op without an armed
    injector, else :meth:`FaultInjector.maybe_fault`."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj.maybe_fault(site, index, path=path)


@contextlib.contextmanager
def inject(plan_or_injector, *, sleeper=None, obs=None):
    """Arm a plan (or a pre-built injector) process-wide for the body of
    the ``with`` block, yielding the injector (its ``fired`` log is the
    post-run evidence). Exactly one injector may be active; nesting
    raises. The hooks are disarmed on EVERY exit path."""
    global _ACTIVE
    if isinstance(plan_or_injector, FaultInjector):
        if sleeper is not None or obs is not None:
            # silently dropping these would de-virtualize sleeps (a
            # "virtual" chaos run blocking for real) and lose every
            # inject event from the telemetry — fail loudly instead
            raise ValueError(
                "pass sleeper=/obs= to FaultInjector(...) itself; "
                "inject() does not rewire a pre-built injector"
            )
        inj = plan_or_injector
    else:
        inj = FaultInjector(plan_or_injector, sleeper=sleeper, obs=obs)
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError(
                "a fault injector is already active; nested inject() is "
                "not supported (occurrence counters are process-global)"
            )
        _ACTIVE = inj
    try:
        yield inj
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
