"""Monitor — continuous multirank quantiles over an unbounded chunk
stream.

The driver consumes any chunk source the streaming subsystem accepts —
replayable callables, chunk lists, AND bare one-shot iterators (a
monitor reads its stream exactly once, so one-shot is first-class here)
— through the SAME ingest machinery as the descent:
``as_chunk_source`` -> the pipelined ``_key_chunk_stream`` (background
produce/encode/stage, round-robin ``devices`` staging) -> a
:class:`~mpi_k_selection_tpu.streaming.executor.StreamExecutor`
consumer folding each chunk's deepest-level histogram into the open
window bucket (on the chunk's own device when staged, exactly like
``RadixSketch.update_stream``). Nothing underneath changed.

Every ``emit_every`` chunks the window advances and one
:class:`MonitorSample` is yielded: the requested quantiles (default
p50/p90/p99 — the ``multirank_p50_p90_p99`` stream) over the live
window, each value carrying the merged sketch's EXACT
``rank_bounds``/``value_bounds``/``rank_error_bound``. With ``decay``
set, the sample is the fixed-point decayed aggregate
(monitor/decay.py). CLI surface: ``kselect monitor`` (cli.py);
Prometheus surface: :func:`start_metrics_server` (text exposition of
the obs registry the samples mirror into).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from mpi_k_selection_tpu.monitor.decay import DecayedWindowedSketch
from mpi_k_selection_tpu.monitor.windows import WindowedSketch
from mpi_k_selection_tpu.resource_protocols import MONITOR_THREAD_PREFIX

DEFAULT_QS = (0.5, 0.9, 0.99)

# MONITOR_THREAD_PREFIX (imported above) names the metrics exporter's
# threads (the ``ksel-`` family the leaked-thread fixture tracks — every
# thread is joined at close()). Canonical value: resource_protocols.py.


def q_label(q: float) -> str:
    """Percentile label of a quantile: ``0.5 -> "p50"``,
    ``0.99 -> "p99"``, ``0.999 -> "p99_9"``."""
    s = format(float(q) * 100, "g").replace(".", "_")
    return f"p{s}"


def _jsonable(v):
    item = getattr(v, "item", None)
    return item() if item is not None else v


@dataclasses.dataclass(frozen=True)
class MonitorSample:
    """One window advance's quantile readout. ``n`` is the merged
    window's count — WEIGHTED (on the ``scale`` fixed point) when
    decayed, raw otherwise; bounds are the sketch's exact guarantees
    over that count space."""

    epoch: int
    buckets: int
    n: int
    scale: int
    qs: tuple
    ranks: tuple
    values: tuple
    rank_bounds: tuple
    value_bounds: tuple
    rank_error_bounds: tuple
    chunks: int
    keys_read: int

    @property
    def metric_name(self) -> str:
        """``multirank_p50_p90_p99`` for the default quantile set."""
        return "multirank_" + "_".join(q_label(q) for q in self.qs)

    def as_dict(self) -> dict:
        return {
            "metric": self.metric_name,
            "epoch": self.epoch,
            "buckets": self.buckets,
            "n": int(self.n),
            "scale": int(self.scale),
            "qs": [float(q) for q in self.qs],
            "ranks": [int(k) for k in self.ranks],
            "values": [_jsonable(v) for v in self.values],
            "rank_bounds": [[int(a), int(b)] for a, b in self.rank_bounds],
            "value_bounds": [
                [_jsonable(a), _jsonable(b)] for a, b in self.value_bounds
            ],
            "rank_error_bounds": [int(e) for e in self.rank_error_bounds],
            "chunks": self.chunks,
            "keys_read": self.keys_read,
        }

    def format_line(self) -> str:
        """One human-readable stream line (the CLI's non-JSON mode)."""
        parts = [
            f"{self.metric_name} epoch={self.epoch} buckets={self.buckets} "
            f"n={self.n}"
        ]
        for q, v, (vlo, vhi), err in zip(
            self.qs, self.values, self.value_bounds, self.rank_error_bounds
        ):
            parts.append(
                f"{q_label(q)}={_jsonable(v)} in [{_jsonable(vlo)}, "
                f"{_jsonable(vhi)}] rank_err<={err}"
            )
        return "  ".join(parts)


class _BucketFoldConsumer:
    """StreamExecutor consumer folding chunks into the window's OPEN
    bucket: staged chunks dispatch their deepest-level histogram +
    extremes on their own device (``RadixSketch._dispatch_staged``) and
    fold at FIFO-pop time INTO THE BUCKET THAT DISPATCHED THEM (the
    handle pins it, and the Monitor drains the window before every
    advance, so a bucket boundary can never split a dispatch/fold
    pair); host/device-resident chunks fold inline."""

    def __init__(self, ws: WindowedSketch, obs=None):
        self._ws = ws
        self._obs = obs
        self.staged_chunks = 0

    def dispatch(self, keys, kv):
        from mpi_k_selection_tpu.obs import wiring as _bw
        from mpi_k_selection_tpu.streaming import pipeline as _pl

        cur = self._ws.current
        if isinstance(keys, _pl.StagedKeys):
            self.staged_chunks += 1
            # two device programs per staged bucket (deep histogram +
            # extremes), same as the sketch consumer — keeps the
            # bucket_read_bytes / staged_bytes amplification honest for
            # monitor runs too
            _bw.bucket_read(self._obs, "monitor", keys, 2)
            return cur, cur._dispatch_staged(keys)
        if not isinstance(kv, np.ndarray):
            kv = np.asarray(kv)
        cur._update_keys(kv)
        return None

    def finish(self, handle) -> None:
        cur, h = handle
        cur._fold_staged(h)


class Monitor:
    """Continuous quantile monitoring over an unbounded stream.

    Configuration: ``qs`` (any rank set — the default is the
    p50/p90/p99 triple), ``window`` (ring length, buckets),
    ``emit_every`` (chunks per bucket: the window advances and a sample
    is emitted every that many chunks), ``decay`` (None = the exact
    sliding window; a float in (0, 1] = the fixed-point exponential
    decay of monitor/decay.py), plus the streaming ingest knobs
    (``pipeline_depth``, ``devices``) and ``obs``. Answers are
    bit-identical at every depth/devices combination (the same contract
    as ``RadixSketch.update_stream``); ``obs`` mirrors each sample into
    ``monitor.quantile{q=}`` gauges and never changes a count bit."""

    def __init__(
        self, *, qs=DEFAULT_QS, window: int = 32, emit_every: int = 1,
        decay: float | None = None, radix_bits: int = 4, levels: int = 4,
        pipeline_depth=None, devices=None, obs=None,
    ):
        self.qs = tuple(float(q) for q in qs)
        if not self.qs:
            raise ValueError("monitor needs at least one quantile")
        self.window = int(window)
        self.emit_every = int(emit_every)
        if self.emit_every < 1:
            raise ValueError(f"emit_every must be >= 1, got {emit_every}")
        self.decay = None if decay is None else float(decay)
        self.radix_bits = int(radix_bits)
        self.levels = int(levels)
        self.pipeline_depth = pipeline_depth
        self.devices = devices
        self.obs = obs
        # label dicts built once: the metric label set is the monitor's
        # fixed configuration, not per-sample data (KSL013's class)
        self._q_labels = tuple({"q": q_label(q)} for q in self.qs)
        self.ws: WindowedSketch | None = None

    def _make_window(self, dtype) -> WindowedSketch:
        if self.decay is None:
            return WindowedSketch(
                dtype, window=self.window, radix_bits=self.radix_bits,
                levels=self.levels,
            )
        return DecayedWindowedSketch(
            dtype, window=self.window, decay=self.decay,
            radix_bits=self.radix_bits, levels=self.levels,
        )

    def sample(self, chunks: int = 0, keys_read: int = 0) -> MonitorSample | None:
        """One readout of the CURRENT window state (None while empty) —
        the per-advance emission, also callable standalone."""
        ws = self.ws
        if ws is None:
            return None
        m = ws.query()
        if m.n == 0:
            return None
        from mpi_k_selection_tpu.api import quantile_ranks

        ranks = quantile_ranks(self.qs, m.n)
        values, rbounds, vbounds, rerrs = [], [], [], []
        for k in ranks:
            lo, hi = m.rank_bounds(k)
            vlo, vhi = m.value_bounds(k)
            values.append(m.query(k))
            rbounds.append((lo, hi))
            vbounds.append((vlo, vhi))
            rerrs.append(hi - lo)
        out = MonitorSample(
            epoch=ws.epoch,
            buckets=ws.n_live,
            n=m.n,
            scale=getattr(m, "scale", 1),
            qs=self.qs,
            ranks=tuple(int(k) for k in ranks),
            values=tuple(values),
            rank_bounds=tuple(rbounds),
            value_bounds=tuple(vbounds),
            rank_error_bounds=tuple(rerrs),
            chunks=chunks,
            keys_read=keys_read,
        )
        if self.obs is not None and self.obs.metrics is not None:
            reg = self.obs.metrics
            for lab, v in zip(self._q_labels, values):
                reg.gauge("monitor.quantile", labels=lab).set(_jsonable(v))
            reg.gauge("monitor.window_n").set(int(m.n))
            reg.gauge("monitor.epoch").set(int(ws.epoch))
            reg.counter("monitor.samples").inc()
        return out

    def run(self, source, dtype=None, *, max_samples=None, timer=None):
        """Generator of :class:`MonitorSample`s — one per window advance
        (plus a final partial-bucket sample at stream end), until the
        source exhausts or ``max_samples`` is reached. ``dtype`` is the
        stream dtype (inferred from a list/array source; required for
        generators/callables — a monitor never replays, so it cannot
        probe). The ingest pipeline is torn down on EVERY exit path,
        including an abandoned generator."""
        from mpi_k_selection_tpu.obs import wiring as _wr
        from mpi_k_selection_tpu.streaming import executor as _exec
        from mpi_k_selection_tpu.streaming import pipeline as _pl
        from mpi_k_selection_tpu.streaming.chunked import (
            _key_chunk_stream,
            as_chunk_source,
        )
        from mpi_k_selection_tpu.utils import dtypes as _dt

        if dtype is None:
            if isinstance(source, (list, tuple)) and len(source):
                dtype = np.asarray(source[0]).dtype
            elif isinstance(source, np.ndarray):
                dtype = source.dtype
            else:
                raise TypeError(
                    "pass dtype= for generator/callable sources: the "
                    "monitor folds chunks as they arrive and cannot "
                    "replay the stream to probe its dtype"
                )
        dtype = np.dtype(dtype)
        kdt = np.dtype(_dt.key_dtype(dtype))
        depth = _pl.validate_pipeline_depth(self.pipeline_depth)
        devs = _pl.resolve_stream_devices(self.devices)
        # gate staging on the raw knobs, not the resolved tuple (KSL022):
        # an explicit single device must stage committed, not host-fold
        staged = depth > 0 and self.devices is not None
        self.ws = self._make_window(dtype)
        src = as_chunk_source(source, one_shot_ok=True)
        timer, _restore = _wr.attach_timer(self.obs, timer)
        consumer = _BucketFoldConsumer(self.ws, obs=self.obs)
        ex = _exec.StreamExecutor(
            [consumer], window=len(devs),
            occupancy=_wr.window_occupancy(self.obs, phase="monitor"),
        )
        chunk_i = keys_read = emitted = in_bucket = 0
        keys = None
        try:
            with _pl._phase(timer, "monitor.pass"), _key_chunk_stream(
                src, dtype, pipeline_depth=depth, timer=timer,
                hist_method="scatter" if staged else None,
                devices=devs if staged else None,
            ) as kc:
                for keys, _ in kc:
                    if self.obs is not None:
                        _wr.chunk_event(
                            self.obs, "monitor", chunk_i, keys, kdt, devs
                        )
                    chunk_i += 1
                    keys_read += int(keys.size)
                    in_bucket += 1
                    ex.push(keys)
                    if in_bucket >= self.emit_every:
                        ex.drain()
                        s = self.sample(chunk_i, keys_read)
                        if s is not None:
                            emitted += 1
                            yield s
                        self.ws.advance()
                        in_bucket = 0
                        if max_samples is not None and emitted >= max_samples:
                            break
                else:
                    ex.drain()
                    if in_bucket:
                        s = self.sample(chunk_i, keys_read)
                        if s is not None:
                            yield s
        except BaseException:
            ex.abort()
            _exec.release_staged(keys)  # the chunk in hand (idempotent)
            raise
        finally:
            _restore()
        if self.obs is not None and self.obs.metrics is not None:
            from mpi_k_selection_tpu.obs.metrics import collect_runtime

            collect_runtime(
                self.obs.metrics, staging_pool=_pl.STAGING_POOL, timer=timer
            )


# ---------------------------------------------------------------------------
# Prometheus text exposition on a port (the CLI monitor's pull surface)


class _MetricsHandler(BaseHTTPRequestHandler):
    server_version = "ksel-monitor"
    protocol_version = "HTTP/1.1"

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # the registry IS the telemetry channel; no stderr chatter

    def do_GET(self):
        if self.path == "/metrics":
            body = self.server.registry.render_prometheus().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path == "/healthz":
            body = b'{"status": "ok"}'
            ctype = "application/json"
        else:
            body = b"not found; GET /metrics or /healthz"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsHTTPServer(ThreadingHTTPServer):
    """Prometheus exposition for a MetricsRegistry: GET /metrics renders
    the registry live. Request threads are named + tracked + joined
    (``ksel-monitor-req-*``; the accept loop runs on
    ``ksel-monitor-http-*``) — the same no-thread-outlives-its-owner
    discipline as serve/http.py, conftest-enforced."""

    daemon_threads = False
    allow_reuse_address = True

    _ids = itertools.count()

    def __init__(self, address, registry):
        super().__init__(address, _MetricsHandler)
        self.registry = registry
        self._req_lock = threading.Lock()
        self._req_threads: list[threading.Thread] = []  # ksel: guarded-by[_req_lock]
        self._serve_thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    def process_request(self, request, client_address):
        t = threading.Thread(
            target=self.process_request_thread,
            args=(request, client_address),
            name=f"{MONITOR_THREAD_PREFIX}-req-{next(self._ids)}",
            daemon=False,
        )
        with self._req_lock:
            self._req_threads = [x for x in self._req_threads if x.is_alive()]
            self._req_threads.append(t)
        t.start()

    def server_close(self):
        super().server_close()
        with self._req_lock:
            threads, self._req_threads = self._req_threads, []
        for t in threads:
            t.join(timeout=10.0)

    def close(self):
        """Stop the accept loop, close the socket, join every thread."""
        self.shutdown()
        self.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10.0)
            self._serve_thread = None

    def __enter__(self) -> "MetricsHTTPServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_metrics_server(
    registry, *, host: str = "127.0.0.1", port: int = 0
) -> MetricsHTTPServer:
    """Serve ``registry``'s Prometheus text exposition in the background
    (``port=0`` binds an ephemeral port — read ``handle.port``).
    ``handle.close()`` tears everything down."""
    httpd = MetricsHTTPServer((host, port), registry)
    t = threading.Thread(
        target=httpd.serve_forever,
        kwargs={"poll_interval": 0.05},
        name=f"{MONITOR_THREAD_PREFIX}-http-{next(MetricsHTTPServer._ids)}",
        daemon=True,
    )
    httpd._serve_thread = t
    t.start()
    return httpd
