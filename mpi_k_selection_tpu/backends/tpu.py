"""TPU backend (``--backend=tpu``) — JAX/XLA execution.

Single-chip selection dispatches to the radix/sort ops (ops/); when more than
one device is visible and the input is large, selection runs sharded over a
1-D device mesh via the distributed radix path (parallel/), which replaces
the reference's MPI scatter/iterate/gather protocol
(``TODO-kth-problem-cgm.c:103-293``) with XLA collectives over ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu import api

NAME = "tpu"


def plan(n: int, algorithm: str = "auto", distribute: str = "auto", n_dev: int | None = None):
    """Resolve (effective_algorithm, distributed) for a selection of size n.

    The radix and cgm algorithms have distributed paths; an explicit
    ``algorithm='sort'`` therefore always runs single-chip, and asking for
    ``distribute='always'`` with it is an error rather than a silent switch.
    CGM is the reference's multi-rank protocol (``TODO-kth-problem-cgm.c``) —
    it is *only* distributed, so ``distribute='never'`` with it is an error
    (mirroring the reference's world_size >= 2 abort at ``:56-59``).

    ``n_dev`` is the mesh size the caller will actually run on (the
    ``devices`` cap of :func:`plan_many`); defaults to every visible device.
    Non-divisible N distributes fine — the distributed paths pad to equal
    shards with order-maximal sentinels (parallel/mesh.py:pad_to_multiple).
    """
    if distribute not in ("auto", "never", "always"):
        raise ValueError(
            f"distribute={distribute!r} must be one of 'auto', 'never', 'always'"
        )
    from mpi_k_selection_tpu import config

    if n_dev is None:
        n_dev = len(jax.devices())

    def check_min_devices():
        # mirror require_distributed / the reference's world_size >= 2 abort
        # (TODO-kth-problem-cgm.c:56-59) instead of a silent single-chip
        # run. Runs AFTER the algorithm-distributability validation so a
        # non-distributable algorithm keeps its more specific error even on
        # single-device hosts.
        if distribute == "always" and n_dev < config.MIN_DEVICES_DISTRIBUTED:
            raise ValueError(
                f"distribute='always' needs >= {config.MIN_DEVICES_DISTRIBUTED} "
                f"devices, have {n_dev}"
            )

    if algorithm == "cgm":
        if distribute == "never":
            raise ValueError(
                "algorithm='cgm' is the distributed parity protocol and has "
                "no single-chip path (the reference aborts below 2 ranks, "
                "TODO-kth-problem-cgm.c:56-59); use algorithm='radix' or "
                "'sort' single-chip"
            )
        check_min_devices()
        return "cgm", True
    distributable = algorithm in ("auto", "radix")
    if distribute == "always" and not distributable:
        # validated independently of the host's device count, so the error
        # surfaces in single-device CI too
        raise ValueError(
            f"algorithm={algorithm!r} has no distributed path; "
            "use algorithm='radix', 'cgm' (or 'auto') with distribute='always'"
        )
    check_min_devices()
    use_mesh = {
        "auto": distributable and n_dev > 1 and n >= 1 << 20,
        "never": False,
        "always": True,
    }[distribute]
    if use_mesh:
        return "radix", True
    if algorithm == "auto":
        algorithm = "sort" if n <= 1 << 14 else "radix"
    return algorithm, False


def kselect(x, k: int, *, algorithm: str = "auto", distribute: str = "auto", **kwargs):
    """Exact k-th smallest (1-indexed). ``distribute`` in {auto, never, always}."""
    n = np.asarray(x).size if not hasattr(x, "size") else x.size
    algorithm, use_mesh = plan(n, algorithm, distribute)
    if use_mesh:
        from mpi_k_selection_tpu.parallel import cgm as pcgm, radix as pradix

        if algorithm == "cgm":
            return pcgm.distributed_cgm_select(jnp.asarray(x), k, **kwargs)
        # raw x: the distributed entry runs the f64-on-TPU host-key route
        # before any device commitment (parallel/radix.py)
        return pradix.distributed_radix_select(x, k, **kwargs)
    return api.kselect(x, k, algorithm=algorithm, **kwargs)


def plan_many(n: int, distribute: str = "auto", devices: int | None = None):
    """Mesh to run multi-rank selection on, or None for single-device.

    The one dispatch decision shared by :func:`kselect_many` and the CLI's
    ``--quantiles`` path: the kselect planner (radix is the only multi-rank
    algorithm), evaluated against the *capped* device count so a ``devices``
    cap and the auto-size gate agree on the mesh that will actually run. A
    cap that shrinks the mesh below the distributed minimum of 2 falls back
    to single-device under ``auto`` (the same fallback the planner applies
    on single-device hosts) and raises under ``always``."""
    n_dev = len(jax.devices())
    if devices is not None:
        n_dev = min(devices, n_dev)
    _, use_mesh = plan(n, "radix", distribute, n_dev=n_dev)
    if not use_mesh:
        return None
    from mpi_k_selection_tpu.parallel import make_mesh

    # n_dev (not the raw cap) so the gate and the mesh always agree — an
    # over-request like devices=16 on an 8-device host caps to 8
    return make_mesh(n_dev)


def kselect_many(x, ks, *, distribute: str = "auto", devices: int | None = None, **kwargs):
    """Exact k-th smallest for every k in ``ks`` (multi-rank selection),
    distributed over the device mesh per the same planner as kselect.
    Multi-rank is radix-only (api.kselect_many handles the small-input
    sort-and-gather case on the single-device path)."""
    n = np.asarray(x).size if not hasattr(x, "size") else x.size
    mesh = plan_many(n, distribute, devices)
    if mesh is not None:
        from mpi_k_selection_tpu.parallel import radix as pradix

        out = pradix.distributed_radix_select_many(x, ks, mesh=mesh, **kwargs)
        return api.restore_k_shape(out, ks)
    return api.kselect_many(x, ks, **kwargs)


def quantiles(x, qs, *, distribute: str = "auto", devices: int | None = None, **kwargs):
    """Exact nearest-rank order statistics at quantiles ``qs``; distributes
    like :func:`kselect_many`."""
    x = api.as_selection_array(x)
    ks = api.quantile_ks(qs, x.size)
    return kselect_many(x, ks, distribute=distribute, devices=devices, **kwargs)


def topk(x, k: int, *, largest: bool = True, **kwargs):
    from mpi_k_selection_tpu.ops.topk import topk as _topk

    return _topk(jnp.asarray(x), k, largest=largest, **kwargs)


def median(x, **kwargs):
    x = api.as_selection_array(x)
    return kselect(x, max(1, x.size // 2), **kwargs)
