"""Metrics registry — counters, gauges and histograms with JSON and
Prometheus-text exposition.

The registry is the *numbers* half of the descent telemetry (obs/events.py
is the *shapes* half): StagingPool hit/miss totals, ``pipeline.stall``
seconds, InflightWindow occupancy samples, spilled bytes per descent, and
chunks-per-device counts — the quantities the TPU validation sweep and the
async-executor work (ROADMAP) need to read off a run instead of inferring
from wall clocks.

Design constraints:

- **Thread-safe**: the pipelined descent records from the producer thread
  (staging, spill tee) and the consumer thread (stall, merges)
  concurrently; every mutation takes the metric's registry lock.
- **Exact**: counters and gauges are plain Python ints/floats (no
  device round-trips, no float accumulation for counts), so a mirrored
  metric can be asserted EQUAL to its source counter
  (tests/test_multidevice_ingest.py, tests/test_spill.py).
- **Off by default**: a registry exists only when the caller passes one
  (via :class:`~mpi_k_selection_tpu.obs.Observability`); library code
  guards every record behind ``obs is None`` checks.

Exposition: :meth:`MetricsRegistry.as_dict` (JSON-ready),
:meth:`MetricsRegistry.to_json`, and
:meth:`MetricsRegistry.render_prometheus` (text format 0.0.4 — dots
become underscores, every name is prefixed ``ksel_``).
"""

from __future__ import annotations

import json
import math
import re
import threading

#: Default occupancy-style histogram buckets (small non-negative counts).
DEFAULT_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: ``# HELP`` one-liners for the catalog metrics (docs/OBSERVABILITY.md);
#: exposition emits HELP only for names listed here — an unlisted name
#: still renders conformant TYPE + sample lines.
HELP_TEXTS = {
    "ingest.chunks": "Chunks consumed per round-robin ingest slot",
    "ingest.bytes": "Key bytes consumed per round-robin ingest slot",
    "inflight.occupancy": "In-flight executor bundles at every windowed push",
    "staging_pool.hits": "StagingPool buffer reuse hits",
    "staging_pool.misses": "StagingPool buffer allocations",
    "staging_pool.resident_bytes": "Free-list bytes currently pooled",
    "spill.passes": "Spill store pass_log entries",
    "spill.disk_bytes_read": "Physical spill bytes read (packed/pruned)",
    "spill.disk_bytes_written": "Physical spill bytes written (packed)",
    "spill.packed_bytes": "Physical bytes resident in live generations",
    "spill.logical_bytes": "Logical key bytes resident in live generations",
    "ingest.resolved_bits": "Resolved key bits after each descent pass",
    "phase.seconds": "Wall seconds per PhaseTimer phase",
    "phase.calls": "Calls per PhaseTimer phase",
    "serve.queries": "Requests answered, by answering tier and op",
    "serve.latency_seconds": "Per-request wall latency by answering tier",
    "serve.queue_depth": "Per-lane dispatch-queue depth at every submit",
    "serve.batch_width": "Total rank width of each coalesced dispatch",
    "serve.fastpath": "Sketch-tier answers served on the request thread",
    "serve.warmup_compiles": "Programs pre-built by add_dataset warmup",
    "serve.lanes": "Dispatch lanes currently open (one per device)",
    "monitor.quantile": "Continuous windowed quantile stream (monitor/)",
    "monitor.window_n": "Merged live-window count of the monitor",
    "monitor.epoch": "Window advances completed by the monitor",
    "monitor.samples": "Samples the monitor has emitted",
}


def _escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline must be escaped (the grammar the conformance test
    in tests/test_prometheus.py parses)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """HELP-text escaping: backslash and newline only (quotes are legal)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(pairs) -> str:
    """``{k="v",...}`` with escaped values, '' for no labels."""
    if not pairs:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(pairs)
    )
    return "{" + inner + "}"


class _Metric:
    """Shared plumbing: identity (name + sorted label pairs) and the
    registry lock every mutation runs under."""

    type_name = "untyped"

    def __init__(self, name: str, labels: tuple, lock: threading.Lock):
        self.name = name
        self.labels = labels  # sorted tuple of (key, value) pairs
        self._lock = lock

    def label_str(self) -> str:
        return _render_labels(self.labels)


class Counter(_Metric):
    """Monotone event count. ``set`` exists for COLLECTED mirrors of
    pre-existing counters (StagingPool.hits, a pass_log total) — the
    snapshot overwrites so repeated collections stay idempotent."""

    type_name = "counter"

    def __init__(self, name, labels, lock):
        super().__init__(name, labels, lock)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def set(self, value) -> None:
        with self._lock:
            self.value = value

    def as_dict(self) -> dict:
        return {"type": self.type_name, "value": self.value}


class Gauge(_Metric):
    """Point-in-time value (seconds, occupancy, fraction)."""

    type_name = "gauge"

    def __init__(self, name, labels, lock):
        super().__init__(name, labels, lock)
        self.value = 0

    def set(self, value) -> None:
        with self._lock:
            self.value = value

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n

    def as_dict(self) -> dict:
        return {"type": self.type_name, "value": self.value}


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: ``le`` bounds,
    implicit ``+Inf``), plus exact count/sum/min/max."""

    type_name = "histogram"

    def __init__(self, name, labels, lock, buckets=DEFAULT_BUCKETS):
        super().__init__(name, labels, lock)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # ksel: guarded-by[_lock] (last = +Inf)
        self.count = 0
        self.sum = 0
        self.min = None
        self.max = None

    def observe(self, value) -> None:
        with self._lock:
            self._observe_locked(value)

    def _observe_locked(self, value) -> None:
        """Bookkeeping under the registry lock — the override point of
        the windowed-histogram bridge (obs/windows.py), which adds its
        sketch fold to the SAME critical section."""
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                self.bucket_counts[i] += 1
                return
        self.bucket_counts[-1] += 1

    def cumulative(self) -> list[int]:
        """Cumulative counts per ``le`` bound (+Inf last) — the
        Prometheus wire shape. Snapshots under the registry lock: an
        observe() racing this iteration would otherwise tear the
        monotone-bucket invariant (KSL015)."""
        with self._lock:
            return self._cumulative_locked()

    def _cumulative_locked(self) -> list[int]:
        """The raw accumulation — callers hold the registry lock (the
        exposition renderer snapshots buckets/count/sum in ONE critical
        section, so the +Inf bucket and _count lines agree)."""
        out, running = [], 0
        for c in self.bucket_counts:
            running += c
            out.append(running)
        return out

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def as_dict(self) -> dict:
        with self._lock:
            cum = self._cumulative_locked()
            count, total = self.count, self.sum
            mn, mx = self.min, self.max
        return {
            "type": self.type_name,
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
            "mean": total / count if count else None,
            "buckets": {
                **{str(b): c for b, c in zip(self.bounds, cum)},
                "+Inf": count,
            },
        }


class MetricsRegistry:
    """Get-or-create home for every metric of one run (or one process).

    Metrics are keyed by ``(name, labels)``; asking for an existing key
    returns the same object, so library code can fetch by name at record
    time without plumbing metric handles around. One lock serializes all
    mutation — metric cardinality here is tiny (tens), contention is not
    a concern at chunk granularity.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}  # ksel: guarded-by[_lock]
        self._window_specs: dict = {}  # ksel: guarded-by[_lock]

    @staticmethod
    def _key(name: str, labels):
        lab = tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))
        return name, lab

    def _get_or_create(self, cls, name, labels, **kwargs):
        key = self._key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, key[1], self._lock, **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.type_name}"
                )
            return m

    def counter(self, name: str, labels=None) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, labels=None) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, labels=None, buckets=DEFAULT_BUCKETS) -> Histogram:
        spec = self._window_specs.get(name)
        if spec is not None:
            from mpi_k_selection_tpu.obs.windows import WindowedHistogram

            return self._get_or_create(
                WindowedHistogram, name, labels, buckets=buckets, **spec
            )
        return self._get_or_create(Histogram, name, labels, buckets=buckets)

    def enable_windowed(
        self, name: str, *, window: int = 8, advance_every: int = 256,
        radix_bits: int = 4, levels: int = 4, decay: float | None = None,
        quantiles=(0.5, 0.9, 0.99),
    ) -> None:
        """Back every future labeled series of histogram ``name`` with a
        sliding-window RadixSketch (obs/windows.py): observations fold
        into per-``advance_every``-observation window buckets, and the
        exposition gains exactly-bounded ``<name>_windowed`` quantile
        gauges next to the unchanged fixed-bucket series. Must run
        BEFORE the metric's first creation — an already-created plain
        histogram cannot be upgraded retroactively (its past
        observations are gone), so that raises instead of silently
        serving a half-empty window."""
        with self._lock:
            existing = [k for k in self._metrics if k[0] == name]
            if existing:
                raise TypeError(
                    f"metric {name!r} already has {len(existing)} series; "
                    "enable_windowed must run before the first observation"
                )
            self._window_specs[name] = dict(
                window=window, advance_every=advance_every,
                radix_bits=radix_bits, levels=levels, decay=decay,
                quantiles=tuple(quantiles),
            )

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- exposition --------------------------------------------------------

    def as_dict(self) -> dict:
        """``{name or name{labels}: metric dict}`` — the JSON-ready
        snapshot bench records and ``--metrics-json`` embed."""
        out = {}
        for m in self.metrics():
            out[m.name + m.label_str()] = m.as_dict()
        return out

    def to_json(self, indent=None) -> str:
        return json.dumps(self.as_dict(), indent=indent, sort_keys=True)

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4): names sanitized to
        ``ksel_<name_with_underscores>``, HELP lines for cataloged
        names, label values escaped per the grammar, histograms as
        ``_bucket{le=...}``/``_sum``/``_count`` series — plus, for
        windowed histograms (obs/windows.py), the exactly-bounded
        ``_windowed``/``_windowed_rank_error``/``_windowed_count``
        quantile gauges. Conformance is test-enforced
        (tests/test_prometheus.py)."""
        by_name: dict = {}
        for m in self.metrics():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_name):
            group = sorted(by_name[name], key=lambda g: g.labels)
            pname = "ksel_" + _NAME_RE.sub("_", name.replace(".", "_"))
            if name in HELP_TEXTS:
                lines.append(f"# HELP {pname} {_escape_help(HELP_TEXTS[name])}")
            lines.append(f"# TYPE {pname} {group[0].type_name}")
            windowed = []  # (labels, snapshot) per windowed member
            for m in group:
                if isinstance(m, Histogram):
                    # one consistent snapshot under the lock: the +Inf
                    # bucket and _count lines MUST agree (the histogram
                    # invariant tests/test_prometheus.py enforces), and
                    # a scrape racing a live observe() would otherwise
                    # read m.count twice across the interleaving
                    with m._lock:
                        cum = m._cumulative_locked()
                        count, total = m.count, m.sum
                    for bound, c in zip(m.bounds, cum):
                        lab = dict(m.labels)
                        lab["le"] = _format_float(bound)
                        lines.append(
                            f"{pname}_bucket{_render_labels(lab.items())} {c}"
                        )
                    inf_lab = dict(m.labels)
                    inf_lab["le"] = "+Inf"
                    lines.append(
                        f"{pname}_bucket{_render_labels(inf_lab.items())} "
                        f"{count}"
                    )
                    lines.append(f"{pname}_sum{m.label_str()} {_format_float(total)}")
                    lines.append(f"{pname}_count{m.label_str()} {count}")
                    snapshot = getattr(m, "windowed_snapshot", None)
                    if snapshot is not None:
                        snap = snapshot()
                        if snap is not None:
                            windowed.append((m.labels, snap))
                else:
                    lines.append(
                        f"{pname}{m.label_str()} {_format_float(m.value)}"
                    )
            if windowed:
                lines.append(
                    f"# HELP {pname}_windowed Sliding-window quantile with "
                    "exact rank/value bounds (obs/windows.py)"
                )
                lines.append(f"# TYPE {pname}_windowed gauge")
                for labels, snap in windowed:
                    for e in snap["quantiles"]:
                        lab = dict(labels)
                        lab["quantile"] = _format_float(e["q"])
                        lines.append(
                            f"{pname}_windowed{_render_labels(lab.items())} "
                            f"{_format_float(e['value'])}"
                        )
                lines.append(f"# TYPE {pname}_windowed_rank_error gauge")
                for labels, snap in windowed:
                    for e in snap["quantiles"]:
                        lab = dict(labels)
                        lab["quantile"] = _format_float(e["q"])
                        lines.append(
                            f"{pname}_windowed_rank_error"
                            f"{_render_labels(lab.items())} "
                            f"{e['rank_error']}"
                        )
                lines.append(f"# TYPE {pname}_windowed_count gauge")
                for labels, snap in windowed:
                    lines.append(
                        f"{pname}_windowed_count{_render_labels(labels)} "
                        f"{snap['n']}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


def _format_float(v) -> str:
    """Prometheus value formatting: ints stay integral, floats drop the
    trailing noise, infinities spell +Inf/-Inf."""
    if isinstance(v, bool):  # pragma: no cover - no bool metrics exist
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def collect_runtime(
    registry: MetricsRegistry,
    *,
    staging_pool=None,
    spill_store=None,
    timer=None,
) -> MetricsRegistry:
    """Snapshot the repo's pre-existing runtime counters into ``registry``
    — the ONE mapping from internal state to exported metric names, so
    the values are the originals by construction (asserted equal in
    tests/test_multidevice_ingest.py and tests/test_spill.py):

    - ``staging_pool.hits`` / ``staging_pool.misses`` (Counter) and
      ``staging_pool.resident_bytes`` (Gauge) from a
      :class:`~mpi_k_selection_tpu.streaming.pipeline.StagingPool`;
    - ``spill.passes`` / ``spill.bytes_read`` / ``spill.bytes_written`` /
      ``spill.keys_read`` / ``spill.keys_written`` (Counter) summed over a
      :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore`'s
      ``pass_log``, their PHYSICAL twins ``spill.disk_bytes_read`` /
      ``spill.disk_bytes_written`` (what the packed/pruned records
      actually moved on disk vs the logical keys-x-itemsize columns),
      plus ``spill.generations_live`` and the resident-footprint pair
      ``spill.packed_bytes`` / ``spill.logical_bytes`` (Gauge — equal
      unless ``pack_spill`` shrank the on-disk records);
    - every :class:`~mpi_k_selection_tpu.utils.profiling.PhaseTimer`
      phase as ``phase.seconds{phase=...}`` / ``phase.calls{phase=...}``
      (the ``pipeline.stall`` seconds the ROADMAP items need ride here).

    Snapshots overwrite (``Counter.set``), so collecting twice is
    idempotent. Returns ``registry``.
    """
    if staging_pool is not None:
        registry.counter("staging_pool.hits").set(int(staging_pool.hits))
        registry.counter("staging_pool.misses").set(int(staging_pool.misses))
        registry.gauge("staging_pool.resident_bytes").set(
            int(staging_pool.resident_bytes)
        )
    if spill_store is not None:
        log = list(spill_store.pass_log)
        registry.counter("spill.passes").set(len(log))
        registry.counter("spill.bytes_read").set(
            sum(int(p.get("bytes_read", 0)) for p in log)
        )
        registry.counter("spill.keys_read").set(
            sum(int(p.get("keys_read", 0)) for p in log)
        )
        registry.counter("spill.bytes_written").set(
            sum(int(p.get("bytes_written", 0)) for p in log)
        )
        registry.counter("spill.keys_written").set(
            sum(int(p.get("keys_written", 0)) for p in log)
        )
        registry.counter("spill.disk_bytes_read").set(
            sum(int(p.get("disk_bytes_read") or 0) for p in log)
        )
        registry.counter("spill.disk_bytes_written").set(
            sum(int(p.get("disk_bytes_written") or 0) for p in log)
        )
        gens = getattr(spill_store, "generations", {})
        registry.gauge("spill.generations_live").set(len(gens))
        live = list(gens.values()) if hasattr(gens, "values") else list(gens)
        registry.gauge("spill.packed_bytes").set(
            sum(int(g.nbytes) for g in live)
        )
        registry.gauge("spill.logical_bytes").set(
            sum(int(getattr(g, "logical_nbytes", g.nbytes)) for g in live)
        )
    if timer is not None:
        for name, d in timer.as_dict().items():
            registry.gauge("phase.seconds", labels={"phase": name}).set(  # ksel: noqa[KSL013] -- phase names are a closed, code-defined set (PhaseTimer phases), not per-request data
                d["seconds"]
            )
            registry.gauge("phase.calls", labels={"phase": name}).set(d["calls"])  # ksel: noqa[KSL013] -- phase names are a closed, code-defined set (PhaseTimer phases), not per-request data
    return registry
