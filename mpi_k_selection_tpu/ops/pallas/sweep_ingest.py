"""Single-sweep Pallas ingest kernel — one GUARANTEED HBM read per
staged bucket.

PR 11's fused ingest (ops/pallas/fused_ingest.py) collapsed the per-chunk
device programs of a streamed pass into ONE XLA program per staged
bucket: one dispatch, shared subexpressions — but an XLA program is a
scheduling contract, not a memory-traffic one. XLA may (and for the
independent histogram / compaction / tee subgraphs often does) walk the
same pow2 staging bucket once per consumer inside that single program,
so "one dispatch" never guaranteed "one sweep". This module is the
hand-written kernel the ROADMAP's follow-on (c) asked for: a grid-tiled
Pallas kernel that, in ONE sequential pass over the bucket's
``(block_rows, 128)`` tiles, accumulates EVERY product a staged bucket's
consumers need — each tile is DMA'd to VMEM exactly once and every
consumer's accumulator is updated from that resident tile, the
Blocked-Filter/ShearSort single-pass shape (PAPERS.md):

- the (multi-prefix) radix digit histogram of the descent pass — the
  very ``z = (key >> shift) ^ (prefix << radix_bits)`` digit/prefix
  fusion of the histogram kernels, accumulated per lane;
- one front-compacted ``(survivors, int32 count)`` pair per survivor-
  collect spec, plus the spill tee's union-of-specs payload: per-spec
  running offsets live in SMEM scratch, each tile's survivors compact to
  the front of a tile-shaped staging window that lands at the running
  offset (the next tile's window overwrites this one's tail, so the
  final buffer is bit-identical to ``fused_ingest.compact_core``'s —
  survivors front-packed in chunk order, zeros after);
- the rank certificate's ``(#keys < v, #keys <= v)`` pair (pad lanes
  excluded in kernel via the global-position mask, so the pair needs no
  host correction), compared in signed space by folding the
  uint32->int32 bias into both sides exactly like ``pallas_tau_counts``;
- the sketch's deepest-level histogram (pads counted, like the staged
  histogram — the consumer's exact bucket-0 subtraction is unchanged)
  and the key-space min/max extremes with pad lanes masked to the
  unsigned identities exactly as ``sketch._staged_extremes`` does —
  closing the last 2-programs-per-staged-bucket consumer
  (``ingest.bucket_reads{phase="sketch"}`` drops to 1).

Like the histogram kernels, the kernel interprets off-TPU
(``interpret = jax.default_backend() != "tpu"``), where it is the exact
jnp program — so the CPU CI enforces bit-equality against both the XLA
fusion tier and the unfused oracle, and the compiled kernel's bandwidth
factor is what ``tpu_smoke.py``'s kernel leg records on silicon.

Support matrix (:func:`sweep_supported` — unsupported buckets fall back
to the XLA fusion tier per bucket, never to a wrong answer): 4-byte key
space (uint32 — the int32/uint32/float32/int16-as-uint32 streams stage
as uint32; uint16/uint64 key spaces ride the XLA tier), buckets of at
least one ``(1, 128)`` lane tile, ``radix_bits <= 8`` histograms and
sketch resolutions up to 20 bits (the RadixSketch cap). Trail
discipline matches the XLA tier: every data-dependent value
(``n_valid``, the histogram prefixes, the ``(shift, prefix)`` spec
scalars, the certificate key) rides as a traced SMEM scalar, so the
program compiles once per (bucket, dtype, #hist-prefixes, #collect,
#tee, parts) and its primitive trail is bucket-size-stable — nothing in
the kernel body unrolls on the tile count (the per-bucket KSC103
contract ``analysis/jaxpr_checks.py:_streaming_sweep_ingest_cases``
pins at both staging buckets). The survivor and deep-histogram outputs
live in compiler-placed memory (``pl.ANY``) and the survivor windows
are written with dynamic-start, static-size stores — the one construct
whose Mosaic lowering the silicon run validates; interpret mode
executes it exactly.
"""

from __future__ import annotations

import functools

import numpy as np

LANES = 128

#: Tile height of the sweep grid. 512 rows x 128 lanes x 4 B = 256 KB of
#: key data per step — small enough that the per-part accumulators (the
#: largest: a 2^20-counter sketch level, 4 MB) and Pallas's double
#: buffering stay inside the 16 MB scoped-VMEM budget together.
DEFAULT_BLOCK_ROWS = 512

#: Histogram digits wider than this leave the per-lane accumulator
#: VPU-unfriendly (2**rb compare rows per prefix); the streaming descent
#: never exceeds 8.
_MAX_KERNEL_RADIX_BITS = 8

#: RadixSketch's own fixed-size cap (streaming/sketch.py) — the deepest
#: level is a flat (2**bits,) int32 accumulator, 4 MB at 20 bits.
_MAX_KERNEL_SKETCH_BITS = 20

_PALLAS_OK = None


def _pallas_available() -> bool:
    """Whether this jax build carries the TPU pallas backend (it is
    importable on CPU builds too, where the kernel interprets) — the
    histogram kernels' own availability guard, probed lazily so this
    module stays jax-import-free at load time (streaming/executor.py
    imports it eagerly)."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            from jax.experimental.pallas import tpu as _  # noqa: F401

            _PALLAS_OK = True
        except ImportError:  # pragma: no cover - all CI builds carry it
            _PALLAS_OK = False
    return _PALLAS_OK


def _i32const(v: int) -> int:
    """Python int with the uint32 bit pattern ``v`` as a signed int32
    value (the kernel computes on int32 bit patterns)."""
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def sweep_supported(staged, kdt, *, radix_bits=None, sketch_bits=0) -> bool:
    """Whether the sweep kernel covers this staged bucket's geometry —
    the per-bucket gate the kernel tier consults before dispatching
    (False = that bucket rides the XLA fusion tier instead; the answer
    is bit-identical either way, this only picks the program)."""
    if not _pallas_available():
        return False
    if np.dtype(kdt).itemsize != 4:
        return False
    bucket = int(staged.data.shape[0])
    if bucket < LANES or bucket % LANES:
        return False
    if bucket & (bucket - 1):
        # non-pow2 lane multiples (e.g. 768 rows) can leave the tile
        # height not dividing the row count — sweep_ingest_core would
        # raise rather than truncate, so route them to the XLA tier
        return False
    if radix_bits is not None and radix_bits > _MAX_KERNEL_RADIX_BITS:
        return False
    if sketch_bits and sketch_bits > _MAX_KERNEL_SKETCH_BITS:
        return False
    return True


def _sweep_kernel(
    *refs,
    shift,
    radix_bits,
    nq,
    n_collect,
    n_tee,
    cert,
    sketch_bits,
    block_rows,
):
    """One grid step: consume one resident (block_rows, 128) tile for
    EVERY enabled part. Ref layout (inputs, then outputs in part order,
    then scratch): ``nv, zrefs, cshifts, cprefs, tshifts, tprefs, vkey,
    keys | [hist] [counts surv_0..surv_C-1 [tee]] [cert] [deep ext] |
    carries``."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    (nv_ref, zrefs_ref, csh_ref, cpr_ref, tsh_ref, tpr_ref, vk_ref,
     keys_ref) = refs[:8]
    outs = list(refs[8:-1])
    carry_ref = refs[-1]
    i = pl.program_id(0)
    nb = 1 << radix_bits
    rows = block_rows
    belems = rows * LANES

    ku = keys_ref[:]  # (rows, LANES) key-space uint32
    k = jax.lax.bitcast_convert_type(ku, jnp.int32)
    # element order is the raveled bucket's (lane fastest): the global
    # position masks pads and keeps compaction order == chunk order
    gpos = (
        (i * rows + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0))
        * LANES
        + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    )
    valid = gpos < nv_ref[0, 0]
    bias = jnp.int32(_i32const(1 << 31))
    sb = k ^ bias  # signed-comparable key view (pallas_tau_counts trick)

    oi = 0
    hist_ref = counts_ref = cert_ref = deep_ref = ext_ref = None
    surv_refs = []
    if nq:
        hist_ref = outs[oi]
        oi += 1
    n_surv = n_collect + (1 if n_tee else 0)
    if n_surv:
        counts_ref = outs[oi]
        oi += 1
        surv_refs = outs[oi:oi + n_surv]
        oi += n_surv
    if cert:
        cert_ref = outs[oi]
        oi += 1
    if sketch_bits:
        deep_ref, ext_ref = outs[oi], outs[oi + 1]
        oi += 2

    @pl.when(i == 0)
    def _():
        if hist_ref is not None:
            hist_ref[:] = jnp.zeros_like(hist_ref)
        if counts_ref is not None:
            counts_ref[:] = jnp.zeros_like(counts_ref)
        for sr in surv_refs:
            sr[:] = jnp.zeros_like(sr)  # compact_core's zeros-after tail
        if cert_ref is not None:
            cert_ref[:] = jnp.zeros_like(cert_ref)
        if sketch_bits:
            deep_ref[:] = jnp.zeros_like(deep_ref)
            # biased-space reduction identities: +max for min, -max for max
            ext_ref[0] = jnp.full((LANES,), jnp.int32(0x7FFFFFFF))
            ext_ref[1] = jnp.full((LANES,), bias)
        carry_ref[:] = jnp.zeros_like(carry_ref)

    if nq:
        # the histogram kernels' digit/prefix fusion, over the WHOLE
        # padded tile (pads are key 0 — the host finish subtracts them,
        # exactly as for the staged XLA histogram)
        s = jax.lax.shift_right_logical(k, jnp.int32(shift))
        for q in range(nq):
            z = s ^ zrefs_ref[q, 0]
            hist_ref[q * nb:(q + 1) * nb] += jnp.stack(
                [
                    jnp.sum(z == jnp.int32(b), axis=0, dtype=jnp.int32)
                    for b in range(nb)
                ]
            )

    def compact(mask, slot):
        # front-compact this tile's survivors into the running window of
        # survivor output `slot` and advance its SMEM offset; the window
        # write is dynamic-start/static-size, and the next tile's window
        # overwrites this one's zero tail — so the final buffer is
        # front-packed survivors in chunk order, zeros after
        mf = mask.reshape(-1)
        csum = jnp.cumsum(mf.astype(jnp.int32))
        cnt = csum[belems - 1]
        tgt = jnp.where(mf, csum - 1, jnp.int32(belems))
        comp = (
            jnp.zeros((belems,), ku.dtype)
            .at[tgt]
            .set(ku.reshape(-1), mode="drop")
        )
        carry = carry_ref[slot]
        surv_refs[slot][pl.ds(carry, belems)] = comp
        carry_ref[slot] = carry + cnt
        counts_ref[slot] += jnp.sum(mask, axis=0, dtype=jnp.int32)

    for j in range(n_collect):
        m = (
            jax.lax.shift_right_logical(k, csh_ref[j, 0]) == cpr_ref[j, 0]
        ) & valid
        compact(m, j)
    if n_tee:
        m = None
        for j in range(n_tee):
            mj = jax.lax.shift_right_logical(k, tsh_ref[j, 0]) == tpr_ref[j, 0]
            m = mj if m is None else (m | mj)
        compact(m & valid, n_collect)

    if cert:
        vb = vk_ref[0, 0]
        cert_ref[0] += jnp.sum((sb < vb) & valid, axis=0, dtype=jnp.int32)
        cert_ref[1] += jnp.sum((sb <= vb) & valid, axis=0, dtype=jnp.int32)

    if sketch_bits:
        dig = jax.lax.shift_right_logical(k, jnp.int32(32 - sketch_bits))
        # flat scatter-add: the deepest level (up to 2^20 counters) is
        # too wide for per-lane rows; pads count into bucket 0 like the
        # staged XLA fold (the consumer's exact subtraction is unchanged)
        deep_ref[:] = deep_ref[:].at[dig.reshape(-1)].add(1)
        ext_ref[0] = jnp.minimum(
            ext_ref[0],
            jnp.min(jnp.where(valid, sb, jnp.int32(0x7FFFFFFF)), axis=0),
        )
        ext_ref[1] = jnp.maximum(
            ext_ref[1], jnp.max(jnp.where(valid, sb, bias), axis=0)
        )


def sweep_ingest_core(
    data,
    n_valid,
    hist_prefixes,
    c_shifts,
    c_prefixes,
    t_shifts,
    t_prefixes,
    vkey,
    *,
    shift=0,
    radix_bits=1,
    hist_mode="none",
    n_collect=0,
    n_tee=0,
    cert=False,
    sketch_bits=0,
    block_rows=DEFAULT_BLOCK_ROWS,
    interpret=None,
):
    """ONE grid sweep of a pow2-padded staging bucket producing every
    enabled consumer product as ``(hist, collect, tee, cert, sketch)``:

    - ``hist``: ``(K, 2**radix_bits)`` int32 digit histograms over the
      whole padded buffer (``hist_mode="multi"``; ``None`` for
      ``"none"``) — the same per-chunk partial as the staged XLA
      dispatch, pad-corrected host-side at finish.
    - ``collect``: ``n_collect`` ``(compacted, int32 count)`` pairs,
      bit-identical to ``fused_ingest.compact_core`` per spec.
    - ``tee``: the union-of-``n_tee``-specs pair (``None`` when no tee).
    - ``cert``: ``(#keys < vkey, #keys <= vkey)`` int32 pair over the
      valid prefix (pad-exact in kernel; ``None`` unless ``cert``).
    - ``sketch``: ``(deep int32 histogram of the top sketch_bits key
      bits over the whole padded buffer, key-space min, key-space max)``
      (``None`` unless ``sketch_bits``).

    Only the part set, the kernel geometry and ``radix_bits``/
    ``sketch_bits`` are static — every data value rides traced, so the
    program compiles once per (bucket, dtype, part shape) and its
    primitive trail is bucket-size-stable (KSC102/KSC103 grid)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpi_k_selection_tpu.utils import compat

    if hist_mode not in ("none", "multi"):
        raise ValueError(f"unknown hist_mode {hist_mode!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bucket = data.shape[0]
    if bucket < LANES or bucket % LANES:
        raise ValueError(
            f"sweep kernel wants a whole-lane-tile bucket, got {bucket}"
        )
    rows = bucket // LANES
    br = min(block_rows, rows)  # pow2 bucket: br always divides rows
    if rows % br:
        raise ValueError(
            f"sweep kernel tile height {br} does not divide the bucket's "
            f"{rows} rows ({bucket} elements) — pad the bucket to a power "
            "of two (the staging contract) or pass a dividing block_rows"
        )
    grid = rows // br
    nq = int(hist_prefixes.shape[0]) if hist_mode == "multi" else 0
    nb = 1 << radix_bits
    n_surv = n_collect + (1 if n_tee else 0)
    kdt = data.dtype

    # traced SMEM scalars: the digit/prefix fusion references, the spec
    # scalars (shift counts are plain small ints; prefixes are bit
    # patterns), the biased certificate key. Disabled parts ride one
    # zero placeholder row (no zero-size SMEM operands) that the static
    # part flags keep off the kernel's trace.
    def i32bits(u):
        return jax.lax.bitcast_convert_type(
            u.astype(jnp.uint32), jnp.int32
        ).reshape(-1, 1)

    zero1 = jnp.zeros((1,), jnp.uint32)
    zrefs = i32bits(
        jax.lax.shift_left(
            hist_prefixes.astype(jnp.uint32), jnp.uint32(radix_bits)
        )
        if nq
        else zero1
    )
    csh = (c_shifts if n_collect else zero1).astype(jnp.int32).reshape(-1, 1)
    cpr = i32bits(c_prefixes if n_collect else zero1)
    tsh = (t_shifts if n_tee else zero1).astype(jnp.int32).reshape(-1, 1)
    tpr = i32bits(t_prefixes if n_tee else zero1)
    vk = i32bits(
        (jnp.asarray(vkey).astype(jnp.uint32) if cert else zero1[0])
        ^ jnp.uint32(1 << 31)
    )
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)

    kernel = functools.partial(
        _sweep_kernel,
        shift=shift,
        radix_bits=radix_bits,
        nq=nq,
        n_collect=n_collect,
        n_tee=n_tee,
        cert=cert,  # a static jit flag already — bool() would host-sync
        sketch_bits=sketch_bits,
        block_rows=br,
    )

    def smem_spec(n):
        return pl.BlockSpec((n, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)

    def acc_spec(shape):
        # a grid-persistent VMEM accumulator (index_map pinned to the
        # origin — the histogram kernels' accumulation discipline)
        return pl.BlockSpec(
            shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM
        )

    in_specs = [
        smem_spec(1),                  # n_valid
        smem_spec(max(nq, 1)),         # hist z references
        smem_spec(max(n_collect, 1)),  # collect shifts
        smem_spec(max(n_collect, 1)),  # collect prefixes
        smem_spec(max(n_tee, 1)),      # tee shifts
        smem_spec(max(n_tee, 1)),      # tee prefixes
        smem_spec(1),                  # biased certificate key
        pl.BlockSpec((br, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]
    out_specs = []
    out_shapes = []

    def add_out(shape, dtype, space):
        out_shapes.append(jax.ShapeDtypeStruct(shape, dtype))
        out_specs.append(
            acc_spec(shape)
            if space is pltpu.VMEM
            # compiler-placed (HBM-resident on TPU), whole-ref: written
            # through the running windows / the flat scatter, never
            # re-read in kernel
            else pl.BlockSpec(memory_space=space)
        )

    if nq:
        add_out((nq * nb, LANES), jnp.int32, pltpu.VMEM)
    if n_surv:
        add_out((n_surv, LANES), jnp.int32, pltpu.VMEM)  # per-lane counts
        for _ in range(n_surv):
            add_out((bucket,), kdt, pl.ANY)
    if cert:
        add_out((2, LANES), jnp.int32, pltpu.VMEM)
    if sketch_bits:
        add_out((1 << sketch_bits,), jnp.int32, pl.ANY)
        add_out((2, LANES), jnp.int32, pltpu.VMEM)

    # trace with x64 off: the kernel is int32-only (Mosaic cannot
    # legalize x64-traced grid indices — the histogram kernels' rule)
    with compat.enable_x64(False):
        results = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            scratch_shapes=[pltpu.SMEM((max(n_surv, 1),), jnp.int32)],
            interpret=interpret,
        )(nv, zrefs, csh, cpr, tsh, tpr, vk, data.reshape(rows, LANES))
    results = list(results)

    hist = None
    if nq:
        lanes = results.pop(0)
        hist = jnp.sum(lanes.reshape(nq, nb, LANES), axis=2, dtype=jnp.int32)
    collect = ()
    tee = None
    if n_surv:
        cnt_lanes = results.pop(0)
        bufs = [results.pop(0) for _ in range(n_surv)]
        pairs = [
            (buf, jnp.sum(cnt_lanes[j], dtype=jnp.int32))
            for j, buf in enumerate(bufs)
        ]
        collect = tuple(pairs[:n_collect])
        if n_tee:
            tee = pairs[n_collect]
    cert_pair = None
    if cert:
        cl = results.pop(0)
        cert_pair = (
            jnp.sum(cl[0], dtype=jnp.int32),
            jnp.sum(cl[1], dtype=jnp.int32),
        )
    sketch = None
    if sketch_bits:
        deep = results.pop(0)
        ext = results.pop(0)
        unbias = jnp.uint32(1 << 31)
        kmin = (
            jax.lax.bitcast_convert_type(jnp.min(ext[0]), jnp.uint32) ^ unbias
        ).astype(kdt)
        kmax = (
            jax.lax.bitcast_convert_type(jnp.max(ext[1]), jnp.uint32) ^ unbias
        ).astype(kdt)
        sketch = (deep, kmin, kmax)
    return hist, collect, tee, cert_pair, sketch


_SWEEP_FN = None


def _sweep_fn():
    global _SWEEP_FN
    if _SWEEP_FN is None:
        import jax

        _SWEEP_FN = jax.jit(
            sweep_ingest_core,
            static_argnames=(
                "shift", "radix_bits", "hist_mode", "n_collect", "n_tee",
                "cert", "sketch_bits", "block_rows", "interpret",
            ),
        )
    return _SWEEP_FN


def dispatch_sweep_ingest(
    staged,
    *,
    kdt,
    total_bits=32,
    shift=None,
    radix_bits=None,
    hist_prefixes=None,
    collect_specs=(),
    tee_specs=(),
    vkey=None,
    sketch_bits=0,
):
    """Launch the sweep kernel for one staged chunk on its OWN device
    (async dispatch — ``staged.data`` is committed, so the program runs
    where the chunk lives). Part selection mirrors the consumers:
    ``hist_prefixes`` (the pass's surviving prefix list, ``None`` = no
    histogram), ``collect_specs``/``tee_specs`` as ``(resolved_bits,
    prefix)`` lists, ``vkey`` the certificate's key-space probe value
    (``None`` = no certificate part), ``sketch_bits`` the sketch's
    resolution (0 = no sketch part). Returns the in-flight ``(hist,
    collect, tee, cert, sketch)`` handle; callers gate on
    :func:`sweep_supported` first — this raises on unsupported geometry
    rather than silently falling back."""
    from mpi_k_selection_tpu.ops.pallas.fused_ingest import _spec_arrays

    if hist_prefixes is not None:
        hist_mode = "multi"
        hp = np.asarray(list(hist_prefixes), kdt)
        hshift, hrb = shift, radix_bits
    else:
        hist_mode = "none"
        hp = np.empty((0,), kdt)
        hshift, hrb = 0, 1  # structural placeholders (one cache line)
    c_shifts, c_prefixes = _spec_arrays(list(collect_specs), kdt, total_bits)
    t_shifts, t_prefixes = _spec_arrays(list(tee_specs), kdt, total_bits)
    return _sweep_fn()(
        staged.data,
        np.int32(staged.n_valid),
        hp,
        c_shifts,
        c_prefixes,
        t_shifts,
        t_prefixes,
        np.asarray(0 if vkey is None else vkey, kdt),
        shift=hshift,
        radix_bits=hrb,
        hist_mode=hist_mode,
        n_collect=len(collect_specs),
        n_tee=len(tee_specs),
        cert=vkey is not None,
        sketch_bits=int(sketch_bits),
    )
