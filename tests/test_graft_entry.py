"""The driver-facing entry points: single-chip compile + multi-chip gauntlet.

Runs the gauntlet *in-process* (conftest provides the 8-device virtual CPU
mesh, so dryrun_multichip takes its fast path); the subprocess bootstrap is
exercised by running __graft_entry__ from a plain interpreter.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import pytest


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    assert out.shape == ()


def test_dryrun_gauntlet_inprocess(monkeypatch):
    import __graft_entry__ as g

    # FAST mode (r5): the harness plumbing, both engines, and the
    # pallas-under-sharding composition — the coverage unique to this
    # entry point. The full 12-case matrix runs in the DRIVER's own
    # dryrun every round (MULTICHIP_r0N.json), and cases 3-8 duplicate
    # tests/test_distributed*.py; in-process they cost ~40 s of suite
    # time for no added path.
    monkeypatch.setenv("_MPIKSEL_GAUNTLET_FAST", "1")
    g.dryrun_multichip(8)  # asserts internally


@pytest.mark.slow
def test_dryrun_gauntlet_full(monkeypatch):
    """The FULL 12-case matrix as an in-repo opt-in (ADVICE r5 #2): tier-1
    runs only the FAST subset above; this slow-marked twin keeps the whole
    gauntlet (cases 3-8, config-5 scale included) runnable without the
    out-of-repo driver: ``pytest -m slow tests/test_graft_entry.py``."""
    import __graft_entry__ as g

    monkeypatch.setenv("_MPIKSEL_GAUNTLET_FAST", "0")
    monkeypatch.delenv("_MPIKSEL_GAUNTLET_SKIP_SLOW", raising=False)
    g.dryrun_multichip(8)  # asserts internally
