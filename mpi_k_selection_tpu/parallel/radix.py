"""Distributed radix k-selection over a device mesh — the flagship path.

The TPU-native replacement for the reference's entire CGM protocol
(``TODO-kth-problem-cgm.c:103-293``). Where the reference scatters data,
iterates gather-medians -> bcast-pivot -> count -> allreduce -> physically
discard, and finally gathers survivors to rank 0, this path:

- keeps every shard resident in HBM and never moves an element
  (the reference's only bulk transfers — initial Scatterv ``:103`` and final
  Gatherv ``:270`` — become a one-time sharding annotation and nothing);
- runs a fixed number of histogram passes (key_bits / radix_bits); each pass
  is one local Pallas/XLA histogram + one ``lax.psum`` of the bucket counts
  over the ICI mesh — the direct analogue of the single
  ``MPI_Allreduce(leg, 3, SUM)`` at ``TODO-…:190``, except 4 rounds total
  instead of O(log N) rounds;
- computes the bucket walk replicated on every device (the reference computes
  the weighted median only on rank 0 and broadcasts, ``:139-168``; SPMD
  replication makes the Bcast implicit).

Per-pass communication is one small vector of counts, independent of N —
the same "O(p) scalars per round" property SURVEY.md §3.2 identifies as the
reference's key design feature, mapped onto ICI collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
from mpi_k_selection_tpu.ops.radix import (
    _collect_prefix_matches,
    _collect_prefix_matches_multi,
    bucket_walk_step,
    collect_view,
    default_radix_bits,
    resolve_cutover,
    run_cutover_ladder,
    select_count_dtype,
)
from mpi_k_selection_tpu.parallel import mesh as mesh_lib
from mpi_k_selection_tpu.utils import compat, debug as _debug, dtypes as _dt


def _prep_shard(hist_method, xs, block_rows=4096):
    """Per-shard kernel-view prep: raw tiles + in-kernel key fold when
    available (saves the per-shard to_sortable pass — see
    ops/histogram.py:prepare_raw), key-space tiles otherwise. Returns
    ``(u, tiles, tiles_n, key_op, key_xor)`` with ``u`` None on the raw
    path."""
    from mpi_k_selection_tpu.ops.histogram import prepare_keys, prepare_raw

    raw = prepare_raw(hist_method, xs, block_rows)
    if raw is not None:
        tiles, tiles_n, key_op, key_xor = raw
        return None, tiles, tiles_n, key_op, key_xor
    u = _dt.to_sortable_bits(xs)
    tiles, tiles_n = prepare_keys(hist_method, u, block_rows)
    return u, tiles, tiles_n, "none", 0


def _shard_map_check_vma(hist_method, total_bits) -> bool:
    """shard_map's varying-manual-axes checking stays on everywhere except
    interpret-mode pallas: interpret re-evaluates the kernel jaxpr under vma
    tracking, where in-kernel constants (traced without vma) cannot be
    reconciled with the varying block operands (JAX's own error suggests
    check_vma=False as the workaround). On real TPU the kernel is an opaque
    custom call and checking works."""
    import numpy as np

    from mpi_k_selection_tpu.ops.histogram import resolve_hist_method

    kd = np.dtype(np.uint32) if total_bits <= 32 else np.dtype(np.uint64)
    method = resolve_hist_method(hist_method, kd)
    is_pallas = method in ("pallas", "pallas_compare", "pallas64", "pallas64_compare")
    return not (is_pallas and jax.default_backend() != "tpu")


def _f64_host_key_route(x):
    """(keys, decode) when the f64-on-TPU exact route applies, else
    (x, None): the distributed entries are eager (k must be concrete), so
    the same host view-cast trick the single-chip wrapper uses
    (ops/radix.py:_f64_tpu_host_keys) keeps the two public entry points
    consistent — without it, device_put would truncate the f64 input to
    the TPU's ~49-bit storage and the distributed result would disagree
    with radix_select on identical input."""
    from mpi_k_selection_tpu.ops.radix import _f64_from_keys_host, _f64_tpu_host_keys

    keys = _f64_tpu_host_keys(x)
    if keys is None:
        return x, None
    return keys, _f64_from_keys_host


@functools.lru_cache(maxsize=64)
def _jitted_select(
    mesh, n, total_bits, cdt, radix_bits, hist_method, chunk, ncut,
    cutover_budget, block_rows,
):
    """Build-and-cache the jitted sharded program for one (mesh, config).

    Rebuilding shard_map + jit per call would force a retrace/recompile on
    every invocation (jit caches are per jit *object*); caching here makes
    repeat calls hit the XLA executable cache like any other jitted fn.

    ``ncut`` enables the distributed cutover ladder: after ``ncut`` passes
    one replicated ``lax.cond`` on the surviving population (free — it is
    the chosen bucket's psummed count) either collects up to
    ``cutover_budget`` candidates PER SHARD, ``all_gather``s them (still
    O(budget) comm — the population bound is global, so every shard's match
    count fits the budget) and sort-indexes the replicated result, or runs
    one more pass and tries again, or falls back to the remaining fixed
    passes. This is the reference CGM's sequential finish
    (``TODO-kth-problem-cgm.c:122, 236-280``) — gather the small survivor
    set, solve locally — with the survivors identified by radix prefix
    instead of physical discards: 64-bit keys run ~6-8 psum rounds instead
    of 16.
    """
    axis = mesh.axis_names[0]
    npasses = total_bits // radix_bits
    check_vma = _shard_map_check_vma(hist_method, total_bits)

    def shard_fn(xs, kk):
        xs = xs.ravel()
        u, tiles, tiles_n, key_op, key_xor = _prep_shard(
            hist_method, xs, block_rows
        )
        kdt = jnp.dtype(_dt.key_dtype(xs.dtype))
        kk = jnp.clip(kk.astype(cdt), 1, n)

        def one_pass(p, prefix, kk):
            shift = total_bits - (p + 1) * radix_bits
            local = masked_radix_histogram(
                u,
                shift=shift,
                radix_bits=radix_bits,
                prefix=prefix if p else None,
                method=hist_method,
                count_dtype=cdt,
                chunk=chunk,
                tiles=tiles,
                orig_n=tiles_n,
                key_op=key_op,
                key_xor=key_xor,
                block_rows=block_rows,
            )
            hist = jax.lax.psum(local, axis)  # the MPI_Allreduce analogue (TODO-…:190)
            return bucket_walk_step(hist, kk, prefix if p else None, kdt, radix_bits)

        prefix = jnp.zeros((), kdt)
        if ncut is None:
            for p in range(npasses):
                prefix, kk, _ = one_pass(p, prefix, kk)
            return _dt.from_sortable_bits(prefix, xs.dtype)

        pop = jnp.asarray(n, cdt)
        for p in range(ncut):
            prefix, kk, pop = one_pass(p, prefix, kk)

        u_collect, n_collect, key_of = collect_view(
            xs.dtype, u, tiles, tiles_n, key_op
        )

        def finish_small(resolved_passes):
            resolved = jnp.asarray(resolved_passes * radix_bits, jnp.int32)

            def fn(args):
                prefix, kk = args
                cand, _pop = _collect_prefix_matches(
                    u_collect, resolved, prefix, cutover_budget, block=128,
                    n_valid=n_collect, key_of=key_of,
                )
                # the final-gather analogue (TODO-…:270): O(budget) values
                # per shard, replicated result — no bulk data movement
                allc = jax.lax.all_gather(cand, axis, tiled=True)
                return jax.lax.sort(allc)[
                    jnp.clip(kk - 1, 0, allc.shape[0] - 1)
                ]

            return fn

        def finish_full_from(p0):
            def fn(args):
                prefix, kk = args
                for p in range(p0, npasses):
                    prefix, kk, _ = one_pass(p, prefix, kk)
                # match the collect branch's varying-manual-axes type (the
                # all_gather output is device-varying to the type system
                # even though its value is replicated)
                return compat.pvary(prefix, axis) if check_vma else prefix

            return fn

        def step(p, args):
            prefix, kk = args
            prefix, kk, pop = one_pass(p, prefix, kk)
            return (prefix, kk), pop

        # the predicate is a psummed (replicated) scalar, so every shard
        # takes the same branch and in-branch collectives stay collective
        ans = run_cutover_ladder(
            ncut, npasses, pop, lambda q: q <= cutover_budget, step,
            finish_small, finish_full_from, (prefix, kk),
        )
        # every shard holds the same answer; the pmax re-establishes the
        # invariant (replicated) type for out_specs=P() at the cost of one
        # scalar collective
        if check_vma:
            ans = jax.lax.pmax(ans, axis)
        return _dt.from_sortable_bits(ans, xs.dtype)

    fn = compat.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=check_vma,
    )
    return jax.jit(fn)


def distributed_radix_select(
    x: jax.Array,
    k,
    *,
    mesh=None,
    radix_bits: int | None = None,
    hist_method: str = "auto",
    chunk: int = 32768,
    cutover: int | str | None = "auto",
    cutover_budget: int = 8192,
    block_rows: int = 4096,
    obs=None,
):
    """Exact k-th smallest (1-indexed) of sharded ``x``; replicated scalar out.

    ``cutover``/``cutover_budget`` enable the distributed sequential-finish
    ladder (see ``_jitted_select``); semantics match
    ops/radix.py:radix_select. Collected sentinel pads are value-safe: they
    carry the order-maximal key, so they sort after every real candidate
    (or tie it exactly, in which case the value is right either way).

    ``obs`` (an :class:`~mpi_k_selection_tpu.obs.Observability`) records
    the resolved dispatch (mesh size, radix_bits, cutover schedule) as a
    ``distributed.select`` event at this host shell; the pass loop itself
    is shard_map/jit-traced, so per-pass events are a streaming-only
    capability (docs/OBSERVABILITY.md).
    """
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)

    x, decode = _f64_host_key_route(x)
    x = jnp.ravel(jnp.asarray(x))
    _debug.check_concrete_k(k, x.shape[0])
    if radix_bits is None:
        radix_bits = default_radix_bits(x.dtype, hist_method)
    x, n = mesh_lib.pad_to_multiple(x, mesh.size)
    # counts are sized for the padded total: sentinels are counted too, and
    # padding can push the histogram total past the unpadded dtype boundary
    cdt = select_count_dtype(x.shape[0])
    total_bits = _dt.key_bits(x.dtype)
    if total_bits % radix_bits:
        raise ValueError(f"radix_bits={radix_bits} must divide {total_bits}")
    from mpi_k_selection_tpu.ops.histogram import check_block_rows

    check_block_rows(block_rows)
    ncut = resolve_cutover(
        cutover, x.shape[0], total_bits, radix_bits, cutover_budget
    )
    if obs is not None:
        from mpi_k_selection_tpu.obs.events import DistributedSelectEvent

        obs.emit(
            DistributedSelectEvent(
                n=int(n),
                queries=1,
                n_devices=int(mesh.size),
                radix_bits=int(radix_bits),
                cutover_passes=None if ncut is None else int(ncut),
                dtype=str(jnp.dtype(x.dtype)),
            )
        )

    fn = _jitted_select(
        mesh, n, total_bits, cdt, radix_bits, hist_method, chunk, ncut,
        cutover_budget, block_rows,
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))
    kk = jnp.asarray(k, cdt)
    ans = fn(xs, kk)
    return decode(ans) if decode is not None else ans


@functools.lru_cache(maxsize=64)
def _jitted_select_many(
    mesh, n, total_bits, cdt, radix_bits, hist_method, chunk, ncut,
    cutover_budget, block_rows,
):
    """Sharded multi-rank selection: the shard's tiled view and the
    prefix-free first pass (one local histogram + one ``psum``) are shared
    by every query, and each later pass runs ALL K queries through one
    shared sweep of the shard (the multi-prefix kernels) followed by one
    ``psum`` of the (K, nbuckets) counts — the shard is read ``npasses``
    times total instead of ``1 + K * (npasses - 1)``, and communication
    stays one small psum per pass for the whole batch.

    ``ncut``: the distributed cutover ladder, batched — one replicated cond
    on the LARGEST query population; the collect branch gathers
    ``cutover_budget`` candidates per query per shard and finishes every
    query with one replicated batched sort (see ``_jitted_select``)."""
    axis = mesh.axis_names[0]
    npasses = total_bits // radix_bits
    check_vma = _shard_map_check_vma(hist_method, total_bits)

    def shard_fn(xs, ks):
        from mpi_k_selection_tpu.ops.histogram import multi_masked_radix_histogram
        from mpi_k_selection_tpu.ops.radix import bucket_walk_step_multi

        xs = xs.ravel()
        u, tiles, tiles_n, key_op, key_xor = _prep_shard(
            hist_method, xs, block_rows
        )
        kdt = jnp.dtype(_dt.key_dtype(xs.dtype))

        hist0 = jax.lax.psum(
            masked_radix_histogram(
                u,
                shift=total_bits - radix_bits,
                radix_bits=radix_bits,
                prefix=None,
                method=hist_method,
                count_dtype=cdt,
                chunk=chunk,
                tiles=tiles,
                orig_n=tiles_n,
                key_op=key_op,
                key_xor=key_xor,
                block_rows=block_rows,
            ),
            axis,
        )
        kk = jnp.clip(ks.astype(cdt), 1, n)
        prefixes, kk, pops = bucket_walk_step_multi(hist0, kk, None, kdt, radix_bits)

        def multi_pass(p, prefixes, kk):
            shift = total_bits - (p + 1) * radix_bits
            local = multi_masked_radix_histogram(
                u,
                shift=shift,
                radix_bits=radix_bits,
                prefixes=prefixes,
                method=hist_method,
                count_dtype=cdt,
                chunk=chunk,
                tiles=tiles,
                orig_n=tiles_n,
                key_op=key_op,
                key_xor=key_xor,
                block_rows=block_rows,
            )
            hist = jax.lax.psum(local, axis)  # (K, nbuckets), one collective
            return bucket_walk_step_multi(hist, kk, prefixes, kdt, radix_bits)

        if ncut is None:
            for p in range(1, npasses):
                prefixes, kk, pops = multi_pass(p, prefixes, kk)
            return _dt.from_sortable_bits(prefixes, xs.dtype)

        for p in range(1, ncut):
            prefixes, kk, pops = multi_pass(p, prefixes, kk)

        u_collect, n_collect, key_of = collect_view(
            xs.dtype, u, tiles, tiles_n, key_op
        )

        def finish_small(resolved_passes):
            resolved = jnp.asarray(resolved_passes * radix_bits, jnp.int32)

            def fn(args):
                prefixes, kk = args
                cand, _pops = _collect_prefix_matches_multi(
                    u_collect, resolved, prefixes, cutover_budget,
                    n_valid=n_collect, key_of=key_of,
                )  # (K, budget) per shard
                allc = jax.lax.all_gather(cand, axis, axis=1, tiled=True)
                s = jnp.sort(allc, axis=1)  # (K, mesh_size * budget)
                idx = jnp.clip(kk - 1, 0, s.shape[1] - 1)
                return jnp.take_along_axis(s, idx[:, None], axis=1)[:, 0]

            return fn

        def finish_full_from(p0):
            def fn(args):
                prefixes, kk = args
                for p in range(p0, npasses):
                    prefixes, kk, _ = multi_pass(p, prefixes, kk)
                # type-match the collect branch (see _jitted_select)
                return compat.pvary(prefixes, axis) if check_vma else prefixes

            return fn

        def step(p, args):
            prefixes, kk = args
            prefixes, kk, pops = multi_pass(p, prefixes, kk)
            return (prefixes, kk), pops

        ans = run_cutover_ladder(
            ncut, npasses, pops, lambda q: jnp.max(q) <= cutover_budget,
            step, finish_small, finish_full_from, (prefixes, kk),
        )
        if check_vma:
            ans = jax.lax.pmax(ans, axis)  # replicated value -> invariant type
        return _dt.from_sortable_bits(ans, xs.dtype)

    fn = compat.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=check_vma,
    )
    return jax.jit(fn)


def distributed_radix_select_many(
    x: jax.Array,
    ks,
    *,
    mesh=None,
    radix_bits: int | None = None,
    hist_method: str = "auto",
    chunk: int = 32768,
    cutover: int | str | None = "auto",
    cutover_budget: int = 8192,
    block_rows: int = 4096,
):
    """Exact k-th smallest of sharded ``x`` for every (1-indexed) k in
    ``ks``; replicated vector out, in ``ks`` order."""
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)

    x, decode = _f64_host_key_route(x)
    x = jnp.ravel(jnp.asarray(x))
    ks_arr = jnp.atleast_1d(jnp.asarray(ks))
    _debug.check_concrete_ks(ks_arr, x.shape[0])
    if radix_bits is None:
        radix_bits = default_radix_bits(x.dtype, hist_method)
    x, n = mesh_lib.pad_to_multiple(x, mesh.size)
    cdt = select_count_dtype(x.shape[0])
    total_bits = _dt.key_bits(x.dtype)
    if total_bits % radix_bits:
        raise ValueError(f"radix_bits={radix_bits} must divide {total_bits}")
    from mpi_k_selection_tpu.ops.histogram import check_block_rows

    check_block_rows(block_rows)
    ncut = resolve_cutover(
        cutover, x.shape[0], total_bits, radix_bits, cutover_budget
    )

    fn = _jitted_select_many(
        mesh, n, total_bits, cdt, radix_bits, hist_method, chunk, ncut,
        cutover_budget, block_rows,
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))
    ans = fn(xs, ks_arr.astype(cdt).ravel()).reshape(ks_arr.shape)
    return decode(ans) if decode is not None else ans
