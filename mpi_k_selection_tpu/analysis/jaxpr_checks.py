"""jaxpr-level contract checks (KSC101-KSC104).

The AST rules see syntax; these see the traced program. Each check
abstractly traces public kernels from ``ops/`` and ``parallel/`` over a
shape/dtype grid — ``jax.eval_shape``/``jax.make_jaxpr`` only, so nothing
runs on a device and a 2^31-element contract costs no memory — and
asserts a property every review round has had to re-derive by hand:

- **KSC101 dtype preservation**: a selection returns its input dtype.
  The silent-demotion twin of the KSL002 truncation class, caught at the
  traced boundary instead of the host boundary.
- **KSC102 counter-width discipline**: histogram accumulators are int32
  only below the documented 2^31-population bound, int64 (x64) beyond,
  and `select_count_dtype` refuses the un-representable case loudly.
  Covers the streaming device/host histogram boundary: every per-chunk
  device count program of the (multi-device) staged ingest — chunked
  single-/multi-prefix and the sketch deep fold — stays int32, the
  cross-chunk host merge int64, the multi-device collect filter stays a
  bool predicate, the deferred executor's compaction keeps an int32
  survivor count and a dtype-preserving compacted buffer, and the
  single-sweep kernel's every part (histogram, compactions, certificate
  pair, sketch fold + extremes) holds the same books on the hand-written
  trace — at two chunk sizes.
- **KSC103 jaxpr stability across batch sizes**: the same kernel traced
  at nearby n produces the identical primitive sequence — a divergence
  means some Python-level branch depends on n in a way that recompiles
  per shape (the recompile-hazard class: jit caches are per-jaxpr).
  Covers the staged-ingest device programs at two adjacent pow2 staging
  buckets (the exact shapes streaming/pipeline.py pads chunks to — and
  the programs every round-robin ingest device compiles per bucket).
- **KSC104 host-transfer census**: every streaming surface program on
  the KSC102/KSC103 case grids stays inside the deferred-transfer
  budget PR 8 promised — ZERO host<->device crossing primitives inside
  the traced program (callbacks, infeed/outfeed, traced device_put),
  and a host-materialized output surface that is a small DECLARED leaf
  budget per program, identical across staging buckets — i.e. one
  materialization per bucket at pop time, never a per-element or
  per-survivor-count trickle mid-pass.

Checks report :class:`~mpi_k_selection_tpu.analysis.core.Finding`s
against the module that owns the kernel; they have no line-level noqa
(deselect with ``--ignore KSC103`` and a written justification in the
caller instead).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from mpi_k_selection_tpu.analysis.core import Finding

CONTRACT_CHECKS: list["ContractCheck"] = []


@dataclasses.dataclass
class ContractCheck:
    id: str
    title: str
    rationale: str
    fn: Callable[[], list[Finding]]

    def run(self) -> list[Finding]:
        try:
            return self.fn()
        except Exception as e:  # a crash is a finding, not a pass
            return [
                Finding(
                    self.id,
                    "<contract-engine>",
                    0,
                    f"contract check crashed: {type(e).__name__}: {e}",
                )
            ]


def contract(id: str, title: str, rationale: str):
    def deco(fn):
        CONTRACT_CHECKS.append(ContractCheck(id, title, rationale, fn))
        return fn

    return deco


def _spec(n, dtype):
    import jax

    return jax.ShapeDtypeStruct((n,), dtype)


def _iter_eqns(jaxpr):
    """Every equation of a (closed) jaxpr, recursing into call/pjit/
    cond/scan sub-jaxprs — the shared walk under both the KSC103
    primitive trail and the KSC104 crossing census."""

    def walk(jx):
        for eqn in jx.eqns:
            yield eqn
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else [v]
                for item in vals:
                    inner = getattr(item, "jaxpr", None)
                    if inner is not None:
                        yield from walk(inner)
                    elif hasattr(item, "eqns"):
                        yield from walk(item)

    yield from walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)


def _primitive_trail(jaxpr) -> list[str]:
    """Flattened primitive-name sequence of a (closed) jaxpr — the
    shape-free program fingerprint KSC103 compares across batch sizes."""
    return [eqn.primitive.name for eqn in _iter_eqns(jaxpr)]


# the dtype grid: every key width class the transform table supports
# without an x64 mode flip, plus the 64-bit pair under compat.enable_x64
_GRID_32 = ("int32", "uint32", "float32", "int16", "bfloat16")
_GRID_64 = ("int64", "float64")

# Two nearby chunk sizes for the streaming double-buffer ingest contracts:
# adjacent pow2 STAGING buckets (streaming/pipeline.py pads every staged
# chunk to its pow2 ceiling, so these are exactly the shapes the pipelined
# descent compiles) — the trail must not diverge between them, or ragged
# streams recompile per bucket.
_STREAMING_INGEST_SIZES = (1 << 12, 1 << 13)


def _streaming_ingest_cases():
    """The per-chunk device programs of the (multi-device) staged ingest
    that produce INT32 COUNT PARTIALS — single-prefix (pass 0 /
    single-rank descent), shared-sweep multi-prefix (multi-rank descent),
    and the sketch's deepest-level fold
    (streaming/sketch.py:RadixSketch._dispatch_staged) — with the
    streaming counter discipline (per-chunk device int32; the host merge
    promotes to int64). With ``devices`` > 1 each program is dispatched
    once per round-robin slot over the SAME pow2 staging buckets, so
    per-bucket trail stability is also per-device compile stability."""
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.histogram import (
        masked_radix_histogram,
        multi_masked_radix_histogram,
    )

    path = "mpi_k_selection_tpu/streaming/chunked.py"
    return [
        (
            path,
            "streaming chunked ingest[uint32, single-prefix]",
            lambda u: masked_radix_histogram(
                u, shift=24, radix_bits=8, prefix=None, method="scatter",
                count_dtype=jnp.int32,
            ),
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
        (
            path,
            "streaming chunked ingest[uint32, multi-prefix shared sweep]",
            lambda u: multi_masked_radix_histogram(
                u, shift=16, radix_bits=8,
                prefixes=np.asarray([0, 3, 129], np.uint32),
                method="scatter", count_dtype=jnp.int32,
            ),
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
        (
            "mpi_k_selection_tpu/streaming/sketch.py",
            "streaming sketch deep fold[uint32, rb=16]",
            lambda u: masked_radix_histogram(
                u, shift=16, radix_bits=16, prefix=None, method="scatter",
                count_dtype=jnp.int32,  # per-chunk partial; host fold int64
            ),
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
    ]


def _streaming_wide_ingest_cases():
    """The WIDE-digit descent programs ``width_schedule`` adds
    (streaming/chunked.py:resolve_width_schedule): pass 0 histograms a
    16-bit digit (2^16 int32 bins — inside the MAX_PASS_BITS device
    budget; wide passes always route ``method="scatter"``, the PR 13
    rb <= 8 kernel rule via ``_pass_method``), and a later schedule step
    runs the same wide program over PACKED-REPLAY survivors re-staged
    from a pruned spill generation (the replay reconstructs full-width
    keys on host, so the device program is identical — the multi-prefix
    sweep with live filter specs). Both carry the int32-partial counter
    discipline and must trace one trail across staging buckets."""
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.histogram import (
        masked_radix_histogram,
        multi_masked_radix_histogram,
    )

    path = "mpi_k_selection_tpu/streaming/chunked.py"
    return [
        (
            path,
            "streaming wide ingest[uint32, pass-0 w=16]",
            lambda u: masked_radix_histogram(
                u, shift=16, radix_bits=16, prefix=None, method="scatter",
                count_dtype=jnp.int32,
            ),
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
        (
            path,
            "streaming wide ingest[uint32, packed-replay step w=16 "
            "multi-prefix]",
            lambda u: multi_masked_radix_histogram(
                u, shift=8, radix_bits=16,
                prefixes=np.asarray([0, 3, 129], np.uint32),
                method="scatter", count_dtype=jnp.int32,
            ),
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
    ]


def _streaming_collect_mask_cases():
    """The survivor-collect filter PREDICATE the eager (``deferred=off``)
    collect/tee paths run on each staged chunk's own device
    (streaming/executor.py:prefix_mask): a shift-compare. It must trace to
    a bool mask (an integer-typed compare would silently widen per-device
    memory and change the gather semantics), and its trail must be stable
    across chunk LENGTHS: the eager filter runs over
    ``StagedKeys.valid()`` — a per-``n_valid`` slice, not the padded
    bucket — so the grid pairs a pow2 bucket size with a ragged
    valid-slice size (each distinct length still costs one XLA compile per
    device; the contract gates program STRUCTURE keying on n, which would
    make that cost a recompile storm)."""
    import jax

    path = "mpi_k_selection_tpu/streaming/executor.py"

    def collect_mask(u):
        return jax.lax.shift_right_logical(
            u, u.dtype.type(16)
        ) == u.dtype.type(3)

    return [
        (
            path,
            "streaming collect filter[uint32, mask]",
            collect_mask,
            "uint32",
            # a staging bucket AND a ragged valid-slice length
            (_STREAMING_INGEST_SIZES[0], _STREAMING_INGEST_SIZES[0] + 311),
        ),
    ]


def _streaming_compaction_cases():
    """The deferred executor's mask -> count -> fixed-shape compaction
    (streaming/executor.py:_compact_core) — the program the collect and
    the spill tee dispatch per staged chunk under ``deferred``. Its
    survivor count must be the per-chunk int32 partial (the streaming
    counter discipline: chunk < 2^31), the compacted output must preserve
    the key dtype, and — because it runs over the WHOLE padded bucket
    with ``n_valid`` and the ``(shift, prefix)`` specs as traced scalars —
    its primitive trail must be identical across bucket sizes (one XLA
    compile per (bucket, dtype, #specs), the KSC103 contract the deferral
    design leans on)."""
    import numpy as np

    from mpi_k_selection_tpu.streaming.executor import _compact_core

    path = "mpi_k_selection_tpu/streaming/executor.py"

    def compact(u):
        # two specs at distinct resolved depths: the union-mask (spill
        # tee) shape; a single-spec (collect) program is the same trace
        # with a shorter unrolled union loop
        return _compact_core(
            u,
            np.int32(u.shape[0] - 7),
            np.asarray([24, 16], np.uint32),
            np.asarray([0, 3], np.uint32),
        )

    return [
        (
            path,
            "streaming deferred compaction[uint32, 2 specs]",
            compact,
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
    ]


def _streaming_fused_ingest_cases():
    """The fused single-read ingest program (ops/pallas/fused_ingest.py:
    fused_ingest_core) — ONE device program per staged bucket per pass,
    producing the multi-prefix histogram, the per-spec survivor
    compactions, and the spill-tee union payload the unfused bundle used
    to dispatch separately. Same contracts as its unfused parts: int32
    histogram/count partials (the streaming counter discipline),
    dtype-preserving compacted buffers, and a bucket-size-stable
    primitive trail (everything data-dependent — ``n_valid``, the
    histogram prefixes, the spec scalars — rides traced, so the program
    compiles once per (bucket, dtype, #prefixes, #collect, #tee))."""
    import numpy as np

    from mpi_k_selection_tpu.ops.pallas.fused_ingest import fused_ingest_core

    path = "mpi_k_selection_tpu/ops/pallas/fused_ingest.py"

    def fused(u):
        # the spill-pass shape: 2 surviving prefixes histogrammed, 2
        # collect specs at distinct resolved depths, a 2-spec union tee
        return fused_ingest_core(
            u,
            np.int32(u.shape[0] - 7),
            np.asarray([0, 3], np.uint32),
            np.asarray([24, 16], np.uint32),
            np.asarray([0, 3], np.uint32),
            np.asarray([24, 16], np.uint32),
            np.asarray([0, 3], np.uint32),
            shift=16,
            radix_bits=8,
            method="scatter",
            hist_mode="multi",
            n_collect=2,
            n_tee=2,
        )

    return [
        (
            path,
            "streaming fused ingest[uint32, 2 prefixes + 2 collect + tee]",
            fused,
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
    ]


def _streaming_sweep_ingest_cases():
    """The single-sweep pallas ingest kernel (ops/pallas/sweep_ingest.py:
    sweep_ingest_core) — ONE grid pass per staged bucket producing every
    consumer product (multi-prefix histogram, per-spec compactions, tee
    payload, certificate pair, sketch deep fold + extremes). Same books
    as the programs it replaces, checked ON the kernel trace: int32
    histogram/count/certificate partials (the streaming counter
    discipline), dtype-preserving compacted buffers, key-dtype extremes,
    int32 deep-level partials — and a bucket-size-stable primitive trail
    (the kernel body never unrolls on the tile count; everything
    data-dependent rides as a traced SMEM scalar), traced at both
    adjacent pow2 staging buckets exactly like its unfused and
    XLA-fusion counterparts."""
    import numpy as np

    from mpi_k_selection_tpu.ops.pallas.sweep_ingest import sweep_ingest_core

    path = "mpi_k_selection_tpu/ops/pallas/sweep_ingest.py"

    def sweep(u):
        # every part armed at once: 2 surviving prefixes histogrammed, 2
        # collect specs at distinct resolved depths, a 2-spec union tee,
        # the certificate probe, and a 16-bit sketch fold — the superset
        # of the shapes the descent/certificate/sketch passes dispatch
        return sweep_ingest_core(
            u,
            np.int32(u.shape[0] - 7),
            np.asarray([0, 3], np.uint32),
            np.asarray([24, 16], np.uint32),
            np.asarray([0, 3], np.uint32),
            np.asarray([24, 16], np.uint32),
            np.asarray([0, 3], np.uint32),
            np.asarray(5, np.uint32),
            shift=16,
            radix_bits=8,
            hist_mode="multi",
            n_collect=2,
            n_tee=2,
            cert=True,
            sketch_bits=16,
        )

    return [
        (
            path,
            "streaming sweep ingest[uint32, hist+collect+tee+cert+sketch]",
            sweep,
            "uint32",
            _STREAMING_INGEST_SIZES,
        ),
    ]


@contract(
    "KSC101",
    "public selections preserve their input dtype",
    "a demoted output dtype means some intermediate silently narrowed the "
    "values — the traced twin of the KSL002 truncation class",
)
def check_dtype_preservation() -> list[Finding]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.radix import radix_select, radix_select_many
    from mpi_k_selection_tpu.ops.sort import sort_select
    from mpi_k_selection_tpu.ops.topk import topk
    from mpi_k_selection_tpu.utils import compat

    findings: list[Finding] = []

    def expect(path, fn, x, want, label):
        out = jax.eval_shape(fn, x)
        got = np.dtype(jnp.result_type(out)) if not hasattr(out, "dtype") else np.dtype(out.dtype)
        if got != np.dtype(want):
            findings.append(
                Finding(
                    "KSC101",
                    path,
                    0,
                    f"{label}: input {np.dtype(want)} -> output {got} "
                    "(silent dtype demotion)",
                )
            )

    def sweep(dtypes):
        for dt in dtypes:
            expect(
                "mpi_k_selection_tpu/ops/radix.py",
                lambda x: radix_select(x, 37), _spec(1 << 16, dt), dt,
                f"radix_select[{dt}, n=2^16]",
            )
            expect(
                "mpi_k_selection_tpu/ops/radix.py",
                lambda x: radix_select_many(x, jnp.asarray([1, 5, 9])),
                _spec(1 << 16, dt), dt,
                f"radix_select_many[{dt}, n=2^16]",
            )
            expect(
                "mpi_k_selection_tpu/ops/sort.py",
                lambda x: sort_select(x, 5), _spec(1 << 10, dt), dt,
                f"sort_select[{dt}, n=2^10]",
            )
            expect(
                "mpi_k_selection_tpu/ops/topk.py",
                lambda x: topk(x, 8)[0], _spec(1 << 14, dt), dt,
                f"topk[{dt}, n=2^14] values",
            )

    sweep(_GRID_32)
    with compat.enable_x64(True):
        sweep(_GRID_64)
    return findings


@contract(
    "KSC102",
    "histogram counter width matches the documented population bound",
    "int32 counts are exact only below 2^31 elements; beyond that the "
    "accumulator must be int64 and the un-representable case must raise "
    "instead of wrapping (SURVEY.md §7 int-overflow hygiene)",
)
def check_counter_width() -> list[Finding]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
    from mpi_k_selection_tpu.ops.radix import select_count_dtype
    from mpi_k_selection_tpu.utils import compat

    path = "mpi_k_selection_tpu/ops/radix.py"
    findings: list[Finding] = []

    # documented-bound int32: the dtype must actually cover the population
    for n in (1 << 10, 1 << 20, (1 << 31) - 1):
        cdt = select_count_dtype(n)
        if np.iinfo(np.dtype(cdt)).max < n:
            findings.append(
                Finding("KSC102", path, 0,
                        f"select_count_dtype({n}) = {np.dtype(cdt)} cannot "
                        f"represent the population")
            )

    # the un-representable case must raise, not wrap
    if not jax.config.jax_enable_x64:
        try:
            select_count_dtype(1 << 31)
            findings.append(
                Finding("KSC102", path, 0,
                        "select_count_dtype(2^31) without x64 must raise "
                        "(int32 would wrap; int64 would silently truncate)")
            )
        except ValueError:
            pass

    # the traced accumulator honors the requested width (no demotion)
    hpath = "mpi_k_selection_tpu/ops/histogram.py"
    out = jax.eval_shape(
        lambda u: masked_radix_histogram(
            u, shift=24, radix_bits=8, method="scatter", count_dtype=jnp.int32
        ),
        _spec(1 << 16, "uint32"),
    )
    if np.dtype(out.dtype) != np.dtype(np.int32):
        findings.append(
            Finding("KSC102", hpath, 0,
                    f"int32 histogram accumulator traced as {out.dtype}")
        )
    with compat.enable_x64(True):
        cdt = select_count_dtype(1 << 31)
        if np.dtype(cdt) != np.dtype(np.int64):
            findings.append(
                Finding("KSC102", path, 0,
                        f"select_count_dtype(2^31) under x64 = {np.dtype(cdt)}, "
                        "want int64")
            )
        out = jax.eval_shape(
            lambda u: masked_radix_histogram(
                u, shift=24, radix_bits=8, method="scatter", count_dtype=jnp.int64
            ),
            _spec(1 << 16, "uint32"),
        )
        if np.dtype(out.dtype) != np.dtype(np.int64):
            findings.append(
                Finding("KSC102", hpath, 0,
                        f"int64 histogram accumulator traced as {out.dtype} "
                        "under x64 (silent counter demotion)")
            )

    # the streaming device/host histogram boundary, at two chunk sizes (the
    # pipeline's adjacent pow2 staging buckets — and with devices > 1, the
    # exact programs every round-robin slot compiles): the per-chunk DEVICE
    # accumulator stays int32 (a chunk never exceeds 2^31 elements — the
    # guard in streaming/chunked.py:_encode_chunk), and the HOST merge the
    # descent accumulates across chunks/passes is int64 regardless of x64,
    # so n is exact to 2^63
    from mpi_k_selection_tpu.streaming.chunked import _chunk_histograms

    spath = "mpi_k_selection_tpu/streaming/chunked.py"
    # width_schedule's refusal surface: a digit wider than MAX_PASS_BITS
    # must be refused LOUDLY at validation time — 2**width int32 device
    # partials per in-flight (prefix, chunk) dispatch is the budget this
    # check's int32 cases below are sized for — and "auto" must never
    # resolve a width past the 16-bit wide-pass cap on its own
    from mpi_k_selection_tpu.streaming.chunked import (
        MAX_PASS_BITS,
        resolve_width_schedule,
        validate_width_schedule,
    )

    try:
        validate_width_schedule((MAX_PASS_BITS + 1,))
        findings.append(
            Finding("KSC102", spath, 0,
                    f"validate_width_schedule accepted a {MAX_PASS_BITS + 1}"
                    "-bit digit — 2**width int32 device partials would blow "
                    "the device histogram budget; the refusal must fire "
                    "before any stream is touched")
        )
    except ValueError:
        pass
    for total, rb in ((64, 8), (32, 4), (16, 8)):
        for w in resolve_width_schedule("auto", total, rb):
            if w > 16:
                findings.append(
                    Finding("KSC102", spath, 0,
                            f"resolve_width_schedule('auto', {total}, {rb}) "
                            f"emitted a {w}-bit digit past the 16-bit "
                            "wide-pass cap")
                )
    for case_path, label, fn, dt, sizes in (
        _streaming_ingest_cases() + _streaming_wide_ingest_cases()
    ):
        for n in sizes:
            out = jax.eval_shape(fn, _spec(n, dt))
            cdt = np.dtype(jnp.result_type(out)) if not hasattr(out, "dtype") else np.dtype(out.dtype)
            if cdt != np.dtype(np.int32):
                findings.append(
                    Finding("KSC102", case_path, 0,
                            f"{label} n={n}: per-chunk device accumulator "
                            f"traced as {cdt}, want int32")
                )
    # the multi-device collect filter must stay a bool PREDICATE: an
    # integer-typed compare would silently change the per-device gather's
    # memory and masking semantics
    for case_path, label, fn, dt, sizes in _streaming_collect_mask_cases():
        for n in sizes:
            out = jax.eval_shape(fn, _spec(n, dt))
            cdt = np.dtype(jnp.result_type(out)) if not hasattr(out, "dtype") else np.dtype(out.dtype)
            if cdt != np.dtype(np.bool_):
                findings.append(
                    Finding("KSC102", case_path, 0,
                            f"{label} n={n}: survivor filter traced as "
                            f"{cdt}, want bool")
                )
    # the deferred compaction: survivor count is the per-chunk int32
    # partial, the compacted buffer preserves the key dtype (a widened
    # compaction would silently double per-device memory; a narrowed one
    # is the KSL002 truncation class on device)
    for case_path, label, fn, dt, sizes in _streaming_compaction_cases():
        for n in sizes:
            out, cnt = jax.eval_shape(fn, _spec(n, dt))
            if np.dtype(out.dtype) != np.dtype(dt):
                findings.append(
                    Finding("KSC102", case_path, 0,
                            f"{label} n={n}: compacted survivors traced as "
                            f"{np.dtype(out.dtype)}, want {dt}")
                )
            if np.dtype(cnt.dtype) != np.dtype(np.int32):
                findings.append(
                    Finding("KSC102", case_path, 0,
                            f"{label} n={n}: survivor count traced as "
                            f"{np.dtype(cnt.dtype)}, want the int32 "
                            "per-chunk partial")
                )
    # the fused single-read ingest program: its histogram half must keep
    # the int32 per-chunk partial, every compaction part must preserve the
    # key dtype with an int32 survivor count — the same books as its
    # unfused parts, checked on the fused trace so the fusion cannot
    # silently widen or narrow anything
    for case_path, label, fn, dt, sizes in _streaming_fused_ingest_cases():
        for n in sizes:
            hist, collect, tee = jax.eval_shape(fn, _spec(n, dt))
            if np.dtype(hist.dtype) != np.dtype(np.int32):
                findings.append(
                    Finding("KSC102", case_path, 0,
                            f"{label} n={n}: fused histogram traced as "
                            f"{np.dtype(hist.dtype)}, want int32")
                )
            for part_label, (out, cnt) in (
                [(f"collect[{i}]", part) for i, part in enumerate(collect)]
                + ([("tee", tee)] if tee is not None else [])
            ):
                if np.dtype(out.dtype) != np.dtype(dt):
                    findings.append(
                        Finding("KSC102", case_path, 0,
                                f"{label} n={n}: fused {part_label} "
                                f"compaction traced as {np.dtype(out.dtype)}, "
                                f"want {dt}")
                    )
                if np.dtype(cnt.dtype) != np.dtype(np.int32):
                    findings.append(
                        Finding("KSC102", case_path, 0,
                                f"{label} n={n}: fused {part_label} count "
                                f"traced as {np.dtype(cnt.dtype)}, want the "
                                "int32 per-chunk partial")
                    )
    # the single-sweep kernel: one program now carries EVERY consumer's
    # accumulator, so the width discipline is checked part by part on the
    # kernel trace — int32 histogram partial, dtype-preserving compactions
    # with int32 counts (collect AND tee), int32 certificate pair, int32
    # deep-level partial with key-dtype extremes
    for case_path, label, fn, dt, sizes in _streaming_sweep_ingest_cases():
        for n in sizes:
            hist, collect, tee, cert, sketch = jax.eval_shape(fn, _spec(n, dt))
            if np.dtype(hist.dtype) != np.dtype(np.int32):
                findings.append(
                    Finding("KSC102", case_path, 0,
                            f"{label} n={n}: sweep histogram traced as "
                            f"{np.dtype(hist.dtype)}, want int32")
                )
            for part_label, (out, cnt) in (
                [(f"collect[{i}]", part) for i, part in enumerate(collect)]
                + ([("tee", tee)] if tee is not None else [])
            ):
                if np.dtype(out.dtype) != np.dtype(dt):
                    findings.append(
                        Finding("KSC102", case_path, 0,
                                f"{label} n={n}: sweep {part_label} "
                                f"compaction traced as {np.dtype(out.dtype)}, "
                                f"want {dt}")
                    )
                if np.dtype(cnt.dtype) != np.dtype(np.int32):
                    findings.append(
                        Finding("KSC102", case_path, 0,
                                f"{label} n={n}: sweep {part_label} count "
                                f"traced as {np.dtype(cnt.dtype)}, want the "
                                "int32 per-chunk partial")
                    )
            for cname, c in zip(("less", "leq"), cert):
                if np.dtype(c.dtype) != np.dtype(np.int32):
                    findings.append(
                        Finding("KSC102", case_path, 0,
                                f"{label} n={n}: sweep certificate {cname} "
                                f"traced as {np.dtype(c.dtype)}, want the "
                                "int32 per-chunk partial")
                    )
            deep, kmin, kmax = sketch
            if np.dtype(deep.dtype) != np.dtype(np.int32):
                findings.append(
                    Finding("KSC102", case_path, 0,
                            f"{label} n={n}: sweep deep-level partial traced "
                            f"as {np.dtype(deep.dtype)}, want int32")
                )
            for ename, e in (("min", kmin), ("max", kmax)):
                if np.dtype(e.dtype) != np.dtype(dt):
                    findings.append(
                        Finding("KSC102", case_path, 0,
                                f"{label} n={n}: sweep key-space {ename} "
                                f"traced as {np.dtype(e.dtype)}, want {dt}")
                    )
    # host-merge side (numpy method — host-only, nothing touches a device):
    # both the single- and multi-prefix merge inputs must already be int64
    kdt = np.dtype(np.uint32)
    probe = np.arange(64, dtype=np.uint32)
    merged = _chunk_histograms(probe, 24, 8, [None], "numpy", kdt)[None]
    multi = _chunk_histograms(probe, 16, 8, [0, 3], "numpy", kdt)
    for label, h in [("single-prefix", merged)] + [
        (f"prefix {p:#x}", h) for p, h in multi.items()
    ]:
        if np.dtype(h.dtype) != np.dtype(np.int64):
            findings.append(
                Finding("KSC102", spath, 0,
                        f"streaming host-merge histogram ({label}) is "
                        f"{np.dtype(h.dtype)}, want int64 — the cross-chunk "
                        "accumulator would wrap past 2^31 elements")
            )
    return findings


@contract(
    "KSC103",
    "selection jaxpr is stable across nearby batch sizes",
    "two nearby n tracing to different primitive sequences means a "
    "Python-level branch keys on n — every distinct jaxpr is a fresh XLA "
    "compile, and a size-dependent program is a latent recompile storm in "
    "serving loops that see ragged batches",
)
def check_jaxpr_stability() -> list[Finding]:
    import jax
    import jax.numpy as jnp

    from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
    from mpi_k_selection_tpu.ops.radix import radix_select
    from mpi_k_selection_tpu.ops.topk import topk

    findings: list[Finding] = []
    cases = [
        (
            "mpi_k_selection_tpu/ops/radix.py",
            "radix_select[int32]",
            lambda x: radix_select(x, 1234),
            "int32",
            (1 << 20, (1 << 20) + (1 << 13)),
        ),
        (
            "mpi_k_selection_tpu/ops/topk.py",
            "topk[float32, k=8]",
            lambda x: topk(x, 8)[0],
            "float32",
            (1 << 16, (1 << 16) + (1 << 10)),
        ),
        (
            "mpi_k_selection_tpu/ops/histogram.py",
            "masked_radix_histogram[uint32]",
            lambda u: masked_radix_histogram(
                u, shift=24, radix_bits=8, method="scatter",
                count_dtype=jnp.int32,
            ),
            "uint32",
            (1 << 16, (1 << 16) + (1 << 10)),
        ),
    ]
    # the streaming double-buffer ingest traced at two chunk sizes
    # (adjacent pow2 staging buckets): a trail divergence would mean every
    # distinct chunk/bucket size compiles a fresh histogram program —
    # defeating the pipeline's pad-to-bucket design outright. With the
    # multi-device round robin, every ingest device compiles these same
    # programs per bucket, so a divergence multiplies by p; the collect
    # filter predicate is on the grid for the same reason
    cases += _streaming_ingest_cases()
    # the wide-digit schedule programs at both staging buckets — the wide
    # pass-0 histogram and the packed-replay schedule step must compile
    # once per (bucket, dtype) exactly like the narrow digits they replace
    cases += _streaming_wide_ingest_cases()
    cases += _streaming_collect_mask_cases()
    cases += _streaming_compaction_cases()
    # the fused single-read program at both staging buckets: a trail
    # divergence would mean the fusion recompiles per bucket — exactly the
    # per-pass compile discipline it inherits from its unfused parts
    cases += _streaming_fused_ingest_cases()
    # the single-sweep kernel at both staging buckets: the kernel body
    # must not unroll on the tile count (grid geometry is a pallas_call
    # param, not program structure), or the kernel tier recompiles per
    # bucket — the same per-(bucket, dtype, spec-counts) compile-once
    # discipline as the XLA tier, now pinned on the hand-written trace
    cases += _streaming_sweep_ingest_cases()
    for path, label, fn, dt, (n1, n2) in cases:
        t1 = _primitive_trail(jax.make_jaxpr(fn)(_spec(n1, dt)))
        t2 = _primitive_trail(jax.make_jaxpr(fn)(_spec(n2, dt)))
        if t1 != t2:
            # locate the first divergence for the report
            i = next(
                (j for j, (a, b) in enumerate(zip(t1, t2)) if a != b),
                min(len(t1), len(t2)),
            )
            a = t1[i] if i < len(t1) else "<end>"
            b = t2[i] if i < len(t2) else "<end>"
            findings.append(
                Finding(
                    "KSC103",
                    path,
                    0,
                    f"{label}: primitive trail diverges between n={n1} "
                    f"({len(t1)} eqns) and n={n2} ({len(t2)} eqns) at "
                    f"position {i} ({a} vs {b}) — n-dependent program "
                    "structure recompiles per batch size",
                )
            )
    # schedule-STEP stability for the width_schedule descent: the same
    # wide digit at two different resolved depths (pass-0 vs a later
    # step's shift) must be ONE trail — the shift is a baked Python
    # constant, so a divergence means the program structure keys on the
    # step index and every schedule step compiles a fresh histogram
    n_step = _STREAMING_INGEST_SIZES[0]
    step_trails = [
        _primitive_trail(
            jax.make_jaxpr(
                lambda u, s=shift: masked_radix_histogram(
                    u, shift=s, radix_bits=16, prefix=None,
                    method="scatter", count_dtype=jnp.int32,
                )
            )(_spec(n_step, "uint32"))
        )
        for shift in (16, 8, 0)
    ]
    if any(t != step_trails[0] for t in step_trails[1:]):
        findings.append(
            Finding(
                "KSC103",
                "mpi_k_selection_tpu/streaming/chunked.py",
                0,
                "wide-digit histogram trail diverges across schedule "
                "steps (shift constants) — step-dependent program "
                "structure recompiles per descent pass",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# KSC104 — host-transfer census over the streaming surface programs


#: Primitives that cross the host<->device boundary from INSIDE a traced
#: program. A streaming surface program containing one pays a host sync
#: per staged bucket per pass — exactly the mid-pass crossing the
#: deferred executor design (PR 8) eliminated; the callback family also
#: catches any future "just call back to numpy for this part" shortcut.
_CROSSING_PRIMITIVES = frozenset(
    {
        "device_put",
        "infeed",
        "outfeed",
        "copy_to_host_async",
    }
)


def _is_crossing_primitive(name: str) -> bool:
    # jax's callback family has churned names across versions
    # (pure_callback / io_callback / debug_callback / host_callback's
    # outside_call) — match the family, not a version's spelling
    return name in _CROSSING_PRIMITIVES or "callback" in name or name.endswith(
        "outside_call"
    )


def _transfer_census(jaxpr) -> list:
    """The mid-pass host<->device crossings in a traced program. A
    ``device_put`` whose every operand is a compile-time LITERAL is
    constant placement — baked once per compile, cached by jit, zero
    per-pop cost (the ``jnp.asarray(scalar)`` idiom) — and does not
    count; a callback always does."""
    from jax import core as jax_core

    out = []
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if not _is_crossing_primitive(name):
            continue
        if name == "device_put" and all(
            isinstance(v, jax_core.Literal) for v in eqn.invars
        ):
            continue
        out.append(name)
    return out


#: The pop-time materialization budget: per streaming surface program
#: (keyed by its case-grid label), the number of host-materialized
#: output leaves ONE FIFO pop transfers. The budget is each program's
#: documented consumer-product count — anything above it means a surface
#: grew an undeclared host-facing output; a label missing from this
#: table is itself a finding (a new surface must declare its budget, the
#: doc-drift posture applied to transfers).
_POP_MATERIALIZATION_BUDGET = {
    # one int32 histogram partial per bucket
    "streaming chunked ingest[uint32, single-prefix]": 1,
    "streaming chunked ingest[uint32, multi-prefix shared sweep]": 1,
    # one deepest-level partial per bucket (host fold at pop)
    "streaming sketch deep fold[uint32, rb=16]": 1,
    # width_schedule's wide digits: one int32 partial per bucket, same
    # as the narrow ingest they replace (pass 0 and packed-replay step)
    "streaming wide ingest[uint32, pass-0 w=16]": 1,
    "streaming wide ingest[uint32, packed-replay step w=16 "
    "multi-prefix]": 1,
    # the eager filter predicate: one bool mask (the deferred="off"
    # oracle's single device product per bucket)
    "streaming collect filter[uint32, mask]": 1,
    # compacted survivors + the int32 count scalar
    "streaming deferred compaction[uint32, 2 specs]": 2,
    # hist + 2 x (collect out, count) + (tee out, count)
    "streaming fused ingest[uint32, 2 prefixes + 2 collect + tee]": 7,
    # hist + 2 x (collect out, count) + (tee out, count) + (less, leq)
    # + (deep, kmin, kmax)
    "streaming sweep ingest[uint32, hist+collect+tee+cert+sketch]": 12,
}


def _census_cases():
    """Every streaming surface program on the contract grids — the same
    case lists KSC102/KSC103 trace, so a new ingest program lands on the
    census the moment it lands on the width/stability grids."""
    return (
        _streaming_ingest_cases()
        + _streaming_wide_ingest_cases()
        + _streaming_collect_mask_cases()
        + _streaming_compaction_cases()
        + _streaming_fused_ingest_cases()
        + _streaming_sweep_ingest_cases()
    )


def _census_findings(cases, budgets) -> list[Finding]:
    """The census body over explicit cases/budgets (tests plant
    violating cases through this seam)."""
    import jax

    findings: list[Finding] = []
    seen_labels = set()
    for path, label, fn, dt, sizes in cases:
        seen_labels.add(label)
        budget = budgets.get(label)
        if budget is None:
            findings.append(
                Finding(
                    "KSC104", path, 0,
                    f"{label}: streaming surface program has no declared "
                    "pop-time materialization budget — register the label "
                    "in _POP_MATERIALIZATION_BUDGET with its consumer-"
                    "product leaf count",
                )
            )
            continue
        leaf_counts = []
        for n in sizes:
            spec = _spec(n, dt)
            jaxpr = jax.make_jaxpr(fn)(spec)
            crossings = _transfer_census(jaxpr)
            if crossings:
                findings.append(
                    Finding(
                        "KSC104", path, 0,
                        f"{label} n={n}: {len(crossings)} mid-pass "
                        f"host<->device crossing(s) inside the traced "
                        f"program ({', '.join(sorted(set(crossings)))}) — "
                        "the deferred-transfer budget is ZERO crossings "
                        "mid-pass; materialize at FIFO pop time instead",
                    )
                )
            # the jaxpr in hand already carries the output surface — one
            # trace serves both the census and the leaf count
            leaves = jaxpr.out_avals
            leaf_counts.append(len(leaves))
            if len(leaves) > budget:
                findings.append(
                    Finding(
                        "KSC104", path, 0,
                        f"{label} n={n}: {len(leaves)} host-materialized "
                        f"output leaves exceed the declared pop-time "
                        f"budget of {budget} — an undeclared host-facing "
                        "output grew on this surface; declare it (and its "
                        "pop-time transfer cost) or fuse it",
                    )
                )
        if len(set(leaf_counts)) > 1:
            findings.append(
                Finding(
                    "KSC104", path, 0,
                    f"{label}: output surface varies across staging "
                    f"buckets {tuple(sizes)} ({leaf_counts} leaves) — a "
                    "bucket-size-dependent materialization surface "
                    "transfers per shape, not once per pop",
                )
            )
    for label in sorted(set(budgets) - seen_labels):
        findings.append(
            Finding(
                "KSC104", "mpi_k_selection_tpu/analysis/jaxpr_checks.py", 0,
                f"_POP_MATERIALIZATION_BUDGET declares `{label}` but no "
                "case-grid program carries that label — stale budget row "
                "(the suppression-staleness posture applied to the "
                "transfer ledger)",
            )
        )
    return findings


@contract(
    "KSC104",
    "streaming surface programs stay inside the deferred-transfer budget",
    "PR 8's deferral contract is one materialization per bucket at pop "
    "time and zero mid-pass crossings — a callback or traced transfer "
    "inside an ingest program re-serializes the p-wide in-flight window "
    "on a per-bucket host sync (the review-r6 class the executor "
    "retired), and an undeclared host-facing output is a silent "
    "per-pop bandwidth tax no benchmark is watching",
)
def check_host_transfer_census() -> list[Finding]:
    return _census_findings(_census_cases(), _POP_MATERIALIZATION_BUDGET)
