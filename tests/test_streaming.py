"""Streaming subsystem: out-of-core chunked selection + RadixSketch.

Everything here runs on the 8-device virtual CPU mesh from conftest. The
acceptance contract under test: chunked selection is BIT-exact against the
seq oracle for inputs only ever materialized in chunks, at n >= 8x the
largest chunk; sketch merge is bitwise order-invariant; rank/value bounds
are exact on random AND adversarial streams; the n/2^bits rank-error form
holds for streams without heavy resolved intervals (full-range uniform).
"""

import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.streaming import (
    RadixSketch,
    as_chunk_source,
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.utils import datagen


def _chunks(x, nchunks):
    return [np.ascontiguousarray(c) for c in np.array_split(x, nchunks)]


STREAM_DTYPES = [
    np.int32,
    np.uint32,
    np.int16,
    np.float32,
    np.float16,
    # 64-bit dtypes stream WITHOUT x64: counts accumulate host-side in
    # numpy int64 and the auto method falls back to host histograms (the
    # device path is exercised under x64 below)
    np.int64,
    np.float64,
]


@pytest.mark.parametrize("dtype", STREAM_DTYPES, ids=lambda d: np.dtype(d).name)
def test_chunked_matches_oracle_across_dtypes(dtype, rng):
    n = 1 << 14
    if np.dtype(dtype).kind == "f":
        x = (rng.standard_normal(n) * 100).astype(dtype)
    else:
        info = np.iinfo(dtype)
        x = rng.integers(info.min, info.max, size=n, dtype=np.int64).astype(dtype)
    chunks = _chunks(x, 8)
    for k in (1, 137, n // 2, n):
        got = streaming_kselect(chunks, k)
        want = seq.kselect_sort(x, k)
        assert got == want
        assert got.dtype == x.dtype


@pytest.mark.parametrize("pattern", datagen.PATTERNS)
def test_chunked_adversarial_patterns(pattern):
    dtype = np.float32 if pattern in ("normal", "funiform") else np.int32
    n = 1 << 14
    x = datagen.generate(n, pattern=pattern, seed=3, dtype=dtype)
    chunks = _chunks(x, 8)
    for k in (1, n // 3, n):
        assert streaming_kselect(chunks, k) == seq.kselect_sort(x, k)


def test_chunked_extremes_fixture():
    for name, x in datagen.adversarial_fixtures(1 << 13, dtype=np.int32, seed=5):
        k = x.size // 2
        assert streaming_kselect(_chunks(x, 8), k) == seq.kselect_sort(x, k), name


def test_chunked_input_never_materialized(rng):
    """Acceptance criterion: exact at n >= 8x the largest single chunk, with
    the data only ever produced chunk-by-chunk from a replayable callable
    (chunk i regenerated from its own seed on every pass)."""
    chunk, nchunks = 1 << 13, 16
    n = chunk * nchunks

    def make(i):
        r = np.random.default_rng(1000 + i)
        return r.integers(-(2**31), 2**31, size=chunk, dtype=np.int64).astype(
            np.int32
        )

    source = lambda: (make(i) for i in range(nchunks))
    k = n // 2
    got = streaming_kselect(source, k)
    x = np.concatenate([make(i) for i in range(nchunks)])  # oracle only
    assert n >= 8 * chunk
    assert got == seq.kselect_sort(x, k)
    less, leq = streaming_rank_certificate(source, got)
    assert less < k <= leq


def test_chunked_device_chunks(rng):
    import jax.numpy as jnp

    x = rng.integers(-(2**31), 2**31, size=1 << 14, dtype=np.int64).astype(np.int32)
    dchunks = [jnp.asarray(c) for c in _chunks(x, 8)]
    k = 4321
    assert streaming_kselect(dchunks, k) == seq.kselect_sort(x, k)


def test_chunked_64bit_device_path_under_x64(rng):
    from mpi_k_selection_tpu.utils import x64

    x = rng.integers(-(2**62), 2**62, size=1 << 13, dtype=np.int64)
    k = x.size // 2
    with x64.enable_x64():
        got = streaming_kselect(_chunks(x, 8), k, hist_method="auto")
    assert got == seq.kselect_sort(x, k)


def test_chunked_full_pass_schedule_on_duplicates():
    # all-equal stream: the population never fits any budget, so every
    # radix pass runs and the fully-resolved prefix IS the answer
    x = np.full(1 << 13, 42, dtype=np.int32)
    assert streaming_kselect(_chunks(x, 8), 17, collect_budget=4) == 42


def test_chunked_tiny_budget_multi_pass(rng):
    x = rng.integers(-(2**31), 2**31, size=1 << 14, dtype=np.int64).astype(np.int32)
    k = x.size // 3
    got = streaming_kselect(_chunks(x, 8), k, collect_budget=64)
    assert got == seq.kselect_sort(x, k)


def test_chunked_empty_and_single_chunk_edges(rng):
    x = rng.integers(0, 1000, size=257, dtype=np.int64).astype(np.int32)
    # empty chunks interspersed are no-ops
    chunks = [x[:100], np.empty(0, np.int32), x[100:], np.empty(0, np.int32)]
    assert streaming_kselect(chunks, 19) == seq.kselect_sort(x, 19)
    # a single chunk degenerates to resident selection
    assert streaming_kselect([x], 19) == seq.kselect_sort(x, 19)
    # all-empty / empty-list streams are errors
    with pytest.raises(ValueError, match="non-empty"):
        streaming_kselect([np.empty(0, np.int32)], 1)
    with pytest.raises(ValueError, match="non-empty"):
        streaming_kselect([], 1)


def test_chunked_input_validation(rng):
    x = rng.integers(0, 1000, size=64, dtype=np.int64).astype(np.int32)
    with pytest.raises(ValueError, match="out of range"):
        streaming_kselect([x], 0)
    with pytest.raises(ValueError, match="out of range"):
        streaming_kselect([x], 65)
    # a one-shot iterator is first-class via the spill store (ISSUE 5);
    # the replay-path rejection remains under spill="off", and now points
    # at the spill knob
    assert streaming_kselect(iter([x]), 1) == seq.kselect_sort(x, 1)
    with pytest.raises(TypeError, match="one-shot iterator"):
        streaming_kselect(iter([x]), 1, spill="off")
    with pytest.raises(TypeError, match="one dtype"):
        streaming_kselect([x, x.astype(np.float32)], 1)
    with pytest.raises(ValueError, match="must divide"):
        streaming_kselect([x], 1, radix_bits=7)


def test_many_matches_single_and_oracle(rng):
    """The shared-pass multi-rank descent: every rank's answer equals both
    the single-rank streaming path and the seq oracle, including ranks that
    share a first-level bucket, duplicated ranks, and the extremes."""
    n = 1 << 14
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    chunks = _chunks(x, 8)
    ks = [1, 2, 137, n // 2, n // 2 + 1, n // 2, n]
    got = streaming_kselect_many(chunks, ks)
    assert got == [seq.kselect_sort(x, k) for k in ks]
    assert got == [streaming_kselect(chunks, k) for k in ks]
    assert streaming_kselect_many(chunks, []) == []


def test_many_tiny_budget_divergent_prefixes(rng):
    # a tiny budget forces deep descents whose prefixes diverge, exercising
    # the per-prefix histogram groups and the multi-spec shared collect
    x = rng.integers(-(2**31), 2**31, size=1 << 14, dtype=np.int64).astype(np.int32)
    chunks = _chunks(x, 8)
    ks = [7, x.size // 4, x.size // 2, x.size - 3]
    got = streaming_kselect_many(chunks, ks, collect_budget=64)
    assert got == [seq.kselect_sort(x, k) for k in ks]
    # all-duplicate stream: every rank runs the full schedule, no collect
    y = np.full(1 << 13, -9, dtype=np.int32)
    assert streaming_kselect_many(_chunks(y, 4), [1, 100], collect_budget=4) == [-9, -9]


def test_many_device_chunks_divergent_prefixes(rng):
    # device chunks + tiny budget: deep multi-prefix passes run through the
    # shared-sweep device histogram (multi_masked_radix_histogram), one
    # chunk read serving every surviving prefix
    import jax.numpy as jnp

    x = rng.integers(-(2**31), 2**31, size=1 << 14, dtype=np.int64).astype(np.int32)
    dchunks = [jnp.asarray(c) for c in _chunks(x, 8)]
    ks = [7, x.size // 4, x.size // 2, x.size - 3]
    got = streaming_kselect_many(dchunks, ks, collect_budget=64)
    assert got == [seq.kselect_sort(x, k) for k in ks]


def test_unstable_source_raises_mid_descent(rng):
    """A source that yields different data on each replay must fail loudly
    on the FIRST re-streamed histogram pass (population under the surviving
    prefix no longer matches), not walk a corrupt histogram to a silently
    wrong answer or a collect-time surprise."""
    calls = [0]

    def source():
        calls[0] += 1
        r = np.random.default_rng(calls[0])  # different stream every replay
        yield r.integers(-(2**31), 2**31, size=1 << 13, dtype=np.int64).astype(
            np.int32
        )

    with pytest.raises(RuntimeError, match="not replay-stable"):
        streaming_kselect(source, 1 << 12, collect_budget=4)


def test_many_validates_every_rank(rng):
    x = rng.integers(0, 1000, size=64, dtype=np.int64).astype(np.int32)
    with pytest.raises(ValueError, match="out of range"):
        streaming_kselect_many([x], [1, 65])


# -- RadixSketch -----------------------------------------------------------


def _sketches_over(x, parts, **kw):
    out = []
    for c in np.array_split(x, parts):
        out.append(RadixSketch(x.dtype, **kw).update(c))
    return out


def test_sketch_merge_is_order_invariant(rng):
    x = rng.integers(-(2**31), 2**31, size=1 << 14, dtype=np.int64).astype(np.int32)
    s1, s2, s3 = _sketches_over(x, 3)
    a = s1.merge(s2).merge(s3)  # ((1+2)+3)
    b = s1.merge(s2.merge(s3))  # (1+(2+3))  -- associativity
    c = s3.merge(s1).merge(s2)  # permuted    -- commutativity
    d = s1 + s2 + s3
    assert a == b == c == d  # bitwise: counts, n, extremes
    # merge is pure: operands unchanged, and the merged sketch equals one
    # accumulated sequentially over the whole stream
    whole = RadixSketch(np.int32).update(x)
    assert a == whole and s1 != whole


def test_sketch_merge_empty_identity(rng):
    x = rng.integers(0, 10**6, size=1000, dtype=np.int64).astype(np.int32)
    s = RadixSketch(np.int32).update(x)
    empty = RadixSketch(np.int32)
    assert s.merge(empty) == s == empty.merge(s)
    assert empty.n == 0
    with pytest.raises(ValueError, match="empty sketch"):
        empty.rank_bounds(1)


def test_sketch_incompatible_merge_raises():
    with pytest.raises(ValueError, match="incompatible"):
        RadixSketch(np.int32).merge(RadixSketch(np.float32))
    with pytest.raises(ValueError, match="incompatible"):
        RadixSketch(np.int32, radix_bits=4).merge(RadixSketch(np.int32, radix_bits=2))
    with pytest.raises(TypeError):
        RadixSketch(np.int32).merge(object())


def test_sketch_fixed_size_cap():
    with pytest.raises(ValueError, match="fixed-size"):
        RadixSketch(np.int32, radix_bits=8, levels=4)  # 32 bits > cap
    with pytest.raises(ValueError, match="exceeds"):
        RadixSketch(np.int16, radix_bits=8, levels=3)  # 24 > key bits


def _true_rank_lt(x, v):
    """#elements < v in key order (ties with v excluded), matching the
    sketch's key-space comparisons."""
    from mpi_k_selection_tpu.utils import dtypes as _dt

    keys = _dt.np_to_sortable_bits(x)
    vkey = _dt.np_to_sortable_bits(np.asarray([v], x.dtype))[0]
    return int(np.count_nonzero(keys < vkey))


@pytest.mark.parametrize(
    "pattern", ["uniform", "sequential", "equal", "descending", "normal"]
)
def test_sketch_bounds_exact_on_any_stream(pattern):
    """The distribution-free guarantees: rank_bounds brackets k exactly and
    value_bounds brackets the true k-th value — including adversarial
    duplicate-heavy streams — and the point estimate's rank error never
    exceeds rank_error_bound (the answering bucket's population)."""
    dtype = np.float32 if pattern == "normal" else np.int32
    n = 1 << 14
    x = datagen.generate(n, pattern=pattern, seed=11, dtype=dtype)
    sk = RadixSketch(dtype)
    for c in np.array_split(x, 7):
        sk.update(c)
    s = np.sort(x, kind="stable")
    for k in (1, n // 100, n // 2, n - 1, n):
        lo, hi = sk.rank_bounds(k)
        assert lo < k <= hi
        vlo, vhi = sk.value_bounds(k)
        want = s[k - 1]
        assert vlo <= want <= vhi
        est = sk.query(k)
        err = abs(_true_rank_lt(x, est) - (k - 1))
        assert err <= sk.rank_error_bound(k)


def test_sketch_rank_error_bound_random_stream(rng):
    """The advertised n / 2^bits form on a stream with no heavy resolved
    interval: full-range uniform int32 keys spread ~evenly over the
    deepest level, so the max bucket population (== the sketch-wide rank
    error bound) sits within a small constant of n / 2^resolution_bits."""
    n = 1 << 16
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    sk = RadixSketch(np.int32, radix_bits=4, levels=3)  # 12 bits resolved
    sk.update(x)
    per_bucket = n / (1 << sk.resolution_bits)  # = 16
    assert sk.max_bucket_population() <= 8 * per_bucket
    s = np.sort(x)
    for q in (0.01, 0.25, 0.5, 0.9, 0.99):
        k = max(1, int(np.ceil(q * n)))
        est = sk.query(k)
        err = abs(_true_rank_lt(x, est) - (k - 1))
        assert err <= 8 * per_bucket


def test_sketch_quantiles_and_refine(rng):
    n = 1 << 14
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    chunks = _chunks(x, 8)
    sk = RadixSketch(np.int32)
    for c in chunks:
        sk.update(c)
    qs = [0.5, 0.9, 0.99]
    approx = sk.quantiles(qs)
    assert len(approx) == 3
    # refine reuses the chunked path seeded by the sketch: bit-exact
    from mpi_k_selection_tpu.api import quantile_ranks

    for q, k in zip(qs, quantile_ranks(qs, n)):
        assert sk.refine(chunks, k) == seq.kselect_sort(x, k)


def test_streaming_quantiles_api(rng):
    from mpi_k_selection_tpu import StreamingQuantiles, kselect_streaming

    n = 1 << 14
    x = rng.integers(0, 10**8, size=n, dtype=np.int64).astype(np.int32)
    chunks = _chunks(x, 8)
    t1 = StreamingQuantiles(np.int32).update(chunks[0]).update(chunks[1])
    t2 = StreamingQuantiles(np.int32)
    for c in chunks[2:]:
        t2.update(c)
    t = t1.merge(t2)
    assert t.n == n
    qs = [0.5, 0.99]
    exact = t.refine_quantiles(qs, chunks)
    s = np.sort(x, kind="stable")
    from mpi_k_selection_tpu.api import quantile_ranks

    assert exact == [s[k - 1] for k in quantile_ranks(qs, n)]
    # api-level chunked entry
    assert kselect_streaming(chunks, n // 2) == s[n // 2 - 1]


def test_sketch_refine_radix_bits_divides_remaining_not_total(rng):
    """Seeded descents only need radix_bits to divide the bits BELOW the
    sketch's resolved prefix: rb=5 doesn't divide 32 key bits but does
    divide the 20 left under a 12-bit sketch — valid and exact."""
    x = rng.integers(-(2**31), 2**31, size=1 << 13, dtype=np.int64).astype(np.int32)
    chunks = _chunks(x, 8)
    sk = RadixSketch(np.int32, radix_bits=4, levels=3)  # 12 resolved bits
    for c in chunks:
        sk.update(c)
    k = x.size // 2
    assert sk.refine(chunks, k, radix_bits=5, collect_budget=64) == seq.kselect_sort(x, k)
    with pytest.raises(ValueError, match="must divide"):
        sk.refine(chunks, k, radix_bits=7)  # 20 % 7 != 0


def test_sketch_float64_stream_no_x64(rng):
    """Host-side sketch + refine over float64 chunks works (and stays
    bit-exact) without ever enabling x64 — keys never touch the device."""
    x = rng.standard_normal(1 << 13)
    chunks = _chunks(x, 8)
    sk = RadixSketch(np.float64)
    for c in chunks:
        sk.update(c)
    k = x.size // 2
    lo, hi = sk.rank_bounds(k)
    assert lo < k <= hi
    assert sk.refine(chunks, k) == seq.kselect_sort(x, k)


def test_distributed_sketch_matches_host_sketch(rng):
    """Sharded merge on the virtual mesh: per-shard device histograms
    psum-merged into a sketch bitwise-equal to sequential host updates over
    the same (ragged — exercises the tail fold) array."""
    import jax.numpy as jnp

    from mpi_k_selection_tpu.parallel import distributed_sketch, make_mesh

    n = (1 << 13) - 5
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    mesh = make_mesh()
    assert mesh.size == 8
    dsk = distributed_sketch(jnp.asarray(x), mesh=mesh)
    assert dsk == RadixSketch(np.int32).update(x)
    xf = rng.standard_normal(1 << 12).astype(np.float32)
    dskf = distributed_sketch(jnp.asarray(xf), mesh=mesh, radix_bits=4, levels=3)
    assert dskf == RadixSketch(np.float32, radix_bits=4, levels=3).update(xf)


def test_distributed_sketch_nan_and_signed_zero_extremes(rng):
    """Extremes are taken in KEY space on device, so streams containing NaN
    and -0.0/+0.0 (where value-space min/max diverge from the keys' total
    order) still produce a sketch bitwise-equal to host accumulation."""
    import jax.numpy as jnp

    from mpi_k_selection_tpu.parallel import distributed_sketch, make_mesh

    x = rng.standard_normal(1 << 12).astype(np.float32)
    x[17] = np.nan
    x[100] = -0.0
    x[200] = +0.0
    dsk = distributed_sketch(jnp.asarray(x), mesh=make_mesh())
    assert dsk == RadixSketch(np.float32).update(x)


def test_distributed_sketch_64bit_no_x64(rng):
    """64-bit host input with x64 OFF must not be silently narrowed by the
    device cast (jnp.asarray would truncate int64->int32): the sketch falls
    back to exact host accumulation, keeping dtype and counts bitwise equal
    to the host sketch."""
    from mpi_k_selection_tpu.parallel import distributed_sketch

    x = rng.integers(-(2**62), 2**62, size=(1 << 12) + 3, dtype=np.int64)
    dsk = distributed_sketch(x)
    assert dsk.dtype == np.int64
    assert dsk == RadixSketch(np.int64).update(x)


def test_distributed_sketch_64bit_device_path_under_x64(rng):
    from mpi_k_selection_tpu.parallel import distributed_sketch, make_mesh
    from mpi_k_selection_tpu.utils import x64

    x = rng.integers(-(2**62), 2**62, size=(1 << 12) - 1, dtype=np.int64)
    with x64.enable_x64():
        import jax.numpy as jnp

        dsk = distributed_sketch(jnp.asarray(x), mesh=make_mesh())
    assert dsk == RadixSketch(np.int64).update(x)


def test_dcn_sketch_merge_single_process_degenerate(rng):
    """Multi-host sketch merge: in a single-process job the DCN merge is
    the exact identity (no allgather, same object), and the virtual
    8-device mesh does NOT count as process-spanning."""
    from mpi_k_selection_tpu.parallel import dcn_merge_sketch, make_mesh
    from mpi_k_selection_tpu.parallel.sketch import _mesh_spans_processes

    x = rng.integers(-(2**31), 2**31, size=1 << 12, dtype=np.int64).astype(np.int32)
    sk = RadixSketch(np.int32).update(x)
    assert dcn_merge_sketch(sk) is sk
    assert _mesh_spans_processes(make_mesh()) is False
    # the 64-bit host fallback still lands on the degenerate single-process
    # route end-to-end (the dcn wiring must not disturb it)
    from mpi_k_selection_tpu.parallel import distributed_sketch

    y = rng.integers(-(2**62), 2**62, size=(1 << 10) + 3, dtype=np.int64)
    assert distributed_sketch(y) == RadixSketch(np.int64).update(y)


def test_dcn_merge_decode_loop_multiprocess_simulated(rng):
    """The multi-process fold itself (pack -> 32-bit wire -> unpack/fold),
    exercised without a real multi-host job: gathered rows for three fake
    processes — two with data, one empty (its extremes must be masked
    out) — must fold to exactly the pairwise host merge."""
    from mpi_k_selection_tpu.parallel.sketch import (
        _pack_sketch_payload,
        _split_u32,
        _unpack_gathered_payloads,
    )

    x = rng.integers(-(2**31), 2**31, size=1 << 12, dtype=np.int64).astype(np.int32)
    s1 = RadixSketch(np.int32).update(x[: 1 << 11])
    s2 = RadixSketch(np.int32).update(x[1 << 11 :])
    empty = RadixSketch(np.int32)
    gathered = np.stack(
        [_split_u32(_pack_sketch_payload(s)) for s in (s1, empty, s2)]
    )
    merged = _unpack_gathered_payloads(gathered, s1)
    assert merged == s1.merge(s2) == RadixSketch(np.int32).update(x)
    # all-empty job: a valid (empty) sketch, not a crash
    allempty = _unpack_gathered_payloads(
        np.stack([_split_u32(_pack_sketch_payload(empty))] * 2), empty
    )
    assert allempty == RadixSketch(np.int32)


def test_dcn_wire_format_roundtrips_full_int64_range():
    """The DCN payload ships int64 counts / uint64 keys as uint32 lo/hi
    words so an x64-off process cannot truncate them: the packing must
    round-trip the FULL width bit-exactly (the silent failure it prevents
    is exactly the KSL002 class)."""
    from mpi_k_selection_tpu.parallel.sketch import _join_u32, _split_u32

    vals = np.asarray(
        [0, 1, (1 << 31) - 1, 1 << 31, (1 << 32) + 7, (1 << 62) + 12345,
         (1 << 63) - 1],
        np.int64,
    )
    got = _join_u32(_split_u32(vals))
    np.testing.assert_array_equal(got.astype(np.int64), vals)
    keys = np.asarray([0, 1 << 63, ~np.uint64(0)], np.uint64)
    np.testing.assert_array_equal(_join_u32(_split_u32(keys)), keys)


def test_cli_streaming_mode(capsys):
    from mpi_k_selection_tpu import cli

    rc = cli.main(
        [
            "--backend", "tpu", "--streaming", "--n", "100000",
            "--chunk-elems", "9973", "--verify", "--check", "--json",
        ]
    )
    assert rc == 0
    import json

    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["algorithm"] == "streaming-chunked"
    assert rec["extra"]["exact_match"] is True
    assert rec["extra"]["certificate_ok"] is True
    assert rec["extra"]["chunks"] == 11


# -- the adaptive width schedule + prefix-packed spill grid -------------------


@pytest.mark.parametrize("width_schedule", ["auto", "off"])
@pytest.mark.parametrize("pack_spill", ["auto", "off"])
def test_width_pack_grid_bit_identical(width_schedule, pack_spill, rng):
    """devices {1,2} x depth {0,2} x spill {off,force} x the two new
    knobs, over float32 and uint64 streams: every leg is bit-identical
    to the seq oracle (the knob-off legs double as the legacy anchor)."""
    n = 1 << 13
    for dtype in (np.float32, np.uint64):
        if np.dtype(dtype).kind == "f":
            x = (rng.standard_normal(n) * 100).astype(dtype)
        else:
            x = rng.integers(0, 1 << 63, size=n, dtype=np.int64).astype(dtype)
        ks = [1, 1337, n // 2, n]
        want = [seq.kselect_sort(x, k) for k in ks]
        chunks = _chunks(x, 8)
        for devices in (1, 2):
            for depth in (0, 2):
                for spill in ("off", "force"):
                    got = streaming_kselect_many(
                        chunks, ks, pipeline_depth=depth, devices=devices,
                        spill=spill, collect_budget=256,
                        width_schedule=width_schedule, pack_spill=pack_spill,
                    )
                    assert [np.asarray(g).item() for g in got] == [
                        np.asarray(w).item() for w in want
                    ], (dtype, devices, depth, spill)


@pytest.mark.parametrize("fused", ["kernel", "xla", "off"])
def test_width_pack_fused_tiers_bit_identical(fused, rng):
    """The knobs compose with every fused ingest tier: wide digits route
    per-bucket counting through the tiers' supported widths (the rb <= 8
    kernel rule downgrades wide passes to the scatter path) with
    bit-identical answers."""
    n = 1 << 13
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    ks = [7, n // 3]
    want = [seq.kselect_sort(x, k) for k in ks]
    got = streaming_kselect_many(
        _chunks(x, 8), ks, pipeline_depth=2, devices=2, spill="force",
        fused=fused, collect_budget=256, width_schedule="auto",
        pack_spill="auto",
    )
    assert [np.asarray(g).item() for g in got] == [
        np.asarray(w).item() for w in want
    ]


def test_width_schedule_tuple_and_one_shot(rng):
    """An explicit per-pass width tuple resolves the full key width, and
    a ONE-SHOT generator source runs the packed spill descent end to
    end; a tuple that does not sum to the key width is refused."""
    n = 1 << 13
    x = rng.integers(0, 1 << 63, size=n, dtype=np.int64).astype(np.uint64)
    want = seq.kselect_sort(x, 999)
    got = streaming_kselect(
        iter(_chunks(x, 8)), 999, collect_budget=128,
        width_schedule=(16, 16, 16, 8, 8), pack_spill="auto",
    )
    assert got == want
    with pytest.raises(ValueError, match="resolves"):
        streaming_kselect(
            _chunks(x, 8), 999, width_schedule=(16, 16), collect_budget=128
        )
    with pytest.raises(ValueError, match="outside"):
        streaming_kselect(_chunks(x, 8), 999, width_schedule=(64,))


def test_knobs_off_is_byte_for_byte_legacy(rng):
    """width_schedule='off' + pack_spill='off' IS the legacy descent:
    the spilled pass_log (passes, logical AND physical byte columns) of
    an explicit knobs-off run equals a defaults run entry for entry."""
    from mpi_k_selection_tpu.streaming import SpillStore

    n = 1 << 13
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)

    def run(**kw):
        store = SpillStore()
        try:
            got = streaming_kselect(
                _chunks(x, 8), n // 2, spill=store, collect_budget=128, **kw
            )
            return np.asarray(got).item(), list(store.pass_log)
        finally:
            store.close()

    got_default, log_default = run()
    got_off, log_off = run(width_schedule="off", pack_spill="off")
    assert got_default == got_off == np.asarray(seq.kselect_sort(x, n // 2)).item()
    assert log_off == log_default
    for entry in log_off:
        # unpacked physical == logical on every write, byte for byte
        if entry.get("bytes_written") is not None:
            assert entry["disk_bytes_written"] == entry["bytes_written"]


def test_sketch_seeded_descent_with_knobs(rng):
    """A sketch-seeded refine under both knobs: the wide schedule starts
    below the sketch's resolved depth, and the packed tee's segments
    prune the refine's first pass — bit-identical to the plain refine."""
    n = 1 << 13
    x = (rng.standard_normal(n) * 50).astype(np.float32)
    sk = RadixSketch(np.float32)
    for c in _chunks(x, 8):
        sk.update(c)
    want = seq.kselect_sort(x, n // 4)
    got = sk.refine(
        _chunks(x, 8), n // 4, collect_budget=128,
        width_schedule="auto", pack_spill="auto", spill="force",
    )
    assert got == want
