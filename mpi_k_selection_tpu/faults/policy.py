"""Resilience policies — bounded retry with injectable backoff.

The reference aborts the whole program on any anomaly (``MPI_Abort``);
the streaming vertical instead classifies failures and retries exactly
the transient class — :class:`~mpi_k_selection_tpu.errors.
TransientError` plus ``ConnectionError``/``TimeoutError`` by default —
with bounded exponential backoff through the injectable sleeper
(faults/sleeper.py; no raw ``time.sleep``, KSL012). Everything else
propagates immediately: retrying a logic error just repeats it, slower.

Two shapes of retry live here:

- :func:`retry_call` — retry ONE operation in place (the staging
  ``device_put``, where the host buffer is still in hand and re-issuing
  the transfer is free);
- :func:`resilient_source` — the mid-pass re-pull for replayable chunk
  sources: a transient error while pulling chunk *i* re-invokes the
  source callable, fast-forwards past the *i* chunks already consumed
  (replay-stability is already a hard contract of the descent — the
  downstream expected-count checks fail loudly if the re-pull drifts),
  and resumes the pass WITHOUT restarting it. Exhaustion raises the
  typed :class:`~mpi_k_selection_tpu.errors.RetryExhaustedError` with
  the last failure as ``__cause__``.

Pass-level recovery (re-running a whole streamed pass from the previous
spill generation, the corrupt-record ladder, the ENOSPC downgrade) is
descent-shaped and lives with the descent
(streaming/chunked.py:_recover_pass); it consumes this module's policy
for its attempt bounds and backoff.
"""

from __future__ import annotations

import dataclasses

from mpi_k_selection_tpu.errors import RetryExhaustedError, TransientError
from mpi_k_selection_tpu.faults.sleeper import resolve_sleeper
from mpi_k_selection_tpu.obs import flight as _flight
from mpi_k_selection_tpu.obs.wiring import fault_event

#: Exception classes the default policy treats as transient. Deliberately
#: narrow: plain RuntimeError/ValueError are logic errors, SpillRecordError
#: has its own (re-read -> rebuild) ladder, and OSError-at-large would
#: swallow ENOSPC, which has its own downgrade path.
DEFAULT_RETRYABLE = (TransientError, ConnectionError, TimeoutError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry configuration. ``max_attempts`` counts TOTAL tries
    (3 = one original + two retries); backoff before retry *r* (1-based)
    is ``min(backoff_base * 2**(r-1), backoff_max)`` seconds through
    ``sleeper`` (None = the real package sleeper; tests pass a
    :class:`~mpi_k_selection_tpu.faults.sleeper.VirtualSleeper`)."""

    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    retryable: tuple = DEFAULT_RETRYABLE
    sleeper: object = None

    def __post_init__(self):
        if int(self.max_attempts) < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.backoff_base < 0 or self.backoff_max < 0:
            raise ValueError("backoff bounds must be >= 0")

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, tuple(self.retryable))

    def backoff(self, retry: int) -> float:
        """Seconds to wait before retry number ``retry`` (1-based)."""
        return min(self.backoff_base * (2.0 ** max(0, retry - 1)), self.backoff_max)

    def sleep(self, retry: int) -> None:
        resolve_sleeper(self.sleeper).sleep(self.backoff(retry))


#: The package default: 3 total attempts, 50 ms doubling backoff capped
#: at 2 s. ``retry=None`` on the streaming entry points resolves here.
DEFAULT_RETRY = RetryPolicy()


def resolve_retry(retry):
    """Normalize the ``retry`` knob: ``None``/``"default"`` ->
    :data:`DEFAULT_RETRY`, ``"off"``/``False`` -> ``None`` (fail on the
    first transient, the pre-resilience behavior), a
    :class:`RetryPolicy` passes through."""
    if retry is None or retry == "default":
        return DEFAULT_RETRY
    if retry == "off" or retry is False:
        return None
    if isinstance(retry, RetryPolicy):
        return retry
    raise ValueError(
        f"retry must be None, 'default', 'off', or a RetryPolicy, got "
        f"{retry!r}"
    )


def _emit_retry(obs, site, retry, exc) -> None:
    fault_event(
        obs, site, "retry", exc=exc, attempt=retry,
        counter="faults.retries", labels={"site": site},
    )


def retry_call(fn, policy: RetryPolicy | None, *, site: str, obs=None):
    """Run ``fn()`` under ``policy``: transient failures are retried in
    place with backoff, up to ``policy.max_attempts`` total tries; the
    exhausted form raises :class:`RetryExhaustedError` (last failure as
    ``__cause__``). ``policy=None`` is a plain call."""
    if policy is None:
        return fn()
    last = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except BaseException as e:
            if not policy.is_retryable(e):
                raise
            last = e
            retry = attempt + 1
            if retry >= policy.max_attempts:
                break
            _emit_retry(obs, site, retry, e)
            policy.sleep(retry)
    exhausted = RetryExhaustedError(
        f"{site}: still failing after {policy.max_attempts} attempts "
        f"({type(last).__name__}: {last})",
        site=site,
        attempts=policy.max_attempts,
    )
    # the fault-triggered debug bundle (obs/flight.py): every terminal
    # retry exhaustion freezes the postmortem ring ONCE per flight
    # recorder, whichever site exhausts first — a no-op without one
    _flight.auto_dump(obs, "retry-exhausted", exc=exhausted)
    raise exhausted from last


def resilient_source(src, policy: RetryPolicy | None, *, obs=None):
    """Wrap a REPLAYABLE chunk-source callable with mid-pass re-pull:
    a transient error while pulling chunk *i* re-invokes ``src()``,
    fast-forwards the fresh iterator past the *i* chunks this pass
    already consumed, and resumes — the pass never restarts, and the
    downstream replay-stability checks (expected per-prefix counts)
    guarantee a drifting re-pull fails loudly rather than answering
    wrong. Transient errors during the fast-forward count against the
    same budget. ``policy=None`` returns ``src`` unchanged.

    Only for replayable sources: a one-shot iterator cannot be
    re-invoked (the spill path's recovery for those is the gen-0 tee —
    streaming/chunked.py)."""
    if policy is None:
        return src

    def wrapped():
        def gen():
            it = iter(src())
            i = 0  # chunks successfully handed downstream
            # the budget is per INCIDENT, not per stream: a successful
            # pull resets it, so isolated transients on a long stream
            # never accumulate into a spurious exhaustion — only
            # max_attempts consecutive failures around one chunk exhaust
            retries = 0

            def _absorb(e, doing: str) -> None:
                """One failure against the incident budget: re-raise
                non-retryables, raise the typed exhausted form past the
                budget, else emit the retry event and back off."""
                nonlocal retries
                if not policy.is_retryable(e):
                    raise e
                retries += 1
                if retries >= policy.max_attempts:
                    exhausted = RetryExhaustedError(
                        f"chunk source: {doing} still failing after "
                        f"{policy.max_attempts} attempts "
                        f"({type(e).__name__}: {e})",
                        site="source",
                        attempts=policy.max_attempts,
                    )
                    # same postmortem hook as retry_call: at most one
                    # bundle per flight recorder, never raises
                    _flight.auto_dump(obs, "retry-exhausted", exc=exhausted)
                    raise exhausted from e
                _emit_retry(obs, "source", retries, e)
                policy.sleep(retries)

            while True:
                try:
                    chunk = next(it)
                except StopIteration:
                    return
                except BaseException as e:
                    _absorb(e, f"pulling chunk {i}")
                    # re-pull: fresh iterator, skip the chunks already
                    # consumed (faults during the skip share the
                    # incident's budget)
                    it = iter(src())
                    skipped = 0
                    while skipped < i:
                        try:
                            next(it)
                            skipped += 1
                        except StopIteration:
                            raise RuntimeError(
                                "chunk source is not replay-stable: the "
                                f"re-pulled stream ended after {skipped} "
                                f"chunks, {i} were already consumed"
                            ) from e
                        except BaseException as e2:
                            _absorb(e2, "the re-pull")
                            it = iter(src())
                            skipped = 0
                    continue
                yield chunk
                i += 1
                retries = 0  # incident over: the next chunk gets a full budget

        return gen()

    return wrapped
