"""Cross-request batcher — one dispatch thread, bounded coalescing window.

Many clients issue small rank queries against the same resident dataset;
the backend's cheapest shape for that is ONE shared-pass
``kselect_many`` walk (ops/radix.py shares the prepared key view and
every histogram pass across all ranks, and
``api.many_sort_dispatch_queries`` already says when a wide-enough batch
should flip to one sort). This module turns concurrent arrivals into
that shape:

- **One dispatch thread** (``ksel-serve-dispatch-*``) owns ALL device
  work. Requests enqueue and block on a per-request event; the thread
  drains the queue, coalesces, executes, and wakes them. Serializing
  device work on one thread is what makes concurrent answers
  bit-identical to serial execution: there is no interleaving to vary.
- **Bounded coalescing window**: when the first request of a batch
  arrives the thread waits at most ``window`` seconds (a plain
  ``Event.wait`` — KSL004: no raw clock reads here) for more to arrive,
  then drains up to ``max_batch`` pending requests. ``window=0`` is the
  no-coalescing extreme (every request dispatches alone — the latency
  floor); a large window is the full-coalescing extreme (every
  concurrent request rides one walk — the throughput ceiling). Answers
  are bit-identical at every window because exact order statistics do
  not depend on which batch computed them.
- **Grouping**: drained requests coalesce only within (dataset, kind) —
  rank queries (kselect/quantiles, already rank-converted by the
  server) against the same dataset merge their ks into one
  ``select_many`` call; non-rank ops (topk, rank certificates) execute
  one at a time, still on the dispatch thread. Arrival order is
  preserved within and across groups.

The thread is joined on ``close()`` on every exit path — the conftest
leaked-thread fixture enforces the same discipline as for
``ksel-pipeline-*`` producers.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading

from mpi_k_selection_tpu.serve.errors import ServerClosedError

#: Every serving-layer thread (dispatch, HTTP serve loop, HTTP request
#: handlers) carries this prefix; tests assert none outlives its server.
SERVE_THREAD_PREFIX = "ksel-serve"

#: Coalescing-window ceiling (seconds) — a minute-long window is a
#: misconfiguration, not a batching strategy.
MAX_WINDOW = 60.0

#: Queue-drain ceiling per dispatch round.
DEFAULT_MAX_BATCH = 1024


@dataclasses.dataclass
class PendingQuery:
    """One enqueued request. ``kind`` is ``"rank"`` (ks carries the
    1-indexed ranks) or an op name executed singly. ``ds`` is the
    RESOLVED ResidentDataset the request validated against — carried by
    object so a concurrent drop+re-add of the same id cannot swap the
    data (and its n) out from under an in-flight request. ``run`` is the
    server-provided executor for non-rank ops. The dispatch thread fills
    exactly one of ``result``/``error`` and sets ``done``."""

    dataset_id: str
    kind: str
    ks: tuple = ()
    ds: object = None
    run: object = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: object = None
    error: BaseException | None = None

    def wait(self):
        """Block until dispatched; re-raise the dispatch error here (on
        the REQUEST thread) or return the result."""
        self.done.wait()
        if self.error is not None:
            raise self.error
        return self.result


def validate_window(window) -> float:
    w = float(window)
    if not 0.0 <= w <= MAX_WINDOW:
        raise ValueError(f"window={w} out of range [0, {MAX_WINDOW}] seconds")
    return w


class QueryBatcher:
    """The dispatch thread + queue. ``execute_ranks(items)``
    (server-provided) runs one coalesced rank group — all items share
    one resolved dataset object — and must fill every item's
    ``result``; ``observe`` hooks (queue depth at submit, batch width
    at dispatch) are optional metrics callbacks."""

    _ids = itertools.count()

    def __init__(
        self,
        execute_ranks,
        *,
        window: float = 0.0,
        max_batch: int = DEFAULT_MAX_BATCH,
        observe_depth=None,
        observe_width=None,
    ):
        self._execute_ranks = execute_ranks
        self.window = validate_window(window)
        self.max_batch = max(1, int(max_batch))
        self._observe_depth = observe_depth
        self._observe_width = observe_width
        self._q: queue.Queue = queue.Queue()
        # serializes submit's check+put against close's final drain, so a
        # submit racing close() either raises or its item is seen by the
        # drain — a queued request can never be left waiting forever
        self._submit_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run,
            name=f"{SERVE_THREAD_PREFIX}-dispatch-{next(self._ids)}",
            daemon=True,
        )
        self._thread.start()

    # -- request side ------------------------------------------------------

    def submit(self, item: PendingQuery) -> PendingQuery:
        with self._submit_lock:
            if self._stop.is_set():
                raise ServerClosedError("server is closed; query rejected")
            if self._observe_depth is not None:
                self._observe_depth(self._q.qsize())
            self._q.put(item)
        return item

    # -- dispatch thread ---------------------------------------------------

    def _run(self) -> None:
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            batch = [first]
            if self.window > 0.0:
                # bounded coalescing: wait once for concurrent arrivals
                # (Event.wait honors close() immediately), then drain
                self._stop.wait(self.window)
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self._q.get_nowait())
                    except queue.Empty:
                        break
            self._dispatch(batch)
            if self._stop.is_set() and self._q.empty():
                return

    def _dispatch(self, batch) -> None:
        """Group a drained batch by (dataset, kind) preserving arrival
        order, execute each group, and wake every request exactly once."""
        groups: dict = {}
        order = []
        for item in batch:
            # identity includes the dataset OBJECT: two requests that
            # resolved the same id across a drop+re-add must not share
            # one walk over whichever dataset happens to be current
            key = (item.dataset_id, item.kind, id(item.ds))
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(item)
        for key in order:
            kind = key[1]
            items = groups[key]
            try:
                if kind == "rank":
                    if self._observe_width is not None:
                        self._observe_width(sum(len(i.ks) for i in items))
                    self._execute_ranks(items)
                else:
                    for item in items:
                        item.result = item.run()
            except BaseException as e:
                for item in items:
                    if item.result is None:
                        item.error = e
            finally:
                for item in items:
                    item.done.set()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Stop accepting queries, let the dispatch thread finish what is
        queued, join it, and fail anything still pending (a request that
        raced the close) with :class:`ServerClosedError` so no client
        thread blocks forever. Idempotent."""
        self._stop.set()
        self._thread.join(timeout=30.0)
        # drain under the submit lock: any submit that won the race into
        # the queue is failed here; any submit after sees the stop flag
        with self._submit_lock:
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                item.error = ServerClosedError("server closed before dispatch")
                item.done.set()

    @property
    def closed(self) -> bool:
        return self._stop.is_set()
