"""Runtime ledger + flight recorder (obs/ledger.py, obs/flight.py,
ISSUE 14): compile & device-memory accounting, request-correlated trace
IDs, and the fault-triggered debug bundle.

The load-bearing contracts:

- **Bit-identity**: ledger + flight recorder + trace channel enabled
  returns identical bits across the devices {1,2} x depth {0,2} x
  spill {off,force} x fused {kernel,xla,off} grid — the same contract
  every prior obs channel carries.
- **Steady state**: a warmed resident serve burst reports ZERO ledger
  compiles/recompiles (all program-cache hits); a deliberately
  shape-churning run fires the typed ``RecompileStormEvent``.
- **Postmortem**: a seeded chaos run that exhausts retries auto-dumps
  exactly ONE debug bundle containing the triggering FaultEvents, the
  ledger, the metrics snapshot, and >= 2 trace thread tracks; the
  serve supervisor's DispatchCrashedError does the same.
- **Byte book**: staging/spill/resident gauges go up while buffers are
  live and return to zero when they are released, peaks retained.
"""

import json

import numpy as np
import pytest

from mpi_k_selection_tpu import faults
from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.errors import RetryExhaustedError
from mpi_k_selection_tpu.obs.events import FaultEvent, RecompileStormEvent
from mpi_k_selection_tpu.obs.flight import (
    BUNDLE_SECTIONS,
    FlightRecorder,
    auto_dump,
    build_bundle,
    resolve_flight,
)
from mpi_k_selection_tpu.obs.ledger import (
    LEDGER,
    ProgramLedger,
    collect_ledger,
    ledger_dispatch,
    snapshot_delta,
)
from mpi_k_selection_tpu.streaming.chunked import (
    streaming_kselect,
    streaming_kselect_many,
)

KW = dict(radix_bits=4, collect_budget=64)


def _chunks(rng, sizes=(4096, 2777, 1, 0, 2048)):
    return [
        rng.integers(-(2**31), 2**31 - 1, size=m, dtype=np.int32)
        for m in sizes
    ]


# ---------------------------------------------------------------------------
# ProgramLedger units


def test_dispatch_counts_compiles_then_hits():
    led = ProgramLedger()
    with led.dispatch("site", ("a", 1)) as compiled:
        assert compiled is True
    with led.dispatch("site", ("a", 1)) as compiled:
        assert compiled is False
    with led.dispatch("site", ("b", 2)) as compiled:
        assert compiled is True
    snap = led.snapshot()
    st = snap["sites"]["site"]
    assert st["compiles"] == 2
    assert st["hits"] == 1
    assert st["recompiles"] == 0
    assert st["distinct_keys"] == 2
    # compile walls accumulate through the sanctioned PhaseTimer route
    assert st["compile_seconds"] >= 0.0
    assert led.compile_seconds()["site"] >= 0.0


def test_note_hit_and_compile_span():
    led = ProgramLedger()
    with led.compile_span("cache", "k1"):
        pass
    led.note_hit("cache", "k1")
    led.note_hit("cache")  # keyless form
    st = led.snapshot()["sites"]["cache"]
    assert (st["compiles"], st["hits"]) == (1, 2)


def test_ledger_dispatch_helper_routes_to_private_ledger():
    led = ProgramLedger()
    with ledger_dispatch("unit.private.site", "k", ledger=led):
        pass
    assert led.snapshot()["sites"]["unit.private.site"]["compiles"] == 1
    # the process book never saw the private route's site
    assert "unit.private.site" not in LEDGER.snapshot()["sites"]


def test_storm_detector_fires_typed_event_into_sink():
    sink = obs_lib.ListSink()
    o = obs_lib.Observability(events=sink)
    led = ProgramLedger(storm_threshold=2)
    for i in range(5):  # 5 distinct keys: compiles 3, 4, 5 are storms
        with led.dispatch("churny", ("shape", i), obs=o):
            pass
    storms = sink.of_kind("ledger.recompile_storm")
    assert len(storms) == 3
    assert all(isinstance(e, RecompileStormEvent) for e in storms)
    assert [e.compiles for e in storms] == [3, 4, 5]
    assert all(e.site == "churny" and e.threshold == 2 for e in storms)
    snap = led.snapshot()
    assert snap["sites"]["churny"]["recompiles"] == 3
    # the ledger's own bounded ring retains them obs-independently
    assert len(snap["storms"]) == 3
    assert snap["storms"][0]["event"] == "ledger.recompile_storm"
    # repeats of a known key are hits, never storms
    with led.dispatch("churny", ("shape", 0), obs=o):
        pass
    assert len(sink.of_kind("ledger.recompile_storm")) == 3


def test_storm_key_strips_static_dimension_from_churn_identity():
    # the descent's per-level shift legitimately multiplies compiles in
    # ONE healthy run (levels x buckets) — a site passing storm_key with
    # that dimension stripped must never read as churn, while genuine
    # shape churn (distinct storm keys) still fires
    sink = obs_lib.ListSink()
    o = obs_lib.Observability(events=sink)
    led = ProgramLedger(storm_threshold=2)
    for shift in range(8):  # 8 levels, one bucket: distinct keys, ONE identity
        key = (4096, "uint32", 1, "device", shift, 4)
        with led.dispatch(
            "ingest.histogram", key, obs=o, storm_key=key[:4] + key[5:]
        ) as compiled:
            assert compiled  # each level really compiles...
    assert not sink.of_kind("ledger.recompile_storm")  # ...but no churn
    snap = led.snapshot()["sites"]["ingest.histogram"]
    assert snap["compiles"] == 8 and snap["recompiles"] == 0
    # genuine churn: distinct BUCKET sizes cross the threshold
    for n in (8192, 16384, 32768):
        key = (n, "uint32", 1, "device", 0, 4)
        with led.dispatch(
            "ingest.histogram", key, obs=o, storm_key=key[:4] + key[5:]
        ):
            pass
    storms = sink.of_kind("ledger.recompile_storm")
    assert len(storms) == 2  # identities 3 and 4 (threshold 2)
    assert [e.compiles for e in storms] == [3, 4]


def test_same_key_rebuilds_are_not_shape_churn():
    # compile_span re-compiling ONE legitimately-invalidated key (a
    # dataset dropped and re-added) is not churn: the detector counts
    # DISTINCT keys, as documented
    sink = obs_lib.ListSink()
    o = obs_lib.Observability(events=sink)
    led = ProgramLedger(storm_threshold=2)
    for _ in range(6):
        with led.compile_span("serve.programs", ("ds", 4096), obs=o):
            pass
    assert not sink.of_kind("ledger.recompile_storm")
    st = led.snapshot()["sites"]["serve.programs"]
    assert st["compiles"] == 6 and st["distinct_keys"] == 1
    assert st["recompiles"] == 0


def test_ledger_key_mirrors_are_bounded():
    # the process ledger lives forever: per-site key mirrors FIFO-evict
    # past MAX_TRACKED_KEYS while the monotone distinct counters keep
    # the honest first-seen totals
    from mpi_k_selection_tpu.obs.ledger import MAX_TRACKED_KEYS

    led = ProgramLedger(storm_threshold=10**9)  # books only, no storms
    for i in range(MAX_TRACKED_KEYS + 100):
        with led.dispatch("churn", ("k", i)):
            pass
    st = led._sites["churn"]
    assert len(st["keys"]) == MAX_TRACKED_KEYS
    assert len(st["storm_keys"]) == MAX_TRACKED_KEYS
    snap = led.snapshot()["sites"]["churn"]
    assert snap["distinct_keys"] == MAX_TRACKED_KEYS + 100
    assert snap["compiles"] == MAX_TRACKED_KEYS + 100


def test_bytes_accounting_live_and_peak():
    led = ProgramLedger()
    led.adjust_bytes("staging", "cpu:0", 1024)
    led.adjust_bytes("staging", "cpu:0", 2048)
    led.adjust_bytes("staging", "cpu:0", -1024)
    led.set_bytes("staging_pool", None, 512)
    led.set_bytes("staging_pool", None, 128)
    snap = led.snapshot()
    assert snap["device_bytes"]["staging/cpu:0"] == 2048
    assert snap["device_bytes_peak"]["staging/cpu:0"] == 3072
    assert snap["device_bytes"]["staging_pool/default"] == 128
    assert snap["device_bytes_peak"]["staging_pool/default"] == 512
    assert led.device_bytes("staging") == {("staging", "cpu:0"): 2048}


def test_snapshot_delta_is_per_run():
    led = ProgramLedger()
    with led.dispatch("s", 1):
        pass
    before = led.snapshot()
    with led.dispatch("s", 2):
        pass
    with led.dispatch("s", 2):
        pass
    d = snapshot_delta(before, led.snapshot())
    assert d["sites"]["s"]["compiles"] == 1
    assert d["sites"]["s"]["hits"] == 1
    assert d["compiles"] == 1
    assert d["recompiles"] == 0
    assert d["compile_seconds"] >= 0.0
    # unchanged sites are omitted entirely
    d2 = snapshot_delta(led.snapshot(), led.snapshot())
    assert d2["sites"] == {} and d2["compiles"] == 0


def test_reset_clears_everything():
    led = ProgramLedger(storm_threshold=1)
    with led.dispatch("s", 1):
        pass
    with led.dispatch("s", 2):
        pass
    led.adjust_bytes("staging", None, 64)
    led.reset()
    snap = led.snapshot()
    assert snap["sites"] == {}
    assert snap["device_bytes"] == {}
    assert snap["storms"] == []


def test_collect_ledger_exports_metric_names():
    led = ProgramLedger()
    with led.dispatch("a.site", ("k",)):
        pass
    led.note_hit("a.site", ("k",))
    led.adjust_bytes("staging", "cpu:0", 4096)
    reg = obs_lib.MetricsRegistry()
    collect_ledger(reg, ledger=led)
    collect_ledger(reg, ledger=led)  # idempotent overwrite, never additive
    snap = reg.as_dict()
    assert snap['ledger.compiles{site="a.site"}']["value"] == 1
    assert snap['ledger.cache_hits{site="a.site"}']["value"] == 1
    assert snap['ledger.recompiles{site="a.site"}']["value"] == 0
    assert snap['ledger.compile_seconds{site="a.site"}']["value"] >= 0.0
    assert (
        snap['ledger.device_bytes{device="cpu:0",pool="staging"}']["value"]
        == 4096
    )
    assert (
        snap['ledger.device_bytes_peak{device="cpu:0",pool="staging"}'][
            "value"
        ]
        == 4096
    )


# ---------------------------------------------------------------------------
# the ledger through the real streaming vertical


def test_streaming_populates_ledger_and_byte_book(rng, monkeypatch):
    # A fresh process ledger: the real one is process-lifetime, so this
    # run's byte peaks may sit below an earlier (bigger) test's high-water
    # mark and a peak-growth delta would be empty. Call sites resolve
    # ``_ledger.LEDGER`` at dispatch time, so the swap reroutes them all.
    from mpi_k_selection_tpu.obs import ledger as ledger_mod

    fresh = ProgramLedger()
    monkeypatch.setattr(ledger_mod, "LEDGER", fresh)
    chunks = _chunks(rng)
    before = fresh.snapshot()
    o = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    got = streaming_kselect(
        chunks, sum(c.size for c in chunks) // 2, pipeline_depth=2,
        spill="force", obs=o, **KW,
    )
    d = snapshot_delta(before, fresh.snapshot())
    # at least one ingest site dispatched; repeat buckets are hits
    ingest_sites = [s for s in d["sites"] if s.startswith("ingest.")]
    assert ingest_sites, d["sites"]
    assert sum(d["sites"][s]["hits"] for s in ingest_sites) > 0
    # the staged byte book saw the padded buckets... and released them
    peaks = d["device_bytes_peak"]
    assert any(k.startswith("staging/") and v > 0 for k, v in peaks.items())
    live = fresh.device_bytes("staging")
    assert all(v == 0 for v in live.values()), live
    # spill generations were accounted and returned to zero at close
    assert peaks.get("spill/disk", 0) > 0
    assert all(v == 0 for v in fresh.device_bytes("spill").values())
    # the descent folded the ledger into the run's registry
    reg = o.metrics.as_dict()
    assert any(k.startswith("ledger.compiles{") for k in reg)
    assert int(np.asarray(got)) == int(
        np.sort(np.concatenate(chunks), kind="stable")[
            sum(c.size for c in chunks) // 2 - 1
        ]
    )


# ---------------------------------------------------------------------------
# bit-identity: everything on, across the whole grid (ISSUE 14 gate)


@pytest.mark.parametrize("fused", ["kernel", "xla", "off"])
@pytest.mark.parametrize("spill", ["off", "force"])
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("devices", [1, 2])
def test_grid_bit_identity_with_ledger_flight_and_trace(
    rng, devices, depth, spill, fused
):
    chunks = _chunks(rng)
    n = sum(c.size for c in chunks)
    ks = [n // 3, n - 1]
    want = streaming_kselect_many(chunks, ks, **KW)
    o = obs_lib.Observability.collecting(flight=True)
    got = streaming_kselect_many(
        chunks, ks, devices=devices, pipeline_depth=depth, spill=spill,
        fused=fused, obs=o, **KW,
    )
    assert [int(v) for v in got] == [int(v) for v in want], (
        f"devices={devices} depth={depth} spill={spill} fused={fused}"
    )
    # the flight ring observed the run (events always; spans whenever
    # the run is pipelined enough to create a timer)
    assert o.flight.events_tail()
    obs_lib.check_stream_invariants(o.events.events)


def test_grid_bit_identity_float32_leg(rng):
    chunks = [
        rng.standard_normal(m).astype(np.float32)
        for m in (4096, 2777, 2048)
    ]
    x = np.concatenate(chunks)
    k = x.size // 2
    want = np.sort(x, kind="stable")[k - 1]
    o = obs_lib.Observability.collecting(flight=True)
    got = streaming_kselect(
        chunks, k, spill="force", fused="kernel", obs=o, **KW
    )
    assert np.asarray(got).tobytes() == np.asarray(want).tobytes()


# ---------------------------------------------------------------------------
# flight recorder units


def test_resolve_flight_forms():
    assert resolve_flight(None) is None
    assert resolve_flight(False) is None
    fr = resolve_flight(True)
    assert isinstance(fr, FlightRecorder)
    small = resolve_flight(7)
    assert small._events.maxlen == 7
    assert resolve_flight(fr) is fr
    with pytest.raises(ValueError, match="flight"):
        resolve_flight("yes")


def test_ring_is_bounded_oldest_evicted():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record_event(
            RecompileStormEvent(site="s", key=str(i), compiles=i, threshold=0)
        )
        fr.record(f"span.{i}", float(i), float(i) + 0.5)
    tail = fr.events_tail()
    assert [e.key for e in tail] == ["6", "7", "8", "9"]
    assert [s[0] for s in fr.spans_tail()] == [
        "span.6", "span.7", "span.8", "span.9"
    ]


def test_bundle_sections_always_present():
    # no obs, no flight: every section still present, degraded to empty
    b = build_bundle(None)
    assert set(BUNDLE_SECTIONS) <= set(b)
    assert b["events"] == [] and b["spans"]["tail"] == []
    assert b["faults"]["plan"] is None
    assert "sites" in b["ledger"]
    # with channels: events + metrics + span tails populated, extra merged
    o = obs_lib.Observability.collecting(flight=True)
    o.emit(RecompileStormEvent(site="s", key="k", compiles=9, threshold=8))
    o.flight.record("phase.x", 0.0, 1.0, {"trace_id": "t1"})
    b = o.flight.bundle(obs=o, reason="unit", extra={"context": 1})
    assert b["reason"] == "unit" and b["context"] == 1
    assert b["events"][0]["event"] == "ledger.recompile_storm"
    assert b["spans"]["tail"][0]["name"] == "phase.x"
    assert b["spans"]["tail"][0]["args"] == {"trace_id": "t1"}
    assert b["spans"]["thread_tracks"] == 1
    assert isinstance(b["metrics"], dict)


def test_dump_writes_valid_json(tmp_path):
    fr = FlightRecorder()
    fr.record_event(
        RecompileStormEvent(site="s", key="k", compiles=1, threshold=0)
    )
    path = tmp_path / "bundle.json"
    got = fr.dump(path, reason="unit")
    assert got == str(path)
    bundle = json.loads(path.read_text())
    assert set(BUNDLE_SECTIONS) <= set(bundle)
    assert bundle["reason"] == "unit"
    # the conftest fixture validates this dump again at teardown (it was
    # registered) — that is part of the assertion


def test_auto_dump_at_most_once_per_recorder(tmp_path):
    fr = FlightRecorder(dump_dir=str(tmp_path))
    o = obs_lib.Observability(flight=fr)
    p1 = auto_dump(o, "retry-exhausted", exc=RuntimeError("boom"))
    p2 = auto_dump(o, "retry-exhausted", exc=RuntimeError("again"))
    assert p1 is not None and p2 is None
    assert fr.auto_dumps == [p1]
    bundle = json.loads(open(p1).read())
    assert bundle["reason"] == "retry-exhausted"
    assert bundle["error"] == "RuntimeError: boom"


def test_auto_dump_without_flight_is_noop_and_never_raises(tmp_path):
    assert auto_dump(None, "x") is None
    assert auto_dump(obs_lib.Observability(), "x") is None
    # a failing postmortem write must not mask the in-flight error
    fr = FlightRecorder(dump_dir=str(tmp_path / "missing" / "dir"))
    o = obs_lib.Observability(flight=fr)
    assert auto_dump(o, "x") is None


def test_span_fanout_feeds_trace_and_flight():
    from mpi_k_selection_tpu.obs.wiring import attach_timer, span_recorder
    from mpi_k_selection_tpu.utils.profiling import PhaseTimer

    o = obs_lib.Observability(
        trace=obs_lib.TraceRecorder(), flight=FlightRecorder()
    )
    timer, restore = attach_timer(o, None)
    with timer.phase("p.one", args={"trace_id": "t"}):
        pass
    restore()
    assert [s.name for s in o.trace.spans] == ["p.one"]
    assert o.trace.spans[0].args == {"trace_id": "t"}
    assert [s[0] for s in o.flight.spans_tail()] == ["p.one"]
    assert o.flight.spans_tail()[0][5] == {"trace_id": "t"}
    # single-channel forms short-circuit to the bare recorder
    assert span_recorder(obs_lib.Observability(flight=o.flight)) is o.flight
    assert span_recorder(obs_lib.Observability()) is None
    # detach honored: a later phase records nowhere
    t2 = PhaseTimer()
    _, restore2 = attach_timer(o, t2)
    restore2()
    with t2.phase("p.two"):
        pass
    assert [s.name for s in o.trace.spans] == ["p.one"]


def test_auto_dump_failed_write_does_not_consume_latch(tmp_path):
    # ENOSPC-class failures often trigger the dump AND fail the write:
    # the once-per-recorder latch must survive a failed attempt so the
    # next terminal failure (after space frees) still gets its bundle
    fr = FlightRecorder(dump_dir=str(tmp_path / "missing-dir"))
    o = obs_lib.Observability(flight=fr)
    assert auto_dump(o, "spill-damage") is None  # write fails, swallowed
    fr.dump_dir = str(tmp_path)
    path = auto_dump(o, "spill-damage")
    assert path is not None
    assert json.loads(open(path).read())["reason"] == "spill-damage"
    # and the latch is consumed by the SUCCESSFUL dump
    assert auto_dump(o, "spill-damage") is None
    assert fr.auto_dumps == [path]


def test_concurrent_release_subtracts_staging_bytes_exactly_once(monkeypatch):
    # unwind paths (executor abort, pipeline close) race the normal
    # release on the same chunk: the latch is atomic, so the byte gauge
    # and the live-staged count each move exactly once
    import threading

    from mpi_k_selection_tpu.obs import ledger as ledger_mod
    from mpi_k_selection_tpu.streaming import pipeline as pl

    fresh = ProgramLedger()
    monkeypatch.setattr(ledger_mod, "LEDGER", fresh)
    for _ in range(20):  # racing windows are narrow: many rounds
        staged = pl.stage_keys(np.arange(1000, dtype=np.uint32))
        assert sum(fresh.device_bytes("staging").values()) > 0
        barrier = threading.Barrier(8)

        def rel():
            barrier.wait()
            staged.release()

        ts = [threading.Thread(target=rel) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        live = fresh.device_bytes("staging")
        assert all(v == 0 for v in live.values()), live
    assert pl.live_staged_keys() == 0


# ---------------------------------------------------------------------------
# the postmortem gate: chaos -> exactly one auto-dumped bundle


def test_retry_exhaustion_auto_dumps_one_bundle(rng, tmp_path):
    chunks = _chunks(rng)
    # fault on a LATE chunk so both pipeline threads have completed
    # spans (producer produce/encode/stage, consumer stall) in the ring
    # by the time the exhaustion freezes it
    plan = faults.FaultPlan(
        (faults.FaultSpec("stage", 3, "raise", attempts=tuple(range(99))),)
    )
    pol = faults.RetryPolicy(max_attempts=2, sleeper=faults.VirtualSleeper())
    o = obs_lib.Observability.collecting(
        flight=FlightRecorder(dump_dir=str(tmp_path))
    )
    with faults.inject(plan):
        with pytest.raises(RetryExhaustedError):
            streaming_kselect(
                chunks, len(chunks[0]), pipeline_depth=2, retry=pol,
                obs=o, **KW,
            )
    # exactly ONE bundle auto-dumped, wherever the exhaustion surfaced
    assert len(o.flight.auto_dumps) == 1
    bundle = json.loads(open(o.flight.auto_dumps[0]).read())
    assert set(BUNDLE_SECTIONS) <= set(bundle)
    assert bundle["reason"] == "retry-exhausted"
    assert "RetryExhaustedError" in bundle["error"]
    # the triggering FaultEvents are in the frozen tail (both views)
    fault_kinds = [e for e in bundle["events"] if e["event"] == "fault"]
    assert fault_kinds, "expected the injected/retry FaultEvents"
    assert bundle["faults"]["events"] == fault_kinds
    # the armed-plan description is best-effort (the dump may race the
    # context exit on the other thread) but the key is always present
    assert "plan" in bundle["faults"]
    # the ledger and metrics snapshots rode along
    assert bundle["ledger"]["sites"]
    assert bundle["metrics"]
    # >= 2 thread tracks: producer AND consumer span'd before the dump
    assert bundle["spans"]["thread_tracks"] >= 2, bundle["spans"]
    # the live sink saw the same faults (the ring is a tail, not a tap)
    assert o.events.of_kind("fault")


def test_dispatch_crash_auto_dumps_one_bundle(tmp_path):
    from mpi_k_selection_tpu.serve import KSelectServer
    from mpi_k_selection_tpu.serve.errors import DispatchCrashedError

    fr = FlightRecorder(dump_dir=str(tmp_path))
    with KSelectServer(
        obs=obs_lib.Observability.collecting(), flight=fr, window=0.0
    ) as srv:
        srv.add_dataset("d", np.arange(100, dtype=np.int32))
        plan = faults.FaultPlan(
            (faults.FaultSpec("serve.dispatch", 0, "raise"),)
        )
        with faults.inject(plan):
            with pytest.raises(DispatchCrashedError):
                srv.kselect("d", 5, tier="exact")
        # restarted in place; later queries answer
        assert int(srv.kselect("d", 5, tier="exact").value) == 4
    assert len(fr.auto_dumps) == 1
    bundle = json.loads(open(fr.auto_dumps[0]).read())
    assert bundle["reason"] == "dispatch-crashed"
    # the error field carries the crash CAUSE the supervisor caught
    assert "injected transient fault at serve.dispatch" in bundle["error"]
    assert set(BUNDLE_SECTIONS) <= set(bundle)


# ---------------------------------------------------------------------------
# serve: steady state, shape churn, trace ids, debug bundle


def _server(**kw):
    from mpi_k_selection_tpu.serve import KSelectServer

    kw.setdefault("obs", obs_lib.Observability.collecting())
    kw.setdefault("window", 0.0)
    return KSelectServer(**kw)


def test_server_close_releases_resident_bytes(rng, monkeypatch):
    # a server torn down WITHOUT per-dataset drop() calls must return
    # its registry's bytes to the resident book: the process gauge would
    # otherwise ratchet upward across server lifetimes and the eviction
    # budgeting it feeds would act on phantom bytes
    from mpi_k_selection_tpu.obs import ledger as ledger_mod
    from mpi_k_selection_tpu.serve import KSelectServer, ServerClosedError

    fresh = ProgramLedger()
    monkeypatch.setattr(ledger_mod, "LEDGER", fresh)
    x = rng.integers(-(2**31), 2**31 - 1, size=4096, dtype=np.int32)
    srv = KSelectServer(window=0.0)
    srv.add_dataset("a", x)
    assert sum(fresh.device_bytes("resident").values()) > 0
    srv.close()
    live = fresh.device_bytes("resident")
    assert all(v == 0 for v in live.values()), live
    srv.close()  # idempotent: no double subtraction
    assert all(v == 0 for v in fresh.device_bytes("resident").values())
    # post-close registration can't re-enter the book unreleasable
    with pytest.raises(ServerClosedError):
        srv.add_dataset("b", x)
    # a CALLER-owned registry stays the caller's: close leaves its book
    from mpi_k_selection_tpu.serve.registry import DatasetRegistry

    reg = DatasetRegistry()
    reg.add_array("c", x)
    held = sum(fresh.device_bytes("resident").values())
    assert held > 0
    srv2 = KSelectServer(registry=reg, window=0.0)
    srv2.close()
    assert sum(fresh.device_bytes("resident").values()) == held
    reg.close()
    assert all(v == 0 for v in fresh.device_bytes("resident").values())
    # the close snapshot is final: a registration racing (or following)
    # close fails instead of adding unreleasable bytes to the book
    with pytest.raises(ServerClosedError):
        reg.add_array("d", x)
    assert all(v == 0 for v in fresh.device_bytes("resident").values())


def test_serve_burst_steady_state_zero_recompiles(rng):
    x = rng.integers(-(2**31), 2**31 - 1, size=40_000, dtype=np.int32)
    with _server() as srv:
        srv.add_dataset("d", x)
        ks = [123, 4567, 39_000]
        for k in ks:  # warmup: compile every shape the burst uses
            srv.kselect("d", k, tier="exact")
        before = LEDGER.snapshot()
        for _ in range(10):  # the steady-state burst: same shapes only
            for k in ks:
                srv.kselect("d", k, tier="exact")
        d = snapshot_delta(before, LEDGER.snapshot())
        site = d["sites"].get("serve.programs", {})
        assert site.get("compiles", 0) == 0, d["sites"]
        assert site.get("recompiles", 0) == 0
        assert site.get("hits", 0) > 0
        assert d["compiles"] == 0, d["sites"]
        # the program-cache mirror agrees
        assert srv.registry.programs.hits > 0


def test_serve_shape_churn_fires_recompile_storm(rng):
    # the negative test: every query against a NEVER-REPEATING dataset —
    # the program cache (keyed per dataset precisely so WIDTH churn
    # cannot evict, test above) compiles fresh programs for each one,
    # and past the process threshold the ledger fires the typed storm
    # event into the server's sink. threshold+1 first-seen keys in THIS
    # test guarantee at least one firing regardless of what earlier
    # tests already compiled at the serve.programs site (the count is
    # process-monotone).
    with _server() as srv:
        for i in range(LEDGER.storm_threshold + 1):
            x = rng.integers(-(2**31), 2**31 - 1, size=4096, dtype=np.int32)
            srv.add_dataset(f"churn-{i}", x)
            srv.kselect(f"churn-{i}", 7 + i, tier="exact")
        storms = srv.obs.events.of_kind("ledger.recompile_storm")
        assert storms, "dataset churn past the threshold must fire"
        assert all(e.site == "serve.programs" for e in storms)
        assert all(e.compiles > e.threshold for e in storms)
        assert all(isinstance(e, RecompileStormEvent) for e in storms)


def test_trace_id_carried_through_events_and_spans(rng):
    x = rng.integers(-(2**31), 2**31 - 1, size=40_000, dtype=np.int32)
    with _server() as srv:
        srv.add_dataset("d", x)
        ans = srv.kselect("d", 777, tier="exact", trace_id="abc-123")
        assert ans.exact is True
        ev = srv.obs.events.of_kind("serve.query")[-1]
        assert ev.trace_id == "abc-123"
        batch = srv.obs.events.of_kind("serve.batch")[-1]
        assert "abc-123" in batch.trace_ids
        spans = {s.name: s for s in srv.obs.trace.spans}
        assert spans["serve.request.exact"].args == {"trace_id": "abc-123"}
        walk = spans["serve.walk"]
        assert walk.args["dataset"] == "d"
        assert "abc-123" in walk.args["trace_ids"]
        # the flight ring is off here; with it on the same spans land
        # in the ring too (test_span_fanout_feeds_trace_and_flight)


def test_trace_id_minted_when_omitted(rng):
    x = rng.integers(-(2**31), 2**31 - 1, size=40_000, dtype=np.int32)
    with _server() as srv:
        srv.add_dataset("d", x)
        srv.kselect("d", 5, tier="exact")
        tid = srv.obs.events.of_kind("serve.query")[-1].trace_id
        assert isinstance(tid, str) and len(tid) == 16
        int(tid, 16)  # hex
        # a second query mints a DIFFERENT id
        srv.kselect("d", 5, tier="exact")
        assert srv.obs.events.of_kind("serve.query")[-1].trace_id != tid


def test_trace_id_sanitized_for_header_echo():
    # the id is echoed verbatim into response headers: CR/LF and other
    # controls from an obs-folded inbound header must not survive into
    # the echo (header-injection primitive), and the length is bounded
    from mpi_k_selection_tpu.serve.server import KSelectServer

    assert KSelectServer._trace_id("abc\r\n\tevil") == "abcevil"
    assert KSelectServer._trace_id("ok-123") == "ok-123"
    minted = KSelectServer._trace_id("\r\n\x00")
    assert len(minted) == 16
    int(minted, 16)  # all-control input falls back to a minted id
    assert len(KSelectServer._trace_id("x" * 500)) == 128


def _http(port, method, path, body=None, headers=None):
    import http.client

    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        c.request(
            method, path, None if body is None else json.dumps(body), h
        )
        r = c.getresponse()
        return r.status, r.read(), dict(r.getheaders())
    finally:
        c.close()


def test_http_trace_id_honored_and_echoed(rng):
    from mpi_k_selection_tpu.serve import start_http_server

    x = rng.integers(-(2**31), 2**31 - 1, size=40_000, dtype=np.int32)
    with _server() as srv:
        srv.add_dataset("d", x)
        with start_http_server(srv) as h:
            # inbound id honored verbatim: response header + body + event
            status, body, hdrs = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "d", "op": "kselect", "k": 9, "tier": "exact"},
                headers={"X-Ksel-Trace-Id": "client-id-42"},
            )
            assert status == 200
            assert hdrs["X-Ksel-Trace-Id"] == "client-id-42"
            assert json.loads(body)["trace_id"] == "client-id-42"
            ev = srv.obs.events.of_kind("serve.query")[-1]
            assert ev.trace_id == "client-id-42"
            # no inbound id: one is minted, echoed on header AND body
            status, body, hdrs = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "d", "op": "kselect", "k": 9},
            )
            assert status == 200
            minted = hdrs["X-Ksel-Trace-Id"]
            assert json.loads(body)["trace_id"] == minted
            assert minted != "client-id-42" and len(minted) == 16
            # error bodies carry the id too (the postmortem handle)
            status, body, hdrs = _http(
                h.port, "POST", "/v1/query",
                {"dataset": "ghost", "op": "kselect", "k": 1},
                headers={"X-Ksel-Trace-Id": "err-7"},
            )
            assert status == 404
            assert hdrs["X-Ksel-Trace-Id"] == "err-7"
            assert json.loads(body)["trace_id"] == "err-7"


def test_server_debug_bundle_and_http_surface(rng):
    from mpi_k_selection_tpu.serve import start_http_server

    x = rng.integers(-(2**31), 2**31 - 1, size=40_000, dtype=np.int32)
    with _server(flight=True) as srv:
        assert isinstance(srv.flight, FlightRecorder)
        srv.add_dataset("d", x)
        srv.kselect("d", 10, tier="exact", trace_id="bundle-t")
        b = srv.debug_bundle()
        assert set(BUNDLE_SECTIONS) <= set(b)
        assert any(e["event"] == "serve.query" for e in b["events"])
        assert b["server"]["datasets"][0]["dataset"] == "d"
        assert b["server"]["program_cache"]["misses"] >= 1
        assert b["server"]["closed"] is False
        # span args survived into the ring tail
        walk = [s for s in b["spans"]["tail"] if s["name"] == "serve.walk"]
        assert walk and "bundle-t" in walk[0]["args"]["trace_ids"]
        with start_http_server(srv) as h:
            status, body, _ = _http(h.port, "GET", "/debug/bundle")
            assert status == 200
            wire = json.loads(body)
            assert set(BUNDLE_SECTIONS) <= set(wire)
            assert wire["reason"] == "http"
    # flightless servers degrade gracefully on the same surfaces
    with _server(obs=None) as srv2:
        srv2.add_dataset("d", x)
        b2 = srv2.debug_bundle()
        assert set(BUNDLE_SECTIONS) <= set(b2)
        assert b2["events"] == []
        with start_http_server(srv2) as h2:
            status, body, _ = _http(h2.port, "GET", "/debug/bundle")
            assert status == 200
            assert set(BUNDLE_SECTIONS) <= set(json.loads(body))


def test_server_flight_knob_attaches_to_existing_obs():
    o = obs_lib.Observability.collecting()
    assert o.flight is None
    with _server(obs=o, flight=16) as srv:
        assert srv.flight is o.flight is not None
        assert srv.flight._events.maxlen == 16


# ---------------------------------------------------------------------------
# CLI --debug-bundle


def test_cli_debug_bundle_written_on_success(tmp_path, capsys):
    from mpi_k_selection_tpu.cli import main

    path = tmp_path / "bundle.json"
    rc = main([
        "--streaming", "--backend", "tpu", "--n", "40000",
        "--chunk-elems", "8192", "--json", "--debug-bundle", str(path),
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["extra"]["debug_bundle"] == str(path)
    bundle = json.loads(path.read_text())
    assert set(BUNDLE_SECTIONS) <= set(bundle)
    assert bundle["reason"] == "cli"
    assert any(e["event"] == "stream.pass" for e in bundle["events"])
    assert bundle["spans"]["thread_tracks"] >= 2  # producer + consumer
    assert bundle["ledger"]["sites"]


def test_cli_trace_events_and_debug_bundle_compose(tmp_path, capsys):
    # --trace-events must not starve the flight ring of spans: the CLI
    # timer feeds the trace+flight FAN recorder, so the bundle's spans
    # section stays populated when both flags are on
    from mpi_k_selection_tpu.cli import main

    bundle_path = tmp_path / "bundle.json"
    trace_path = tmp_path / "trace.json"
    rc = main([
        "--streaming", "--backend", "tpu", "--n", "40000",
        "--chunk-elems", "8192", "--json",
        "--trace-events", str(trace_path), "--debug-bundle", str(bundle_path),
    ])
    assert rc == 0
    capsys.readouterr()
    bundle = json.loads(bundle_path.read_text())
    assert bundle["spans"]["tail"], "flight ring must see spans"
    assert bundle["spans"]["thread_tracks"] >= 2  # producer + consumer
    # and the trace export still works alongside
    trace = json.loads(trace_path.read_text())
    assert trace["traceEvents"]


def test_cli_serve_shutdown_bundle_has_server_section(tmp_path):
    # the shutdown artifact must carry the documented `server` section
    # (datasets, program-cache counters, restarts) — the same bundle
    # GET /debug/bundle serves, not a bare FlightRecorder dump
    import threading
    import time

    from mpi_k_selection_tpu.cli import main

    port_file = tmp_path / "port"
    bundle_path = tmp_path / "bundle.json"
    rc = []
    t = threading.Thread(
        target=lambda: rc.append(main([
            "serve", "--n", "4096", "--dtype", "int32",
            "--port", "0", "--port-file", str(port_file),
            "--batch-window", "0", "--quit-after", "1",
            "--debug-bundle", str(bundle_path),
        ])),
        name="cli-serve-bundle",
    )
    t.start()
    for _ in range(400):
        if port_file.exists() and port_file.read_text():
            break
        time.sleep(0.05)
    else:
        pytest.fail("serve CLI never wrote its port file")
    port = int(port_file.read_text())
    status, _, _ = _http(
        port, "POST", "/v1/query",
        {"dataset": "default", "op": "kselect", "k": 1, "tier": "exact"},
    )
    assert status == 200
    t.join(timeout=60)
    assert not t.is_alive() and rc == [0]
    bundle = json.loads(bundle_path.read_text())
    assert set(BUNDLE_SECTIONS) <= set(bundle)
    assert bundle["reason"] == "serve-shutdown"
    assert [d["dataset"] for d in bundle["server"]["datasets"]] == ["default"]
    assert "program_cache" in bundle["server"]


def test_cli_serve_parser_accepts_debug_bundle():
    from mpi_k_selection_tpu.cli import build_serve_parser

    args = build_serve_parser().parse_args(
        ["--n", "1000", "--debug-bundle", "/tmp/b.json"]
    )
    assert args.debug_bundle == "/tmp/b.json"
