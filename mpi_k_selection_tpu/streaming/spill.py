"""Survivor spill store — the per-device on-disk chunk cache that lets the
out-of-core descent shrink geometrically instead of replaying the source.

The chunked descent (streaming/chunked.py) is a ``key_bits / radix_bits``-
pass walk, and without a cache EVERY pass re-streams the entire source:
a P-pass descent over an out-of-core input moves ~P·N key bytes across the
host->device boundary when only pass 0 actually needs all N. The reference
CGM's core perf idea is the opposite discipline — discard the partitions
that provably cannot hold the k-th element and recurse on a shrinking
window (``TODO-kth-problem-cgm.c`` L/E/G counts + window rebase). This
module is that discipline applied to the streaming axis:

- pass 0 TEES each chunk's encoded keys to a spill *generation* (written on
  the pipeline's producer thread, so the disk write overlaps device
  compute);
- every later pass reads the previous generation, filters each chunk to the
  surviving prefixes ON its owning device, and writes only the compacted
  survivors — ~1/2^radix_bits of the prior generation — as the next
  generation;
- total bytes streamed drop from ~P·N to ~N·(2 + 1/2^b + 1/4^b + ...), and
  one-shot (non-replayable) sources become first-class: passes >= 1 never
  touch the source.

Format v2 (``pack_spill="auto"``, streaming/chunked.py) shrinks the disk
AND the replay-read side further: a survivor generation's records store
only the unresolved low ``total_bits - resolved`` bits of each key,
bit-packed per ``(resolved, prefix)`` segment (the tee union mixes
prefixes, so each record carries a segment directory), CRC'd per segment
and reconstructed exactly at replay — disk bytes shrink multiplicatively
with population AND resolved depth. The pass-0 tee writes the same
format segmented by each key's top :data:`GEN0_SEGMENT_BITS` digit
(``pack_digit_bits``), and filtered replays PRUNE through the directory
to the segments under their surviving prefixes — so the historical
second full-N read of generation 0 collapses to a read of the surviving
buckets. Records where packing would not help (directory-dominated tiny
chunks) fall back to v1 per record, so a generation's physical bytes
(``nbytes``) never exceed its logical bytes (``logical_nbytes``).

Each v2 directory entry also lands in a GENERATION-LEVEL segment index
(:class:`SpillGeneration` hoists every record's ``(resolved, prefix,
count, crc)`` layout plus computed offsets into one in-memory map at
commit), so pruned replays seek straight to their segments without
re-reading each record's on-disk directory — deleting the per-record
directory tax that could push a small pruned read's physical bytes above
its logical bytes. The on-disk directory stays authoritative and v2
records remain readable without any index (external readers, pre-index
stores). The read side also mirrors the write side's ingest pool:
``iter_chunks(workers=n)`` decodes records (file read + CRC + bit
unpack) on ``ksel-ingest-decode-*`` threads while still yielding
strictly in record order.

Records are bucket-sized and keyed by ``(chunk_index, bucket, dtype,
device)`` — the :class:`~mpi_k_selection_tpu.streaming.pipeline.
StagingPool` key plus the chunk index — so a replay re-stages every chunk
onto the round-robin device that already compiled its bucket programs,
preserving the chunk->device determinism contract of the multi-device
ingest. The write itself splits into an order-free ``prepare`` (pack +
checksum, safe from any ingest worker) and a sequencer-serialized
``append_prepared`` (index assignment + disk write), so a pooled ingest
plane produces byte-identical generations to the single-threaded path. Every record carries a CRC32 and a full metadata header; any
mismatch raises :class:`~mpi_k_selection_tpu.errors.SpillRecordError`
before a single key reaches a histogram (a corrupt cache fails loudly,
never answers wrong).

Disk bound: descents drop older generations eagerly, so an
internally-created store holds at most two generations at once —
~2·N·key_bytes worst case (adversarial duplicates), ~N·(1 + 1/2^b)
typically. A CALLER-owned store additionally keeps its pass-0 tee alive
for later calls, so its worst case is ~3·N·key_bytes (kept gen 0 + the
generation being read + the one being written), ~N typical.

Lifecycle: stores created internally by ``streaming_kselect{,_many}``
live in a ``ksel-spill-*`` temp directory and are removed on EVERY exit
path (success, consumer raise, producer raise — tests/conftest.py fails
any test that leaks one). Caller-owned stores (``spill=SpillStore(...)``,
or a sketch ``update_stream(..., spill=store)`` tee) keep their pass-0
generation so it can serve later calls (``refine``, the rank
certificate, a second descent); only descent-internal generations are
dropped.

This module is the ONE sanctioned file-writing surface under streaming/ —
lint rule KSL008 flags any other raw ``open``/``np.save``-class write
there, because a write that dodges the record keying, checksums and
cleanup discipline is exactly how a cache silently feeds a descent stale
or truncated survivors.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import queue
import shutil
import struct
import tempfile
import threading
import zlib

import numpy as np

from mpi_k_selection_tpu.errors import SpillError, SpillRecordError
from mpi_k_selection_tpu.faults.inject import maybe_fault as _maybe_fault
from mpi_k_selection_tpu.obs import ledger as _ledger
from mpi_k_selection_tpu.resource_protocols import (
    INGEST_THREAD_PREFIX,
    SPILL_DIR_PREFIX,
)
from mpi_k_selection_tpu.streaming.pipeline import _bucket_elems

# SPILL_DIR_PREFIX (imported above): temp-directory prefix for
# internally-created stores; tests assert none outlive their call (the
# spill twin of pipeline.THREAD_NAME_PREFIX). Canonical value:
# resource_protocols.py (conftest + KSL020 registry).

#: The ``spill=`` knob's string modes (a SpillStore instance is also legal).
SPILL_MODES = ("auto", "off", "force")

#: The ``pack_spill`` knob's modes: ``"auto"`` writes format-v2
#: prefix-packed records wherever packing actually shrinks the record
#: (falling back to v1 per record otherwise — so physical bytes never
#: exceed logical bytes), ``"off"`` keeps the v1 full-width records.
PACK_SPILL_MODES = ("auto", "off")

_MAGIC = b"KSPILL1\x00"
_VERSION = 1
#: Format v2 — prefix-packed records: the payload stores, per
#: ``(resolved, prefix)`` segment, only the unresolved low
#: ``total_bits - resolved`` bits of each survivor, bit-packed. The base
#: header is unchanged (same magic/struct); ``crc32``/``nbytes`` describe
#: the PACKED tail (segment directory + payloads), and the reader
#: reconstructs the full-width keys exactly.
_VERSION_PACKED = 2
# magic, version, chunk_index, n_valid, bucket, device_slot,
# key dtype str, orig dtype str, crc32, payload nbytes. The crc covers
# the whole payload for v1; for v2 it covers the SEGMENT DIRECTORY only
# (each directory entry carries its own payload crc — see _SEG_ENTRY),
# so pruned reads validate exactly what they touch.
_HEADER = struct.Struct("<8sIqqqq8s8sIQ")
# v2 segment directory: one count, then per segment (resolved_bits,
# prefix, n_keys, payload crc32); payloads follow in directory order,
# each byte-aligned. The header's crc32 covers the DIRECTORY bytes and
# each entry's crc covers its own payload — so a replay that PRUNES to
# the segments matching its filter specs checksums exactly what it
# reads, without touching the pruned-away bytes (which cannot reach a
# consumer, hence cannot corrupt an answer).
_SEG_COUNT = struct.Struct("<q")
# resolved_bits (u8 — key widths cap at 64), prefix (u64), n_keys (u32 —
# records cap at the int32 device-partial chunk bound), payload crc (u32)
_SEG_ENTRY = struct.Struct("<BQII")
#: Top-digit granularity of a ``pack_digit_bits`` tee (the pass-0 /
#: sketch tee under ``pack_spill="auto"``): records segment by the top
#: ``GEN0_SEGMENT_BITS`` of each key, so a later pass's filtered replay
#: seeks straight to the surviving buckets and reads ~population/2^8 of
#: the generation instead of all of it. 8 keeps the per-record directory
#: at <= 256 entries (bounded overhead on small chunks) while any deeper
#: filter spec still prunes through it (ancestor matching).
GEN0_SEGMENT_BITS = 8
#: Values per ``np.packbits`` slice — a multiple of 8, so every slice of
#: the bit stream is byte-aligned and pack/unpack can work in bounded
#: memory without splitting a byte across slices.
_PACK_SLICE = 1 << 16

# distinguishes concurrent pooled reads' thread names (the conftest leak
# fixture matches on the INGEST_THREAD_PREFIX family either way)
_DECODE_IDS = itertools.count()


def validate_pack_spill(pack_spill):
    """Normalize the ``pack_spill`` knob (None = the ``"off"`` default)."""
    if pack_spill is None:
        return "off"
    if pack_spill in PACK_SPILL_MODES:
        return pack_spill
    raise ValueError(
        f"pack_spill must be one of {PACK_SPILL_MODES}, got {pack_spill!r}"
    )


def validate_spill_mode(spill):
    """Normalize the ``spill`` knob: one of :data:`SPILL_MODES`, or an open
    :class:`SpillStore` to tee into / read from (caller-owned lifecycle)."""
    if isinstance(spill, SpillStore):
        if spill.closed:
            raise SpillError("spill store is closed")
        return spill
    if spill in SPILL_MODES:
        return spill
    raise ValueError(
        f"spill must be one of {SPILL_MODES} or a SpillStore, got {spill!r}"
    )


def _pack_dtype(dt) -> bytes:
    s = np.dtype(dt).str.encode("ascii")
    if len(s) > 8:  # pragma: no cover - no supported dtype exceeds '<u8'
        raise SpillError(f"dtype tag {s!r} exceeds the 8-byte record field")
    return s.ljust(8, b"\x00")


def _unpack_dtype(raw: bytes, path: str) -> np.dtype:
    try:
        return np.dtype(raw.rstrip(b"\x00").decode("ascii"))
    except (TypeError, UnicodeDecodeError) as e:
        raise SpillRecordError(f"spill record {path}: bad dtype tag {raw!r}") from e


def _pack_low_bits(vals: np.ndarray, width: int) -> np.ndarray:
    """Bit-pack ``vals`` (uint64, each < 2**width) big-endian-within-value
    into a uint8 array of ``ceil(len(vals) * width / 8)`` bytes (the final
    byte zero-padded). Works in :data:`_PACK_SLICE`-value slices so the
    transient bit expansion stays bounded regardless of chunk size."""
    n = int(vals.shape[0])
    if n == 0:
        return np.empty((0,), np.uint8)
    shifts = np.arange(width - 1, -1, -1, dtype=np.uint64)
    parts = []
    for lo in range(0, n, _PACK_SLICE):
        part = vals[lo:lo + _PACK_SLICE]
        bits = ((part[:, None] >> shifts) & np.uint64(1)).astype(np.uint8)
        parts.append(np.packbits(bits.ravel()))
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


def _unpack_low_bits(buf: np.ndarray, count: int, width: int) -> np.ndarray:
    """Exact inverse of :func:`_pack_low_bits`: ``buf`` (uint8) back to a
    uint64 array of ``count`` values."""
    if count == 0:
        return np.empty((0,), np.uint64)
    shifts = np.arange(width - 1, -1, -1, dtype=np.uint64)
    out = np.empty((count,), np.uint64)
    slice_bytes = _PACK_SLICE * width // 8
    for i, lo in enumerate(range(0, count, _PACK_SLICE)):
        cnt = min(_PACK_SLICE, count - lo)
        seg = buf[i * slice_bytes:i * slice_bytes + (cnt * width + 7) // 8]
        bits = np.unpackbits(
            np.ascontiguousarray(seg), count=cnt * width
        ).reshape(cnt, width).astype(np.uint64)
        out[lo:lo + cnt] = (bits << shifts).sum(axis=1, dtype=np.uint64)
    return out


def _pack_payload(keys: np.ndarray, specs, total_bits: int):
    """Build a v2 record tail: segment ``keys`` by the DEEPEST matching
    ``(resolved_bits, prefix)`` spec (the tee union mixes prefixes whose
    resolved depths differ — parked ranks sit shallower than the active
    set — and a key under a deep prefix also matches every shallower
    ancestor, so deepest-first assignment packs each key as small as its
    true spec allows), then bit-pack each segment's unresolved low
    ``total_bits - resolved`` bits, CRC'ing each segment's packed bytes
    into its directory entry. Returns ``(tail, dir_nbytes, segments)``:
    the directory + payloads as one contiguous uint8 array, the
    directory's byte length, and the ``(resolved, prefix, count,
    payload_crc32)`` layout tuple the writer records — the raw material
    of the GENERATION-level segment index (static pruned-read
    accounting, and direct-seek pruned reads that skip the on-disk
    per-record directory entirely). A key matching NO spec is a
    tee-filter bug and raises
    :class:`~mpi_k_selection_tpu.errors.SpillError` loudly."""
    u = np.ascontiguousarray(keys).astype(np.uint64)
    ordered = sorted(specs, key=lambda s: (-s[0], s[1]))
    segments = []
    if len({r for r, _ in ordered}) == 1:
        # uniform-depth fast path (the digit-segmented tee, and filter
        # unions with no parked ranks): ONE stable sort groups every
        # segment instead of one boolean sweep per spec — original key
        # order is preserved within each segment either way
        r0 = ordered[0][0]
        tops = (
            u >> np.uint64(total_bits - r0)
            if r0 else np.zeros(u.shape[0], np.uint64)
        )
        order = np.argsort(tops, kind="stable")
        su, stops = u[order], tops[order]
        pref = np.asarray([p for _, p in ordered], np.uint64)
        lo = np.searchsorted(stops, pref, side="left")
        hi = np.searchsorted(stops, pref, side="right")
        if int((hi - lo).sum()) != u.shape[0]:
            raise SpillError(
                f"packed spill writer: {u.shape[0] - int((hi - lo).sum())} "
                "keys match no (resolved, prefix) spec — the tee filter "
                "and the pack specs disagree (a bug in streaming/"
                "chunked.py, not in the stream)"
            )
        width = total_bits - r0
        mask = np.uint64((1 << width) - 1) if width < 64 else None
        for (rr, pp), start, stop in zip(ordered, lo, hi):
            vals = su[start:stop]
            if mask is not None:
                vals = vals & mask
            segments.append(
                (int(rr), int(pp), int(stop - start),
                 _pack_low_bits(vals, width))
            )
    else:
        assigned = np.zeros(u.shape[0], dtype=bool)
        for resolved, prefix in ordered:
            sel = ~assigned
            if resolved:
                sel &= (
                    u >> np.uint64(total_bits - resolved)
                ) == np.uint64(prefix)
            vals = u[sel]
            assigned |= sel
            width = total_bits - resolved
            if width < 64:
                vals = vals & np.uint64((1 << width) - 1)
            segments.append(
                (int(resolved), int(prefix), int(vals.shape[0]),
                 _pack_low_bits(vals, width))
            )
        if not bool(assigned.all()):
            raise SpillError(
                f"packed spill writer: {int((~assigned).sum())} keys match "
                "no (resolved, prefix) spec — the tee filter and the pack "
                "specs disagree (a bug in streaming/chunked.py, not in the "
                "stream)"
            )
    crcs = [
        zlib.crc32(payload.data) & 0xFFFFFFFF for *_, payload in segments
    ]
    parts = [np.frombuffer(_SEG_COUNT.pack(len(segments)), np.uint8)]
    for (resolved, prefix, count, _payload), seg_crc in zip(segments, crcs):
        parts.append(
            np.frombuffer(
                _SEG_ENTRY.pack(resolved, prefix, count, seg_crc),
                np.uint8,
            )
        )
    parts.extend(payload for *_, payload in segments)
    dir_nbytes = _SEG_COUNT.size + len(segments) * _SEG_ENTRY.size
    layout = tuple(
        (r, p, c, seg_crc)
        for (r, p, c, _), seg_crc in zip(segments, crcs)
    )
    return np.concatenate(parts), dir_nbytes, layout


def _segment_matches(r_seg: int, p_seg: int, specs) -> bool:
    """True when a ``(r_seg, p_seg)`` segment may hold keys under ANY
    ``(resolved, prefix)`` filter spec: a deeper filter matches iff the
    segment prefix is its ancestor, a shallower one iff the segment sits
    under it — keys live in exactly one segment, so a pruned read that
    keeps every matching segment keeps every key a filtered consumer
    could possibly select."""
    for r_f, p_f in specs:
        if r_f >= r_seg:
            if (p_f >> (r_f - r_seg) if r_f > r_seg else p_f) == p_seg:
                return True
        elif (p_seg >> (r_seg - r_f)) == p_f:
            return True
    return False


def _read_packed(read_at, nbytes, n_valid, key_dt, dir_crc, path,
                 filter_specs=None, seg_index=None) -> np.ndarray:
    """Directory-driven v2 record read: validate the segment directory
    (its own CRC is the record header's ``crc32``), then read, checksum
    and reconstruct each segment — ONLY the segments matching
    ``filter_specs`` when given, seeking past the rest, which is what
    turns a filtered replay's full-generation read into a read of the
    surviving buckets. ``read_at(offset, size)`` serves bytes relative
    to the payload start (file seek+read, or an mmap slice, so pruning
    skips real I/O on both routes); any truncation, count/size
    inconsistency or checksum mismatch raises
    :class:`~mpi_k_selection_tpu.errors.SpillRecordError` before a single
    key reaches a consumer.

    ``seg_index`` (a generation-level ``(resolved, prefix, count,
    payload_crc, offset, nbytes)`` tuple — :class:`SpillGeneration`'s
    hoisted copy of this record's directory) turns a PRUNED read into
    direct seeks: the matching segments are read and per-segment
    checksummed without touching the on-disk directory at all, so a
    small pruned read stops paying the per-record directory tax (the
    overhead that could push physical read bytes above logical on
    directory-dominated records). Full reads keep the directory-driven
    path — the header-crc-validates-directory defense is unchanged
    there — and v2 records stay readable without any index."""
    total_bits = key_dt.itemsize * 8
    if seg_index is not None and filter_specs is not None:
        parts = []
        for r, p, c, seg_crc, off, nb in seg_index:
            if not c or not _segment_matches(r, p, filter_specs):
                continue
            buf = read_at(off, nb)
            if (zlib.crc32(buf) & 0xFFFFFFFF) != seg_crc:
                raise SpillRecordError(
                    f"spill record {path}: checksum mismatch (corrupt "
                    f"segment resolved={r} prefix={p:#x})"
                )
            width = total_bits - r
            low = _unpack_low_bits(buf, c, width)
            if r:
                low |= np.uint64(p << width)
            parts.append(low.astype(key_dt))
        if not parts:
            return np.empty((0,), key_dt)
        return parts[0] if len(parts) == 1 else np.concatenate(parts)
    if nbytes < _SEG_COUNT.size:
        raise SpillRecordError(
            f"spill record {path}: truncated segment directory"
        )
    head = read_at(0, _SEG_COUNT.size)
    (nseg,) = _SEG_COUNT.unpack(head.tobytes())
    dirlen = _SEG_COUNT.size + nseg * _SEG_ENTRY.size
    if nseg < 0 or dirlen > nbytes:
        raise SpillRecordError(
            f"spill record {path}: segment directory of {nseg} entries "
            "does not fit the payload"
        )
    dirbytes = read_at(0, dirlen)
    if (zlib.crc32(dirbytes) & 0xFFFFFFFF) != dir_crc:
        raise SpillRecordError(
            f"spill record {path}: checksum mismatch (corrupt segment "
            "directory)"
        )
    entries = []
    pos = _SEG_COUNT.size
    raw_dir = dirbytes.tobytes()
    for _ in range(nseg):
        r, p, c, seg_crc = _SEG_ENTRY.unpack_from(raw_dir, pos)
        pos += _SEG_ENTRY.size
        if not 0 <= r < total_bits or c < 0 or (p >> r if r else p):
            raise SpillRecordError(
                f"spill record {path}: bad segment (resolved={r}, "
                f"prefix={p:#x}, count={c}) for {total_bits}-bit keys"
            )
        entries.append((r, p, c, seg_crc))
    if sum(c for _, _, c, _ in entries) != n_valid:
        raise SpillRecordError(
            f"spill record {path}: segment counts sum to "
            f"{sum(c for _, _, c, _ in entries)}, header says "
            f"{n_valid} keys"
        )
    expect = dirlen + sum(
        (c * (total_bits - r) + 7) // 8 for r, _, c, _ in entries
    )
    if expect != nbytes:
        raise SpillRecordError(
            f"spill record {path}: packed payload is {nbytes} bytes, "
            f"segment directory implies {expect}"
        )
    off = dirlen
    parts = []
    for r, p, c, seg_crc in entries:
        width = total_bits - r
        nb = (c * width + 7) // 8
        if c and (
            filter_specs is None or _segment_matches(r, p, filter_specs)
        ):
            buf = read_at(off, nb)
            if (zlib.crc32(buf) & 0xFFFFFFFF) != seg_crc:
                raise SpillRecordError(
                    f"spill record {path}: checksum mismatch (corrupt "
                    f"segment resolved={r} prefix={p:#x})"
                )
            low = _unpack_low_bits(buf, c, width)
            if r:
                low |= np.uint64(p << width)
            parts.append(low.astype(key_dt))
        off += nb
    if not parts:
        return np.empty((0,), key_dt)
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


@dataclasses.dataclass(frozen=True)
class SpillRecord:
    """On-disk metadata of one spilled chunk — the ``(chunk_index, bucket,
    dtype, device)`` key plus payload size/checksum. The header written to
    disk repeats all of it, and the reader cross-checks both. ``nbytes``
    and ``crc32`` describe the PHYSICAL payload — the full-width keys for
    format v1, the packed tail (directory + bit-packed segments) for v2;
    ``logical_nbytes`` is always the full-width key bytes a pass reading
    this record streams into its consumers."""

    path: str
    chunk_index: int
    n_valid: int
    bucket: int
    device_slot: int | None
    key_dtype: np.dtype
    orig_dtype: np.dtype
    crc32: int
    nbytes: int
    version: int = _VERSION
    #: v2 records: the ``(resolved, prefix, count, payload_crc32)``
    #: segment layout the writer produced — what
    #: :meth:`SpillGeneration.read_nbytes` prices a pruned read against
    #: without touching disk, and the raw material of the generation's
    #: segment index (direct-seek pruned reads). ``None`` for v1.
    segments: tuple | None = None

    @property
    def packed(self) -> bool:
        return self.version >= _VERSION_PACKED

    @property
    def logical_nbytes(self) -> int:
        return self.n_valid * self.key_dtype.itemsize


@dataclasses.dataclass(frozen=True)
class SpillChunk:
    """One replayed chunk: already-encoded keys (host, key space) plus the
    staging metadata the pipeline needs to re-stage it onto the SAME
    round-robin slot that consumed it originally. ``streaming/chunked.py:
    _encode_chunk`` recognizes this type and skips re-encoding."""

    keys: np.ndarray
    orig_dtype: np.dtype
    device_slot: int | None
    chunk_index: int
    bucket: int


@dataclasses.dataclass(frozen=True)
class PreparedSpillRecord:
    """The order-free half of one spill append: keys packed (or not) and
    checksummed, but not yet assigned a record index or written to disk.
    :meth:`SpillWriter.prepare` builds these from ANY thread (the ingest
    pool's pack phase); :meth:`SpillWriter.append_prepared` turns one
    into an on-disk record on the sequencer-serialized in-order path."""

    n: int
    key_dtype: np.dtype
    orig_dtype: np.dtype
    version: int
    payload: np.ndarray
    crc: int
    segments: tuple | None


class SpillWriter:
    """Append-only writer for ONE spill generation. ``append`` is called
    from a single thread per pass (the pipeline's producer for the pass-0
    tee, the descent's consumer for the filtered survivor writes);
    ``commit``/``abort`` run after the pass's threads are joined.

    With ``pack_specs`` (the pass's ``(resolved_bits, prefix)`` filter
    union) and ``total_bits``, each appended record is prefix-packed on
    the appending thread into format v2 — only the unresolved low bits of
    each survivor hit disk — whenever the packed form is actually smaller
    than the full-width record (tiny records where the segment directory
    would dominate fall back to v1 per record, so a generation's physical
    bytes never exceed its logical bytes).

    ``pack_digit_bits`` is the UNFILTERED tee's form of the same format
    (the pass-0 / sketch tee, where nothing is resolved yet and there is
    no spec union): each record segments its keys by their top
    ``pack_digit_bits`` bits, with the specs derived per record from the
    digits actually present. The point is less the pack (it strips only
    ``pack_digit_bits`` per key) than the DIRECTORY: a later pass's
    filtered replay prunes straight to the segments under its surviving
    prefixes, which is what deletes the historical second full-N read."""

    def __init__(
        self, store: "SpillStore", index: int, path: str,
        pack_specs=None, total_bits: int | None = None,
        pack_digit_bits: int | None = None,
    ):
        self.store = store
        self.index = index
        self.path = path
        if pack_specs is not None and total_bits is None:  # pragma: no cover
            raise SpillError("pack_specs requires total_bits")
        if pack_specs is not None and pack_digit_bits:  # pragma: no cover
            raise SpillError("pack_specs and pack_digit_bits are exclusive")
        self._pack_specs = (
            None if pack_specs is None else tuple(
                (int(r), int(p)) for r, p in pack_specs
            )
        )
        self._total_bits = total_bits
        self._pack_digit_bits = (
            int(pack_digit_bits) if pack_digit_bits else None
        )
        os.makedirs(path)
        self._records: list[SpillRecord] = []
        self._count = 0
        self._done = False

    def prepare(self, keys: np.ndarray, orig_dtype) -> PreparedSpillRecord:
        """The order-free half of :meth:`append`: ravel, derive the pack
        specs, pack, checksum. Reads only the writer's IMMUTABLE config
        (``_pack_specs``/``_total_bits``/``_pack_digit_bits``), so any
        ingest-pool worker may call it concurrently and out of order —
        no record index is assigned and nothing touches disk until
        :meth:`append_prepared` runs on the in-order path."""
        keys = np.ascontiguousarray(keys)
        if keys.ndim != 1:  # pragma: no cover - callers always ravel
            keys = keys.ravel()
        n = int(keys.shape[0])
        specs, total_bits = self._pack_specs, self._total_bits
        if specs is None and self._pack_digit_bits is not None and n:
            # digit-segmented tee: specs derive from the record's own
            # keys (the digits present), so every key assigns and empty
            # segments never burden the directory
            total_bits = keys.dtype.itemsize * 8
            s = min(self._pack_digit_bits, total_bits - 1)
            tops = np.unique(
                np.ascontiguousarray(keys).astype(np.uint64)
                >> np.uint64(total_bits - s)
            )
            specs = tuple((s, int(t)) for t in tops)
        version, payload, layout = _VERSION, keys, None
        if specs is not None:
            tail, dir_nbytes, seg_layout = _pack_payload(
                keys, specs, total_bits
            )
            if tail.nbytes < keys.nbytes:
                # packing wins only when the directory + packed segments
                # undercut the full-width record — per record, so a
                # packed generation is never physically larger than v1
                version, payload, layout = _VERSION_PACKED, tail, seg_layout
        crc = zlib.crc32(
            payload[:dir_nbytes].data if version == _VERSION_PACKED
            else payload.data
        ) & 0xFFFFFFFF
        return PreparedSpillRecord(
            n=n,
            key_dtype=np.dtype(keys.dtype),
            orig_dtype=np.dtype(orig_dtype),
            version=version,
            payload=payload,
            crc=crc,
            segments=layout,
        )

    def append_prepared(
        self, prep: PreparedSpillRecord, device_slot=None
    ) -> SpillRecord:
        """Write one prepared record to disk as the NEXT record of the
        generation — the ordered half of :meth:`append`, called from
        exactly one thread at a time in stream order (the pipeline's
        sequencer serializes the ingest pool onto this path)."""
        if self._done:
            raise SpillError("spill generation already committed/aborted")
        # chaos hook, keyed by the record index WITHIN the generation
        # (ENOSPC, transient raise) — stable across recovery re-runs: a
        # re-run pass builds a fresh writer whose counts restart, so
        # re-appending record i advances the (site, i) ATTEMPT counter
        # instead of landing on a fresh index, which is what lets a plan
        # schedule both one-shot and hard write faults — and stable
        # across ingest-pool widths, because the index is assigned at
        # in-order write time, not at pack time. Fires BEFORE anything
        # touches disk, so a recovered pass re-appends cleanly; a real
        # mid-write ENOSPC surfaces from the open/write below as the
        # same OSError class either way.
        _maybe_fault("spill.write", index=self._count)
        slot = -1 if device_slot is None else int(device_slot)
        rec_path = os.path.join(self.path, f"r{self._count:08d}.kspill")
        header = _HEADER.pack(
            _MAGIC,
            prep.version,
            self._count,
            prep.n,
            _bucket_elems(prep.n),
            slot,
            _pack_dtype(prep.key_dtype),
            _pack_dtype(prep.orig_dtype),
            prep.crc,
            prep.payload.nbytes,
        )
        with open(rec_path, "wb") as f:
            f.write(header)
            f.write(prep.payload.data)
        rec = SpillRecord(
            path=rec_path,
            chunk_index=self._count,
            n_valid=prep.n,
            bucket=_bucket_elems(prep.n),
            device_slot=device_slot,
            key_dtype=prep.key_dtype,
            orig_dtype=prep.orig_dtype,
            crc32=prep.crc,
            nbytes=int(prep.payload.nbytes),
            version=prep.version,
            segments=prep.segments,
        )
        self._records.append(rec)
        self._count += 1
        return rec

    def append(self, keys: np.ndarray, orig_dtype, device_slot=None) -> SpillRecord:
        """Write one chunk's encoded keys as a record. ``keys`` must be a
        host key-space array (the caller materializes device survivors);
        ``orig_dtype`` is the STREAM dtype the keys encode (recorded so a
        replay validates against the stream like any other chunk).
        Composition of :meth:`prepare` + :meth:`append_prepared` — the
        single-threaded legacy shape, byte-identical on disk to the
        pooled split."""
        return self.append_prepared(
            self.prepare(keys, orig_dtype), device_slot=device_slot
        )

    def commit(self) -> "SpillGeneration":
        """Finalize: register the generation with the store and return it."""
        if self._done:
            raise SpillError("spill generation already committed/aborted")
        self._done = True
        gen = SpillGeneration(self.store, self.index, self.path, tuple(self._records))
        self.store._register(gen)
        return gen

    def abort(self) -> None:
        """Drop every record written so far (idempotent) — the unwind path
        when the pass feeding this generation raises mid-stream."""
        if self._done:
            return
        self._done = True
        shutil.rmtree(self.path, ignore_errors=True)


class SpillGeneration:
    """One committed generation: an ordered, replayable set of records.
    ``as_source()`` is a valid chunk source for every streaming entry
    point — each invocation re-reads (and re-validates) the records."""

    def __init__(self, store, index: int, path: str, records: tuple):
        self.store = store
        self.index = index
        self.path = path
        self.records = records
        self.dropped = False
        # generation-level segment index: the per-record v2 directories
        # hoisted into one in-memory map (chunk_index -> ((resolved,
        # prefix, count, payload_crc, offset, nbytes), ...)), offsets
        # relative to the payload start. Pruned reads seek straight to
        # matching segments through this instead of re-reading each
        # record's on-disk directory — the per-record directory tax that
        # could push a small pruned read's physical bytes above logical.
        # Records written before the 4-tuple layout (no per-segment crc)
        # stay index-less and keep the directory-driven read.
        seg_index = {}
        for rec in records:
            if rec.segments is None or any(len(s) != 4 for s in rec.segments):
                continue
            bits = rec.key_dtype.itemsize * 8
            off = _SEG_COUNT.size + len(rec.segments) * _SEG_ENTRY.size
            entries = []
            for r, p, c, seg_crc in rec.segments:
                nb = (c * (bits - r) + 7) // 8
                entries.append((r, p, c, seg_crc, off, nb))
                off += nb
            seg_index[rec.chunk_index] = tuple(entries)
        self._seg_index = seg_index

    @property
    def nbytes(self) -> int:
        """Total PHYSICAL payload bytes on disk (packed size for v2
        records) — what the generation costs in disk and disk-read I/O."""
        return sum(r.nbytes for r in self.records)

    @property
    def logical_nbytes(self) -> int:
        """Total full-width key bytes a pass reading this generation
        streams into its consumers (== ``nbytes`` for all-v1 gens)."""
        return sum(r.logical_nbytes for r in self.records)

    @property
    def packed(self) -> bool:
        """True when any record is format-v2 prefix-packed."""
        return any(r.packed for r in self.records)

    @property
    def keys(self) -> int:
        return sum(r.n_valid for r in self.records)

    def iter_chunks(self, mmap: bool = False, filter_specs=None,
                    workers: int = 1):
        """Yield every record as a :class:`SpillChunk`, validating headers,
        sizes and checksums — any mismatch raises
        :class:`~mpi_k_selection_tpu.errors.SpillRecordError`. With
        ``mmap`` the payload is served as a read-only ``np.memmap`` view
        (page-cache backed, checksummed in place) instead of a fresh heap
        copy — the deferred executor's replay mode, where most of each
        record's bytes are about to be filtered away on device anyway.

        ``filter_specs`` (a ``(resolved_bits, prefix)`` union) PRUNES the
        read of v2 records to the segments that may hold matching keys,
        seeking past the rest — the consumers' own exact filters see
        every key they would have selected from the full read (segment
        pruning is a superset of the spec filter), so answers are
        bit-identical while the generation's I/O shrinks to the surviving
        buckets. v1 records have no directory and are always read whole;
        records left with no matching segment (or no keys) are skipped
        entirely.

        ``workers`` > 1 decodes records on a pool of
        ``ksel-ingest-decode-*`` threads (file read + CRC + v2 bit
        unpack off the consumer thread, both heap and mmap routes) while
        this generator still yields strictly in record order — the read
        side's mirror of the ingest pool, same chunks in the same order
        as the serial path. Decode-ahead is bounded (pool + 2 records)
        so a slow consumer never forces the whole generation resident."""
        if self.dropped:
            raise SpillError(
                f"spill generation {self.index} was dropped (or its store "
                "closed); it can no longer serve as a chunk source"
            )
        pool_n = min(int(workers), len(self.records))
        if pool_n > 1:
            yield from self._iter_chunks_pooled(pool_n, mmap, filter_specs)
            return
        for rec in self.records:
            chunk = _read_record(
                rec, mmap=mmap, filter_specs=filter_specs,
                seg_index=self._seg_index.get(rec.chunk_index),
            )
            if filter_specs is not None and chunk.keys.shape[0] == 0:
                continue
            yield chunk

    def _iter_chunks_pooled(self, pool_n: int, mmap, filter_specs):
        """Worker-pool decode: each ``ksel-ingest-decode-*`` thread pulls
        record indices, runs ``_read_record`` OUTSIDE any lock, and
        parks the result (or the exception) for the main generator to
        release in index order. Every record still passes through
        ``_read_record`` — the ``spill.read`` chaos hook and header
        validation fire per record exactly as on the serial path, so
        seeded fault plans replay identically at any pool width."""
        gen_id = next(_DECODE_IDS)
        window = pool_n + 2  # bounded decode-ahead
        tasks = queue.Queue()
        for i in range(len(self.records)):
            tasks.put(i)
        stop = threading.Event()
        cond = threading.Condition()
        results = {}  # ksel: guarded-by[cond]
        state = {"next": 0}  # ksel: guarded-by[cond]

        def _decode():
            while not stop.is_set():
                try:
                    i = tasks.get_nowait()
                except queue.Empty:
                    return
                with cond:
                    while (
                        i >= state["next"] + window and not stop.is_set()
                    ):
                        cond.wait(0.05)
                if stop.is_set():
                    return
                rec = self.records[i]
                try:
                    out = _read_record(
                        rec, mmap=mmap, filter_specs=filter_specs,
                        seg_index=self._seg_index.get(rec.chunk_index),
                    )
                except BaseException as e:  # noqa: BLE001 - surfaced in order
                    out = e
                with cond:
                    results[i] = out
                    cond.notify_all()

        threads = [
            threading.Thread(
                target=_decode,
                name=f"{INGEST_THREAD_PREFIX}-decode-{gen_id}-{w}",
                daemon=True,
            )
            for w in range(pool_n)
        ]
        for t in threads:
            t.start()
        try:
            for i in range(len(self.records)):
                with cond:
                    while i not in results:
                        cond.wait(0.05)
                    chunk = results.pop(i)
                    state["next"] = i + 1
                    cond.notify_all()
                if isinstance(chunk, BaseException):
                    raise chunk
                if filter_specs is not None and chunk.keys.shape[0] == 0:
                    continue
                yield chunk
        finally:
            stop.set()
            with cond:
                cond.notify_all()
            for t in threads:
                t.join(timeout=10.0)

    def as_source(self, mmap: bool = False, filter_specs=None,
                  workers: int = 1):
        """Zero-arg callable returning a fresh record iterator — the
        replayable chunk-source form streaming/chunked.py consumes.
        ``filter_specs`` prunes v2 records to matching segments;
        ``workers`` > 1 decodes on a thread pool (see
        :meth:`iter_chunks`)."""
        if not mmap and filter_specs is None and workers <= 1:
            return self.iter_chunks
        import functools

        return functools.partial(
            self.iter_chunks, mmap=mmap,
            filter_specs=(
                None if filter_specs is None
                else tuple((int(r), int(p)) for r, p in filter_specs)
            ),
            workers=int(workers),
        )

    def read_nbytes(self, filter_specs=None) -> int:
        """PHYSICAL bytes a (possibly pruned) read of this generation
        touches: every v1 record whole; for v2 records the segments
        matching ``filter_specs`` — plus the on-disk directory only for
        records the generation-level segment index does not cover (an
        indexed pruned read seeks straight to its segments and never
        touches the directory). Priced statically from the writers'
        recorded segment layouts, so the descent's disk accounting needs
        no second pass over the files."""
        if filter_specs is None:
            return self.nbytes
        specs = tuple((int(r), int(p)) for r, p in filter_specs)
        total = 0
        for rec in self.records:
            if rec.segments is None:
                total += rec.nbytes
                continue
            bits = rec.key_dtype.itemsize * 8
            if rec.chunk_index not in self._seg_index:
                total += _SEG_COUNT.size + len(rec.segments) * _SEG_ENTRY.size
            total += sum(
                (c * (bits - r) + 7) // 8
                for r, p, c, *_ in rec.segments
                if _segment_matches(r, p, specs)
            )
        return total

    def read_keys(self, filter_specs=None) -> int:
        """Keys a (possibly pruned) read of this generation streams into
        its consumers — the logical twin of :meth:`read_nbytes`."""
        if filter_specs is None:
            return self.keys
        specs = tuple((int(r), int(p)) for r, p in filter_specs)
        total = 0
        for rec in self.records:
            if rec.segments is None:
                total += rec.n_valid
            else:
                total += sum(
                    c for r, p, c, *_ in rec.segments
                    if _segment_matches(r, p, specs)
                )
        return total

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"SpillGeneration(index={self.index}, records={len(self.records)}, "
            f"keys={self.keys}, nbytes={self.nbytes})"
        )


def _read_record(
    rec: SpillRecord, mmap: bool = False, filter_specs=None, seg_index=None
) -> SpillChunk:
    # chaos hook, keyed by the record's chunk index: transient raises and
    # checksum blips fire here; the persistent kinds (corrupt_disk,
    # truncate) damage the file on disk and fall through, so the REAL
    # header/size/CRC validation below is what raises — the recovery
    # ladder (streaming/chunked.py:_recover_pass) is exercised against
    # the production error surface, not a simulated one.
    _maybe_fault("spill.read", index=rec.chunk_index, path=rec.path)
    try:
        f = open(rec.path, "rb")
    except OSError as e:
        raise SpillRecordError(f"spill record {rec.path}: unreadable ({e})") from e
    with f:
        head = f.read(_HEADER.size)
        if len(head) != _HEADER.size:
            raise SpillRecordError(
                f"spill record {rec.path}: truncated header "
                f"({len(head)} of {_HEADER.size} bytes)"
            )
        (
            magic, version, chunk_index, n_valid, bucket, slot,
            key_dt_raw, orig_dt_raw, crc, nbytes,
        ) = _HEADER.unpack(head)
        if magic != _MAGIC or version not in (_VERSION, _VERSION_PACKED):
            raise SpillRecordError(
                f"spill record {rec.path}: bad magic/version "
                f"({magic!r}, {version})"
            )
        key_dt = _unpack_dtype(key_dt_raw, rec.path)
        orig_dt = _unpack_dtype(orig_dt_raw, rec.path)
        meta = (
            version, chunk_index, n_valid, bucket,
            None if slot < 0 else slot, key_dt, orig_dt, crc, nbytes,
        )
        want = (
            rec.version, rec.chunk_index, rec.n_valid, rec.bucket,
            rec.device_slot, rec.key_dtype, rec.orig_dtype, rec.crc32, rec.nbytes,
        )
        if meta != want:
            raise SpillRecordError(
                f"spill record {rec.path}: header does not match the "
                f"writer's metadata (header {meta}, expected {want})"
            )
        if version == _VERSION and nbytes != n_valid * key_dt.itemsize:
            # a v2 payload's size is validated against its own segment
            # directory inside _unpack_payload instead
            raise SpillRecordError(
                f"spill record {rec.path}: payload size {nbytes} != "
                f"{n_valid} x {key_dt.itemsize}-byte keys"
            )
        if not mmap and version == _VERSION:
            payload = f.read(nbytes)
            if len(payload) != nbytes:
                raise SpillRecordError(
                    f"spill record {rec.path}: truncated payload "
                    f"({len(payload)} of {nbytes} bytes)"
                )
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise SpillRecordError(
                    f"spill record {rec.path}: checksum mismatch (corrupt payload)"
                )
            keys = np.frombuffer(payload, dtype=key_dt)
        elif not mmap:
            # v2 on the read route: seek-driven — the directory names
            # every segment's offset, so a pruned read's file I/O really
            # is only the directory plus the matching segments
            def _file_at(off, size, f=f):
                f.seek(_HEADER.size + off)
                buf = f.read(size)
                if len(buf) != size:
                    raise SpillRecordError(
                        f"spill record {rec.path}: truncated payload "
                        f"({len(buf)} of {size} bytes at offset {off})"
                    )
                return np.frombuffer(buf, np.uint8)

            keys = _read_packed(
                _file_at, int(nbytes), int(n_valid), key_dt, crc, rec.path,
                filter_specs, seg_index=seg_index,
            )
    if mmap and n_valid == 0:  # pragma: no cover - writers skip empty chunks
        keys = np.empty((0,), key_dt)
    elif mmap:
        # read-only page-cache view of the payload (no heap copy); the
        # checksum still runs over every payload byte a consumer can see
        # before a single key reaches it (v2 pruned reads checksum the
        # directory + each read segment) — mmap changes residency, never
        # the contract
        try:
            raw = np.memmap(  # read-only payload view inside the sanctioned spill module (KSL008 exempts spill.py; the staleness audit retired the old noqa)
                rec.path,
                dtype=key_dt if version == _VERSION else np.uint8,
                mode="r", offset=_HEADER.size,
                shape=(int(n_valid if version == _VERSION else nbytes),),
            )
        except (OSError, ValueError) as e:
            raise SpillRecordError(
                f"spill record {rec.path}: truncated payload (mmap of "
                f"{nbytes} bytes failed: {e})"
            ) from e
        if version == _VERSION:
            if (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
                raise SpillRecordError(
                    f"spill record {rec.path}: checksum mismatch (corrupt payload)"
                )
            keys = raw  # v1 serves the page-cache view itself
        else:
            # a packed record necessarily reconstructs onto the heap
            # (bits -> full keys); pruned segments' pages stay untouched
            def _mem_at(off, size, raw=raw):
                return raw[off:off + size]

            keys = _read_packed(
                _mem_at, int(nbytes), int(n_valid), key_dt, crc, rec.path,
                filter_specs, seg_index=seg_index,
            )
    return SpillChunk(
        keys=keys,
        orig_dtype=orig_dt,
        device_slot=None if slot < 0 else int(slot),
        chunk_index=int(chunk_index),
        bucket=int(bucket),
    )


class SpillStore:
    """A directory of spill generations plus the per-pass streaming log.

    Create one explicitly to own the lifecycle (tee a sketch's single
    stream pass, inspect ``pass_log`` after a descent, reuse gen 0 across
    calls), or let ``streaming_kselect{,_many}`` create and clean one up
    internally (``spill='force'``, or ``'auto'`` with a one-shot source).
    Context-manager protocol closes (removes) the directory.
    """

    def __init__(self, spill_dir: str | None = None):
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        self.root = tempfile.mkdtemp(prefix=SPILL_DIR_PREFIX, dir=spill_dir)
        self.generations: dict[int, SpillGeneration] = {}
        #: One dict per streamed pass of a spill-enabled descent:
        #: ``{"pass", "read", "keys_read", "bytes_read"[, "keys_written",
        #: "bytes_written", "disk_bytes_read", "disk_bytes_written"]}`` —
        #: the raw material of bench_streaming_oc's ``_spill`` record
        #: (pass_shrink_ratio, disk_bytes_ratio). ``bytes_*`` are LOGICAL
        #: full-width key bytes; the ``disk_bytes_*`` columns are the
        #: physical on-disk bytes (smaller for packed v2 generations).
        self.pass_log: list[dict] = []
        self._counter = 0
        self._closed = False

    @property
    def closed(self) -> bool:
        return self._closed

    def _check_open(self) -> None:
        if self._closed:
            raise SpillError("spill store is closed")

    def new_generation(
        self, pack_specs=None, total_bits=None, pack_digit_bits=None,
    ) -> SpillWriter:
        """Open a writer for the next generation. ``pack_specs`` (a
        ``(resolved_bits, prefix)`` union) + ``total_bits`` turn on the
        format-v2 prefix packing for every record the writer appends —
        the descent passes its tee filter specs here under
        ``pack_spill="auto"``. ``pack_digit_bits`` is the unfiltered
        (pass-0 / sketch) tee's v2 mode: records segment by their keys'
        top digit so later filtered replays can prune (see
        :class:`SpillWriter`). ``None`` for both keeps the full-width v1
        records."""
        self._check_open()
        idx = self._counter
        self._counter += 1
        return SpillWriter(
            self, idx, os.path.join(self.root, f"gen-{idx:04d}"),
            pack_specs=pack_specs, total_bits=total_bits,
            pack_digit_bits=pack_digit_bits,
        )

    def _register(self, gen: SpillGeneration) -> None:
        self._check_open()
        self.generations[gen.index] = gen
        # the on-disk byte book (obs/ledger.py): committed generations add
        # their payload bytes, drop/close subtracts them exactly once
        _ledger.LEDGER.adjust_bytes("spill", "disk", gen.nbytes)

    def latest_generation(self) -> SpillGeneration:
        """The newest committed generation — what a store-as-source read
        (``streaming_kselect(store, k)``, the certificate, ``refine``)
        streams from."""
        self._check_open()
        if not self.generations:
            raise SpillError(
                "spill store holds no committed generation; run a teeing "
                "pass first (streaming_kselect(..., spill=store) or "
                "RadixSketch.update_stream(..., spill=store))"
            )
        return self.generations[max(self.generations)]

    def drop_generation(self, gen: SpillGeneration) -> None:
        """Delete one generation's records (the eager disk-bound trim:
        at most two generations coexist during a descent)."""
        gen.dropped = True
        if self.generations.pop(gen.index, None) is not None:
            # pop-guarded so a double drop cannot double-subtract
            _ledger.LEDGER.adjust_bytes("spill", "disk", -gen.nbytes)
        shutil.rmtree(gen.path, ignore_errors=True)

    def close(self) -> None:
        """Remove the whole store directory. Idempotent; every generation
        becomes unreadable (``dropped``)."""
        if self._closed:
            return
        self._closed = True
        for gen in self.generations.values():
            gen.dropped = True
            _ledger.LEDGER.adjust_bytes("spill", "disk", -gen.nbytes)
        self.generations.clear()
        shutil.rmtree(self.root, ignore_errors=True)

    def __enter__(self) -> "SpillStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "closed" if self._closed else f"{len(self.generations)} gens"
        return f"SpillStore({self.root!r}, {state})"
