import sys

from mpi_k_selection_tpu.cli import main

sys.exit(main())
