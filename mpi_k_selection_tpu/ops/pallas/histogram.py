"""Pallas TPU radix-histogram kernel — the production hot loop.

This is the hand-written replacement for the reference's hot local compute
(the per-shard ``qsort`` at ``TODO-kth-problem-cgm.c:115`` and the linear
L/E/G counting sweep at ``:175-185``): one streaming pass over the shard that
counts radix-digit occurrences among elements matching the current prefix.

Kernel design (per the TPU architecture, not the reference's C loops):

- The input is viewed as ``(M, 128)`` — lanes are the fast axis — and the
  grid walks row-blocks of ``block_rows`` rows. Each step DMAs one block to
  VMEM (Pallas double-buffers automatically) and the VPU computes a
  *per-lane* histogram: ``blockhist[b, lane] = #{rows: digit == b}``.
  Keeping 128 independent lane-histograms avoids any cross-lane reduction
  inside the kernel; the tiny ``(nbuckets, 128)`` accumulator is summed over
  lanes once at the end, outside the kernel.
- The prefix test is fused into the digit compare: with
  ``z = (key >> shift) ^ (prefix << radix_bits)``, ``z == b`` holds iff the
  digit is b AND the key's high bits equal the prefix — one compare per
  bucket covers both, and the first (prefix-free) pass is just ``prefix=0``
  with ``shift + radix_bits`` past the top of the key.
- No masking in the kernel at all: the wrapper zero-pads to whole blocks,
  and the padded elements' fixed bucket (``z == prefix << radix_bits``,
  hit only when prefix == 0) is subtracted analytically afterward.
- Buckets are enumerated statically (``nbuckets`` compares of a
  ``(block_rows, 128)`` tile per step): dense VPU work, no scatter, no
  dynamic shapes. With ``radix_bits=4`` that is ~34 ops/element/pass,
  streaming near HBM bandwidth.

TPU vector lanes are 32-bit, so 64-bit keys run as two u32 *planes*
(``pallas_radix_histogram64``): radix descent resolves the high 32 bits
first — those passes read only the hi plane through the 32-bit kernel — and
the low-bit passes use a two-plane kernel whose active test fuses
``hi == prefix_hi`` into the digit compare with one select.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu.utils import compat
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128


def _i32const(v: int) -> int:
    """Python int with the uint32 bit pattern ``v`` as a signed int32 value
    (the kernels compute on int32 bit patterns)."""
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def split_planes(keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``(hi, lo)`` uint32 planes of uint64 ``keys``, materialized contiguously.

    Radix descent calls the histogram once per pass; deinterleaving the
    planes inside each call re-materializes the strided split every pass
    (XLA does not hoist the large intermediate out of the unrolled pass
    loop) — measured ~5x the kernel's own runtime on v5e. Pass-loop callers
    split + tile once up front via ``ops/histogram.py:prepare_keys`` and
    thread the result through ``masked_radix_histogram(..., tiles=...,
    orig_n=...)``.
    """
    keys = keys.ravel()
    hi = jax.lax.shift_right_logical(keys, jnp.uint64(32)).astype(jnp.uint32)
    lo = keys.astype(jnp.uint32)  # truncation: low 32 bits
    return hi, lo


def prepare_tiles32(keys: jax.Array, block_rows: int = 4096):
    """``(tiles, n)``: keys raveled, zero-padded to whole ``(block_rows,
    LANES)`` blocks, in the kernel's 2-D layout, kept in uint32.

    Pass-loop callers prepare ONCE and thread ``tiles`` through every pass
    (and the cutover collect): at 1B-element scale, letting each pass
    re-derive the tiled view makes XLA hold/remat several extra full-size
    temporaries — enough to exceed a 16 GB HBM by itself. Prepared, the
    program's big buffers are exactly the input and this one view. The
    tiles stay uint32 so the collect path can consume the very same buffer
    (an int32 view would make XLA cancel the bitcast pair and materialize
    both dtypes' pipelines); the kernels bitcast to int32 per block in VMEM.
    """
    keys = keys.ravel()
    if keys.dtype.itemsize > 4:
        raise ValueError("prepare_tiles32 wants <=32-bit keys")
    if keys.dtype != jnp.uint32:
        keys = keys.astype(jnp.uint32)
    n = keys.shape[0]
    grid = -(-n // (block_rows * LANES))
    pad_to = grid * block_rows * LANES
    kp = jnp.pad(keys, (0, pad_to - n))
    return kp.reshape(grid * block_rows, LANES), n


def prepare_tiles64(keys: jax.Array, block_rows: int = 4096):
    """``(hi_tiles, lo_tiles, n)`` for the two-plane 64-bit kernel; the hi
    tiles also serve the ``shift >= 32`` passes through the 32-bit kernel."""
    hi, lo = split_planes(keys)
    hi2, n = prepare_tiles32(hi, block_rows)
    lo2, _ = prepare_tiles32(lo, block_rows)
    return hi2, lo2, n


def prepare_raw_tiles32(x: jax.Array, block_rows: int = 4096):
    """``(tiles, n)`` raw tiles of a 4-byte-dtype array IN ITS OWN DTYPE —
    no key transform pass, no bitcast. The sortable-key transform happens
    inside the kernel (``key_op``/``key_xor``, utils/dtypes.py:key_fold),
    which bitcasts each block in VMEM anyway.

    Keeping the original dtype is load-bearing: a dtype-changing bitcast
    feeding a Pallas custom call makes XLA materialize a full copy of the
    array (measured 1.63 ms for 537 MB on v5e), while a pure row-major
    reshape aliases the input buffer — so on block-aligned n this prepare
    is FREE and a single-shot select touches the data only inside the
    kernels. Padding (ragged n) writes raw-zero elements; the wrappers'
    pad correction accounts for their key being to_sortable(0)."""
    x = x.ravel()
    if np.dtype(x.dtype).itemsize != 4:
        raise ValueError(f"prepare_raw_tiles32 wants a 4-byte dtype, got {x.dtype}")
    n = x.shape[0]
    grid = -(-n // (block_rows * LANES))
    pad_to = grid * block_rows * LANES
    xp = jnp.pad(x, (0, pad_to - n)) if pad_to != n else x
    return xp.reshape(grid * block_rows, LANES), n


def prepare_raw_tiles64(x: jax.Array, block_rows: int = 4096):
    """``(hi_tiles, lo_tiles, n)`` of RAW bit planes of an 8-byte-dtype
    array; the key transform happens in kernel (see prepare_raw_tiles32).
    Skips the full-array to_sortable pass; the plane deinterleave remains
    (it is the kernels' required layout)."""
    x = x.ravel()
    if np.dtype(x.dtype).itemsize != 8:
        raise ValueError(f"prepare_raw_tiles64 wants an 8-byte dtype, got {x.dtype}")
    if x.dtype == jnp.float64:
        from mpi_k_selection_tpu.utils.dtypes import f64_to_u64_bits

        raw = f64_to_u64_bits(x)  # f64-source bitcasts crash the TPU compiler
    else:
        raw = jax.lax.bitcast_convert_type(x, jnp.uint64)
    return prepare_tiles64(raw, block_rows)


def _match_vma(x, vma):
    """Promote ``x``'s varying-manual-axes type to ``vma`` — the SMEM scalar
    refs are derived from psummed (invariant) walk state, while the tiles
    are device-varying under shard_map; pallas_call wants them to agree.
    No-op outside shard_map (both sides empty)."""
    missing = tuple(sorted(vma - compat.vma_of(x)))
    return compat.pvary(x, missing) if missing else x


from mpi_k_selection_tpu.ops.histogram import check_block_rows as _check_block_rows  # noqa: E402  (shared geometry contract; no cycle — ops.histogram imports pallas lazily)


def _cap_block_rows(block_rows: int, radix_bits: int) -> int:
    """Largest safe block height for the kernel's scoped-VMEM budget.

    radix_bits > 4 multiplies the SWAR register footprint (nreg =
    nbuckets/8 block-sized mask arrays), blowing the 16 MB scoped VMEM at
    4096 rows; 1024 is the measured-safe height there. The cap always
    divides the 4096-row prepared tiling, so capped calls still consume
    prepared tiles (the grid just gets finer).
    """
    return min(block_rows, 4096 if radix_bits <= 4 else 1024)


def _packed_count(z, out_ref, radix_bits, group=8, row0=0):
    """SWAR accumulation shared by the 32- and 64-bit packed kernels.

    Per element, one one-hot *bitfield* ``f = 1 << ((z & 7) * 4)`` selects a
    4-bit field; ``R = ceil(nbuckets/8)`` registers of 8 fields each cover
    the buckets, gated by ``z >> 3 == r``. Fields accumulate vertically over
    ``group``-row tiles (counts <= 15 per field per 15 groups), widen into
    8-bit fields every 15 groups, and are drained into the per-lane
    ``(nbuckets, 128)`` accumulator every 17 flushes (17 * 15 = 255, the
    byte-field ceiling — skew-safe at any block size) and at block end.
    Elements with any bit of ``z`` above ``radix_bits`` set (prefix
    mismatch / deactivated) match no register gate and count nowhere.
    """
    nb = 1 << radix_bits
    nreg = -(-nb // 8)
    rows = z.shape[0]
    ngroups = rows // group
    f = jax.lax.shift_left(
        jnp.int32(1), jax.lax.shift_left(z & jnp.int32(7), jnp.int32(2))
    )
    gate = jax.lax.shift_right_logical(z, jnp.int32(3))
    masks = [jnp.where(gate == jnp.int32(r), f, jnp.int32(0)) for r in range(nreg)]

    lo_mask = jnp.int32(0x0F0F0F0F)
    byte = jnp.int32(0xFF)
    zero = jnp.zeros((group, LANES), jnp.int32)
    acc = [zero for _ in range(nreg)]  # 4-bit fields, <= 15 groups
    wide_lo = [zero for _ in range(nreg)]  # 8-bit fields: buckets 8r+{0,2,4,6}
    wide_hi = [zero for _ in range(nreg)]  # 8-bit fields: buckets 8r+{1,3,5,7}

    def extract():
        # drain the byte fields into the 32-bit accumulator; a byte field
        # saturates at 255, so this must run at least every 17 flushes
        # (17 * 15 = 255) — skew-proof: a block that lands every element in
        # one bucket stays exact (the bug br>1920 had before this drain)
        rows_out = []
        for b in range(nb):
            r, j = b >> 3, b & 7
            w = wide_lo[r] if j % 2 == 0 else wide_hi[r]
            cnt = jax.lax.shift_right_logical(w, jnp.int32(8 * (j // 2))) & byte
            rows_out.append(jnp.sum(cnt, axis=0, dtype=jnp.int32))
        out_ref[row0:row0 + nb] += jnp.stack(rows_out)
        for r in range(nreg):
            wide_lo[r] = zero
            wide_hi[r] = zero

    since_flush = 0
    flushes = 0
    for g in range(ngroups):
        sl = slice(g * group, (g + 1) * group)
        for r in range(nreg):
            acc[r] = acc[r] + masks[r][sl]
        since_flush += 1
        if since_flush == 15 or g == ngroups - 1:
            for r in range(nreg):
                wide_lo[r] = wide_lo[r] + (acc[r] & lo_mask)
                wide_hi[r] = wide_hi[r] + (
                    jax.lax.shift_right_logical(acc[r], jnp.int32(4)) & lo_mask
                )
                acc[r] = zero
            since_flush = 0
            flushes += 1
            if flushes == 17 or g == ngroups - 1:
                extract()
                flushes = 0


def _shifted_digit(keys_ref, zref_ref, shift, radix_bits, has_prefix, key_op):
    """``z`` such that ``z == b`` iff the element is active and its digit is
    b — shared by the packed and compare 32-bit kernels.

    ``key_op`` selects the in-kernel key transform over the RAW bit tiles:

    - ``"none"``  — tiles already hold sortable keys (legacy path).
    - ``"xor"``   — key = raw ^ C for integer dtypes. FREE here: the shift
      distributes over xor, so C>>shift is folded into ``zref`` by the
      wrapper and this path is byte-identical to "none" with a prefix.
    - ``"float"`` — float32 keys (neg ? ~raw : raw | MSB). Two extra VPU
      ops: ``key >> shift`` equals ``(raw >> shift) ^ (neg ? ~0 >> shift
      : MSB >> shift)`` with both constants static.
    """
    k = jax.lax.bitcast_convert_type(keys_ref[:], jnp.int32)
    s = jax.lax.shift_right_logical(k, jnp.int32(shift))
    if key_op == "float":
        m_neg = jnp.int32(_i32const(0xFFFFFFFF >> shift))
        m_pos = jnp.int32(_i32const(0x80000000 >> shift))
        s = s ^ jnp.where(k < jnp.int32(0), m_neg, m_pos)
    if has_prefix or key_op != "none":
        # key_op="xor"/"float" route prefix-free passes through here too
        # (zref carries the fold constant; the wrapper enforces that the
        # digit then sits at the top of the key, so no mask is needed)
        return s ^ zref_ref[0, 0]
    return s & jnp.int32((1 << radix_bits) - 1)


def _hist_kernel_packed(
    zref_ref, keys_ref, out_ref, *, shift, radix_bits, has_prefix, key_op="none"
):
    """Packed-field (SWAR) histogram: ~3x fewer VPU ops than the compare-
    per-bucket kernel; measured 1.8x end-to-end on v5e (6.2ms vs 11.4ms for
    the 8-pass 134M select). Prefix fusion identical to ``_hist_kernel``."""
    i = pl.program_id(0)
    z = _shifted_digit(keys_ref, zref_ref, shift, radix_bits, has_prefix, key_op)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    _packed_count(z, out_ref, radix_bits)


def _lo_digit64(phi_ref, zlo_ref, hi_ref, lo_ref, shift, radix_bits, key_op):
    """``z`` for the low-bit passes over two RAW (with ``key_op``) or
    key-space planes; inactive elements (hi-plane prefix mismatch) are
    pushed out of every bucket with one select.

    ``key_op="float"`` applies the float64 transform in kernel: the whole
    64-bit key flips with the sign (held by the hi plane), so the lo plane's
    contribution is ``raw_lo ^ (neg ? ~0 : 0)`` and the hi compare uses
    ``raw_hi ^ (neg ? ~0 : MSB)``. ``key_op="xor"`` needs no kernel code:
    the wrapper folds the per-plane constants into ``phi``/``zlo``.
    """
    hi = jax.lax.bitcast_convert_type(hi_ref[:], jnp.int32)
    lo = jax.lax.bitcast_convert_type(lo_ref[:], jnp.int32)
    z = jax.lax.shift_right_logical(lo, jnp.int32(shift)) ^ zlo_ref[0, 0]
    if key_op == "float":
        neg = hi < jnp.int32(0)
        hk = hi ^ jnp.where(neg, jnp.int32(-1), jnp.int32(_i32const(1 << 31)))
        z = z ^ jnp.where(neg, jnp.int32(_i32const(0xFFFFFFFF >> shift)), jnp.int32(0))
        active = hk == phi_ref[0, 0]
    else:
        active = hi == phi_ref[0, 0]
    return jnp.where(active, z, jnp.int32(1 << (radix_bits + 1)))


def _hist_kernel64_packed(
    phi_ref, zlo_ref, hi_ref, lo_ref, out_ref, *, shift, radix_bits, key_op="none"
):
    """Packed-field variant of the 64-bit two-plane kernel: digit/prefix-lo
    from the lo plane via the xor trick, hi-plane mismatch pushed out of
    every register gate with one select (see ``_hist_kernel64``)."""
    i = pl.program_id(0)
    z = _lo_digit64(phi_ref, zlo_ref, hi_ref, lo_ref, shift, radix_bits, key_op)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    _packed_count(z, out_ref, radix_bits)


def _hist_kernel(
    zref_ref, keys_ref, out_ref, *, shift, radix_bits, has_prefix, key_op="none"
):
    """One grid step: per-lane digit histogram of one (block_rows, 128) block.

    With a prefix, ``zref_ref`` holds ``prefix << radix_bits`` and
    ``z = (key >> shift) ^ zref`` equals the digit iff the prefix matches
    (otherwise a bit above ``radix_bits`` is set, matching no bucket) — one
    compare per bucket covers digit + prefix. Without a prefix every element
    is active regardless of its high bits, so ``z`` is just the masked digit.
    """
    i = pl.program_id(0)
    z = _shifted_digit(keys_ref, zref_ref, shift, radix_bits, has_prefix, key_op)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jnp.stack(
        [
            jnp.sum(z == jnp.int32(b), axis=0, dtype=jnp.int32)
            for b in range(1 << radix_bits)
        ]
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "shift",
        "radix_bits",
        "block_rows",
        "interpret",
        "count_dtype",
        "packed",
        "orig_n",
        "key_op",
        "key_xor",
    ),
)
def pallas_radix_histogram(
    keys: jax.Array | None,
    *,
    shift: int,
    radix_bits: int,
    prefix=None,
    count_dtype=jnp.int32,
    block_rows: int = 4096,
    interpret: bool | None = None,
    packed: bool = True,
    tiles: jax.Array | None = None,
    orig_n: int | None = None,
    key_op: str = "none",
    key_xor: int = 0,
) -> jax.Array:
    """Histogram of the ``radix_bits`` digit at ``shift`` over active keys.

    Same contract as ``masked_radix_histogram`` (ops/histogram.py): ``keys``
    unsigned <= 32 bits, active means ``keys >> (shift + radix_bits) ==
    prefix`` (all active when ``prefix`` is None). Returns ``(2**radix_bits,)``
    counts in ``count_dtype``.

    ``tiles``/``orig_n`` (from :func:`prepare_tiles32` or, with ``key_op``,
    :func:`prepare_raw_tiles32`) skip the per-call pad/reshape so pass loops
    materialize the tiled view once; ``keys`` may be None then.

    ``key_op``/``key_xor`` (utils/dtypes.py:key_fold) make the tiles RAW bit
    patterns and apply the sortable-key transform in kernel — free for
    integer dtypes (the xor constant folds into ``zref``), two VPU ops for
    float32. ``prefix`` and the returned bucket walk stay in key space.
    Removes the full-array to_sortable pass (measured 1.63 ms at N=2^27 on
    v5e — ~22% of the whole select).

    ``block_rows=4096`` is the measured v5e sweet spot; 8192 exceeds the
    16 MB scoped-VMEM budget with double buffering.
    """
    if pltpu is None:
        raise NotImplementedError(
            "the pallas histogram kernel is not available in this jax build"
        )
    if key_op not in ("none", "xor", "float"):
        raise ValueError(f"unknown key_op {key_op!r}")
    _check_block_rows(block_rows)
    if key_op != "none" and prefix is None and shift + radix_bits != 32:
        # fold modes compute z by xor only; a prefix-free digit below the
        # top of the key would need the legacy mask path
        raise ValueError("key_op needs shift + radix_bits == 32 when prefix is None")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb = 1 << radix_bits
    block_rows = _cap_block_rows(block_rows, radix_bits)

    if tiles is None:
        if keys is None:
            raise ValueError("need keys or tiles")
        # view as (rows, 128); zero-pad to whole blocks (no masking
        # in-kernel — the pad contribution is subtracted analytically below)
        k2d, n = prepare_tiles32(keys, block_rows)
    else:
        if orig_n is None:
            raise ValueError("tiles needs orig_n (the unpadded key count)")
        k2d, n = tiles, orig_n
        if key_op == "none":
            if k2d.dtype != jnp.uint32:
                raise ValueError(f"key-space tiles must be uint32, got {k2d.dtype}")
        elif np.dtype(k2d.dtype).itemsize != 4:
            # raw tiles keep the input's own 4-byte dtype (a dtype-changing
            # bitcast before the custom call costs a full copy; the kernel
            # bitcasts per block in VMEM for free)
            raise ValueError(f"raw tiles must be a 4-byte dtype, got {k2d.dtype}")
        if k2d.shape[0] % block_rows or k2d.shape[1] != LANES:
            raise ValueError(
                f"tiles shape {k2d.shape} does not match block_rows={block_rows}"
            )
    grid = k2d.shape[0] // block_rows
    pad_to = grid * block_rows * LANES

    has_prefix = prefix is not None
    pref = jnp.asarray(0 if prefix is None else prefix, jnp.uint32)
    zbits = jax.lax.shift_left(pref, jnp.uint32(radix_bits))
    if key_op == "xor":
        # the integer-key fold: (raw ^ C) >> s == (raw >> s) ^ (C >> s),
        # so C lands in the xor reference for free
        zbits = zbits ^ jnp.uint32((key_xor & 0xFFFFFFFF) >> shift)
    zref = jax.lax.bitcast_convert_type(zbits, jnp.int32).reshape(1, 1)

    kern = _hist_kernel_packed if packed else _hist_kernel
    kernel = functools.partial(
        kern, shift=shift, radix_bits=radix_bits, has_prefix=has_prefix,
        key_op=key_op,
    )
    # under shard_map the tiles are device-varying; the out_shape must carry
    # the same varying-manual-axes type for check_vma (empty set otherwise)
    vma = compat.vma_of(k2d)
    zref = _match_vma(zref, vma)
    # trace the kernel with x64 off: the kernel is int32-only, and Mosaic
    # fails to legalize programs traced in x64 mode (int64 grid indices)
    with compat.enable_x64(False):
        lane_hist = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec((nb, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
            out_shape=compat.shape_dtype_struct((nb, LANES), jnp.int32, vma=vma),
            interpret=interpret,
        )(zref, k2d)
    hist = jnp.sum(lane_hist, axis=1, dtype=count_dtype)

    pad = pad_to - n
    if pad:
        # padded raw zeros hold the key K0 = to_sortable(raw 0) for the
        # tile mode in use; they count in bucket (K0 >> shift) & mask
        # exactly when the prefix matches K0's high bits (always, on the
        # prefix-free pass — shift + radix_bits covers the whole key then)
        k0 = {"none": 0, "xor": key_xor & 0xFFFFFFFF, "float": 1 << 31}[key_op]
        b0 = (k0 >> shift) & (nb - 1)
        if has_prefix:
            cmp0 = jnp.uint32(k0 >> (shift + radix_bits))
            correction = jnp.where(pref == cmp0, count_dtype(pad), count_dtype(0))
        else:
            correction = count_dtype(pad)
        hist = hist.at[b0].add(-correction)
    return hist


def _hist_kernel64(
    phi_ref, zlo_ref, hi_ref, lo_ref, out_ref, *, shift, radix_bits, key_op="none"
):
    """Low-bit pass over 64-bit keys: digit from the lo plane, activity =
    (hi plane == prefix_hi) AND (lo high bits == prefix_lo), the latter fused
    into the digit compare by xor (see _hist_kernel)."""
    i = pl.program_id(0)
    z = _lo_digit64(phi_ref, zlo_ref, hi_ref, lo_ref, shift, radix_bits, key_op)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jnp.stack(
        [
            jnp.sum(z == jnp.int32(b), axis=0, dtype=jnp.int32)
            for b in range(1 << radix_bits)
        ]
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "shift",
        "radix_bits",
        "block_rows",
        "interpret",
        "count_dtype",
        "packed",
        "orig_n",
        "key_op",
        "key_xor",
    ),
)
def pallas_radix_histogram64(
    keys: jax.Array | None,
    *,
    shift: int,
    radix_bits: int,
    prefix=None,
    count_dtype=jnp.int32,
    block_rows: int = 4096,
    interpret: bool | None = None,
    packed: bool = True,
    tiles: tuple[jax.Array, jax.Array] | None = None,
    orig_n: int | None = None,
    key_op: str = "none",
    key_xor: int = 0,
) -> jax.Array:
    """64-bit-key variant of :func:`pallas_radix_histogram` (same contract).

    ``prefix=None`` is supported only on the top pass (``shift + radix_bits
    == 64``) — exactly how radix descent calls it; other prefix-free shapes
    take the XLA fallback in ops/histogram.py.

    ``tiles=(hi_tiles, lo_tiles)`` + ``orig_n`` (from
    :func:`prepare_tiles64`, or :func:`prepare_raw_tiles64` with
    ``key_op``) skip the per-call deinterleave + pad/reshape; pass-loop
    callers prepare once up front. ``keys`` may be None then.

    ``key_op``/``key_xor``: in-kernel key transform over raw bit planes
    (utils/dtypes.py:key_fold) — free for int64/uint64 (per-plane xor
    constants fold into ``phi``/``zlo``), a few VPU ops for float64 (the
    sign lives in the hi plane and gates both planes' flips).
    """
    if pltpu is None:
        raise NotImplementedError(
            "the pallas histogram kernel is not available in this jax build"
        )
    if key_op not in ("none", "xor", "float"):
        raise ValueError(f"unknown key_op {key_op!r}")
    if prefix is None and shift + radix_bits != 64:
        raise ValueError(
            "prefix=None needs shift + radix_bits == 64 on the 64-bit kernel"
        )
    _check_block_rows(block_rows)
    block_rows = _cap_block_rows(block_rows, radix_bits)
    if tiles is not None:
        if orig_n is None:
            raise ValueError("tiles needs orig_n (the unpadded key count)")
        hi2, lo2 = tiles
        if hi2.shape != lo2.shape:
            raise ValueError(
                f"tile shape mismatch: hi {hi2.shape} vs lo {lo2.shape}"
            )
        if hi2.dtype != jnp.uint32 or lo2.dtype != jnp.uint32:
            raise ValueError("tiles must be uint32 (hi, lo)")
        n = orig_n
    else:
        if keys is None:
            raise ValueError("need keys or tiles")
        keys = keys.ravel()
        if keys.dtype != jnp.uint64:
            raise ValueError(
                f"pallas_radix_histogram64 wants uint64 keys, got {keys.dtype}"
            )
        hi2, lo2, n = prepare_tiles64(keys, block_rows)
    if shift >= 32:
        # digit and the whole prefix live in the hi plane: 32-bit kernel.
        # key_op carries over — for "xor" the hi plane's fold constant is
        # the hi word of C; for "float" the f64 transform restricted to the
        # hi plane IS the f32 transform (the sign bit lives there).
        pref32 = None if prefix is None else jnp.asarray(prefix, jnp.uint64).astype(jnp.uint32)
        return pallas_radix_histogram(
            None,
            shift=shift - 32,
            radix_bits=radix_bits,
            prefix=pref32,
            count_dtype=count_dtype,
            block_rows=block_rows,
            interpret=interpret,
            packed=packed,
            tiles=hi2,
            orig_n=n,
            key_op=key_op,
            key_xor=(key_xor >> 32) & 0xFFFFFFFF,
        )
    if shift + radix_bits > 32:
        raise ValueError(
            f"digit at shift={shift} straddles the 32-bit plane boundary; "
            f"use a radix_bits that divides 32"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb = 1 << radix_bits

    pref = jnp.asarray(prefix, jnp.uint64)
    lo_prefix_bits = 32 - shift - radix_bits  # prefix bits living in the lo plane
    phi = jax.lax.shift_right_logical(pref, jnp.uint64(lo_prefix_bits)).astype(jnp.uint32)
    plo = (pref & jnp.uint64((1 << lo_prefix_bits) - 1)).astype(jnp.uint32)
    zlo = jax.lax.shift_left(plo, jnp.uint32(radix_bits))
    if key_op == "xor":
        # per-plane fold: key_hi = raw_hi ^ C_hi (compared against phi),
        # key_lo = raw_lo ^ C_lo (digit + lo-prefix via the z xor)
        phi = phi ^ jnp.uint32((key_xor >> 32) & 0xFFFFFFFF)
        zlo = zlo ^ jnp.uint32(((key_xor & 0xFFFFFFFF) >> shift))
    phi = jax.lax.bitcast_convert_type(phi, jnp.int32).reshape(1, 1)
    zlo = jax.lax.bitcast_convert_type(zlo, jnp.int32).reshape(1, 1)

    if hi2.shape[0] % block_rows or hi2.shape[1] != LANES:
        raise ValueError(
            f"tiles shape {hi2.shape} does not match block_rows={block_rows}"
        )
    grid = hi2.shape[0] // block_rows
    pad_to = grid * block_rows * LANES

    kern64 = _hist_kernel64_packed if packed else _hist_kernel64
    kernel = functools.partial(
        kern64, shift=shift, radix_bits=radix_bits, key_op=key_op
    )
    vma = compat.vma_of(hi2)  # see 32-bit variant
    phi = _match_vma(phi, vma)
    zlo = _match_vma(zlo, vma)
    # x64 off while tracing: the kernel is int32-only (see 32-bit variant)
    with compat.enable_x64(False):
        lane_hist = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec((nb, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
            out_shape=compat.shape_dtype_struct((nb, LANES), jnp.int32, vma=vma),
            interpret=interpret,
        )(phi, zlo, hi2, lo2)
    hist = jnp.sum(lane_hist, axis=1, dtype=count_dtype)

    pad = pad_to - n
    if pad:
        # padded raw zeros hold the 64-bit key K0 = to_sortable(raw 0);
        # they count in bucket (K0 >> shift) & mask when the prefix matches
        k0 = {"none": 0, "xor": key_xor & ~(-1 << 64), "float": 1 << 63}[key_op]
        b0 = (k0 >> shift) & (nb - 1)
        cmp0 = jnp.uint64(k0 >> (shift + radix_bits))
        correction = jnp.where(pref == cmp0, count_dtype(pad), count_dtype(0))
        hist = hist.at[b0].add(-correction)
    return hist


# ---------------------------------------------------------------------------
# Multi-prefix histograms: one data sweep serves K selection queries.
#
# Multi-rank selection (kselect_many / quantiles) walks K different prefixes
# through the same array. Calling the single-prefix kernel per query reads
# the data K times per pass; here the block is loaded once and the digit
# base ``s = raw >> shift`` is computed once, then each query pays only its
# xor + SWAR accumulation (~9 VPU ops/element/query) into its own slice of
# a (K * nbuckets, 128) accumulator. The walk reads the data
# ``npasses`` times total instead of ``1 + K * (npasses - 1)`` — the
# reference anchor is the CGM round sharing one data sweep across all
# protocol steps (TODO-kth-problem-cgm.c:170-190).
# ---------------------------------------------------------------------------


def _hist_kernel_multi_packed(
    zrefs_ref, keys_ref, out_ref, *, shift, radix_bits, key_op, nq
):
    """K-query SWAR histogram over one 32-bit block: shared shift (and
    float transform), per-query fused xor reference in SMEM."""
    i = pl.program_id(0)
    nb = 1 << radix_bits
    k = jax.lax.bitcast_convert_type(keys_ref[:], jnp.int32)
    s = jax.lax.shift_right_logical(k, jnp.int32(shift))
    if key_op == "float":
        m_neg = jnp.int32(_i32const(0xFFFFFFFF >> shift))
        m_pos = jnp.int32(_i32const(0x80000000 >> shift))
        s = s ^ jnp.where(k < jnp.int32(0), m_neg, m_pos)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    for q in range(nq):
        _packed_count(s ^ zrefs_ref[q, 0], out_ref, radix_bits, row0=q * nb)


def _hist_kernel64_multi_packed(
    phis_ref, zlos_ref, hi_ref, lo_ref, out_ref, *, shift, radix_bits, key_op, nq
):
    """K-query variant of the two-plane 64-bit low-bit kernel."""
    i = pl.program_id(0)
    nb = 1 << radix_bits
    hi = jax.lax.bitcast_convert_type(hi_ref[:], jnp.int32)
    lo = jax.lax.bitcast_convert_type(lo_ref[:], jnp.int32)
    base = jax.lax.shift_right_logical(lo, jnp.int32(shift))
    if key_op == "float":
        neg = hi < jnp.int32(0)
        hk = hi ^ jnp.where(neg, jnp.int32(-1), jnp.int32(_i32const(1 << 31)))
        base = base ^ jnp.where(
            neg, jnp.int32(_i32const(0xFFFFFFFF >> shift)), jnp.int32(0)
        )
    else:
        hk = hi

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out = jnp.int32(1 << (radix_bits + 1))
    for q in range(nq):
        z = jnp.where(hk == phis_ref[q, 0], base ^ zlos_ref[q, 0], out)
        _packed_count(z, out_ref, radix_bits, row0=q * nb)


def _multi_block_rows(block_rows: int, nq: int) -> int:
    """Block cap for the multi kernel: each query keeps 6 block-height
    register arrays live, so larger K needs shorter blocks to stay inside
    scoped VMEM (same discipline as _cap_block_rows for radix_bits > 4)."""
    return min(block_rows, 4096 if nq <= 2 else 1024)


@functools.partial(
    jax.jit,
    static_argnames=(
        "shift", "radix_bits", "block_rows", "interpret", "count_dtype",
        "orig_n", "key_op", "key_xor",
    ),
)
def pallas_radix_histogram_multi(
    *,
    shift: int,
    radix_bits: int,
    prefixes: jax.Array,
    count_dtype=jnp.int32,
    block_rows: int = 4096,
    interpret: bool | None = None,
    tiles: jax.Array = None,
    orig_n: int = None,
    key_op: str = "none",
    key_xor: int = 0,
) -> jax.Array:
    """``(K, 2**radix_bits)`` counts: for each key-space prefix in
    ``prefixes`` (shape (K,), traced), the digit histogram over elements
    whose top bits match that prefix. One data read for all K queries.

    32-bit keys only (``tiles`` from prepare_tiles32 / prepare_raw_tiles32);
    64-bit callers go through :func:`pallas_radix_histogram64_multi`.
    """
    if pltpu is None:
        raise NotImplementedError(
            "the pallas histogram kernel is not available in this jax build"
        )
    if key_op not in ("none", "xor", "float"):
        raise ValueError(f"unknown key_op {key_op!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb = 1 << radix_bits
    nq = int(prefixes.shape[0])
    _check_block_rows(block_rows)
    block_rows = _multi_block_rows(_cap_block_rows(block_rows, radix_bits), nq)
    if orig_n is None:
        raise ValueError("tiles needs orig_n")
    k2d, n = tiles, orig_n
    if key_op == "none":
        if k2d.dtype != jnp.uint32:
            raise ValueError(f"key-space tiles must be uint32, got {k2d.dtype}")
    elif np.dtype(k2d.dtype).itemsize != 4:
        raise ValueError(f"raw tiles must be a 4-byte dtype, got {k2d.dtype}")
    if k2d.shape[0] % block_rows or k2d.shape[1] != LANES:
        raise ValueError(
            f"tiles shape {k2d.shape} does not match block_rows={block_rows}"
        )
    grid = k2d.shape[0] // block_rows
    pad_to = grid * block_rows * LANES

    prefs = prefixes.astype(jnp.uint32)
    zbits = jax.lax.shift_left(prefs, jnp.uint32(radix_bits))
    if key_op == "xor":
        zbits = zbits ^ jnp.uint32((key_xor & 0xFFFFFFFF) >> shift)
    zrefs = jax.lax.bitcast_convert_type(zbits, jnp.int32).reshape(nq, 1)

    kernel = functools.partial(
        _hist_kernel_multi_packed,
        shift=shift, radix_bits=radix_bits, key_op=key_op, nq=nq,
    )
    vma = compat.vma_of(k2d)  # see pallas_radix_histogram
    zrefs = _match_vma(zrefs, vma)
    with compat.enable_x64(False):
        lane_hist = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((nq, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (nq * nb, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            out_shape=compat.shape_dtype_struct((nq * nb, LANES), jnp.int32, vma=vma),
            interpret=interpret,
        )(zrefs, k2d)
    hist = jnp.sum(
        lane_hist.reshape(nq, nb, LANES), axis=2, dtype=count_dtype
    )

    pad = pad_to - n
    if pad:
        k0 = {"none": 0, "xor": key_xor & 0xFFFFFFFF, "float": 1 << 31}[key_op]
        b0 = (k0 >> shift) & (nb - 1)
        cmp0 = jnp.uint32(k0 >> (shift + radix_bits))
        corr = jnp.where(prefs == cmp0, count_dtype(pad), count_dtype(0))
        hist = hist.at[:, b0].add(-corr)
    return hist


@functools.partial(
    jax.jit,
    static_argnames=(
        "shift", "radix_bits", "block_rows", "interpret", "count_dtype",
        "orig_n", "key_op", "key_xor",
    ),
)
def pallas_radix_histogram64_multi(
    *,
    shift: int,
    radix_bits: int,
    prefixes: jax.Array,
    count_dtype=jnp.int32,
    block_rows: int = 4096,
    interpret: bool | None = None,
    tiles: tuple[jax.Array, jax.Array] = None,
    orig_n: int = None,
    key_op: str = "none",
    key_xor: int = 0,
) -> jax.Array:
    """64-bit-key variant of :func:`pallas_radix_histogram_multi`:
    ``prefixes`` is (K,) uint64 in key space."""
    if pltpu is None:
        raise NotImplementedError(
            "the pallas histogram kernel is not available in this jax build"
        )
    if key_op not in ("none", "xor", "float"):
        raise ValueError(f"unknown key_op {key_op!r}")
    nb = 1 << radix_bits
    nq = int(prefixes.shape[0])
    if orig_n is None:
        raise ValueError("tiles needs orig_n")
    hi2, lo2 = tiles
    if shift >= 32:
        # digit + whole prefix in the hi plane: 32-bit multi kernel
        return pallas_radix_histogram_multi(
            shift=shift - 32,
            radix_bits=radix_bits,
            prefixes=prefixes.astype(jnp.uint32),
            count_dtype=count_dtype,
            block_rows=block_rows,
            interpret=interpret,
            tiles=hi2,
            orig_n=orig_n,
            key_op=key_op,
            key_xor=(key_xor >> 32) & 0xFFFFFFFF,
        )
    if shift + radix_bits > 32:
        raise ValueError(
            f"digit at shift={shift} straddles the 32-bit plane boundary"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_block_rows(block_rows)
    block_rows = _multi_block_rows(_cap_block_rows(block_rows, radix_bits), nq)
    if hi2.shape[0] % block_rows or hi2.shape[1] != LANES:
        raise ValueError(
            f"tiles shape {hi2.shape} does not match block_rows={block_rows}"
        )
    grid = hi2.shape[0] // block_rows
    pad_to = grid * block_rows * LANES
    n = orig_n

    prefs = prefixes.astype(jnp.uint64)
    lo_prefix_bits = 32 - shift - radix_bits
    phis = jax.lax.shift_right_logical(
        prefs, jnp.uint64(lo_prefix_bits)
    ).astype(jnp.uint32)
    plos = (prefs & jnp.uint64((1 << lo_prefix_bits) - 1)).astype(jnp.uint32)
    zlos = jax.lax.shift_left(plos, jnp.uint32(radix_bits))
    if key_op == "xor":
        phis = phis ^ jnp.uint32((key_xor >> 32) & 0xFFFFFFFF)
        zlos = zlos ^ jnp.uint32((key_xor & 0xFFFFFFFF) >> shift)
    phis = jax.lax.bitcast_convert_type(phis, jnp.int32).reshape(nq, 1)
    zlos = jax.lax.bitcast_convert_type(zlos, jnp.int32).reshape(nq, 1)

    kernel = functools.partial(
        _hist_kernel64_multi_packed,
        shift=shift, radix_bits=radix_bits, key_op=key_op, nq=nq,
    )
    vma = compat.vma_of(hi2)  # see pallas_radix_histogram
    phis = _match_vma(phis, vma)
    zlos = _match_vma(zlos, vma)
    with compat.enable_x64(False):
        lane_hist = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((nq, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((nq, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (nq * nb, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            out_shape=compat.shape_dtype_struct((nq * nb, LANES), jnp.int32, vma=vma),
            interpret=interpret,
        )(phis, zlos, hi2, lo2)
    hist = jnp.sum(
        lane_hist.reshape(nq, nb, LANES), axis=2, dtype=count_dtype
    )

    pad = pad_to - n
    if pad:
        k0 = {"none": 0, "xor": key_xor & ~(-1 << 64), "float": 1 << 63}[key_op]
        b0 = (k0 >> shift) & (nb - 1)
        cmp0 = jnp.uint64(k0 >> (shift + radix_bits))
        corr = jnp.where(prefs == cmp0, count_dtype(pad), count_dtype(0))
        hist = hist.at[:, b0].add(-corr)
    return hist


# ---------------------------------------------------------------------------
# Multi-prefix match counts: the collect phase's streaming counter.
#
# The cutover collect needs, for each query prefix, how many matching
# elements live in each small "subblock" of the array, so slot j of the
# candidate buffer can be routed to its subblock with a rank search. In
# XLA the K-prefix count over (rows, 128) tiles refuses to fuse (measured
# ~20 ms for K=9 at 2^27 vs the 0.7 ms read floor); this kernel does it in
# one streaming read for all K queries.
#
# Subblock = one tile ROW (128 contiguous elements): the collect's slot
# gather then fetches whole rows — the one gather shape XLA lowers well.
# The kernel's per-query lane-axis reduction produces (rows,) counts,
# re-laid out as a (rows/128, 128) tile in the query's slice of the
# (nq * rows/128, 128) output block; subblock index == global row index.
# Candidate order within a subblock is lane order (the gather uses same).
# ---------------------------------------------------------------------------


def _match_count_kernel(crefs_ref, keys_ref, out_ref, *, mshift, key_op, nq, n):
    i = pl.program_id(0)
    rows = keys_ref.shape[0]
    groups = rows // 128
    k = jax.lax.bitcast_convert_type(keys_ref[:], jnp.int32)
    s = jax.lax.shift_right_logical(k, jnp.int32(mshift))
    if key_op == "float":
        m_neg = jnp.int32(_i32const(0xFFFFFFFF >> mshift))
        m_pos = jnp.int32(_i32const(0x80000000 >> mshift))
        s = s ^ jnp.where(k < jnp.int32(0), m_neg, m_pos)
    # pad positions (global element index >= n) are masked out of the
    # compare directly — a sentinel value would collide with a legitimate
    # reference at mshift == 0, where the full 32-bit word is compared
    base = i * rows
    gpos = (
        (base + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0))
        * LANES
        + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    )
    valid = gpos < jnp.int32(n)
    for q in range(nq):
        m = jnp.logical_and(s == crefs_ref[q, 0], valid).astype(jnp.int32)
        # per-row counts: reduce lanes, then re-lay the (rows,) vector as
        # a (groups, 128) tile (row r -> out[r // 128, r % 128])
        mg = jnp.sum(m.reshape(groups, 128, LANES), axis=2)
        out_ref[q * groups:(q + 1) * groups, :] = mg


@functools.partial(
    jax.jit,
    static_argnames=("resolved_bits", "block_rows", "interpret", "orig_n",
                     "key_op", "key_xor", "count_dtype"),
)
def pallas_match_counts(
    *,
    resolved_bits: int,
    prefixes: jax.Array,
    tiles: jax.Array,
    orig_n: int,
    key_op: str = "none",
    key_xor: int = 0,
    count_dtype=jnp.int32,
    block_rows: int = 4096,
    interpret: bool | None = None,
):
    """``(K, R)`` match counts per tile ROW (R = tile rows):
    ``counts[q, r]`` = number of elements in row r whose key's top
    ``resolved_bits`` bits equal ``prefixes[q]``. 32-bit tiles only (for
    64-bit keys pass the HI plane — valid while resolved_bits <= 32).

    Row r covers elements ``[r * 128, r * 128 + 128)`` in lane order. Pad
    positions past ``orig_n`` are excluded in kernel (no analytic
    correction needed).
    """
    if pltpu is None:
        raise NotImplementedError(
            "the pallas histogram kernel is not available in this jax build"
        )
    if key_op not in ("none", "xor", "float"):
        raise ValueError(f"unknown key_op {key_op!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_block_rows(block_rows)
    if block_rows % 128:
        # groups = block_rows // 128 must cover the block exactly; a smaller
        # height would build a degenerate zero-group kernel
        raise ValueError(f"block_rows={block_rows} must be a multiple of 128")
    nq = int(prefixes.shape[0])
    R = tiles.shape[0]
    if R % block_rows or tiles.shape[1] != LANES:
        raise ValueError(f"tiles shape {tiles.shape} vs block_rows={block_rows}")
    if np.dtype(tiles.dtype).itemsize != 4:
        raise ValueError(f"tiles must be a 4-byte dtype, got {tiles.dtype}")
    grid = R // block_rows
    groups = block_rows // 128
    mshift = 32 - resolved_bits
    crefs = prefixes.astype(jnp.uint32)
    if key_op == "xor":
        # match: (raw >> mshift) == prefix ^ (C >> mshift)
        crefs = crefs ^ jnp.uint32((key_xor & 0xFFFFFFFF) >> mshift)
    crefs = jax.lax.bitcast_convert_type(crefs, jnp.int32).reshape(nq, 1)

    kernel = functools.partial(
        _match_count_kernel, mshift=mshift, key_op=key_op, nq=nq, n=orig_n
    )
    vma = compat.vma_of(tiles)  # see pallas_radix_histogram
    crefs = _match_vma(crefs, vma)
    with compat.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((nq, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (nq * groups, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=compat.shape_dtype_struct((grid * nq * groups, LANES), jnp.int32, vma=vma),
            interpret=interpret,
        )(crefs, tiles)
    # (grid, nq, groups, 128) -> (nq, grid*groups*128) == (nq, R)
    cnt = out.reshape(grid, nq, groups, LANES).transpose(1, 0, 2, 3).reshape(nq, -1)
    return cnt.astype(count_dtype)


# ---------------------------------------------------------------------------
# Tau-threshold counts: the top-k winner collect's streaming counter (r5).
#
# The top-k threshold path (ops/topk.py:_threshold_topk_indices) needs, for
# ONE full-width key tau, how many elements per tile row compare strictly
# beyond tau and how many equal it — the two numbers that route every winner
# slot to its subblock. Same tile geometry and in-kernel key transform as
# the match-count kernel above; the order compare runs in signed space by
# folding the uint32->int32 bias (^0x80000000) into both the key transform
# and the reference.
# ---------------------------------------------------------------------------


def _tau_count_kernel(tau_ref, keys_ref, out_ref, *, key_op, key_xor, largest, n):
    i = pl.program_id(0)
    rows = keys_ref.shape[0]
    groups = rows // 128
    k = jax.lax.bitcast_convert_type(keys_ref[:], jnp.int32)
    if key_op == "float":
        # sortable key ^ 0x80000000: raw ^ (raw < 0 ? 0x7FFFFFFF : 0)
        s = k ^ jnp.where(k < jnp.int32(0), jnp.int32(_i32const(0x7FFFFFFF)), jnp.int32(0))
    elif key_op == "xor":
        s = k ^ jnp.int32(_i32const((key_xor ^ 0x80000000) & 0xFFFFFFFF))
    else:  # key-space uint32 tiles
        s = k ^ jnp.int32(_i32const(0x80000000))
    base = i * rows
    gpos = (
        (base + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)) * LANES
        + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    )
    valid = gpos < jnp.int32(n)
    tau = tau_ref[0, 0]
    beyond = (s > tau) if largest else (s < tau)
    mg = jnp.logical_and(beyond, valid).astype(jnp.int32)
    me = jnp.logical_and(s == tau, valid).astype(jnp.int32)
    out_ref[0:groups, :] = jnp.sum(mg.reshape(groups, 128, LANES), axis=2)
    out_ref[groups:2 * groups, :] = jnp.sum(me.reshape(groups, 128, LANES), axis=2)


@functools.partial(
    jax.jit,
    static_argnames=("block_rows", "interpret", "orig_n", "key_op", "key_xor",
                     "largest", "count_dtype"),
)
def pallas_tau_counts(
    *,
    tau_key: jax.Array,
    tiles: jax.Array,
    orig_n: int,
    key_op: str = "none",
    key_xor: int = 0,
    largest: bool = True,
    count_dtype=jnp.int32,
    block_rows: int = 4096,
    interpret: bool | None = None,
):
    """``(beyond, eq)`` counts per tile ROW for one full-width key ``tau_key``
    (uint32 key space): ``beyond[r]`` = elements in row r whose key is
    strictly greater (``largest=True``) or strictly less (``largest=False``)
    than tau; ``eq[r]`` = exact key matches. One streaming read; 32-bit
    tiles only. Row r covers elements ``[r*128, r*128+128)`` in lane order;
    pad positions past ``orig_n`` are excluded in kernel."""
    if pltpu is None:
        raise NotImplementedError(
            "the pallas histogram kernel is not available in this jax build"
        )
    if key_op not in ("none", "xor", "float"):
        raise ValueError(f"unknown key_op {key_op!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_block_rows(block_rows)
    if block_rows % 128:
        raise ValueError(f"block_rows={block_rows} must be a multiple of 128")
    R = tiles.shape[0]
    if R % block_rows or tiles.shape[1] != LANES:
        raise ValueError(f"tiles shape {tiles.shape} vs block_rows={block_rows}")
    if np.dtype(tiles.dtype).itemsize != 4:
        raise ValueError(f"tiles must be a 4-byte dtype, got {tiles.dtype}")
    grid = R // block_rows
    groups = block_rows // 128
    # signed-comparable reference: key ^ 0x80000000, bitcast to int32
    tau = jax.lax.bitcast_convert_type(
        tau_key.astype(jnp.uint32) ^ jnp.uint32(0x80000000), jnp.int32
    ).reshape(1, 1)
    kernel = functools.partial(
        _tau_count_kernel, key_op=key_op, key_xor=key_xor, largest=largest,
        n=orig_n,
    )
    vma = compat.vma_of(tiles)  # see pallas_radix_histogram
    tau = _match_vma(tau, vma)
    with compat.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (2 * groups, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=compat.shape_dtype_struct((grid * 2 * groups, LANES), jnp.int32, vma=vma),
            interpret=interpret,
        )(tau, tiles)
    # (grid, 2, groups, 128) -> (2, grid*groups*128) == (2, R)
    cnt = out.reshape(grid, 2, groups, LANES).transpose(1, 0, 2, 3).reshape(2, -1)
    return cnt[0].astype(count_dtype), cnt[1].astype(count_dtype)
