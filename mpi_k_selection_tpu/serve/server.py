"""KSelectServer — the in-process resident-dataset query server.

Composes the subsystem: a :class:`~mpi_k_selection_tpu.serve.registry.
DatasetRegistry` (resident shards + keyed program cache), a
:class:`~mpi_k_selection_tpu.serve.lanes.LaneDispatcher` (one supervised
dispatch lane per execution device, each a bounded-coalescing-window
:class:`~mpi_k_selection_tpu.serve.batcher.QueryBatcher`), and the
latency tiers (serve/tiers.py). The HTTP front (serve/http.py) and the
CLI ``serve`` mode are thin shells over this class; embedding callers
use it directly::

    from mpi_k_selection_tpu.serve import KSelectServer

    with KSelectServer(window=0.002) as srv:
        srv.add_dataset("logits", x, warmup=True)  # shard+compile ONCE
        a = srv.kselect("logits", k, tier="auto")
        qs = srv.quantiles("logits", [0.5, 0.99], tier="sketch")
        qs[0].rank_error_bound                   # bounds always attached

Guarantees (tested in tests/test_serve.py, tests/test_serve_lanes.py):

- **Determinism**: answers are bit-identical to serial one-at-a-time
  ``api.kselect``/``api.quantiles`` calls, for every tier, dataset
  residency, coalescing window, client concurrency, lane layout,
  ``fast_path`` setting and ``warmup`` setting — each dataset's device
  work runs on exactly one dispatch-lane thread, resident shards are
  immutable, and exact order statistics are batch-invariant.
- **Hot-path shape** (docs/API.md "Serving"): sketch-tier answers (and
  auto-tier answers the sketch pins) are pure numpy reads over an
  immutable resident pyramid, so with ``fast_path=True`` (default)
  they are answered directly ON THE REQUEST THREAD — no enqueue, no
  dispatch wake, counted in ``serve.fastpath{tier=}``.
  ``fast_path=False`` routes them through the dispatch lane — the
  bit-for-bit oracle for the fast path (and the qps baseline
  ``bench_serve`` compares against). Exact-tier work always dispatches
  through the dataset's lane.
- **No recompiles on repeat shapes**: compiled walk closures and the
  sort path's descent state live in the registry's keyed program cache
  (``serve.program_cache.{hits,misses}`` mirror its counters exactly).
- **Observability**: pass an :class:`~mpi_k_selection_tpu.obs.
  Observability` — per-request ``serve.query`` events, per-group
  ``serve.batch`` events, and the server metric namespace
  (queue depth, batch width, per-tier query counts and latency
  histograms, tier escalations; docs/OBSERVABILITY.md). Off by
  default; enabling it never changes an answer bit.
- **Clean shutdown**: ``close()`` joins the dispatch thread and fails
  queued stragglers with :class:`ServerClosedError`; no ``ksel-serve-*``
  thread outlives the server (conftest-enforced).
"""

from __future__ import annotations

import uuid

import numpy as np

from mpi_k_selection_tpu.serve import tiers as _tiers
from mpi_k_selection_tpu.serve.batcher import (
    DEFAULT_MAX_BATCH,
    PendingQuery,
)
from mpi_k_selection_tpu.serve.lanes import LaneDispatcher
from mpi_k_selection_tpu.serve.errors import (
    DeadlineExceededError,
    QueryError,
    ServerClosedError,
)
from mpi_k_selection_tpu.serve.registry import DatasetRegistry
from mpi_k_selection_tpu.serve.tiers import RankAnswer
from mpi_k_selection_tpu.utils.timing import Deadline

#: Latency-histogram bucket bounds (seconds) — sub-ms sketch reads up to
#: multi-second out-of-core descents.
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)

OPS = ("kselect", "quantiles", "topk", "rank_certificate")


class _LatencyRecorder:
    """PhaseTimer recorder bridging request phases to the obs channels:
    observes each finished ``serve.request.<tier>`` duration into the
    per-tier latency histogram and forwards every span — with its
    ``args`` context (the request/walk trace ids) — to the trace
    recorder and the flight ring. Receives finished ``(name, t0, t1)``
    triples only — no clock is read here (KSL004)."""

    def __init__(self, metrics, trace, flight=None):
        self._metrics = metrics
        self._trace = trace
        self._flight = flight

    def record(self, name: str, t0: float, t1: float, args=None) -> None:
        if self._metrics is not None and name.startswith("serve.request."):
            tier = name.rsplit(".", 1)[-1]
            self._metrics.histogram(
                "serve.latency_seconds",
                labels={"tier": tier},
                buckets=LATENCY_BUCKETS,
            ).observe(t1 - t0)
        if self._trace is not None:
            self._trace.record(name, t0, t1, args)
        if self._flight is not None:
            self._flight.record(name, t0, t1, args)


class KSelectServer:
    """Long-lived serving facade: register datasets once, answer
    kselect / quantile / top-k / rank-certificate queries from many
    concurrent clients. ``window`` is the batcher's coalescing window in
    seconds (0 = dispatch every request alone).

    Hot-path knobs: ``fast_path`` (default True) answers sketch-tier
    (and auto-pinned) queries inline on the request thread —
    ``fast_path=False`` is the queued bit-for-bit oracle; ``lanes``
    (``"auto"`` = one dispatch lane per distinct execution device, or
    an explicit int — ``1`` degenerates to the single PR 7 batcher)
    routes each dataset's exact-tier work to its device's lane.

    Resilience knobs (docs/ROBUSTNESS.md): ``max_queue_depth`` bounds
    each lane's dispatch queue — arrivals past it are shed with
    :class:`~mpi_k_selection_tpu.serve.errors.ServerOverloadedError`
    (HTTP 503 + ``Retry-After``, ``retry_after`` seconds, counted in
    ``serve.load_shed``) instead of queueing unboundedly;
    ``default_deadline`` (seconds) applies to every query that names
    none — expired queries fail fast with
    :class:`~mpi_k_selection_tpu.serve.errors.DeadlineExceededError`
    (HTTP 504, ``serve.deadline_exceeded``); the dispatch loop runs
    supervised — a crash fails only the in-flight batch and restarts the
    loop (``serve.dispatch_restarts``)."""

    def __init__(
        self,
        *,
        window: float = 0.0,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_queue_depth: int | None = None,
        retry_after: float = 1.0,
        default_deadline: float | None = None,
        fast_path: bool = True,
        lanes="auto",
        latency_windows=None,
        flight=None,
        obs=None,
        registry: DatasetRegistry | None = None,
    ):
        from mpi_k_selection_tpu.obs import Observability
        from mpi_k_selection_tpu.obs.flight import resolve_flight

        from mpi_k_selection_tpu.utils.profiling import PhaseTimer

        # flight (off by default): the postmortem ring (obs/flight.py) —
        # True/int/FlightRecorder per resolve_flight. It attaches to the
        # obs bundle so every emitted event fans into it; a server built
        # without obs gets a flight-only bundle, so debug_bundle() and
        # the auto-dump triggers work regardless.
        fr = resolve_flight(flight)
        if fr is not None:
            if obs is None:
                obs = Observability(flight=fr)
            elif obs.flight is None:
                obs.flight = fr
            elif flight is not True and obs.flight is not fr:
                # a concrete recorder (or capacity) that conflicts with
                # the obs bundle's existing ring must not be silently
                # dropped — auto-dumps would freeze the wrong ring;
                # flight=True just means "on" and keeps the existing one
                raise ValueError(
                    "flight= names a recorder but obs already carries a "
                    "different flight ring — pass one of them, or "
                    "flight=True to keep the obs ring"
                )
        self.obs = obs
        self.flight = None if obs is None else obs.flight
        self.metrics = None if obs is None else obs.metrics
        # latency_windows (off by default): back serve.latency_seconds
        # with a sliding-window RadixSketch (obs/windows.py), so /metrics
        # p50/p90/p99 become windowed, EXACTLY-bounded quantiles instead
        # of fixed-bucket interpolation. True = defaults (8 buckets x 256
        # observations); an int = that many window buckets; a dict
        # forwards to MetricsRegistry.enable_windowed (window/
        # advance_every/decay/quantiles). Purely observational — answers
        # are bit-identical with the knob on (tests/test_monitor.py).
        if latency_windows:
            if self.metrics is None:
                raise ValueError(
                    "latency_windows needs a metrics registry: pass "
                    "obs=Observability(metrics=MetricsRegistry()) — the "
                    "windowed quantiles live in /metrics"
                )
            if latency_windows is True:
                spec = {}
            elif isinstance(latency_windows, int):
                spec = {"window": latency_windows}
            else:
                spec = dict(latency_windows)
            self.metrics.enable_windowed("serve.latency_seconds", **spec)
        self._owns_registry = registry is None
        self._closed = False
        self.registry = registry if registry is not None else DatasetRegistry()
        # the program cache reports into the process ProgramLedger; give
        # its storm events this server's sink — but never STEAL the sink
        # of a shared caller-owned registry another server already wired
        # (its storms would land on the wrong event stream)
        if self._owns_registry or self.registry.programs.obs is None:
            self.registry.programs.obs = self.obs
        self.default_deadline = (
            None if default_deadline is None else float(default_deadline)
        )
        self.timer = PhaseTimer(
            recorder=_LatencyRecorder(
                self.metrics,
                None if obs is None else obs.trace,
                self.flight,
            )
        )
        self.fast_path = bool(fast_path)
        self.batcher = LaneDispatcher(
            self._execute_ranks,
            lanes=lanes,
            window=window,
            max_batch=max_batch,
            max_depth=max_queue_depth,
            retry_after=retry_after,
            observe_depth=self._observe_depth,
            observe_width=self._observe_width,
            observe_shed=self._observe_shed,
            observe_expired=self._observe_expired,
            observe_restart=self._observe_restart,
        )

    # -- dataset lifecycle -------------------------------------------------

    def _get(self, dataset_id: str):
        """Resolve a dataset for a request, with the closed check FIRST:
        close() empties an owned registry, so without it a post-close
        query would read as "dataset not found" instead of the
        documented :class:`ServerClosedError`."""
        if self._closed:
            raise ServerClosedError("server is closed; query rejected")
        return self.registry.get(dataset_id)

    def add_dataset(
        self, dataset_id: str, data=None, *, source=None,
        warmup: bool = False, **kwargs
    ):
        """Register a dataset: ``data`` (an array — converted/sharded
        once) or ``source`` (a replayable chunk source — sketched once,
        exact queries re-stream). ``warmup=True`` additionally
        pre-builds the dataset's selection programs (cached sort, walk
        closure with its width-1 compile forced, stream-select closure,
        sketch pin path) through the program cache at registration time
        — the compile wall lands here, clocked under the ledger's
        ``serve.programs`` compile book, instead of on the first client
        (``serve.warmup_compiles`` counts the programs built). Other
        keyword options per :meth:`DatasetRegistry.add_array` /
        :meth:`add_stream`."""
        if self._closed:
            # a post-close registration would re-enter the ledger's
            # resident byte book with nothing left to release it
            raise ServerClosedError("server is closed; query rejected")
        if (data is None) == (source is None):
            raise QueryError("pass exactly one of data= or source=")
        if data is not None:
            ds = self.registry.add_array(dataset_id, data, **kwargs)
        else:
            ds = self.registry.add_stream(dataset_id, source, **kwargs)
        if warmup:
            built = self.registry.warmup(ds)
            if self.metrics is not None:
                self.metrics.counter("serve.warmup_compiles").inc(built)
        if self.metrics is not None:
            self.metrics.gauge("serve.datasets").set(len(self.registry))
        return ds

    def drop_dataset(self, dataset_id: str) -> None:
        self.registry.drop(dataset_id)
        if self.metrics is not None:
            self.metrics.gauge("serve.datasets").set(len(self.registry))

    def list_datasets(self) -> list[dict]:
        return self.registry.list_datasets()

    # -- queries (request threads) -----------------------------------------

    def kselect(
        self, dataset_id: str, k, *, tier: str = "auto", deadline=None,
        trace_id=None,
    ) -> RankAnswer:
        """Exact-or-bounded k-th smallest (1-indexed). Returns one
        :class:`RankAnswer`; ``tier`` per serve/tiers.py. ``deadline``
        (seconds, or a :class:`~mpi_k_selection_tpu.utils.timing.
        Deadline`) bounds the whole request — expiry raises the typed
        :class:`~mpi_k_selection_tpu.serve.errors.
        DeadlineExceededError` (HTTP 504). ``trace_id`` is the request-
        correlation id (minted when None — docs/OBSERVABILITY.md "Trace
        IDs"); it rides the query's events and spans."""
        ds = self._get(dataset_id)
        return self._rank_query(ds, [k], tier, "kselect", deadline, trace_id)[0]

    def kselect_many(
        self, dataset_id: str, ks, *, tier: str = "auto", deadline=None,
        trace_id=None,
    ):
        """One :class:`RankAnswer` per rank in ``ks``, in order — the
        whole request rides one dispatch (and one shared walk)."""
        ds = self._get(dataset_id)
        return self._rank_query(ds, list(ks), tier, "kselect", deadline, trace_id)

    def quantiles(
        self, dataset_id: str, qs, *, tier: str = "auto", deadline=None,
        trace_id=None,
    ):
        """Nearest-rank quantile answers (``api.quantile_ranks``
        conversion, so exact-tier values are bit-identical to
        ``api.quantiles`` over the same resident bits)."""
        from mpi_k_selection_tpu.api import quantile_ranks

        ds = self._get(dataset_id)
        try:
            ks = quantile_ranks(qs, ds.n)
        except ValueError as e:
            raise QueryError(str(e)) from e
        return self._rank_query(ds, ks, tier, "quantiles", deadline, trace_id)

    def topk(
        self, dataset_id: str, k: int, *, largest: bool = True, deadline=None,
        trace_id=None,
    ):
        """Exact top-k ``(values, indices)`` over a resident dataset
        (earliest-position tie break, matching ``lax.top_k``)."""
        ds = self._get(dataset_id)
        tid = self._trace_id(trace_id)
        result = self._run_single(
            ds, "topk",
            lambda: self.registry.topk(ds, k, largest=largest),
            deadline, tid,
        )
        self._account(ds, "topk", None, "exact", 1, False, tid)
        return result

    def rank_certificate(
        self, dataset_id: str, value, *, deadline=None, trace_id=None
    ):
        """Exact ``(#<, #<=)`` counts for ``value`` — the O(n) proof a
        served answer is the true order statistic."""
        ds = self._get(dataset_id)
        tid = self._trace_id(trace_id)
        result = self._run_single(
            ds, "rank_certificate",
            lambda: self.registry.rank_certificate(ds, value),
            deadline, tid,
        )
        self._account(ds, "rank_certificate", None, "exact", 1, False, tid)
        return result

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _trace_id(trace_id) -> str:
        """Honor a caller-supplied correlation id, mint one otherwise
        (the HTTP front passes the client's ``X-Ksel-Trace-Id`` through
        here, so one id follows a query across services). The id is
        echoed verbatim into response HEADERS, so it is clamped to
        printable ASCII and bounded — an obs-folded inbound value
        (``abc\\r\\n\\tevil`` survives the stdlib header parse) must not
        become a CR/LF header-injection primitive on the echo. An id
        that sanitizes to nothing is replaced by a minted one."""
        if trace_id:
            tid = "".join(c for c in str(trace_id)[:128] if " " <= c <= "~")
            if tid:
                return tid
        return uuid.uuid4().hex[:16]

    def _check_open(self) -> None:
        if self.batcher.closed:
            raise ServerClosedError("server is closed")

    def _resolve_deadline(self, deadline):
        if deadline is None:
            deadline = self.default_deadline
        if deadline is None or isinstance(deadline, Deadline):
            return deadline
        return Deadline.after(float(deadline))

    def _wait(self, pending):
        """Wait for a dispatched query, accounting deadline expiry: the
        waiter-side timeout is counted here; dispatch-side drops were
        already counted by the expired hook (``pending.error`` carries
        the same exception instance then — count once)."""
        try:
            return pending.wait()
        except DeadlineExceededError as e:
            if pending.error is not e:
                self._fault_obs("serve.request", "deadline", e)
                if self.metrics is not None:
                    self.metrics.counter("serve.deadline_exceeded").inc()
            raise

    def _rank_query(
        self, ds, ks, tier, op, deadline=None, trace_id=None
    ) -> list[RankAnswer]:
        """``ds`` is the RESOLVED dataset (not an id): validation and
        execution must describe the same object even if the id is
        dropped and re-registered mid-request."""
        self._check_open()
        tier = _tiers.validate_tier(tier)
        dl = self._resolve_deadline(deadline)
        tid = self._trace_id(trace_id)
        ks = [int(k) for k in ks]
        for k in ks:
            if not 1 <= k <= ds.n:
                raise QueryError(f"k={k} out of range [1, {ds.n}]")
        if tier == "sketch" or (tier == "auto" and _tiers.auto_pins(ds, ks)):
            _tiers.require_sketch(ds)
            with self.timer.phase(
                "serve.request.sketch", args={"trace_id": tid}
            ):
                if self.fast_path:
                    # the sketch is immutable and its reads are pure
                    # numpy: answer on the request thread — no enqueue,
                    # no dispatch wake, no lane serialization needed
                    answers = _tiers.sketch_answers(ds, ks)
                    if self.metrics is not None:
                        self.metrics.counter(
                            "serve.fastpath", labels={"tier": tier}
                        ).inc()
                else:
                    # the queued oracle: same answers, through the lane
                    pending = self.batcher.submit(
                        PendingQuery(
                            ds.dataset_id, "sketch", ds=ds, deadline=dl,
                            trace_id=tid,
                            run=lambda: _tiers.sketch_answers(ds, ks),
                        )
                    )
                    answers = self._wait(pending)
            self._account(ds, op, tier, "sketch", len(ks), False, tid)
            return answers
        escalated = tier == "auto"
        with self.timer.phase("serve.request.exact", args={"trace_id": tid}):
            pending = self.batcher.submit(
                PendingQuery(
                    ds.dataset_id, "rank", ks=tuple(ks), ds=ds, deadline=dl,
                    trace_id=tid,
                )
            )
            values = self._wait(pending)
        answers = [
            RankAnswer(
                k=k, value=values[i], tier="exact", exact=True,
                escalated=escalated,
            )
            for i, k in enumerate(ks)
        ]
        self._account(ds, op, tier, "exact", len(ks), escalated, tid)
        return answers

    def _run_single(self, ds, kind, run, deadline=None, trace_id=None):
        """Route one non-rank op through the dispatch thread (all device
        work stays serialized there)."""
        self._check_open()
        dl = self._resolve_deadline(deadline)
        with self.timer.phase(
            "serve.request.exact", args={"trace_id": trace_id}
        ):
            return self._wait(
                self.batcher.submit(
                    PendingQuery(
                        ds.dataset_id, kind, ds=ds, run=run, deadline=dl,
                        trace_id=trace_id,
                    )
                )
            )

    def _execute_ranks(self, items) -> None:
        """Dispatch-thread executor: ONE shared-pass select over the
        coalesced ranks of every request in the group (all items carry
        the same resolved dataset object), split back in submission
        order. The walk span carries every rider's trace id, so one
        slow coalesced walk is joinable back to the client requests
        that rode it (and to their FaultEvents)."""
        ds = items[0].ds
        all_ks = [k for item in items for k in item.ks]
        trace_ids = tuple(i.trace_id for i in items if i.trace_id)
        with self.timer.phase(
            "serve.walk",
            args={"dataset": ds.dataset_id, "trace_ids": list(trace_ids)},
        ):
            values = np.asarray(self.registry.select_many(ds, all_ks))
        pos = 0
        for item in items:
            item.result = values[pos : pos + len(item.ks)]
            pos += len(item.ks)
        if self.obs is not None:
            from mpi_k_selection_tpu.obs.events import ServeBatchEvent

            self.obs.emit(
                ServeBatchEvent(
                    dataset=ds.dataset_id,
                    requests=len(items),
                    width=len(all_ks),
                    trace_ids=trace_ids,
                )
            )

    def _observe_depth(self, depth: int, lane: str) -> None:
        if self.metrics is not None:
            self.metrics.histogram(
                "serve.queue_depth", labels={"lane": lane}
            ).observe(depth)

    def _observe_width(self, width: int) -> None:
        if self.metrics is not None:
            self.metrics.histogram("serve.batch_width").observe(width)

    def _fault_obs(self, site: str, action: str, exc=None) -> None:
        """One serving-layer fault observation (shed, deadline, restart)
        — a typed FaultEvent; the matching counters are kept next to the
        call sites (some mirror pre-existing sources rather than inc)."""
        from mpi_k_selection_tpu.obs.wiring import fault_event

        fault_event(self.obs, site, action, exc=exc)

    def _observe_shed(self) -> None:
        self._fault_obs("serve.submit", "shed")
        if self.metrics is not None:
            self.metrics.counter("serve.load_shed").inc()

    def _observe_expired(self) -> None:
        self._fault_obs("serve.dispatch", "deadline")
        if self.metrics is not None:
            self.metrics.counter("serve.deadline_exceeded").inc()

    def _observe_restart(self, exc, lane: str) -> None:
        self._fault_obs("serve.dispatch", "restart", exc)
        if self.metrics is not None:
            # mirror of the lanes' own counters (set, not inc: the lane
            # increments BEFORE this hook runs, and collect_metrics
            # re-mirrors the sum idempotently)
            self.metrics.counter("serve.dispatch_restarts").set(
                int(self.batcher.restarts)
            )
        # a supervisor restart means a DispatchCrashedError reached
        # clients: freeze the postmortem ring ONCE (obs/flight.py; no-op
        # without a flight channel, never raises)
        from mpi_k_selection_tpu.obs.flight import auto_dump

        auto_dump(self.obs, "dispatch-crashed", exc=exc)

    def _account(
        self, ds, op, tier_requested, tier_answered, queries, escalated,
        trace_id=None,
    ):
        """Per-request accounting: one ``serve.query`` event plus the
        tier/op counters. Pure host-int observation."""
        if self.obs is None:
            return
        from mpi_k_selection_tpu.obs.events import ServeQueryEvent

        self.obs.emit(
            ServeQueryEvent(
                dataset=ds.dataset_id,
                op=op,
                tier_requested=tier_requested,
                tier_answered=tier_answered,
                queries=queries,
                escalated=escalated,
                trace_id=trace_id,
            )
        )
        if self.metrics is not None:
            self.metrics.counter(
                "serve.queries", labels={"tier": tier_answered, "op": op}
            ).inc()
            if escalated:
                self.metrics.counter("serve.tier_escalations").inc()

    def collect_metrics(self):
        """Fold the registry/program-cache/phase state into the metrics
        registry (idempotent snapshot — the same overwrite discipline as
        ``obs.metrics.collect_runtime``) and return it. The /metrics
        endpoint and ``render_prometheus`` call this before exposition."""
        if self.metrics is None:
            return None
        from mpi_k_selection_tpu.obs.ledger import collect_ledger
        from mpi_k_selection_tpu.obs.metrics import collect_runtime

        self.metrics.counter("serve.program_cache.hits").set(
            int(self.registry.programs.hits)
        )
        self.metrics.counter("serve.program_cache.misses").set(
            int(self.registry.programs.misses)
        )
        self.metrics.gauge("serve.program_cache.entries").set(
            len(self.registry.programs)
        )
        self.metrics.gauge("serve.datasets").set(len(self.registry))
        self.metrics.counter("serve.dispatch_restarts").set(
            int(self.batcher.restarts)
        )
        self.metrics.gauge("serve.lanes").set(self.batcher.lane_count)
        collect_runtime(self.metrics, timer=self.timer)
        # the process ProgramLedger's compile/byte book rides /metrics
        # too (ledger.compiles{site=}, ledger.device_bytes{pool=,device=})
        collect_ledger(self.metrics)
        return self.metrics

    def _server_section(self) -> dict:
        return {
            "datasets": self.list_datasets(),
            "program_cache": {
                "hits": int(self.registry.programs.hits),
                "misses": int(self.registry.programs.misses),
                "entries": len(self.registry.programs),
            },
            "dispatch_restarts": int(self.batcher.restarts),
            "fast_path": self.fast_path,
            "lanes": self.batcher.lane_summary(),
            "closed": self.batcher.closed,
        }

    def debug_bundle(self, *, reason: str = "on-demand") -> dict:
        """Assemble the JSON-ready debug bundle (obs/flight.py): the
        flight ring's event/span tails (empty without a ``flight=``
        channel — the bundle degrades gracefully), the live metrics
        snapshot, the process ledger, the fault section, and this
        server's own state. ``GET /debug/bundle`` serves exactly this."""
        from mpi_k_selection_tpu.obs.flight import build_bundle

        if self.metrics is not None:
            self.collect_metrics()
        return build_bundle(
            self.obs, reason=reason, extra={"server": self._server_section()}
        )

    def dump_debug_bundle(self, path, *, reason: str = "on-demand") -> str:
        """:meth:`debug_bundle` written as JSON through the flight
        ring's registered dump (the CLI ``--debug-bundle`` shutdown
        artifact) — the ``server`` section rides along, which a bare
        ``FlightRecorder.dump`` would drop. Requires the ``flight=``
        channel."""
        if self.flight is None:
            raise ValueError("dump_debug_bundle needs the flight= channel")
        if self.metrics is not None:
            self.collect_metrics()
        return self.flight.dump(
            path, obs=self.obs, reason=reason,
            extra={"server": self._server_section()},
        )

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the server metrics (empty when
        the server runs without a metrics registry)."""
        metrics = self.collect_metrics()
        return "" if metrics is None else metrics.render_prometheus()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Join every dispatch-lane thread; fail queued stragglers. A
        registry
        this server created is closed too (its datasets leave the ledger
        resident byte book); a caller-provided one stays the caller's.
        Idempotent."""
        self._closed = True
        self.batcher.close()
        if self._owns_registry:
            self.registry.close()

    def __enter__(self) -> "KSelectServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
