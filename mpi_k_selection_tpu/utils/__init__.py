from mpi_k_selection_tpu.utils import datagen, dtypes, timing

__all__ = ["datagen", "dtypes", "timing"]
