"""Cross-thread span tracing — Chrome trace-event export over PhaseTimer.

``jax.profiler`` (utils/profiling.trace, the CLI's ``--trace-dir``) sees
XLA ops but not the HOST threads the streaming vertical lives on: the
pipelined descent is a producer thread (produce / encode / stage / spill)
overlapping a consumer thread (stall / merge / collect), and questions
like "did the eager survivor gather serialize the consumer?" (review r6)
are questions about the GAPS between host spans on two tracks.

This module records those spans and exports them as Chrome trace-event
JSON (the ``traceEvents`` array format), loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing`` — one track per thread,
thread ids and names preserved, so producer/consumer overlap is read
directly off the timeline.

Layering (KSL004: raw clocks live ONLY in utils/timing + utils/profiling):
the recorder never reads a clock. :class:`~mpi_k_selection_tpu.utils.
profiling.PhaseTimer` timestamps each phase as it always has and, when a
recorder is attached (``PhaseTimer(recorder=...)``), hands the finished
``(name, t0, t1)`` triple over on the thread that ran the phase — the
recorder adds the thread identity and appends under its own lock. Every
``timer.phase(...)`` in the code base (the pipeline's producer phases, the
consumer's stall, the descent's per-pass phases) becomes a span for free.
"""

from __future__ import annotations

import dataclasses
import json
import threading


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed phase on one thread (times are perf_counter seconds,
    a shared monotonic base across threads of one process). ``args`` is
    optional span context a phase attached (e.g. the serve walk's
    request-correlation trace ids — docs/OBSERVABILITY.md "Trace IDs");
    it rides into the Chrome trace's ``args`` field."""

    name: str
    t0: float
    t1: float
    thread_id: int
    thread_name: str
    args: dict | None = None

    @property
    def duration(self) -> float:
        return self.t1 - self.t0


class TraceRecorder:
    """Thread-safe span collector + Chrome trace-event exporter.

    Attach to any :class:`~mpi_k_selection_tpu.utils.profiling.PhaseTimer`
    (``PhaseTimer(recorder=rec)``); one recorder may serve several timers
    (e.g. the CLI's solve timer and the pipeline timer), interleaving
    their spans on the shared timeline.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.spans: list[Span] = []  # ksel: guarded-by[_lock]

    def record(self, name: str, t0: float, t1: float, args=None) -> None:
        """Called by PhaseTimer on the thread that ran the phase."""
        t = threading.current_thread()
        span = Span(name, t0, t1, t.ident or 0, t.name, args)
        with self._lock:
            self.spans.append(span)

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self.spans)

    def thread_ids(self) -> set[int]:
        """Distinct thread tracks recorded — a pipelined streaming run
        shows >= 2 (producer + consumer)."""
        return {s.thread_id for s in self.snapshot()}

    def to_chrome_trace(self, *, pid: int = 1) -> dict:
        """The Chrome trace-event JSON object: complete (``"X"``) events
        in microseconds rebased to the earliest span, plus
        ``thread_name`` metadata events so Perfetto labels each track
        (``ksel-pipeline-*`` = producer, ``MainThread`` = consumer)."""
        spans = self.snapshot()
        base = min((s.t0 for s in spans), default=0.0)
        events = []
        named: set[int] = set()
        for s in spans:
            if s.thread_id not in named:
                named.add(s.thread_id)
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": s.thread_id,
                        "args": {"name": s.thread_name},
                    }
                )
            events.append(
                {
                    "name": s.name,
                    "ph": "X",
                    "pid": pid,
                    "tid": s.thread_id,
                    "ts": (s.t0 - base) * 1e6,
                    "dur": s.duration * 1e6,
                    "cat": s.name.split(".")[0],
                    "args": dict(s.args) if s.args else {},
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def to_json(self, indent=None) -> str:
        return json.dumps(self.to_chrome_trace(), indent=indent)

    def write(self, path: str) -> None:
        """Write the Chrome trace JSON to ``path`` (open it at
        https://ui.perfetto.dev or chrome://tracing)."""
        with open(path, "w") as f:
            f.write(self.to_json())
