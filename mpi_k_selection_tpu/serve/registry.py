"""Dataset registry + keyed program cache — the resident state of the
query server.

The reference has no driver layer at all (every parameter is a
compile-time constant; PAPER.md's L3 gap), so "load the data once, answer
many queries" is exactly the state this module owns:

- :class:`ResidentDataset` — one registered dataset: an immutable
  resident representation (device array, host array for the exact
  f64-on-TPU route, or a replayable chunk source for out-of-core data)
  plus an optional resident :class:`~mpi_k_selection_tpu.streaming.
  sketch.RadixSketch` that serves the sketch/auto latency tiers.
- :class:`DatasetRegistry` — the id -> dataset map (one lock, copy-on-read
  listings) and the ONE selection dispatch the server's dispatch thread
  calls (:meth:`DatasetRegistry.select_many`). Residency is decided here,
  once, at registration: caller-typed 64-bit integers without x64 and
  host float64 on TPU both take the host-exact routes the library already
  guarantees — the server never silently truncates what the library
  would not.
- :class:`ProgramCache` — a ``StagingPool``-style keyed cache (hits /
  misses counters, LRU eviction) for compiled selection programs and
  descent state: the per-(dataset, query-count) shared-walk callables and
  the dataset's cached full sort (the "descent state" the sort path
  reuses — one ``jnp.sort`` serves every later sort-path batch as a pure
  gather). KSL010 enforces the other direction: ``serve/`` handler code
  must not wrap anything in ``jax.jit`` itself — every compile-bearing
  callable is built here and cached by key, so repeat query shapes never
  recompile.

Concurrency discipline: datasets are immutable once registered (host
arrays are defensively copied and marked read-only; device arrays are
immutable by construction), the registry dict is guarded by one lock,
and each dataset's device work runs on exactly one dispatch-lane thread
(serve/batcher.py routed by serve/lanes.py) — the registry itself never
starts a thread. :class:`ProgramCache` is safe for concurrent lanes:
builds run behind a per-key latch, so two lanes racing a first query
never compile the same program twice.
"""

from __future__ import annotations

import collections
import dataclasses
import threading

import numpy as np

from mpi_k_selection_tpu.obs import ledger as _ldg
from mpi_k_selection_tpu.serve.errors import (
    DatasetExistsError,
    DatasetNotFoundError,
    QueryError,
    ServerClosedError,
)

#: Default resident-sketch geometry (matches RadixSketch defaults).
DEFAULT_SKETCH_BITS = 4
DEFAULT_SKETCH_LEVELS = 4


class ProgramCache:
    """Keyed LRU cache for compiled programs / descent state, with the
    exact hit/miss counter discipline of
    :class:`~mpi_k_selection_tpu.streaming.pipeline.StagingPool` (plain
    ints under the lock, mirrored into the obs registry by the server so
    tests can assert them EQUAL)."""

    #: ProgramLedger site this cache reports into (obs/ledger.py): hits
    #: count as cache hits, builds as compiles with their wall clocked —
    #: the runtime book behind the serve steady-state recompile gate.
    LEDGER_SITE = "serve.programs"

    def __init__(self, *, max_entries: int = 64):
        self._lock = threading.Lock()
        self._entries: collections.OrderedDict = collections.OrderedDict()  # ksel: guarded-by[_lock]
        #: per-key build latches: key -> Event set when that build ends
        #: (success OR failure) — the thundering-herd gate
        self._building: dict = {}  # ksel: guarded-by[_lock]
        self.max_entries = int(max_entries)
        self.hits = 0
        self.misses = 0
        #: optional Observability whose sink receives RecompileStormEvents
        #: (set by KSelectServer; the ledger bookkeeping itself is
        #: unconditional)
        self.obs = None

    def get_or_build(self, key, builder):
        """The cached value for ``key``, building (and caching) it on the
        first request. The build runs OUTSIDE the lock — it may compile
        for seconds — behind a per-key latch: the first caller installs
        the latch and builds; concurrent callers for the SAME key wait
        on the latch and take the finished value as a HIT (one compile,
        one ledger entry — the thundering-herd fix; two racing first
        queries used to compile the same program twice). If the build
        RAISES, waiters retry the build themselves rather than caching
        the failure."""
        while True:
            with self._lock:
                if key in self._entries:
                    self.hits += 1
                    self._entries.move_to_end(key)
                    value = self._entries[key]
                    latch = None
                else:
                    latch = self._building.get(key)
                    if latch is None:
                        # we are the builder for this key
                        self.misses += 1
                        self._building[key] = threading.Event()
                        break
            # ledger reporting OUTSIDE the cache lock (it locks itself)
            if latch is None:
                _ldg.LEDGER.note_hit(self.LEDGER_SITE, key)
                return value
            # another thread is building this key: wait for its latch,
            # then re-enter — the entry is there (our hit), or the build
            # failed / the entry was LRU-evicted meanwhile (we rebuild)
            latch.wait()
        try:
            with _ldg.LEDGER.compile_span(self.LEDGER_SITE, key, obs=self.obs):
                value = builder()
        except BaseException:
            with self._lock:
                self._building.pop(key).set()
            raise
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            # release waiters only AFTER the entry is visible, so every
            # waiter's re-entry counts a clean hit
            self._building.pop(key).set()
        return value

    def drop_dataset(self, dataset_id: str) -> None:
        """Evict every entry of one dataset (keys are ``(kind, dataset_id,
        ...)`` tuples) — called when the dataset is dropped so its cached
        sort / walk closures release their device memory."""
        with self._lock:
            for key in [k for k in self._entries if k[1] == dataset_id]:
                del self._entries[key]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


@dataclasses.dataclass(frozen=True)
class ResidentDataset:
    """One registered dataset. ``residency`` is ``"device"`` (a committed
    jax array), ``"host"`` (a read-only numpy array — the exact
    f64-on-TPU route), or ``"stream"`` (a replayable chunk source; exact
    queries run the sketch-seeded streaming descent). ``sketch`` is the
    resident :class:`RadixSketch` (None = exact tier only)."""

    dataset_id: str
    residency: str
    dtype: object  # np.dtype
    n: int
    data: object = None  # device or host array (None for "stream")
    source: object = None  # replayable chunk source (None for resident)
    sketch: object = None
    stream_kwargs: dict = dataclasses.field(default_factory=dict)

    @property
    def nbytes(self) -> int:
        """Resident footprint of the dataset's data (device or host) —
        the per-dataset byte book the ledger's ``resident`` pool
        aggregates (stream datasets hold no resident array: 0)."""
        if self.data is None:
            return 0
        return int(self.n) * np.dtype(self.dtype).itemsize

    def summary(self) -> dict:
        """JSON-ready description (the /v1/datasets listing row)."""
        out = {
            "dataset": self.dataset_id,
            "residency": self.residency,
            "dtype": str(np.dtype(self.dtype)),
            "n": self.n,
            "resident_bytes": self.nbytes,
            "sketch": self.sketch is not None,
        }
        if self.sketch is not None:
            out["sketch_resolution_bits"] = self.sketch.resolution_bits
            out["sketch_max_bucket"] = self.sketch.max_bucket_population()
        return out


def _host_keys(arr: np.ndarray) -> np.ndarray:
    from mpi_k_selection_tpu.utils.dtypes import np_to_sortable_bits

    return np_to_sortable_bits(np.ravel(arr))


def _build_sketch(data_or_chunks, dtype, radix_bits, levels):
    from mpi_k_selection_tpu.streaming.sketch import RadixSketch

    sk = RadixSketch(dtype, radix_bits=radix_bits, levels=levels)
    for chunk in data_or_chunks:
        sk.update(chunk)
    return sk


class DatasetRegistry:
    """Id-keyed home of resident datasets plus the program cache."""

    def __init__(self, *, programs: ProgramCache | None = None):
        self._lock = threading.Lock()
        self._datasets: dict[str, ResidentDataset] = {}  # ksel: guarded-by[_lock]
        self._closed = False  # ksel: guarded-by[_lock]
        self.programs = programs if programs is not None else ProgramCache()

    # -- lifecycle ---------------------------------------------------------

    def _check_new_id(self, dataset_id: str) -> None:
        """Fail-fast duplicate check BEFORE the expensive registration
        work (defensive copy, device transfer, full sketch/stream pass);
        :meth:`_register`'s locked check still closes the race."""
        with self._lock:
            self._check_open_locked()
            if dataset_id in self._datasets:
                raise DatasetExistsError(
                    f"dataset {dataset_id!r} already registered; resident "
                    "shards are immutable — drop() it first"
                )

    def _check_open_locked(self) -> None:
        if self._closed:
            raise ServerClosedError(
                "registry is closed; datasets can no longer be registered"
            )

    def _register(self, ds: ResidentDataset) -> ResidentDataset:
        with self._lock:
            # closed-ness decided under the SAME lock as the insert: a
            # registration racing close() either lands before the close
            # snapshot (and is released by it) or fails here — it can
            # never add bytes to the resident book after the snapshot
            # subtracted, which would leave phantom bytes forever
            self._check_open_locked()
            if ds.dataset_id in self._datasets:
                raise DatasetExistsError(
                    f"dataset {ds.dataset_id!r} already registered; resident "
                    "shards are immutable — drop() it first"
                )
            self._datasets[ds.dataset_id] = ds
        # the resident byte book (obs/ledger.py) — outside the registry
        # lock; the residency label is a closed 3-value set, per-dataset
        # figures live in each ResidentDataset.summary()
        _ldg.LEDGER.adjust_bytes("resident", ds.residency, ds.nbytes)
        return ds

    def add_array(
        self,
        dataset_id: str,
        data,
        *,
        sketch: bool = True,
        sketch_bits: int = DEFAULT_SKETCH_BITS,
        sketch_levels: int = DEFAULT_SKETCH_LEVELS,
    ) -> ResidentDataset:
        """Register an in-core dataset. ``data`` is converted ONCE through
        :func:`~mpi_k_selection_tpu.api.as_selection_array` (so the exact
        f64-on-TPU host route is reachable), EXCEPT caller-typed 64-bit
        integer host data with x64 off, which becomes a single-chunk
        STREAM dataset — the library's host-exact 64-bit route — instead
        of raising at registration. The resident sketch is built from the
        RESIDENT representation (post-conversion), so sketch answers and
        exact answers always describe the same bits."""
        import jax

        from mpi_k_selection_tpu import api as _api

        self._check_new_id(dataset_id)
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            arr = np.asarray(data)
            if (
                hasattr(data, "dtype")
                and arr.dtype.kind in "iu"
                and arr.dtype.itemsize == 8
                and not jax.config.jax_enable_x64
            ):
                # jnp.asarray would silently truncate (KSL002); route the
                # data through the streaming layer's host-exact counting
                arr = np.ascontiguousarray(arr)
                return self.add_stream(
                    dataset_id,
                    [arr],
                    sketch=sketch,
                    sketch_bits=sketch_bits,
                    sketch_levels=sketch_levels,
                )
        x = _api.as_selection_array(data)
        if x.size == 0:
            raise QueryError("cannot register an empty dataset")
        if isinstance(x, np.ndarray):
            # host residency (exact f64-on-TPU): defensive copy, frozen —
            # a caller mutating its array must not change served answers
            x = np.ascontiguousarray(x).copy()
            x.flags.writeable = False
            residency = "host"
        else:
            residency = "device"
        sk = None
        if sketch:
            host_view = x if isinstance(x, np.ndarray) else np.asarray(x)
            sk = _build_sketch(
                [host_view], np.dtype(x.dtype), sketch_bits, sketch_levels
            )
        return self._register(
            ResidentDataset(
                dataset_id=dataset_id,
                residency=residency,
                dtype=np.dtype(x.dtype),
                n=int(x.size),
                data=x,
                sketch=sk,
            )
        )

    def add_stream(
        self,
        dataset_id: str,
        source,
        *,
        sketch: bool = True,
        sketch_bits: int = DEFAULT_SKETCH_BITS,
        sketch_levels: int = DEFAULT_SKETCH_LEVELS,
        **stream_kwargs,
    ) -> ResidentDataset:
        """Register an out-of-core dataset from a REPLAYABLE chunk source
        (list/tuple of chunks, a zero-arg callable returning a fresh
        iterator, or a committed SpillStore). One accumulation pass runs
        here to build the resident sketch (and establish n/dtype); exact
        queries later replay the source through the sketch-seeded
        streaming descent. ``stream_kwargs`` are held for those descents
        (``pipeline_depth``, ``devices``, ``hist_method``,
        ``width_schedule``, ``pack_spill``, ``ingest_workers``, ...);
        the accumulation pass below honors the staging/data-plane subset
        (depth, devices, fused, ingest_workers) immediately."""
        from mpi_k_selection_tpu.streaming.chunked import as_chunk_source
        from mpi_k_selection_tpu.streaming.sketch import RadixSketch

        self._check_new_id(dataset_id)
        src = as_chunk_source(source)  # rejects one-shot sources loudly
        dtype = None
        for chunk in src():  # dtype probe only; the fold is one stream pass
            cdt = getattr(chunk, "orig_dtype", None)  # spill records
            if cdt is not None:
                dtype = np.dtype(cdt)
                break
            c = np.ravel(np.asarray(chunk))
            if c.size:
                dtype = np.dtype(c.dtype)
                break
        if dtype is None:
            raise QueryError("cannot register an empty dataset")
        sk = RadixSketch(dtype, radix_bits=sketch_bits, levels=sketch_levels)
        # the accumulation pass rides the streaming layer so the
        # dataset's held staging knobs govern the sketch build too — the
        # registry must not host-fold a stream its caller staged on
        # devices (the KSL022 placement hole this loop used to be)
        sk.update_stream(
            src,
            pipeline_depth=stream_kwargs.get("pipeline_depth", 0),
            devices=stream_kwargs.get("devices"),
            fused=stream_kwargs.get("fused"),
            ingest_workers=stream_kwargs.get("ingest_workers"),
        )
        n = int(sk.n)
        if n == 0:
            raise QueryError("cannot register an empty dataset")
        return self._register(
            ResidentDataset(
                dataset_id=dataset_id,
                residency="stream",
                dtype=dtype,
                n=n,
                source=src,
                # the accumulation pass is the sketch build; tier
                # resolution needs it resident even with sketch=False
                # for seeding, but honor the caller's visibility choice
                sketch=sk if sketch else None,
                stream_kwargs=dict(stream_kwargs),
            )
        )

    def get(self, dataset_id: str) -> ResidentDataset:
        with self._lock:
            ds = self._datasets.get(dataset_id)
        if ds is None:
            raise DatasetNotFoundError(f"no dataset registered as {dataset_id!r}")
        return ds

    def drop(self, dataset_id: str) -> None:
        with self._lock:
            ds = self._datasets.get(dataset_id)
            if ds is None:
                raise DatasetNotFoundError(
                    f"no dataset registered as {dataset_id!r}"
                )
            del self._datasets[dataset_id]
        _ldg.LEDGER.adjust_bytes("resident", ds.residency, -ds.nbytes)
        self.programs.drop_dataset(dataset_id)

    def close(self) -> None:
        """Unregister every dataset, returning its bytes to the resident
        book (obs/ledger.py). Without this, a registry discarded whole —
        a server torn down without per-dataset ``drop()`` calls — would
        ratchet the process-wide ``ledger.device_bytes{pool="resident"}``
        gauge upward across server lifetimes, and the eviction budgeting
        that book feeds would act on phantom bytes. Idempotent; races
        with :meth:`drop` subtract each dataset exactly once (both pop
        under the lock before touching the ledger). A closed registry
        permanently rejects new registrations — the byte snapshot below
        must be final."""
        with self._lock:
            self._closed = True
            datasets = list(self._datasets.values())
            self._datasets.clear()
        for ds in datasets:
            _ldg.LEDGER.adjust_bytes("resident", ds.residency, -ds.nbytes)

    def list_datasets(self) -> list[dict]:
        with self._lock:
            datasets = list(self._datasets.values())
        return [ds.summary() for ds in sorted(datasets, key=lambda d: d.dataset_id)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._datasets)

    # -- selection dispatch (dispatch-thread only) -------------------------

    def select_many(self, ds: ResidentDataset, ks) -> np.ndarray:
        """Exact values at 1-indexed ranks ``ks`` (a list of ints), in
        order — THE exact-tier entry the dispatch thread calls. Mirrors
        :func:`~mpi_k_selection_tpu.api.kselect_many`'s n-aware dispatch
        (same crossover rule, same clip-gather sort path, same radix
        walk), with the compiled pieces drawn from :attr:`programs`:
        answers are bit-identical to one ``api.kselect`` per rank because
        both run the same exact order-statistic machinery over the same
        resident bits."""
        ks = [int(k) for k in ks]
        for k in ks:
            if not 1 <= k <= ds.n:
                raise QueryError(f"k={k} out of range [1, {ds.n}]")
        if ds.residency == "stream":
            fn = self.programs.get_or_build(
                ("stream_select", ds.dataset_id),
                lambda: self._build_stream_select(ds),
            )
            return np.asarray(fn(ks))
        from mpi_k_selection_tpu.api import many_sort_dispatch_queries

        if ds.n <= 1 << 14 or len(ks) >= many_sort_dispatch_queries(ds.n):
            s = self.programs.get_or_build(
                ("sorted", ds.dataset_id), lambda: self._build_sorted(ds)
            )
            idx = np.clip(np.asarray(ks, np.int64) - 1, 0, ds.n - 1)
            if isinstance(s, np.ndarray):
                return s[idx]
            return np.asarray(s[idx])
        # keyed per DATASET, not per batch width: the closure serves any
        # width (jit's own cache keys the compiled program by ks shape
        # underneath), and width-fragmented entries could LRU-evict the
        # genuinely expensive cached sort above
        fn = self.programs.get_or_build(
            ("walk", ds.dataset_id),
            lambda: self._build_walk(ds),
        )
        return np.asarray(fn(ks))

    # -- registration-time warmup ------------------------------------------

    def warmup(self, ds: ResidentDataset) -> int:
        """Pre-build every program :meth:`select_many` can reach for
        this dataset — through :class:`ProgramCache`, so the compile
        wall is clocked under the ledger's ``compile_span`` at
        registration time instead of landing on the first client.
        Returns the number of programs built (the cache misses this
        call caused; 0 when everything was already resident).

        The warm builders go one step further than the lazy ones: they
        FORCE the first execution (``block_until_ready`` on the cached
        sort, one width-1 walk call) so jax's trace+compile happens
        inside the span too — after this, a warmed dataset's
        steady-state query mix records ZERO on-path compiles at the
        ``serve.programs`` site (the tier-1 gate in
        tests/test_serve_lanes.py). Walk widths other than 1 still
        jit-specialize on first use — that cost is per width, inside
        jax, and invisible to the program cache by design (the closure
        is keyed per dataset, not per width)."""
        miss0 = self.programs.misses
        if ds.residency == "stream":
            # the streamed descent's closure is host logic; its device
            # programs belong to the streaming layer's own caches.
            # Building the closure here still takes the first query's
            # cache miss off the request path
            self.programs.get_or_build(
                ("stream_select", ds.dataset_id),
                lambda: self._build_stream_select(ds),
            )
        else:
            self.programs.get_or_build(
                ("sorted", ds.dataset_id),
                lambda: self._build_sorted_warm(ds),
            )
            if ds.n > 1 << 14:
                # large datasets dispatch narrow batches to the walk
                self.programs.get_or_build(
                    ("walk", ds.dataset_id),
                    lambda: self._build_walk_warm(ds),
                )
        built = self.programs.misses - miss0
        if ds.sketch is not None:
            # the sketch fast path is pure numpy, but its first pin/
            # bounds touch materializes the pyramid's cumulative views —
            # warm those reads too so the first sketch answer is steady
            ds.sketch.pin(1)
            ds.sketch.rank_bounds(1)
            ds.sketch.value_bounds(1)
        return built

    @staticmethod
    def _build_sorted_warm(ds: ResidentDataset):
        """:meth:`_build_sorted` plus a device sync, so the sort's
        compile+execute wall lands inside the warmup compile span."""
        s = DatasetRegistry._build_sorted(ds)
        if not isinstance(s, np.ndarray):
            s.block_until_ready()
        return s

    @staticmethod
    def _build_walk_warm(ds: ResidentDataset):
        """:meth:`_build_walk` plus one width-1 execution: the walk's
        jit trace+compile for the single-rank shape happens here, inside
        the warmup compile span, not on the first client's query."""
        fn = DatasetRegistry._build_walk(ds)
        np.asarray(fn([1]))
        return fn

    @staticmethod
    def _build_sorted(ds: ResidentDataset):
        """Descent state for the sort path: the dataset sorted ONCE (host
        stable sort for host residency — the f64-exact route — else one
        device sort). Every later sort-path batch is a pure gather."""
        if isinstance(ds.data, np.ndarray):
            return np.sort(np.ravel(ds.data), kind="stable")
        import jax.numpy as jnp

        return jnp.sort(jnp.ravel(ds.data))

    @staticmethod
    def _build_walk(ds: ResidentDataset):
        """The shared-pass multi-rank walk over the resident array —
        compilation happens inside ops/radix.py on first call per batch
        width and is reused for every later batch of that width."""
        from mpi_k_selection_tpu.ops.radix import radix_select_many, select_count_dtype

        def fn(ks):
            import jax.numpy as jnp

            ks_arr = jnp.asarray(ks, select_count_dtype(ds.n))
            return radix_select_many(ds.data, ks_arr)

        return fn

    @staticmethod
    def _build_stream_select(ds: ResidentDataset):
        """Exact streamed multi-rank select — through the resident
        sketch's ``refine_many`` entry when a sketch is resident (its
        resolved prefix skips ``levels`` streamed passes), else the bare
        shared-pass streaming descent."""
        kwargs = dict(ds.stream_kwargs)
        if ds.sketch is not None:
            return lambda ks: ds.sketch.refine_many(ds.source, ks, **kwargs)
        from mpi_k_selection_tpu.streaming.chunked import streaming_kselect_many

        return lambda ks: streaming_kselect_many(ds.source, ks, **kwargs)

    # -- non-rank ops (dispatch-thread only) -------------------------------

    def topk(self, ds: ResidentDataset, k: int, *, largest: bool = True):
        """Top-k (values, indices) over a RESIDENT dataset. Stream
        datasets raise: a streamed top-k pass is a different workload
        (ROADMAP) and silently re-streaming the source per query would
        wreck the latency contract."""
        if not 1 <= int(k) <= ds.n:
            raise QueryError(f"topk k={k} out of range [1, {ds.n}]")
        k = int(k)
        if ds.residency == "stream":
            raise QueryError(
                "topk requires a resident (array) dataset; "
                f"{ds.dataset_id!r} is stream-resident"
            )
        if isinstance(ds.data, np.ndarray):
            # host residency: exact top-k in key space, earliest-position
            # tie break (lax.top_k's rule) via stable argsort
            keys = _host_keys(ds.data)
            order_keys = ~keys if largest else keys
            idx = np.argsort(order_keys, kind="stable")[:k]
            return np.ravel(ds.data)[idx], idx
        from mpi_k_selection_tpu.ops.topk import topk as _topk

        v, i = _topk(ds.data, k, largest=largest)
        return np.asarray(v), np.asarray(i)

    def rank_certificate(self, ds: ResidentDataset, value):
        """Exact ``(#<, #<=)`` counts of ``value`` in the dataset — the
        O(n) proof that a served answer is the true order statistic."""
        if ds.residency == "stream":
            from mpi_k_selection_tpu.streaming.chunked import (
                streaming_rank_certificate,
            )

            kwargs = {
                key: ds.stream_kwargs[key]
                for key in ("pipeline_depth", "devices")
                if key in ds.stream_kwargs
            }
            less, leq = streaming_rank_certificate(ds.source, value, **kwargs)
            return int(less), int(leq)
        if isinstance(ds.data, np.ndarray):
            keys = _host_keys(ds.data)
            kv = _host_keys(np.asarray([value], ds.dtype))[0]
            return int((keys < kv).sum()), int((keys <= kv).sum())
        from mpi_k_selection_tpu.utils import debug

        less, leq = debug.rank_certificate(ds.data, value)
        return int(less), int(leq)
