"""MPI-parity backend (``--backend=mpi``) — multi-process CGM selection.

Reproduces the reference's CGM weighted-median k-selection
(``TODO-kth-problem-cgm.c:35-296``) as P local OS processes communicating
through the framework's native shared-memory collectives runtime
(native/kselect_native.cpp), the in-tree equivalent of the MPICH
``libmpi.so.12`` the reference links.
"""

from __future__ import annotations

from mpi_k_selection_tpu.errors import NativeUnavailableError

NAME = "mpi"

_NOT_BUILT = (
    "the mpi backend requires the native shared-memory collectives runtime; "
    "build it with `python -m mpi_k_selection_tpu.native.build`"
)


def kselect(x, k: int, *, num_procs: int = 4, **kwargs):
    try:
        from mpi_k_selection_tpu.native import cgm_driver
    except ImportError as e:
        raise NativeUnavailableError(_NOT_BUILT) from e

    return cgm_driver.kselect(x, k, num_procs=num_procs, **kwargs)


def median(x, **kwargs):
    import numpy as np

    x = np.asarray(x).ravel()
    return kselect(x, max(1, x.size // 2), **kwargs)
