"""Multi-device staged ingest (ISSUE 4): round-robin staging across
`jax.devices()`.

The acceptance contract: streaming answers are BIT-identical across
``devices`` in {1, 2, max} x ``pipeline_depth`` in {0, 2} on the 8-device
virtual CPU mesh (conftest.py) — heterogeneous chunk sizes, ragged final
chunks, empty chunks, host-exact fallback routes, survivor collect and
rank certificate included — with the host int64 merge drained in chunk
order, one staged buffer per round-robin slot, and no producer thread
surviving any pass (the autouse conftest fixture backstops every test
here).
"""

import threading

import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.streaming import (
    RadixSketch,
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.streaming import pipeline as pl


def _chunks(x, nchunks):
    return [np.ascontiguousarray(c) for c in np.array_split(x, nchunks)]


def _ints(rng, n, dtype=np.int32):
    return rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(dtype)


def _device_grid():
    import jax

    return sorted({1, 2, len(jax.devices())})


# -- the determinism grid ----------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_grid_bit_identical_heterogeneous_chunks(depth, rng):
    """Heterogeneous chunk sizes (np.array_split on a non-multiple) across
    the full devices x depth grid, vs the devices=1 depth=0 oracle."""
    x = _ints(rng, (1 << 14) + 311)
    chunks = _chunks(x, 7)  # ragged: sizes differ by one across chunks
    ks = [1, 137, x.size // 2, x.size]
    oracle = streaming_kselect_many(chunks, ks, pipeline_depth=0, devices=1)
    assert oracle == [seq.kselect_sort(x, k) for k in ks]
    for devices in _device_grid():
        got = streaming_kselect_many(
            chunks, ks, pipeline_depth=depth, devices=devices
        )
        assert got == oracle, (devices, depth)


def test_grid_bit_identical_ragged_final_chunk(rng):
    """A short final chunk lands in a DIFFERENT pow2 staging bucket than
    its predecessors: the round robin must keep chunk->device assignment
    and pad correction exact across bucket changes."""
    x = _ints(rng, 5 * 1000 + 537)
    chunks = [x[i * 1000:(i + 1) * 1000] for i in range(5)] + [x[5000:]]
    want = seq.kselect_sort(x, x.size // 2)
    for devices in _device_grid():
        got = streaming_kselect(
            chunks, x.size // 2, hist_method="scatter",
            pipeline_depth=2, devices=devices,
        )
        assert got == want, devices


def test_grid_bit_identical_empty_chunks(rng):
    """Empty chunks are no-ops and must NOT advance the round-robin slot
    (the chunk->device assignment is a function of the staged sequence)."""
    x = _ints(rng, 4096)
    chunks = [
        x[:1000], np.empty(0, np.int32), x[1000:2048],
        np.empty(0, np.int32), x[2048:],
    ]
    want = seq.kselect_sort(x, 19)
    for devices in _device_grid():
        assert streaming_kselect(
            chunks, 19, pipeline_depth=2, devices=devices
        ) == want


def test_grid_host_exact_64bit_route_ignores_devices(rng):
    """64-bit keys without x64 resolve to host counting: the devices knob
    must not push them onto a device (where jnp would truncate)."""
    import jax

    assert not jax.config.jax_enable_x64
    x = rng.integers(-(2**62), 2**62, size=1 << 13, dtype=np.int64)
    k = x.size // 2
    want = seq.kselect_sort(x, k)
    for devices in _device_grid():
        got = streaming_kselect(
            _chunks(x, 8), k, pipeline_depth=2, devices=devices
        )
        assert got == want, devices


def test_grid_tiny_budget_multi_prefix_and_collect(rng):
    """A tiny collect budget drives deep shared-sweep passes AND the
    multi-device survivor collect (each device filters its own resident
    chunks) through several pipeline generations."""
    x = _ints(rng, 1 << 14)
    chunks = _chunks(x, 9)
    ks = [7, x.size // 4, x.size // 2, x.size - 3]
    oracle = streaming_kselect_many(chunks, ks, collect_budget=64, pipeline_depth=0)
    for devices in _device_grid():
        got = streaming_kselect_many(
            chunks, ks, collect_budget=64, pipeline_depth=2, devices=devices
        )
        assert got == oracle, devices


def test_certificate_grid_matches_sync(rng):
    x = _ints(rng, 1 << 13)
    chunks = _chunks(x, 8)
    v = int(np.sort(x)[x.size // 2])
    oracle = streaming_rank_certificate(chunks, v, pipeline_depth=0)
    for devices in _device_grid():
        got = streaming_rank_certificate(
            chunks, v, pipeline_depth=2, devices=devices
        )
        assert got == oracle, devices


def test_sketch_update_stream_devices_bit_identical(rng):
    """The multi-device deepest-level device fold must produce a sketch ==
    sequential host update() accumulation (counts, n, AND key-space
    extremes — the pad zeros must not leak into min)."""
    x = _ints(rng, (1 << 13) + 77)
    chunks = _chunks(x, 7)
    want = RadixSketch(np.int32)
    for c in chunks:
        want.update(c)
    for devices in _device_grid():
        got = RadixSketch(np.int32).update_stream(
            chunks, pipeline_depth=2, devices=devices
        )
        assert got == want, devices


def test_streaming_quantiles_devices_surface(rng):
    from mpi_k_selection_tpu import StreamingQuantiles
    from mpi_k_selection_tpu.api import quantile_ranks

    x = _ints(rng, 1 << 13)
    chunks = _chunks(x, 8)
    t = StreamingQuantiles(np.int32, devices=2).update_stream(chunks)
    t1 = StreamingQuantiles(np.int32, pipeline_depth=0)
    for c in chunks:
        t1.update(c)
    assert t.sketch == t1.sketch
    assert t.merge(t1).devices == 2  # knob survives the (pure) merge
    qs = [0.5, 0.99]
    s = np.sort(x, kind="stable")
    want = [s[k - 1] for k in quantile_ranks(qs, x.size)]
    assert t.refine_quantiles(qs, chunks) == want
    with pytest.raises(ValueError, match="devices"):
        StreamingQuantiles(np.int32, devices=0)


# -- round-robin placement ---------------------------------------------------


def test_round_robin_places_chunks_on_successive_devices(rng):
    import jax

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs the multi-device virtual mesh")
    chunks = _chunks(_ints(rng, 6 * 1024), 6)  # pow2 chunks: staged unpadded
    pipe = pl.ChunkPipeline(
        lambda: iter(chunks), depth=2, hist_method="scatter", devices=devs
    )
    try:
        seen = []
        for keys, _ in pipe:
            assert isinstance(keys, pl.StagedKeys)
            seen.append(next(iter(keys.data.devices())))
            keys.release()  # the consumer contract: every staged slot freed
    finally:
        pipe.close()
    assert seen == [devs[i % len(devs)] for i in range(6)]


def test_resolve_stream_devices_knob():
    import jax

    devs = jax.devices()
    assert pl.resolve_stream_devices(None) == (None,)
    assert pl.resolve_stream_devices(1) == (devs[0],)
    assert pl.resolve_stream_devices(2) == tuple(devs[:2])
    # caps at the available count (the CLI's --devices semantics)
    assert pl.resolve_stream_devices(10**6) == tuple(devs)
    assert pl.resolve_stream_devices([devs[-1]]) == (devs[-1],)
    for bad in (0, -1, True, 1.5, "all", [], ["x"]):
        with pytest.raises(ValueError):
            pl.resolve_stream_devices(bad)
    with pytest.raises(ValueError, match="devices"):
        streaming_kselect([np.arange(4, dtype=np.int32)], 1, devices=-2)


def test_depth_zero_stays_synchronous_oracle(rng):
    """devices > 1 with pipeline_depth=0 must neither spawn a thread nor
    stage: the synchronous oracle is untouched by the knob."""
    x = _ints(rng, 1 << 10)
    before = {t.ident for t in threading.enumerate()}
    got = streaming_kselect(_chunks(x, 4), 17, pipeline_depth=0, devices=8)
    assert got == seq.kselect_sort(x, 17)
    new = [
        t for t in threading.enumerate()
        if t.ident not in before and t.name.startswith(pl.THREAD_NAME_PREFIX)
    ]
    assert not new


# -- error paths with multi-device producers in flight -----------------------


def test_drifting_source_raises_and_joins_multidevice(rng):
    """The replay-stability raise must unwind cleanly with round-robin
    staged buffers in flight on several devices — and join the producer
    (the leaked-thread check of the ISSUE)."""
    calls = [0]

    def source():
        calls[0] += 1
        r = np.random.default_rng(calls[0])
        for _ in range(8):  # enough chunks to fill every round-robin slot
            yield r.integers(-(2**31), 2**31, size=1 << 11, dtype=np.int64).astype(
                np.int32
            )

    with pytest.raises(RuntimeError, match="not replay-stable"):
        streaming_kselect(
            source, 1 << 12, collect_budget=4, pipeline_depth=3, devices=8
        )
    assert not [
        t for t in threading.enumerate()
        if t.name.startswith(pl.THREAD_NAME_PREFIX)
    ]


def test_source_exception_propagates_multidevice(rng):
    x = _ints(rng, 2048)

    def source():
        yield x[:1024]
        yield x[1024:]
        raise OSError("disk gone")

    with pytest.raises(OSError, match="disk gone"):
        streaming_kselect(source, 5, pipeline_depth=2, devices=8)


# -- staging-buffer free list ------------------------------------------------


def test_staging_pool_reuses_released_buffers():
    pool = pl.StagingPool()
    keys = np.arange(1000, dtype=np.uint32)  # pads to the 1024 bucket
    s1 = pl.stage_keys(keys, None, pool)
    buf1 = s1.host_buf
    assert buf1 is not None and buf1.shape[0] == 1024
    assert pool.misses == 1 and pool.hits == 0
    s1.release()
    s1.release()  # idempotent: the buffer must enter the free list ONCE
    s2 = pl.stage_keys(keys + 1, None, pool)
    assert s2.host_buf is buf1  # recycled, not re-allocated
    assert pool.hits == 1
    np.testing.assert_array_equal(np.asarray(s2.valid()), keys + 1)
    s2.release()


def test_staging_pool_keys_by_bucket_dtype_device():
    import jax

    devs = jax.devices()
    pool = pl.StagingPool()
    a = pl.stage_keys(np.arange(1000, dtype=np.uint32), devs[0], pool)
    a.release()
    # different bucket -> fresh allocation
    b = pl.stage_keys(np.arange(2000, dtype=np.uint32), devs[0], pool)
    assert b.host_buf.shape[0] == 2048 and pool.hits == 0
    b.release()
    if len(devs) > 1:
        # same bucket, different device -> its own free list
        c = pl.stage_keys(np.arange(1000, dtype=np.uint32), devs[1], pool)
        assert pool.hits == 0
        c.release()
        d = pl.stage_keys(np.arange(1000, dtype=np.uint32), devs[1], pool)
        assert pool.hits == 1  # now recycled from device 1's list
        d.release()


def test_staging_pool_holds_buffer_until_release():
    """The host pad buffer must NOT be reused while the device array
    lives: device_put may alias host memory (CPU zero-copy), so recycling
    early would corrupt staged keys."""
    pool = pl.StagingPool()
    keys = np.arange(1000, dtype=np.uint32)
    s = pl.stage_keys(keys, None, pool)
    # not in the free list yet: an acquire must MISS while s is alive
    buf = pool.acquire(1024, np.uint32, None)
    assert pool.misses == 2 and buf is not s.host_buf
    np.testing.assert_array_equal(np.asarray(s.valid()), keys)
    s.release()


def test_staging_pool_respects_byte_cap():
    pool = pl.StagingPool(max_per_key=4, max_bytes=3 * 4096)
    bufs = [pool.acquire(1024, np.uint32, None) for _ in range(4)]
    for b in bufs:
        pool.release(b, None)
    # 4 x 4096 bytes released into a 3-buffer budget: oldest evicted
    assert pool._bytes <= 3 * 4096


def test_unpadded_pow2_chunk_carries_no_pool_buffer():
    staged = pl.stage_keys(np.arange(1024, dtype=np.uint32))
    assert staged.pad == 0 and staged.host_buf is None
    staged.release()


def test_staging_pool_metrics_mirror_counters_exactly():
    """ISSUE 6 satellite: the obs registry exposes the pool's hit/miss
    counters VERBATIM — collect_runtime snapshots the very ints the pool
    increments, so the two can never drift."""
    from mpi_k_selection_tpu.obs import MetricsRegistry
    from mpi_k_selection_tpu.obs.metrics import collect_runtime

    pool = pl.StagingPool()
    a = pl.stage_keys(np.arange(1000, dtype=np.uint32), None, pool)  # miss
    a.release()
    b = pl.stage_keys(np.arange(1000, dtype=np.uint32), None, pool)  # hit
    c = pl.stage_keys(np.arange(2000, dtype=np.uint32), None, pool)  # miss
    reg = MetricsRegistry()
    collect_runtime(reg, staging_pool=pool)
    assert reg.counter("staging_pool.hits").value == pool.hits == 1
    assert reg.counter("staging_pool.misses").value == pool.misses == 2
    b.release()
    c.release()
    # re-collection tracks the live counters, idempotently
    d = pl.stage_keys(np.arange(1000, dtype=np.uint32), None, pool)
    collect_runtime(reg, staging_pool=pool)
    assert reg.counter("staging_pool.hits").value == pool.hits == 2
    d.release()


def test_descent_metrics_snapshot_matches_module_pool(rng):
    """An instrumented multi-device descent snapshots the MODULE staging
    pool's counters into its registry at descent end — the registry must
    equal the pool's own (monotone) counters right after the call."""
    from mpi_k_selection_tpu.obs import MetricsRegistry, Observability

    chunks = [
        rng.integers(0, 2**31 - 1, size=1500, dtype=np.int32) for _ in range(4)
    ]
    n = sum(c.size for c in chunks)
    o = Observability(metrics=MetricsRegistry())
    got = int(
        streaming_kselect(chunks, n // 2, pipeline_depth=2, devices=2, obs=o)
    )
    assert got == seq.kselect_sort(np.concatenate(chunks), n // 2)
    assert o.metrics.counter("staging_pool.hits").value == pl.STAGING_POOL.hits
    assert (
        o.metrics.counter("staging_pool.misses").value
        == pl.STAGING_POOL.misses
    )


# -- CLI ----------------------------------------------------------------------


def test_cli_streaming_devices_flag(capsys):
    import json

    from mpi_k_selection_tpu import cli

    args = [
        "--backend", "tpu", "--streaming", "--n", "60000",
        "--chunk-elems", "9973", "--verify", "--check", "--json",
        "--pipeline-depth", "2",
    ]
    rc = cli.main(args + ["--devices", "8"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["n_devices"] == 8
    assert rec["extra"]["ingest_devices"] == 8
    assert rec["extra"]["exact_match"] is True
    assert rec["extra"]["certificate_ok"] is True
    rc = cli.main(args)  # default: single-device ingest
    assert rc == 0
    rec1 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec1["n_devices"] == 1
    assert rec1["answer"] == rec["answer"]
