"""Runtime ledger — process-wide compile & device-memory accounting.

KSC103 and KSL010 catch recompile hazards *statically* (a primitive
trail that changes with nearby n; a jit wrap on a serve handler), but
nothing watched a recompile storm happen at *runtime*: a dispatch site
that quietly compiles a fresh program for every request shape serves
every request at compile latency, and nothing said which datasets,
staging buffers or spill generations were resident when it happened.
This module is the runtime twin of those rules plus the byte book the
multi-tenant eviction work (ROADMAP) budgets against:

- **Program ledger**: every jit/kernel dispatch surface — the executor's
  per-chunk consumers, the fused/sweep ingest dispatchers, the serve
  :class:`~mpi_k_selection_tpu.serve.registry.ProgramCache`, the
  resident ``api.kselect{,_many}`` shells — reports through
  :func:`ledger_dispatch` with its compile-relevant key (shapes, widths,
  dtypes). The FIRST dispatch of a key at a site is counted as a compile
  and its wall clocked through the sanctioned
  :class:`~mpi_k_selection_tpu.utils.profiling.PhaseTimer` route
  (KSL004: no raw clocks here); repeats are cache hits. The per-site
  compile-vs-hit book is what ``recompiles_after_warmup == 0`` gates
  read (bench_kselect_1b, the serve steady-state test).
- **Recompile-storm detector**: a site whose distinct-key compile count
  exceeds ``storm_threshold`` fires a typed
  :class:`~mpi_k_selection_tpu.obs.events.RecompileStormEvent` on that
  compile and every later one (emitted to the caller's ``obs`` when one
  is passed, always kept in the ledger's own bounded ring) and bumps the
  per-site recompile count — ``ledger.recompiles{site}`` in the metric
  export.
- **Device-memory accounting**: ``ledger.device_bytes{pool,device}``
  gauges fed by the surfaces that already know their bytes — staged key
  buffers (``pipeline.stage_keys`` / ``stage_device_keys`` add the
  PADDED bucket bytes, ``StagedKeys.release`` subtracts them once), the
  StagingPool free-list footprint, resident datasets
  (serve/registry.py registration/drop), spill generations on disk —
  with per-key peaks for the bench records.

Everything is plain host ints/floats under one lock: reporting can
never change an answer bit (tests/test_ledger.py enforces bit-identity
with every channel on over the devices x depth x spill x fused grid),
and the module-level :data:`LEDGER` is process-wide like
``pipeline.STAGING_POOL`` — per-run readings are snapshot deltas
(:meth:`ProgramLedger.snapshot` / :func:`snapshot_delta`).

Export: :func:`collect_ledger` snapshots the ledger into a
:class:`~mpi_k_selection_tpu.obs.metrics.MetricsRegistry` (the same
idempotent overwrite discipline as ``collect_runtime``, and the ONE
writer of the ``ledger.*`` metric names — dispatch sites never write
metrics directly, so repeated collections can never fight an inc); the
streaming descent folds it in at descent end and the query server on
every ``/metrics`` scrape.
"""

from __future__ import annotations

import collections
import contextlib
import threading

from mpi_k_selection_tpu.obs.events import RecompileStormEvent

#: Distinct-key compiles at ONE site beyond which further compiles are
#: counted as recompiles and fire RecompileStormEvents. A healthy site
#: compiles a handful of programs (one per staging bucket / dtype /
#: spec width) and then hits; a site crossing this is serving shape
#: churn at compile latency — the KSC103 hazard observed live.
DEFAULT_STORM_THRESHOLD = 8

#: Bounded ring of the most recent storm events the ledger itself keeps
#: (obs-independent — the flight recorder's bundle reads it).
STORM_RING = 64

#: Per-site bound on the key mirrors (FIFO-evicted past it). The ledger
#: is process-lifetime, so unbounded retention of every distinct compile
#: key — serve keys embed dataset ids, eager certificate keys every
#: ragged chunk length — would grow monotonically until the process
#: dies. Past the bound an evicted key that recurs is re-counted as a
#: compile (and re-inflates the distinct counters): a site with 4096
#: live program identities is deep in the churn pathology the storm
#: detector fired on ~4088 keys earlier, so the books degrade to
#: approximations only where they already read "storm".
MAX_TRACKED_KEYS = 4096


def _new_site() -> dict:
    return {
        "keys": {},  # key -> dispatch count (bounded mirror, FIFO-evicted)
        "storm_keys": {},  # shape-churn identities (bounded like keys)
        "distinct": 0,  # first-seen keys, monotone (survives eviction)
        "storm_distinct": 0,  # first-seen churn identities, monotone
        "compiles": 0,
        "hits": 0,
        "recompiles": 0,
    }


def _bounded_insert(book: dict, key, count: int = 1) -> bool:
    """Record ``key`` in a bounded FIFO mirror (dict insertion order):
    returns True when it is first-seen; evicts the oldest entry past
    :data:`MAX_TRACKED_KEYS`."""
    if key in book:
        book[key] += count
        return False
    book[key] = count
    if len(book) > MAX_TRACKED_KEYS:
        del book[next(iter(book))]
    return True


class ProgramLedger:
    """Process-wide compile & device-memory book. Thread-safe; every
    mutation is host-int bookkeeping under one lock, cheap enough to sit
    on per-chunk dispatch paths."""

    def __init__(self, *, storm_threshold: int = DEFAULT_STORM_THRESHOLD):
        self._lock = threading.Lock()
        self._sites: dict[str, dict] = {}  # ksel: guarded-by[_lock]
        self._bytes: dict = {}  # ksel: guarded-by[_lock] ((pool, device) -> bytes)
        self._bytes_peak: dict = {}  # ksel: guarded-by[_lock]
        #: compile walls accumulate here as ``ledger.compile.<site>``
        #: phases — the ONE sanctioned clock route (KSL004). Created
        #: lazily so importing this module (and the obs package) never
        #: imports jax (utils/profiling.py does, at module level).
        self._timer = None  # ksel: guarded-by[_lock] (slot; the timer locks itself)
        self.storm_threshold = int(storm_threshold)
        self.storm_events: collections.deque = collections.deque(
            maxlen=STORM_RING
        )  # deque: self-synchronizing appends; snapshot() copies it whole

    def _get_timer(self):
        with self._lock:
            if self._timer is None:
                from mpi_k_selection_tpu.utils.profiling import PhaseTimer

                self._timer = PhaseTimer()
            return self._timer

    # -- program accounting ------------------------------------------------

    def _note_compile_locked(self, st: dict, site: str, key, storm_key=None):
        """Count one compile at ``site`` (caller holds the lock) and
        return the storm event to publish, or None below threshold. The
        storm trigger is the DISTINCT-key compile count — the documented
        shape-churn signal — so a :meth:`compile_span` site rebuilding
        the SAME program key (a legitimately invalidated cache, e.g. a
        dataset dropped and re-added) never reads as churn; keyless
        compiles fall back to the total as the conservative bound.
        ``storm_key`` (default: the key itself) is the identity counted
        toward the threshold — sites whose keys carry a bounded static
        dimension that legitimately multiplies compiles in ONE healthy
        run (the descent's per-level ``shift``) pass the key with that
        dimension stripped, so levels x buckets can't read as churn."""
        st["compiles"] += 1
        if key is not None:
            if _bounded_insert(st["keys"], key):
                st["distinct"] += 1
            if _bounded_insert(
                st["storm_keys"], key if storm_key is None else storm_key
            ):
                st["storm_distinct"] += 1
        distinct = st["storm_distinct"] if key is not None else st["compiles"]
        if distinct <= self.storm_threshold:
            return None
        st["recompiles"] += 1
        return RecompileStormEvent(
            site=site,
            key=repr(key),
            compiles=distinct,
            threshold=self.storm_threshold,
        )

    def _publish_storm(self, storm, obs) -> None:
        if storm is None:
            return
        self.storm_events.append(storm)
        if obs is not None:
            obs.emit(storm)

    def _note(self, site: str, key, obs, storm_key=None):
        """Record one dispatch; returns True when it is a first-key
        compile (the caller's block should be clocked)."""
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                st = self._sites[site] = _new_site()
            cnt = st["keys"].get(key)
            if cnt is not None:
                st["keys"][key] = cnt + 1
                st["hits"] += 1
                return False
            # _note_compile_locked records the key's first dispatch
            storm = self._note_compile_locked(st, site, key, storm_key)
        self._publish_storm(storm, obs)
        return True

    @contextlib.contextmanager
    def dispatch(self, site: str, key, obs=None, storm_key=None):
        """Context manager around ONE program dispatch at ``site`` whose
        compile identity is ``key`` (a hashable of the shapes / widths /
        dtypes the program specializes on). First key per site = a
        compile: the wrapped block's wall — trace + compile + first run,
        the latency a client actually pays — accumulates as the site's
        compile seconds. Repeat keys are cache hits (unclocked). Yields
        ``True`` on the compile dispatch. With ``obs``, a storm past the
        threshold emits the typed
        :class:`~mpi_k_selection_tpu.obs.events.RecompileStormEvent` to
        its sink (the ``ledger.recompiles{site}`` counter rides
        :func:`collect_ledger`'s snapshot, never a dispatch-time inc).
        ``storm_key`` strips a static dimension from the churn identity
        (see :meth:`_note_compile_locked`)."""
        if not self._note(site, key, obs, storm_key):
            yield False
            return
        with self._get_timer().phase(f"ledger.compile.{site}"):
            yield True

    def note_hit(self, site: str, key=None) -> None:
        """Count one cache hit at ``site`` WITHOUT inferring novelty from
        the key — for caches that already know (serve ProgramCache)."""
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                st = self._sites[site] = _new_site()
            st["hits"] += 1
            if key is not None and _bounded_insert(st["keys"], key):
                st["distinct"] += 1

    @contextlib.contextmanager
    def compile_span(self, site: str, key, obs=None):
        """Count (and clock) one KNOWN compile at ``site`` — the twin of
        :meth:`note_hit` for caches that decide hit/miss themselves. The
        storm discipline is identical to :meth:`dispatch`."""
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                st = self._sites[site] = _new_site()
            storm = self._note_compile_locked(st, site, key)
        self._publish_storm(storm, obs)
        with self._get_timer().phase(f"ledger.compile.{site}"):
            yield True

    # -- device-memory accounting ------------------------------------------

    @staticmethod
    def _bytes_key(pool, device) -> tuple:
        return (str(pool), "default" if device is None else str(device))

    def adjust_bytes(self, pool: str, device, delta: int) -> None:
        """Add ``delta`` (may be negative) to the live byte gauge of one
        ``(pool, device)`` slot, tracking its peak. Pools in use:
        ``staging`` (live StagedKeys buffers, padded bucket bytes),
        ``staging_pool`` (host free-list footprint), ``resident``
        (registered serve datasets), ``spill`` (generations on disk,
        device ``"disk"``)."""
        key = self._bytes_key(pool, device)
        with self._lock:
            v = self._bytes.get(key, 0) + int(delta)
            self._bytes[key] = v
            if v > self._bytes_peak.get(key, 0):
                self._bytes_peak[key] = v

    def set_bytes(self, pool: str, device, value: int) -> None:
        """Absolute form of :meth:`adjust_bytes` for surfaces that track
        their own total (StagingPool.resident_bytes)."""
        key = self._bytes_key(pool, device)
        with self._lock:
            v = int(value)
            self._bytes[key] = v
            if v > self._bytes_peak.get(key, 0):
                self._bytes_peak[key] = v

    def device_bytes(self, pool: str | None = None) -> dict:
        """``{(pool, device): bytes}`` live snapshot (one pool's slots
        when ``pool`` names one)."""
        with self._lock:
            return {
                k: v
                for k, v in self._bytes.items()
                if pool is None or k[0] == pool
            }

    # -- snapshots ---------------------------------------------------------

    def compile_seconds(self) -> dict:
        """``{site: seconds}`` accumulated first-dispatch walls. Never
        CREATES the timer: a snapshot in a process that dispatched
        nothing must stay pure bookkeeping (the PhaseTimer module
        imports jax)."""
        with self._lock:
            timer = self._timer
        if timer is None:
            return {}
        prefix = "ledger.compile."
        return {
            name[len(prefix):]: d["seconds"]
            for name, d in timer.as_dict().items()
            if name.startswith(prefix)
        }

    def snapshot(self) -> dict:
        """Plain-dict state: per-site compile/hit/recompile counts and
        distinct program keys, compile walls, live and peak bytes per
        (pool, device), and the recent storm tail — the JSON-ready form
        bench records delta and the flight recorder bundles."""
        with self._lock:
            sites = {
                site: {
                    "compiles": st["compiles"],
                    "hits": st["hits"],
                    "recompiles": st["recompiles"],
                    "distinct_keys": st["distinct"],
                }
                for site, st in self._sites.items()
            }
            dev_bytes = {
                f"{pool}/{dev}": v for (pool, dev), v in self._bytes.items()
            }
            dev_peak = {
                f"{pool}/{dev}": v
                for (pool, dev), v in self._bytes_peak.items()
            }
            storms = list(self.storm_events)
        for site, s in self.compile_seconds().items():
            sites.setdefault(
                site,
                {"compiles": 0, "hits": 0, "recompiles": 0, "distinct_keys": 0},
            )["compile_seconds"] = round(s, 6)
        return {
            "storm_threshold": self.storm_threshold,
            "sites": sites,
            "device_bytes": dev_bytes,
            "device_bytes_peak": dev_peak,
            "storms": [e.as_dict() for e in storms],
        }

    def reset(self) -> None:
        """Drop every count — tests owning a private ledger instance;
        production readings snapshot-delta instead (the process ledger
        is shared exactly like ``pipeline.STAGING_POOL``)."""
        with self._lock:
            self._sites.clear()
            self._bytes.clear()
            self._bytes_peak.clear()
            self._timer = None
        self.storm_events.clear()


#: The process-wide ledger every dispatch surface reports into (the
#: STAGING_POOL discipline: module-level, shared across runs; per-run
#: readings are snapshot deltas).
LEDGER = ProgramLedger()


def ledger_dispatch(
    site: str, key, obs=None, ledger: ProgramLedger | None = None,
    storm_key=None,
):
    """THE wiring helper dispatch sites use::

        with ledger_dispatch("ingest.histogram", (bucket, dt, nspecs), obs):
            handle = dispatch_chunk_histograms(...)

    Reports into :data:`LEDGER` unless a private ``ledger`` is passed
    (unit tests). ``storm_key`` strips a static dimension (the per-level
    ``shift``) from the storm detector's churn identity. Pure host
    bookkeeping — never touches the dispatched values."""
    return (LEDGER if ledger is None else ledger).dispatch(
        site, key, obs=obs, storm_key=storm_key
    )


def snapshot_delta(before: dict, after: dict) -> dict:
    """Per-site compile/hit/recompile deltas between two
    :meth:`ProgramLedger.snapshot` readings — the per-run form bench
    records embed (the ledger itself is process-lifetime).
    ``device_bytes_peak`` keeps only the slots whose peak GREW inside
    the window: an unchanged peak was attained by an earlier run and
    would misattribute that run's high-water mark to this one."""
    sites = {}
    for site, st in after["sites"].items():
        b = before["sites"].get(site, {})
        d = {
            k: st.get(k, 0) - b.get(k, 0)
            for k in ("compiles", "hits", "recompiles", "distinct_keys")
        }
        d["compile_seconds"] = round(
            st.get("compile_seconds", 0.0) - b.get("compile_seconds", 0.0), 6
        )
        if any(d.values()):
            sites[site] = d
    return {
        "sites": sites,
        "compiles": sum(d["compiles"] for d in sites.values()),
        "recompiles": sum(d["recompiles"] for d in sites.values()),
        "compile_seconds": round(
            sum(d["compile_seconds"] for d in sites.values()), 6
        ),
        "device_bytes_peak": {
            slot: v
            for slot, v in after["device_bytes_peak"].items()
            if v > before["device_bytes_peak"].get(slot, 0)
        },
    }


def collect_ledger(registry, ledger: ProgramLedger | None = None):
    """Snapshot the ledger into ``registry`` — the ONE mapping from
    ledger state to exported metric names, idempotent like
    ``collect_runtime`` (Counter.set overwrites; no dispatch site ever
    writes these names directly, so there is a single writer):

    - ``ledger.compiles{site=}`` / ``ledger.cache_hits{site=}`` /
      ``ledger.recompiles{site=}`` (Counter) and
      ``ledger.compile_seconds{site=}`` (Gauge);
    - ``ledger.device_bytes{pool=,device=}`` /
      ``ledger.device_bytes_peak{pool=,device=}`` (Gauge).

    Values are the PROCESS ledger's (STAGING_POOL discipline) — per-run
    readings subtract two snapshots (:func:`snapshot_delta`). Returns
    ``registry``."""
    led = LEDGER if ledger is None else ledger
    snap = led.snapshot()
    for site, st in snap["sites"].items():
        registry.counter("ledger.compiles", labels={"site": site}).set(  # ksel: noqa[KSL013] -- ledger sites are a closed, code-defined set (the wired dispatch surfaces), not per-request data
            st["compiles"]
        )
        registry.counter("ledger.cache_hits", labels={"site": site}).set(  # ksel: noqa[KSL013] -- same closed site set
            st["hits"]
        )
        registry.counter("ledger.recompiles", labels={"site": site}).set(  # ksel: noqa[KSL013] -- same closed site set
            st["recompiles"]
        )
        registry.gauge("ledger.compile_seconds", labels={"site": site}).set(  # ksel: noqa[KSL013] -- same closed site set
            st.get("compile_seconds", 0.0)
        )
    for (pool, dev), v in led.device_bytes().items():
        registry.gauge("ledger.device_bytes", labels={"pool": pool, "device": dev}).set(  # ksel: noqa[KSL013] -- pools are a closed code-defined set and devices are bounded by the host's chip count
            v
        )
        registry.gauge("ledger.device_bytes_peak", labels={"pool": pool, "device": dev}).set(  # ksel: noqa[KSL013] -- same bounded (pool, device) set
            snap["device_bytes_peak"].get(f"{pool}/{dev}", v)
        )
    return registry
