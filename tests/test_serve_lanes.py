"""Serve hot-path overhaul (ISSUE 18): sketch fast path, per-device
dispatch lanes, registration-time warmup, program-cache build latch.

The load-bearing contracts:

- **Determinism grid** — answers are bit-identical to serial
  ``api.kselect`` across fast_path {on, off} × warmup {on, off} ×
  tiers × residency (device/host/stream) × concurrency; sketch answers
  (bounds included) are identical between the fast path and the queued
  oracle.
- **Lanes** — datasets on distinct devices get distinct supervised
  dispatch lanes that answer concurrently; one lane's dispatch crash
  restarts only that lane; ``lanes=1`` degenerates to the single PR 7
  batcher. Lane threads carry the ``ksel-serve`` prefix, so the
  conftest leaked-thread fixture covers them with no new vocabulary.
- **Warmup** — ``add_dataset(..., warmup=True)`` pre-builds the
  selection programs through the ProgramCache; the ledger's
  ``serve.programs`` book then records ZERO on-path compiles for the
  steady-state query mix (the tier-1 gate of the ISSUE 18 acceptance).
- **Build latch** — two racing first queries for the same program key
  compile it ONCE; the second caller waits and counts as a hit (cache
  counters and the ledger book agree).
"""

import threading
import time

import numpy as np
import pytest

import jax

from mpi_k_selection_tpu import api
from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.obs import ledger as ldg
from mpi_k_selection_tpu.serve import (
    DispatchCrashedError,
    KSelectServer,
    LaneDispatcher,
    PendingQuery,
    ProgramCache,
    lane_key_for,
)
from mpi_k_selection_tpu.serve import tiers as tiers_mod
from mpi_k_selection_tpu.serve.registry import ResidentDataset, _build_sketch

# > 2^14 so single exact rank queries take the shared radix walk (the
# same dispatch api.kselect resolves to at this n)
N_BIG = 40_000


@pytest.fixture
def x_int32(rng):
    return rng.integers(-(2**31), 2**31 - 1, size=N_BIG, dtype=np.int32)


def _bits(values, dtype):
    return np.asarray(values, dtype=dtype).tobytes()


def _serial_reference(x, ks):
    return [np.asarray(api.kselect(x, int(k))).item() for k in ks]


def _add_host_dataset(srv, dataset_id, x):
    """Register a HOST-resident dataset directly (the f64-on-TPU route's
    residency — unreachable through add_dataset on CPU CI, where every
    array converts to a device array)."""
    arr = np.ascontiguousarray(x).copy()
    arr.flags.writeable = False
    ds = ResidentDataset(
        dataset_id=dataset_id,
        residency="host",
        dtype=np.dtype(arr.dtype),
        n=int(arr.size),
        data=arr,
        sketch=_build_sketch([arr], np.dtype(arr.dtype), 4, 4),
    )
    return srv.registry._register(ds)


def _answer_bits(answers, dtype):
    return _bits([a.value for a in answers], dtype)


# ---------------------------------------------------------------------------
# the determinism grid: fast_path x warmup x residency x tier x concurrency


@pytest.mark.parametrize("fast_path", [True, False])
@pytest.mark.parametrize("warmup", [True, False])
def test_determinism_grid(x_int32, fast_path, warmup):
    ks = [1, 17, N_BIG // 2, N_BIG]
    ref = _serial_reference(x_int32, ks)
    sketch_oracle = None
    with KSelectServer(window=0.002, fast_path=fast_path) as srv:
        srv.add_dataset("dev", x_int32, warmup=warmup)
        host_ds = _add_host_dataset(srv, "host", x_int32)
        chunks = [c.copy() for c in np.array_split(x_int32, 5)]
        srv.add_dataset("stream", source=chunks, warmup=warmup)
        if warmup:
            srv.registry.warmup(host_ds)
        for dataset in ("dev", "host", "stream"):
            for tier in ("exact", "auto"):
                answers = srv.kselect_many(dataset, ks, tier=tier)
                assert _answer_bits(answers, np.int32) == _bits(
                    ref, np.int32
                ), (dataset, tier)
                assert all(a.exact for a in answers)
            # sketch answers: bounds contract + identical to the pure
            # tiers oracle (the fast path and the queued path must
            # return THE SAME answers, fields and all)
            ds = srv.registry.get(dataset)
            oracle = tiers_mod.sketch_answers(ds, ks)
            got = srv.kselect_many(dataset, ks, tier="sketch")
            for a, o in zip(got, oracle):
                assert (a.value, a.rank_bounds, a.value_bounds) == (
                    o.value, o.rank_bounds, o.value_bounds,
                ), dataset
                assert a.rank_error_bound == o.rank_error_bound
            if dataset == "dev":
                sketch_oracle = [(a.value, a.rank_bounds) for a in got]
        # concurrency: 4 threads per dataset, every answer bit-checked
        errors = []

        def worker(dataset, my_ks):
            try:
                answers = srv.kselect_many(dataset, my_ks, tier="exact")
                assert _answer_bits(answers, np.int32) == _bits(
                    _serial_reference(x_int32, my_ks), np.int32
                )
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append((dataset, e))

        shards = [ks, list(reversed(ks)), [7, 9999], [N_BIG - 1]]
        threads = [
            threading.Thread(target=worker, args=(dataset, shard))
            for dataset in ("dev", "host", "stream")
            for shard in shards
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
    assert sketch_oracle is not None


def test_fast_path_on_off_sketch_bits_identical(x_int32):
    """The queued oracle (fast_path=False) and the fast path answer the
    SAME bits, bounds included, rank by rank."""
    ks = [1, 100, N_BIG // 3, N_BIG]
    with KSelectServer(fast_path=True) as fast:
        fast.add_dataset("a", x_int32)
        a_fast = fast.kselect_many("a", ks, tier="sketch")
    with KSelectServer(fast_path=False) as queued:
        queued.add_dataset("a", x_int32)
        a_queued = queued.kselect_many("a", ks, tier="sketch")
    for f, q in zip(a_fast, a_queued):
        assert (f.k, f.value, f.tier, f.exact) == (q.k, q.value, q.tier, q.exact)
        assert f.rank_bounds == q.rank_bounds
        assert f.value_bounds == q.value_bounds
        assert f.rank_error_bound == q.rank_error_bound


def test_fastpath_counter_and_routing(rng):
    """fast_path=True answers sketch/auto-pinned on the request thread
    (counted in serve.fastpath{tier=}, nothing enqueued); fast_path=False
    routes the same queries through the dataset's lane."""
    # int16 keys: the default 4x4 sketch resolves the FULL key width, so
    # it pins every rank and tier=auto stays on the sketch (the
    # auto_pins fast-path branch)
    x = rng.integers(-(2**15), 2**15 - 1, size=N_BIG).astype(np.int16)
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs, fast_path=True) as srv:
        srv.add_dataset("a", x)
        srv.kselect("a", 5, tier="sketch")
        srv.kselect("a", 5, tier="auto")
        assert obs.metrics.counter(
            "serve.fastpath", labels={"tier": "sketch"}
        ).value == 1
        assert obs.metrics.counter(
            "serve.fastpath", labels={"tier": "auto"}
        ).value == 1
        # nothing was enqueued: the lane map is still empty
        assert srv.batcher.lane_summary() == {}
    obs2 = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs2, fast_path=False) as srv:
        srv.add_dataset("a", x)
        a = srv.kselect("a", 5, tier="sketch")
        assert a.tier == "sketch" and a.rank_bounds is not None
        assert obs2.metrics.counter(
            "serve.fastpath", labels={"tier": "sketch"}
        ).value == 0
        summary = srv.batcher.lane_summary()
        assert sum(s["submitted"] for s in summary.values()) == 1


# ---------------------------------------------------------------------------
# per-device dispatch lanes


def _two_device_arrays(x):
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices (xla_force_host_platform_device_count)")
    return (
        jax.device_put(x, devs[0]),
        jax.device_put(np.roll(x, 7), devs[1]),
        devs,
    )


def test_lane_per_device_and_keys(x_int32):
    xa, xb, devs = _two_device_arrays(x_int32)
    with KSelectServer() as srv:
        srv.add_dataset("a", xa)
        srv.add_dataset("b", xb)
        assert lane_key_for(srv.registry.get("a")) != lane_key_for(
            srv.registry.get("b")
        )
        va = srv.kselect("a", 1234, tier="exact").value
        vb = srv.kselect("b", 1234, tier="exact").value
        assert va == api.kselect(x_int32, 1234)
        assert vb == api.kselect(np.roll(x_int32, 7), 1234)
        assert va == vb  # same multiset, different devices
        summary = srv.batcher.lane_summary()
        assert len(summary) == 2
        assert all(s["submitted"] == 1 for s in summary.values())
        # lane threads are live, ksel-serve named, and die with close()
        lane_threads = [
            t.name
            for t in threading.enumerate()
            if t.name.startswith("ksel-serve-lane-")
        ]
        assert len(lane_threads) == 2
    assert not [
        t
        for t in threading.enumerate()
        if t.name.startswith("ksel-serve-lane-") and t.is_alive()
    ]


def test_lanes_answer_concurrently(x_int32):
    """A blocked lane must not stall another device's lane — the whole
    point of per-device dispatch (a single global dispatch thread would
    deadline this test)."""
    xa, xb, devs = _two_device_arrays(x_int32)
    with KSelectServer() as srv:
        dsa = srv.add_dataset("a", xa)
        srv.add_dataset("b", xb)
        release = threading.Event()
        entered = threading.Event()

        def block():
            entered.set()
            release.wait(30)
            return "blocked-op"

        blocker = srv.batcher.submit(
            PendingQuery("a", "op", ds=dsa, run=block)
        )
        assert entered.wait(10)
        try:
            # lane "a" is busy inside block(); lane "b" still answers —
            # with one global dispatch thread this query would sit
            # behind block() past its deadline and raise
            vb = srv.kselect("b", 99, tier="exact", deadline=20.0).value
            assert vb == api.kselect(np.roll(x_int32, 7), 99)
        finally:
            release.set()
        assert blocker.wait() == "blocked-op"


def test_lane_failure_isolation(x_int32):
    """One lane's dispatch-loop crash restarts ONLY that lane: the
    other lane never notices, and the crashed lane keeps serving after
    its supervisor restart."""

    class _PoisonDeadline:
        def remaining(self):
            return 30.0

        @property
        def expired(self):
            raise RuntimeError("poisoned deadline (lane-crash probe)")

    xa, xb, devs = _two_device_arrays(x_int32)
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs) as srv:
        dsa = srv.add_dataset("a", xa)
        srv.add_dataset("b", xb)
        # open both lanes first so the summary names are stable
        srv.kselect("a", 1, tier="exact")
        srv.kselect("b", 1, tier="exact")
        poisoned = srv.batcher.submit(
            PendingQuery("a", "rank", ks=(1,), ds=dsa,
                         deadline=_PoisonDeadline())
        )
        with pytest.raises(DispatchCrashedError):
            poisoned.wait()
        summary = srv.batcher.lane_summary()
        crashed = lane_key_for(dsa) if srv.batcher.lanes == "auto" else None
        assert crashed in summary
        assert summary[crashed]["restarts"] == 1
        others = {k: v for k, v in summary.items() if k != crashed}
        assert all(v["restarts"] == 0 for v in others.values())
        assert srv.batcher.restarts == 1
        # both lanes still serve, bit-identically
        assert srv.kselect("a", 77, tier="exact").value == api.kselect(
            x_int32, 77
        )
        assert srv.kselect("b", 77, tier="exact").value == api.kselect(
            np.roll(x_int32, 7), 77
        )
        assert obs.metrics.counter("serve.dispatch_restarts").value == 1


def test_lanes_one_degenerates_to_single_batcher(x_int32):
    """lanes=1 is today's batcher: every dataset serializes through ONE
    dispatch thread, answers unchanged."""
    xa, xb, devs = _two_device_arrays(x_int32)
    with KSelectServer(lanes=1) as srv:
        srv.add_dataset("a", xa)
        srv.add_dataset("b", xb)
        assert srv.kselect("a", 50, tier="exact").value == api.kselect(
            x_int32, 50
        )
        assert srv.kselect("b", 50, tier="exact").value == api.kselect(
            np.roll(x_int32, 7), 50
        )
        summary = srv.batcher.lane_summary()
        assert set(summary) == {"lane0"}
        assert summary["lane0"]["submitted"] == 2


def test_lanes_validation_and_modular_fold(x_int32):
    with pytest.raises(ValueError):
        KSelectServer(lanes=0)
    with pytest.raises(ValueError):
        LaneDispatcher(lambda items: None, lanes="three")
    xa, xb, devs = _two_device_arrays(x_int32)
    with KSelectServer(lanes=2) as srv:
        srv.add_dataset("a", xa)
        srv.add_dataset("b", xb)
        for k in (3, 1000):
            assert srv.kselect("a", k, tier="exact").value == api.kselect(
                x_int32, k
            )
        summary = srv.batcher.lane_summary()
        assert set(summary) <= {"lane0", "lane1"}


def test_per_lane_queue_depth_metric(x_int32):
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs) as srv:
        srv.add_dataset("a", x_int32)
        srv.kselect("a", 12, tier="exact")
        text = srv.render_prometheus()
    assert "ksel_serve_queue_depth" in text
    assert 'lane="' in text
    assert "ksel_serve_lanes" in text


# ---------------------------------------------------------------------------
# ProgramCache build latch (the thundering-herd fix)


def test_program_cache_build_latch_single_compile():
    pc = ProgramCache()
    before = ldg.LEDGER.snapshot()
    started = threading.Event()
    release = threading.Event()
    builds = []

    def builder():
        builds.append(threading.current_thread().name)
        started.set()
        assert release.wait(10)
        return "program"

    results = []

    def call():
        results.append(pc.get_or_build(("walk", "latch-herd-ds"), builder))

    t1 = threading.Thread(target=call)
    t1.start()
    assert started.wait(10)  # t1 is inside builder, latch installed
    t2 = threading.Thread(target=call)
    t2.start()
    # t2 must wait on the latch, not run a second build
    t2.join(timeout=0.2)
    assert t2.is_alive() and len(builds) == 1
    release.set()
    t1.join(timeout=10)
    t2.join(timeout=10)
    assert not t1.is_alive() and not t2.is_alive()
    assert results == ["program", "program"]
    assert len(builds) == 1  # ONE compile for two racing first callers
    assert (pc.misses, pc.hits) == (1, 1)  # the waiter counts as a hit
    delta = ldg.snapshot_delta(before, ldg.LEDGER.snapshot())
    book = delta["sites"]["serve.programs"]
    assert book["compiles"] == 1
    assert book["hits"] == 1


def test_program_cache_build_latch_failure_releases_waiters():
    """A failing build must not cache the failure NOR strand waiters:
    the first caller raises, the waiter retries the build itself."""
    pc = ProgramCache()
    waiter_queued = threading.Event()
    calls = []

    def builder():
        calls.append(1)
        if len(calls) == 1:
            assert waiter_queued.wait(10)
            raise RuntimeError("first build fails")
        return 42

    outcomes = []

    def call(tag):
        try:
            outcomes.append(
                (tag, pc.get_or_build(("sorted", "latch-fail-ds"), builder))
            )
        except RuntimeError as e:
            outcomes.append((tag, e))

    t1 = threading.Thread(target=call, args=("first",))
    t1.start()
    while not calls:  # t1 inside the (gated) failing build
        time.sleep(0.005)
    t2 = threading.Thread(target=call, args=("second",))
    t2.start()
    t2.join(timeout=0.2)
    assert t2.is_alive()  # parked on the latch
    waiter_queued.set()
    t1.join(timeout=10)
    t2.join(timeout=10)
    got = dict(outcomes)
    assert isinstance(got["first"], RuntimeError)
    assert got["second"] == 42
    assert len(calls) == 2
    assert (pc.misses, pc.hits) == (2, 0)


# ---------------------------------------------------------------------------
# registration-time warmup: the zero-on-path-compiles tier-1 gate


def test_warmup_zero_on_path_compiles_gate(x_int32):
    """ISSUE 18 acceptance: a warmed dataset's steady-state query mix
    records ZERO compiles at the serve.programs ledger site — the
    compile wall was paid at registration, under the warmup span."""
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs) as srv:
        srv.add_dataset("a", x_int32, warmup=True)
        assert obs.metrics.counter("serve.warmup_compiles").value == 2
        assert srv.registry.programs.misses == 2  # sorted + walk
        before = ldg.LEDGER.snapshot()
        # the steady-state mix: narrow exacts (walk), a wide quantile
        # batch (sort path), sketch reads, an auto escalation
        for k in (5, 17, 31_337):
            srv.kselect("a", k, tier="exact")
        srv.quantiles("a", [i / 256 for i in range(1, 256)], tier="exact")
        srv.kselect("a", 9, tier="sketch")
        srv.kselect("a", 9, tier="auto")
        delta = ldg.snapshot_delta(before, ldg.LEDGER.snapshot())
        book = delta["sites"]["serve.programs"]
        assert book["compiles"] == 0, book
        assert book["hits"] >= 4
        # and the answers still match the serial oracle bit for bit
        assert srv.kselect("a", 17, tier="exact").value == api.kselect(
            x_int32, 17
        )


def test_cold_dataset_compiles_on_path(x_int32):
    """The control for the gate above: WITHOUT warmup the first exact
    query carries the build (the PR 7 behavior the warmup knob removes)."""
    with KSelectServer() as srv:
        srv.add_dataset("a", x_int32)
        before = ldg.LEDGER.snapshot()
        srv.kselect("a", 17, tier="exact")
        delta = ldg.snapshot_delta(before, ldg.LEDGER.snapshot())
        assert delta["sites"]["serve.programs"]["compiles"] == 1


def test_warmup_stream_and_small_datasets(rng):
    """Stream datasets warm their select closure; small (<= 2^14)
    resident datasets warm only the cached sort (no walk program)."""
    x_small = rng.integers(0, 1 << 20, size=4096).astype(np.int32)
    chunks = [c.copy() for c in np.array_split(x_small, 4)]
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs) as srv:
        srv.add_dataset("small", x_small, warmup=True)
        assert ("sorted", "small") in srv.registry.programs._entries
        assert ("walk", "small") not in srv.registry.programs._entries
        srv.add_dataset("st", source=chunks, warmup=True)
        assert ("stream_select", "st") in srv.registry.programs._entries
        before = ldg.LEDGER.snapshot()
        for dataset in ("small", "st"):
            a = srv.kselect(dataset, 1000, tier="exact")
            assert a.value == api.kselect(x_small, 1000)
        delta = ldg.snapshot_delta(before, ldg.LEDGER.snapshot())
        assert delta["sites"]["serve.programs"]["compiles"] == 0


def test_warmup_idempotent_and_counter(x_int32):
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=obs) as srv:
        srv.add_dataset("a", x_int32, warmup=True)
        built_again = srv.registry.warmup(srv.registry.get("a"))
        assert built_again == 0  # everything already resident
        assert obs.metrics.counter("serve.warmup_compiles").value == 2
