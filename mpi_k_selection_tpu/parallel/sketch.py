"""Sharded RadixSketch construction — per-shard histograms merged by psum.

The sketch's merge is an elementwise sum (streaming/sketch.py), so building
one over a device-sharded array is a single shard_map: every shard counts
its local DEEPEST-level histogram with the same ops/histogram.py primitive
the selects use, one ``lax.psum`` merges the counts (the shallower pyramid
is derived host-side by reshape-sum) — the exact analogue
of the reference CGM's ``MPI_Allreduce`` of per-rank counts
(``TODO-kth-problem-cgm.c:190``), except the reduced object here IS the
final queryable summary. The replicated result is lifted into a host
:class:`RadixSketch`, interchangeable (bitwise) with one accumulated by
sequential ``update`` calls over the same data.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
from mpi_k_selection_tpu.parallel import mesh as mesh_lib
from mpi_k_selection_tpu.streaming.sketch import RadixSketch
from mpi_k_selection_tpu.utils import compat
from mpi_k_selection_tpu.utils import dtypes as _dt


def distributed_sketch(
    x,
    *,
    mesh=None,
    radix_bits: int = 4,
    levels: int = 4,
    hist_method: str = "scatter",
) -> RadixSketch:
    """Build a :class:`RadixSketch` of device-resident ``x`` over ``mesh``
    (all devices by default): one psum-merged deepest-level histogram pass,
    shallower levels derived host-side.

    ``hist_method`` defaults to ``"scatter"``: the deepest level needs
    ``2**resolution_bits`` buckets, beyond the Pallas kernels' digit-width
    sweet spot — scatter handles any bucket count. A non-multiple-of-mesh
    tail is folded in host-side (sentinel padding would corrupt the top
    bucket's count, unlike selection where sentinels are rank-safe).
    """
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)
    xh = x if hasattr(x, "dtype") else np.asarray(x)
    dtype = np.dtype(xh.dtype)  # BEFORE any device cast can narrow it
    sk = RadixSketch(dtype, radix_bits=radix_bits, levels=levels)
    if dtype.itemsize == 8 and not jax.config.jax_enable_x64:
        # jnp.asarray would silently truncate 64-bit host input to 32 bits
        # (wrong counts, wrong sketch dtype) — the same hole
        # streaming/chunked.py:resolve_stream_hist guards; accumulate
        # host-side instead: exact, and no x64 mode flip required
        return sk.update(np.ravel(np.asarray(xh)))
    x = jnp.ravel(jnp.asarray(x))
    if dtype == np.float64 and jax.default_backend() == "tpu":
        # TPU f64 device keys are the ~49-bit approximation
        # (utils/dtypes.py:f64_raw_bits), which would break the bitwise
        # host-parity contract — accumulate host-side instead, exact
        # w.r.t. the (already storage-truncated) device contents
        return sk.update(np.asarray(x))
    n = x.shape[0]
    nmain = n - n % mesh.size
    axis = mesh.axis_names[0]
    total_bits = sk.total_bits

    if nmain:

        def shard_fn(xs):
            u = _dt.to_sortable_bits(xs.ravel())
            # ONE kernel + one psum: only the deepest level is counted on
            # device; the shallower pyramid is derived host-side from the
            # merged int64 counts (RadixSketch._fold_deep_histogram), which
            # is bitwise identical and cuts device reads and collective
            # traffic by ~levels x
            local = masked_radix_histogram(
                u,
                shift=total_bits - levels * radix_bits,
                radix_bits=levels * radix_bits,
                prefix=None,
                method=hist_method,
                count_dtype=jnp.int32,  # exact: segment < 2^31 elements
            )
            # extremes in KEY space (not value space): bitwise identical to
            # the host sketch's update() extremes for every stream, NaN and
            # -0.0/+0.0 included, where value-space min/max diverge from the
            # keys' total order
            return (
                jax.lax.psum(local, axis),
                jax.lax.pmin(jnp.min(u), axis),
                jax.lax.pmax(jnp.max(u), axis),
            )

        fn = jax.jit(
            compat.shard_map(shard_fn, mesh=mesh, in_specs=(P(axis),), out_specs=P())
        )
        # the psum reduces int32 counts across shards: cap each call's total
        # population below 2^31 so the merged counts cannot wrap, and
        # accumulate segments host-side in int64 (the same discipline as
        # streaming/chunked.py's per-chunk histograms)
        seg = ((1 << 31) - 1) // mesh.size * mesh.size
        kmin = kmax = None
        for off in range(0, nmain, seg):
            xs = mesh_lib.shard_1d(x[off : off + min(seg, nmain - off)], mesh)
            deep, dmin, dmax = fn(xs)
            sk._fold_deep_histogram(np.asarray(deep).astype(np.int64))
            smin = sk.kdt.type(np.asarray(dmin))
            smax = sk.kdt.type(np.asarray(dmax))
            kmin = smin if kmin is None else min(kmin, smin)
            kmax = smax if kmax is None else max(kmax, smax)
        sk.n = nmain
        sk._min_key, sk._max_key = kmin, kmax
    if nmain != n:
        sk.update(np.asarray(x[nmain:]))
    return sk
