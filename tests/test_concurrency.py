"""Concurrency analysis (KSL015-KSL017) + the runtime lock-order
sanitizer.

Four layers of coverage:

- **rule fixtures** — positive/negative/guarded-by/noqa sources for the
  guard-consistency lint (KSL015), the static lock-order cycles
  (KSL016), and blocking-while-holding (KSL017);
- **engine extensions** — the useless-suppression (staleness) audit and
  the doc-drift gate (every registered rule id has a docs/ANALYSIS.md
  row and vice versa);
- **sanitizer units** — a constructed AB/BA deadlock is detected at
  runtime, reentrant RLocks record no self-edge, out-of-order releases
  keep the books straight, and static-vs-runtime direction conflicts
  are reported;
- **the runtime gate** — the serve burst, the streaming executor, a
  seeded chaos descent and the monitor run under ONE sanitizer; the
  observed acquired-while-holding graph must be acyclic and consistent
  with the static KSL016 graph, and is checked in as a JSON artifact
  (/tmp/kselect_lockorder.json) next to the lint report.
"""

import json
import pathlib
import textwrap
import threading
import warnings

import numpy as np
import pytest

from mpi_k_selection_tpu.analysis import run_analysis, shared_modules
from mpi_k_selection_tpu.analysis.__main__ import main as lint_main
from mpi_k_selection_tpu.analysis.concurrency import (
    analyze_module,
    build_concurrency_report,
)
from mpi_k_selection_tpu.analysis.core import load_module
from mpi_k_selection_tpu.analysis.lockorder import (
    LockOrderSanitizer,
    TrackedLock,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = "mpi_k_selection_tpu"


def _lint_source(tmp_path, source, name="mod.py", **kwargs):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    kwargs.setdefault("contracts", False)
    return run_analysis([f], **kwargs)


def _rules_hit(report):
    return {f.rule for f in report.unsuppressed}


# ---------------------------------------------------------------------------
# KSL015 — guard consistency


KSL015_POSITIVE = """
    import threading

    class Accum:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []
            self.total = 0

        def add(self, x):
            with self._lock:
                self.items.append(x)
                self.total += x

        def reset(self):
            self.items.clear()          # mutated without the lock

        def report(self):
            return sorted(self.items.items())   # iterated without the lock

        def bump(self):
            self.total += 1             # written without the lock
"""

KSL015_NEGATIVE = """
    import queue
    import threading

    class Accum:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []             # init writes are exempt
            self.total = 0
            self._q = queue.Queue()     # self-synchronizing: exempt

        def add(self, x):
            with self._lock:
                self.items.append(x)
                self.total += x
            self._q.put(x)

        def snapshot(self):
            with self._lock:
                return list(self.items), self.total

        def _fold_locked(self, x):
            # the `*_locked` convention: the caller holds self._lock
            self.items.append(x)
            self.total += x

        def drain(self):
            while True:
                self._q.get(timeout=0.1)
"""

KSL015_ANNOTATED = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()
            self.entries = []  # ksel: guarded-by[_lock]

        def add(self, e):
            self.entries.append(e)      # annotation-driven finding
"""

KSL015_STALE_ANNOTATION = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()
            self.entries = []  # ksel: guarded-by[_mutex]
"""

KSL015_GLOBALS_POSITIVE = """
    import threading

    _LOCK = threading.Lock()
    _COUNT = 0

    def inc():
        global _COUNT
        with _LOCK:
            _COUNT += 1

    def reset():
        global _COUNT
        _COUNT = 0                      # written without the lock
"""

KSL015_GLOBALS_NEGATIVE = """
    import threading

    _LOCK = threading.Lock()
    _COUNT = 0

    def inc():
        global _COUNT
        with _LOCK:
            _COUNT += 1

    def read():
        return _COUNT                   # bare reads stay out of scope
"""


def test_ksl015_positive(tmp_path):
    report = _lint_source(
        tmp_path, KSL015_POSITIVE, name=f"{PKG}/serve/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL015"]
    # the unlocked clear + the unlocked iteration + the unlocked write
    assert len(hits) == 3
    assert any("mutated" in f.message for f in hits)
    assert any("iterated" in f.message for f in hits)
    assert any("written" in f.message for f in hits)


def test_ksl015_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL015_NEGATIVE, name=f"{PKG}/serve/mod.py"
    )
    assert "KSL015" not in _rules_hit(report)


def test_ksl015_annotation_drives_enforcement(tmp_path):
    report = _lint_source(
        tmp_path, KSL015_ANNOTATED, name=f"{PKG}/obs/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL015"]
    assert len(hits) == 1
    assert "guarded-by annotation" in hits[0].message


def test_ksl015_stale_annotation_flagged(tmp_path):
    report = _lint_source(
        tmp_path, KSL015_STALE_ANNOTATION, name=f"{PKG}/obs/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL015"]
    assert len(hits) == 1
    assert "stale guarded-by annotation" in hits[0].message
    assert "_mutex" in hits[0].message


def test_ksl015_module_globals(tmp_path):
    report = _lint_source(
        tmp_path, KSL015_GLOBALS_POSITIVE, name=f"{PKG}/faults/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL015"]
    assert len(hits) == 1 and "module global" in hits[0].message
    report = _lint_source(
        tmp_path, KSL015_GLOBALS_NEGATIVE, name=f"{PKG}/faults/mod.py"
    )
    assert "KSL015" not in _rules_hit(report)


def test_ksl015_scope_and_noqa(tmp_path):
    # outside the package (bench scripts, drivers): quiet
    report = _lint_source(tmp_path, KSL015_POSITIVE, name="scripts/mod.py")
    assert "KSL015" not in _rules_hit(report)
    # test files poke shared state freely
    report = _lint_source(
        tmp_path, KSL015_POSITIVE, name=f"{PKG}/serve/test_mod.py"
    )
    assert "KSL015" not in _rules_hit(report)
    src = KSL015_POSITIVE.replace(
        "self.items.clear()          # mutated without the lock",
        "self.items.clear()  # ksel: noqa[KSL015] -- fixture justification",
    )
    report = _lint_source(tmp_path, src, name=f"{PKG}/serve/mod.py")
    hits = [f for f in report.unsuppressed if f.rule == "KSL015"]
    assert len(hits) == 2  # the other two still fire
    sup = [f for f in report.findings if f.rule == "KSL015" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


def test_ksl015_inherited_lock(tmp_path):
    # obs/metrics.py pattern: the base class owns the lock, the subclass
    # mutates under the `*_locked` convention — and a bare iteration in
    # the subclass is still a finding
    src = """
    import threading

    class Base:
        def __init__(self, lock):
            self._lock = lock

    class Hist(Base):
        def __init__(self, lock):
            super().__init__(lock)
            self.buckets = [0] * 8

        def observe(self, i):
            with self._lock:
                self._observe_locked(i)

        def _observe_locked(self, i):
            self.buckets[i] += 1

        def snapshot(self):
            return [c for c in self.buckets]    # unlocked iteration
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/obs/mod.py")
    hits = [f for f in report.unsuppressed if f.rule == "KSL015"]
    assert len(hits) == 1 and "snapshot" in hits[0].message


# ---------------------------------------------------------------------------
# KSL016 — static lock-order cycles


KSL016_POSITIVE = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def ab(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def ba(self):
            with self._b_lock:
                with self._a_lock:
                    pass
"""

KSL016_NEGATIVE = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def ab(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def ab_again(self):
            with self._a_lock, self._b_lock:
                pass
"""

KSL016_INTERPROCEDURAL = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def _take_b(self):
            with self._b_lock:
                pass

        def _take_a(self):
            with self._a_lock:
                pass

        def ab(self):
            with self._a_lock:
                self._take_b()          # A -> B through the call

        def ba(self):
            with self._b_lock:
                self._take_a()          # B -> A through the call
"""


def test_ksl016_positive(tmp_path):
    report = _lint_source(
        tmp_path, KSL016_POSITIVE, name=f"{PKG}/serve/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL016"]
    assert len(hits) == 1
    assert "potential deadlock" in hits[0].message
    assert "_a_lock" in hits[0].message and "_b_lock" in hits[0].message


def test_ksl016_negative_consistent_order(tmp_path):
    report = _lint_source(
        tmp_path, KSL016_NEGATIVE, name=f"{PKG}/serve/mod.py"
    )
    assert "KSL016" not in _rules_hit(report)


def test_ksl016_interprocedural_cycle(tmp_path):
    report = _lint_source(
        tmp_path, KSL016_INTERPROCEDURAL, name=f"{PKG}/serve/mod.py"
    )
    assert "KSL016" in _rules_hit(report)


KSL016_MUTUAL_RECURSION = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()
            self._c_lock = threading.Lock()
            self._d_lock = threading.Lock()

        def f(self, n):
            with self._b_lock:
                pass
            self.g(n)

        def g(self, n):
            with self._c_lock:
                pass
            self.f(n)

        def hold_d_call_g(self):
            with self._d_lock:
                self.g(1)       # resolves g's closure FIRST

        def hold_a_call_f(self):
            with self._a_lock:
                self.f(1)       # f must transitively acquire {b, c}

        def ca(self):
            with self._c_lock:
                with self._a_lock:
                    pass
"""


def test_ksl016_mutually_recursive_closure_complete(tmp_path):
    """f and g call each other; the may-acquire closure must reach a
    FIXPOINT — a memoized recursive walk truncates at the cycle cut and
    drops f's transitive `_c_lock`, losing the a->c edge and with it the
    a->c->a deadlock (review finding, PR 12)."""
    report = _lint_source(
        tmp_path, KSL016_MUTUAL_RECURSION, name=f"{PKG}/serve/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL016"]
    assert hits and any(
        "_a_lock" in h.message and "_c_lock" in h.message for h in hits
    )


KSL016_CLOSURE_NEGATIVE = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def _make_cb(self):
            def cb():
                with self._b_lock:      # runs LATER, never under A
                    pass
            return cb

        def ab(self):
            with self._a_lock:
                cb = self._make_cb()    # only DEFINES the closure
            return cb

        def ba(self):
            with self._b_lock:
                with self._a_lock:
                    pass
"""


def test_ksl016_closure_acquisition_not_attributed_to_definer(tmp_path):
    """A lock taken inside a nested def belongs to the closure (which
    runs later, with nothing held) — defining it while holding another
    lock is NOT an acquired-while-holding edge (review finding, PR 12)."""
    report = _lint_source(
        tmp_path, KSL016_CLOSURE_NEGATIVE, name=f"{PKG}/serve/mod.py"
    )
    assert "KSL016" not in _rules_hit(report)


def test_ksl016_noqa(tmp_path):
    src = KSL016_POSITIVE.replace(
        "with self._b_lock:\n                    pass",
        "with self._b_lock:  # ksel: noqa[KSL016] -- fixture justification\n"
        "                    pass",
        1,
    )
    report = _lint_source(tmp_path, src, name=f"{PKG}/serve/mod.py")
    assert "KSL016" not in _rules_hit(report)
    sup = [f for f in report.findings if f.rule == "KSL016" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


def test_repo_static_lock_graph_acyclic():
    """The shipped package's own static lock-order graph has no cycle
    (the KSL016 gate property, asserted directly on the graph)."""
    report = build_concurrency_report(
        [REPO / PKG], root=REPO,
        mods=shared_modules([REPO / PKG], root=REPO),
    )
    assert report["lock_graph"]["cycles"] == []
    assert len(report["lock_graph"]["nodes"]) >= 10


# ---------------------------------------------------------------------------
# KSL017 — blocking while holding


KSL017_POSITIVE = """
    import queue
    import threading
    import time

    from mpi_k_selection_tpu.faults.inject import maybe_fault

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()
            self._done = threading.Event()
            self._t = threading.Thread(target=self._run)

        def _run(self):
            pass

        def bad_get(self):
            with self._lock:
                return self._q.get()            # unbounded

        def bad_wait(self):
            with self._lock:
                self._done.wait()               # unbounded

        def bad_join(self):
            with self._lock:
                self._t.join()                  # unbounded

        def bad_sleep(self):
            with self._lock:
                time.sleep(0.5)

        def bad_stall(self):
            with self._lock:
                maybe_fault("serve.dispatch")
"""

KSL017_NEGATIVE = """
    import queue
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()
            self._done = threading.Event()
            self._t = threading.Thread(target=self._run)
            self._parts = []

        def _run(self):
            pass

        def bounded_get(self):
            with self._lock:
                return self._q.get(timeout=0.05)    # bounded: passes

        def bounded_wait(self):
            with self._lock:
                self._done.wait(0.1)                # bounded: passes

        def bounded_join(self):
            with self._lock:
                self._t.join(timeout=10.0)          # bounded: passes

        def join_strings(self):
            with self._lock:
                return ",".join(str(p) for p in self._parts)

        def get_dict(self, d, k):
            with self._lock:
                return d.get(k)                     # has args: passes

        def blocking_outside(self):
            self._done.wait()                       # no lock held: passes
            return self._q.get()
"""


def test_ksl017_positive(tmp_path):
    report = _lint_source(
        tmp_path, KSL017_POSITIVE, name=f"{PKG}/serve/mod.py"
    )
    hits = [f for f in report.unsuppressed if f.rule == "KSL017"]
    assert len(hits) == 5
    assert any("maybe_fault" in f.message for f in hits)
    assert any("time.sleep" in f.message for f in hits)
    assert all("_lock" in f.message for f in hits)


def test_ksl017_negative_timeouts_pass(tmp_path):
    report = _lint_source(
        tmp_path, KSL017_NEGATIVE, name=f"{PKG}/serve/mod.py"
    )
    assert "KSL017" not in _rules_hit(report)


def test_ksl017_scope_and_noqa(tmp_path):
    report = _lint_source(tmp_path, KSL017_POSITIVE, name="scripts/mod.py")
    assert "KSL017" not in _rules_hit(report)
    report = _lint_source(
        tmp_path, KSL017_POSITIVE, name=f"{PKG}/serve/test_mod.py"
    )
    assert "KSL017" not in _rules_hit(report)
    src = KSL017_POSITIVE.replace(
        "return self._q.get()            # unbounded",
        "return self._q.get()  # ksel: noqa[KSL017] -- fixture justification",
    )
    report = _lint_source(tmp_path, src, name=f"{PKG}/serve/mod.py")
    hits = [f for f in report.unsuppressed if f.rule == "KSL017"]
    assert len(hits) == 4
    sup = [f for f in report.findings if f.rule == "KSL017" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# the thread-reachability call graph


def test_thread_graph_finds_package_roots():
    report = build_concurrency_report(
        [REPO / PKG], root=REPO,
        mods=shared_modules([REPO / PKG], root=REPO),
    )
    threads = report["threads"]
    assert "QueryBatcher._run" in threads[f"{PKG}/serve/batcher.py"]["roots"]
    assert (
        "ChunkPipeline._produce"
        in threads[f"{PKG}/streaming/pipeline.py"]["roots"]
    )
    assert "_Handler.do_POST" in threads[f"{PKG}/serve/http.py"]["roots"]
    # reachability closes over module-local calls
    reach = threads[f"{PKG}/serve/batcher.py"]["reachable"]
    assert "QueryBatcher._serve_loop" in reach
    assert "QueryBatcher._dispatch" in reach


def test_thread_graph_fixture(tmp_path):
    src = """
    import threading

    def worker():
        helper()

    def helper():
        pass

    def untouched():
        pass

    def spawn():
        return threading.Thread(target=worker)
    """
    mod = load_module(
        _write(tmp_path, src, f"{PKG}/streaming/mod.py"), root=tmp_path
    )
    mc = analyze_module(mod)
    assert mc.thread_roots == ["worker"]
    assert "helper" in mc.thread_reachable
    assert "untouched" not in mc.thread_reachable


def _write(tmp_path, source, name):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


# ---------------------------------------------------------------------------
# useless-suppression (staleness) audit


def test_dead_suppression_detected(tmp_path):
    src = """
    import numpy as np

    def clean():
        return np.sum([1, 2])  # ksel: noqa[KSL004] -- nothing fires here
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/ops/mod.py")
    dead = report.dead_suppressions
    assert len(dead) == 1
    assert dead[0]["rule"] == "KSL004" and dead[0]["scope"] == "line"
    assert dead[0]["justification"] == "nothing fires here"


def test_live_suppression_not_flagged(tmp_path):
    src = """
    import time

    def bench():
        return time.perf_counter()  # ksel: noqa[KSL004] -- fixture
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/ops/mod.py")
    assert report.dead_suppressions == []
    assert any(f.rule == "KSL004" and f.suppressed for f in report.findings)


def test_dead_suppression_skips_string_literals(tmp_path):
    src = '''
    DOC = """
    example: x = 1  # ksel: noqa[KSL004] -- this is documentation text
    """
    '''
    report = _lint_source(tmp_path, src, name=f"{PKG}/ops/mod.py")
    assert report.dead_suppressions == []


def test_dead_suppression_skips_deselected_rules(tmp_path):
    src = """
    def clean():
        return 1  # ksel: noqa[KSL004] -- rule not selected: silence proves nothing
    """
    report = _lint_source(
        tmp_path, src, name=f"{PKG}/ops/mod.py", select=["KSL009"]
    )
    assert report.dead_suppressions == []


def test_dead_suppression_file_scope(tmp_path):
    src = (
        "# ksel: noqa-file[KSL004] -- nothing in this file reads a clock\n"
        "x = 1\n"
    )
    f = tmp_path / PKG / "ops" / "mod.py"
    f.parent.mkdir(parents=True)
    f.write_text(src)
    report = run_analysis([f], contracts=False)
    dead = report.dead_suppressions
    assert len(dead) == 1 and dead[0]["scope"] == "file"


def test_dead_suppressions_in_json_report(tmp_path, capsys):
    from mpi_k_selection_tpu.analysis import render_json

    src = "def clean():\n    return 1  # ksel: noqa[KSL004] -- stale\n"
    f = tmp_path / "mod.py"
    f.write_text(src)
    report = run_analysis([f], contracts=False)
    data = json.loads(render_json(report))
    assert data["dead_suppressions"] == report.dead_suppressions


def test_repo_has_no_dead_suppressions():
    """The shipped ledger carries no stale entries (the audit retired
    the redundant compat.py / spill.py noqas when it landed)."""
    report = run_analysis(
        [REPO], root=REPO, contracts=False,
        mods=shared_modules([REPO], root=REPO),
    )
    assert report.dead_suppressions == [], report.dead_suppressions


# ---------------------------------------------------------------------------
# doc-drift gate: registry ids <-> docs/ANALYSIS.md catalog rows


def test_rule_catalog_matches_docs():
    import re

    from mpi_k_selection_tpu.analysis import CONTRACT_CHECKS, all_rules

    registered = set(all_rules()) | {c.id for c in CONTRACT_CHECKS}
    registered.add("KSL000")  # engine-internal, documented
    doc = (REPO / "docs" / "ANALYSIS.md").read_text()
    documented = set(re.findall(r"^\| (KS[LC]\d{3}) \|", doc, re.MULTILINE))
    missing_rows = registered - documented
    assert not missing_rows, (
        f"registered rules missing a docs/ANALYSIS.md catalog row: "
        f"{sorted(missing_rows)}"
    )
    ghost_rows = documented - registered
    assert not ghost_rows, (
        f"docs/ANALYSIS.md documents rules that are not registered: "
        f"{sorted(ghost_rows)}"
    )


# ---------------------------------------------------------------------------
# CLI: --concurrency-report


def test_cli_concurrency_report(tmp_path, capsys):
    out = tmp_path / "conc.json"
    rc = lint_main(
        [
            str(REPO / PKG), "--no-contracts", "--select", "KSL016",
            "--concurrency-report", str(out),
        ]
    )
    capsys.readouterr()
    assert rc == 0
    data = json.loads(out.read_text())
    assert set(data) == {"threads", "lock_graph", "guards"}
    assert data["lock_graph"]["cycles"] == []
    assert any("PendingQuery" in k for k in data["guards"])
    assert any("StagingPool" in k for k in data["guards"])
    # the ingest pool's reorder sequencer (a Condition IS a lock) is in
    # the exported graph, so the runtime sanitizer can order it against
    # every other package lock
    assert any(
        k.endswith("ChunkPipeline._cond") for k in data["lock_graph"]["nodes"]
    )
    assert any(k.endswith("ChunkPipeline") for k in data["guards"])
    # node sites are package-relative regardless of the scan's cwd/root,
    # so they join the runtime sanitizer's labels (review finding, PR 12)
    for node in data["lock_graph"]["nodes"].values():
        assert node["site"].startswith(f"{PKG}/"), node


def test_concurrency_report_sites_cwd_independent(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    report = build_concurrency_report([REPO / PKG])  # no root passed
    nodes = report["lock_graph"]["nodes"]
    assert nodes and all(
        k.startswith(f"{PKG}/") and n["site"].startswith(f"{PKG}/")
        for k, n in nodes.items()
    )


# ---------------------------------------------------------------------------
# lock-order sanitizer units


def test_sanitizer_detects_ab_ba_cycle():
    with LockOrderSanitizer() as san:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        # the reverse order on another thread (as a real deadlock would
        # interleave it — here serialized so the test cannot hang)
        t = threading.Thread(target=lambda: _take_pair(b, a))
        t.start()
        t.join(timeout=10)
    cycles = san.find_cycles(package_only=False)
    assert len(cycles) == 1
    assert sorted(cycles[0]) == sorted({a.label, b.label})
    # assert_acyclic covers the PACKAGE subgraph — these ext-labeled test
    # locks are outside the contract, so it still passes here
    san.assert_acyclic()


def _take_pair(x, y):
    with x:
        with y:
            pass


def test_sanitizer_consistent_order_acyclic():
    with LockOrderSanitizer() as san:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    assert san.find_cycles(package_only=False) == []
    san.assert_acyclic()
    assert len(san.edges) == 1 and list(san.edges.values()) == [3]


def test_sanitizer_rlock_reentrancy_no_self_edge():
    with LockOrderSanitizer() as san:
        r = threading.RLock()
        with r:
            with r:  # reentrant re-acquire: no edge, no phantom hold
                pass
        assert not san.edges
        # still correctly released: another thread can take it
        t = threading.Thread(target=lambda: r.acquire(timeout=5) and r.release())
        t.start()
        t.join(timeout=10)
        assert not t.is_alive()


def test_sanitizer_out_of_order_release():
    with LockOrderSanitizer() as san:
        a = threading.Lock()
        b = threading.Lock()
        a.acquire()
        b.acquire()
        a.release()  # not LIFO — books must not corrupt
        c = threading.Lock()
        with c:
            pass
        b.release()
    # only (a->b) and (b->c): a was released before c was taken
    assert set(san.edges) == {(a.label, b.label), (b.label, c.label)}


def test_sanitizer_event_and_queue_still_work():
    import queue

    with LockOrderSanitizer():
        ev = threading.Event()
        q = queue.Queue()

        def worker():
            q.put(1)
            ev.set()

        t = threading.Thread(target=worker)
        t.start()
        assert ev.wait(timeout=10)
        assert q.get(timeout=10) == 1
        t.join(timeout=10)
        assert not t.is_alive()


def test_sanitizer_same_label_pairs_recorded():
    with LockOrderSanitizer() as san:
        def mk():
            return threading.Lock()  # both instances share this line

        l1, l2 = mk(), mk()
        with l1:
            with l2:
                pass
    assert san.same_label_pairs  # the two-instances-one-class hazard
    assert not san.edges  # not a graph self-loop


def test_sanitizer_consistency_conflict_detection():
    static_graph = {
        "nodes": {
            "m.py::A": {"name": "A", "site": "m.py:1"},
            "m.py::B": {"name": "B", "site": "m.py:2"},
        },
        "edges": [{"src": "m.py::A", "dst": "m.py::B", "site": "m.py:10"}],
    }
    san = LockOrderSanitizer()
    # a runtime observation ordering B before A, joined via the sites
    san.edges[("m.py:2", "m.py:1")] = 4
    conflicts = san.check_consistency(static_graph)
    assert len(conflicts) == 1 and conflicts[0]["count"] == 4
    # the agreeing direction is no conflict
    san.edges.clear()
    san.edges[("m.py:1", "m.py:2")] = 2
    assert san.check_consistency(static_graph) == []


def test_sanitizer_not_reentrant():
    with LockOrderSanitizer() as san:
        with pytest.raises(RuntimeError, match="not reentrant"):
            san.__enter__()


# ---------------------------------------------------------------------------
# the runtime gate: real concurrency workloads under one sanitizer


def _serve_burst(san):
    from mpi_k_selection_tpu.serve import KSelectServer

    x = np.random.default_rng(7).integers(-(2**20), 2**20, 4096, np.int64)
    x = x.astype(np.int32)
    with KSelectServer(window=0.001) as srv:
        srv.add_dataset("burst", x)
        want = srv.kselect("burst", 100, tier="exact").value
        results, errors = [None] * 6, []
        barrier = threading.Barrier(6)

        def client(i):
            try:
                barrier.wait(timeout=30)
                results[i] = srv.kselect("burst", 100, tier="exact").value
            except BaseException as e:  # surfaced below
                errors.append(e)

        ts = [
            threading.Thread(target=client, args=(i,), name=f"client-{i}")
            for i in range(6)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errors, errors
        assert all(int(r) == int(want) for r in results)


def _chaos_descent(san):
    from mpi_k_selection_tpu import faults
    from mpi_k_selection_tpu.streaming.chunked import streaming_kselect

    rng = np.random.default_rng(0)
    chunks = [
        rng.integers(-(2**31), 2**31 - 1, m, np.int64).astype(np.int32)
        for m in (5000, 4096, 2048)
    ]
    x = np.concatenate(chunks)
    k = x.size // 2
    plan = faults.FaultPlan.seeded(11, n_chunks=len(chunks), faults=3)
    policy = faults.RetryPolicy(sleeper=faults.VirtualSleeper())
    with faults.inject(plan, sleeper=faults.VirtualSleeper()) as inj:
        got = streaming_kselect(
            inj.wrap_chunk_source(lambda: iter(chunks)), k,
            spill="force", devices=2, retry=policy, radix_bits=4,
            collect_budget=64,
        )
    assert int(got) == int(np.sort(x, kind="stable")[k - 1])
    # the pooled host data plane under the same chaos plan: the reorder
    # sequencer's Condition + the ingest workers contend with the spill
    # writer and the injector under the sanitizer, and the recovered
    # answer is identical
    plan2 = faults.FaultPlan.seeded(11, n_chunks=len(chunks), faults=3)
    with faults.inject(plan2, sleeper=faults.VirtualSleeper()) as inj:
        got_pooled = streaming_kselect(
            inj.wrap_chunk_source(lambda: iter(chunks)), k,
            spill="force", devices=2, retry=policy, radix_bits=4,
            collect_budget=64, ingest_workers=3,
        )
    assert int(got_pooled) == int(got)


def _monitor_run(san):
    from mpi_k_selection_tpu.monitor import Monitor
    from mpi_k_selection_tpu.obs import Observability

    obs = Observability.collecting()
    rng = np.random.default_rng(3)
    chunks = [rng.integers(0, 2**20, 2048, np.int64).astype(np.int32)
              for _ in range(6)]
    mon = Monitor(window=4, emit_every=2, obs=obs, pipeline_depth=2)
    samples = list(mon.run(iter(chunks), dtype=np.int32))
    assert samples and samples[-1].n > 0
    obs.metrics.render_prometheus()


def test_lockorder_sanitizer_gate(tmp_path):
    """The dynamic half of the KSL016 acceptance: serve burst + chaos
    descent (executor, spill, pipeline, injector) + monitor run under
    ONE sanitizer; the observed package lock graph is acyclic and has no
    direction conflict with the static graph, and the observed order is
    checked in as the JSON artifact."""
    with LockOrderSanitizer() as san:
        san.patch_package_locks()
        _serve_burst(san)
        _chaos_descent(san)
        _monitor_run(san)
    assert san.threads_seen, "no lock activity recorded at all?"
    san.assert_acyclic()
    static = build_concurrency_report(
        [REPO / PKG], root=REPO,
        mods=shared_modules([REPO / PKG], root=REPO),
    )
    conflicts = san.check_consistency(static["lock_graph"])
    assert conflicts == [], conflicts
    artifact = san.to_dict()
    artifact["static_nodes"] = len(static["lock_graph"]["nodes"])
    artifact["conflicts"] = conflicts
    text = json.dumps(artifact, indent=2, sort_keys=True)
    (tmp_path / "kselect_lockorder.json").write_text(text)
    # best-effort mirror at the documented debugging path — a shared
    # host where another user owns the file must not fail the gate
    import contextlib

    with contextlib.suppress(OSError):
        pathlib.Path("/tmp/kselect_lockorder.json").write_text(text)
    # the workloads really did contend: at least the batcher dispatch
    # thread plus client/request threads appear in the books
    assert len(san.threads_seen) >= 3


def test_lockorder_sanitizer_chaos_stress():
    """Stress leg: repeated seeded chaos descents under the sanitizer.
    The conftest leaked-thread / staged-buffer / spill-dir fixtures hold
    on every iteration, and the observed order stays acyclic."""
    from mpi_k_selection_tpu import faults
    from mpi_k_selection_tpu.streaming.chunked import streaming_kselect_many

    rng = np.random.default_rng(5)
    chunks = [
        rng.integers(-(2**31), 2**31 - 1, m, np.int64).astype(np.int32)
        for m in (4096, 2048, 4096)
    ]
    x = np.concatenate(chunks)
    ks = [x.size // 4, x.size // 2]
    want = [int(np.sort(x, kind="stable")[k - 1]) for k in ks]
    with LockOrderSanitizer() as san:
        san.patch_package_locks()
        for seed in (1, 2, 3):
            plan = faults.FaultPlan.seeded(
                seed, n_chunks=len(chunks), faults=2
            )
            policy = faults.RetryPolicy(sleeper=faults.VirtualSleeper())
            with faults.inject(plan, sleeper=faults.VirtualSleeper()) as inj:
                got = streaming_kselect_many(
                    inj.wrap_chunk_source(lambda: iter(chunks)), ks,
                    devices=2, retry=policy, radix_bits=4,
                    collect_budget=64,
                )
            assert [int(v) for v in got] == want, seed
    san.assert_acyclic()


# ---------------------------------------------------------------------------
# regression tests for the true positives the rules surfaced (PR 12)


def test_phasetimer_report_safe_under_concurrent_phases():
    """PhaseTimer.report() iterated `phases` without the lock (KSL015's
    first-run finding): a producer thread landing a phase mid-report
    raised `dictionary changed size during iteration`. Now it snapshots
    under the lock."""
    from mpi_k_selection_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            timer.record(f"phase-{i % 251}", 0.001)
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(300):
            out = timer.report()
            assert out.startswith("phase timing:")
    finally:
        stop.set()
        t.join(timeout=10)
    assert not t.is_alive()


def test_histogram_snapshot_consistent_under_concurrent_observe():
    """Histogram.cumulative()/as_dict() read the buckets without the
    registry lock (KSL015's second first-run finding): a scrape racing
    observe() could see +Inf cumulative != count. Both now snapshot in
    one critical section — the invariant holds at every interleaving."""
    from mpi_k_selection_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("gate.test")
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            h.observe(i % 40)
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(300):
            d = h.as_dict()
            assert d["buckets"]["+Inf"] == d["count"]
            cum = h.cumulative()
            assert all(a <= b for a, b in zip(cum, cum[1:]))
    finally:
        stop.set()
        t.join(timeout=10)
    assert not t.is_alive()
