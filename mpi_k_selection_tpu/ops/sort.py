"""Sort-then-index selection — the XLA baseline and on-device oracle.

Reproduces the reference's sequential semantics exactly: sort ascending and
take element ``k-1`` (1-indexed k, ``kth-problem-seq.c:32-33``, via
``VecQuickSort`` -> libc ``qsort``, ``vector.c:239-241``). O(n log n) — used
as the correctness baseline that radix_select (O(n) passes) is tested and
benchmarked against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def sort_select(x: jax.Array, k) -> jax.Array:
    """Exact k-th smallest (1-indexed) by full sort."""
    x = x.ravel()
    s = jax.lax.sort(x)
    idx = jnp.clip(jnp.asarray(k, jnp.int32) - 1, 0, x.shape[0] - 1)
    return jnp.take(s, idx)
