"""Distributed top-k on the 8-device virtual CPU mesh vs the seq oracle."""

import jax
import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.parallel import distributed_topk, make_mesh
from mpi_k_selection_tpu.utils import datagen


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8
    return make_mesh(8)


N = 1 << 15


@pytest.mark.parametrize("largest", [True, False])
@pytest.mark.parametrize("dtype", [np.int32, np.float32])
def test_distributed_topk_matches_oracle(mesh8, largest, dtype):
    pattern = "uniform" if np.dtype(dtype).kind == "i" else "normal"
    x = datagen.generate(N, pattern=pattern, seed=5, dtype=dtype)
    for k in (1, 8, 128):
        vals, idx = distributed_topk(x, k, largest=largest, mesh=mesh8)
        want_v, _ = seq.topk(x, k, largest=largest)
        np.testing.assert_array_equal(np.asarray(vals), want_v)
        # indices must point at elements with the returned values
        np.testing.assert_array_equal(x[np.asarray(idx)], np.asarray(vals))


def test_distributed_topk_ragged_n(mesh8):
    n = N + 3  # padding path: loser sentinels
    x = datagen.generate(n, pattern="uniform", seed=6, dtype=np.int32)
    for largest in (True, False):
        vals, _ = distributed_topk(x, 16, largest=largest, mesh=mesh8)
        want_v, _ = seq.topk(x, 16, largest=largest)
        np.testing.assert_array_equal(np.asarray(vals), want_v)


def test_distributed_topk_duplicates(mesh8):
    rng = np.random.default_rng(7)
    x = rng.integers(0, 3, size=N, dtype=np.int32)
    vals, idx = distributed_topk(x, 64, mesh=mesh8)
    want_v, _ = seq.topk(x, 64)
    np.testing.assert_array_equal(np.asarray(vals), want_v)
    np.testing.assert_array_equal(x[np.asarray(idx)], np.asarray(vals))


def test_distributed_topk_k_too_large(mesh8):
    x = datagen.generate(1 << 10, pattern="uniform", seed=8, dtype=np.int32)
    with pytest.raises(ValueError, match="shard size"):
        distributed_topk(x, 1 << 9, mesh=mesh8)
    with pytest.raises(ValueError, match="out of range"):
        distributed_topk(x, 0, mesh=mesh8)


def test_distributed_topk_sentinel_tie_indices(mesh8):
    """Order-extreme values + ragged n: a padding sentinel ties real elements;
    returned indices must still point at *real* occurrences (< n)."""
    n = N + 5  # ragged -> 3 padding sentinels appended
    for largest in (True, False):
        extreme = np.int32(np.iinfo(np.int32).min if largest else np.iinfo(np.int32).max)
        x = np.full(n, extreme, dtype=np.int32)
        rng = np.random.default_rng(9)
        lucky = rng.choice(n, size=7, replace=False)
        x[lucky] = rng.integers(-100, 100, size=7).astype(np.int32)
        vals, idx = distributed_topk(x, 32, largest=largest, mesh=mesh8)
        idx = np.asarray(idx)
        vals = np.asarray(vals)
        want_v, _ = seq.topk(x, 32, largest=largest)
        np.testing.assert_array_equal(vals, want_v)
        assert (idx < n).all(), f"index points at padding slot: {idx}"
        np.testing.assert_array_equal(x[idx], vals)
        assert len(set(idx.tolist())) == len(idx), "indices must be distinct"


def test_distributed_topk_float_nan_sentinel_indices(mesh8):
    """Float dtypes: the padding sentinel's payload is a NaN bit pattern, so
    the remap must match ties bitwise (== never matches NaN)."""
    n = N + 5
    # order-minimum float32 key is the -NaN pattern 0xFFFFFFFF (largest=True
    # sentinel); fill the array with it so sentinels tie into the top-k
    x = np.full(n, -1, dtype=np.int32).view(np.float32).copy()
    rng = np.random.default_rng(10)
    lucky = rng.choice(n, size=7, replace=False)
    x[lucky] = rng.uniform(-1, 1, size=7).astype(np.float32)
    vals, idx = distributed_topk(x, 32, largest=True, mesh=mesh8)
    idx, vals = np.asarray(idx), np.asarray(vals)
    assert (idx < n).all(), f"index points at padding slot: {idx}"
    np.testing.assert_array_equal(
        x[idx].view(np.uint32), vals.view(np.uint32)
    )  # bitwise: NaN-safe
    assert len(set(idx.tolist())) == len(idx), "indices must be distinct"
