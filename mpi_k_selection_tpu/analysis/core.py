"""Rule framework: findings, suppressions, the registry, and the driver.

The engine is deliberately dependency-free (stdlib ``ast`` only) so the
lint gate can run in any environment that can import the package — the
jaxpr contract engine (jaxpr_checks.py) is the only part that needs jax,
and it is skipped with ``contracts=False``.

Suppression syntax (mirrors flake8's ``# noqa`` but namespaced so the two
tools never fight over a comment):

- ``# ksel: noqa[KSL001]`` — suppress that rule on this line; everything
  after ``--`` is the recorded justification::

      t0 = time.perf_counter()  # ksel: noqa[KSL004] -- differential chain

- ``# ksel: noqa[KSL001,KSL004] -- reason`` — several rules, one line.
- ``# ksel: noqa-file[KSL005] -- reason`` — suppress for the whole file
  (for rules whose findings do not attach to a meaningful line).

A suppressed finding still appears in the JSON report (``suppressed:
true`` with its justification) so the gate's artifact doubles as the
ledger of accepted exceptions.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

_NOQA_RE = re.compile(
    r"#\s*ksel:\s*noqa(?P<scope>-file)?\[(?P<rules>[A-Z0-9_,\s]+)\]"
    r"(?:\s*--\s*(?P<why>.*?))?\s*$"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}{tag} {self.message}"


class SourceModule:
    """A parsed source file plus its suppression table."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # line -> {rule -> justification}; rule "" means all rules
        self.line_noqa: dict[int, dict[str, str]] = {}
        self.file_noqa: dict[str, str] = {}
        self.file_noqa_lines: dict[str, int] = {}
        for lineno, line in enumerate(self.lines, start=1):
            m = _NOQA_RE.search(line)
            if not m:
                continue
            rules = [r.strip() for r in m.group("rules").split(",") if r.strip()]
            why = (m.group("why") or "").strip()
            if m.group("scope"):
                for r in rules:
                    self.file_noqa[r] = why
                    self.file_noqa_lines[r] = lineno
            else:
                table = self.line_noqa.setdefault(lineno, {})
                for r in rules:
                    table[r] = why

    def string_literal_lines(self) -> set[int]:
        """Line numbers carrying string constants (docstrings, fixture
        sources, ``.replace`` arguments) — a noqa-shaped comment INSIDE
        one is text, not a suppression, so the staleness audit skips
        those lines (a true suppression sharing a line with a string
        merely dodges the audit, never enforcement)."""
        out: set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                end = node.end_lineno or node.lineno
                out.update(range(node.lineno, end + 1))
        return out

    def suppression(self, rule: str, line: int) -> str | None:
        """Justification string when ``rule`` is suppressed at ``line``
        (empty string = suppressed without justification), else None."""
        table = self.line_noqa.get(line)
        if table is not None and rule in table:
            return table[rule]
        if rule in self.file_noqa:
            return self.file_noqa[rule]
        return None

    def segment(self, node: ast.AST) -> str:
        """Source text of ``node`` (used for token-level heuristics)."""
        return ast.get_source_segment(self.text, node) or ""


class Rule:
    """Base AST rule. Subclasses set the class attributes and implement
    either :meth:`check_module` (per-file) or :meth:`check_tree`
    (whole-scan rules like the tier-1 membership audit)."""

    id: str = ""
    title: str = ""
    rationale: str = ""

    def check_module(self, mod: SourceModule):
        """Yield ``(line, message)`` violations for one file."""
        return ()

    def check_tree(self, mods: list[SourceModule]):
        """Yield ``(mod, line, message)`` violations for the whole scan."""
        return ()


_REGISTRY: dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    return dict(_REGISTRY)


@dataclasses.dataclass
class Report:
    findings: list[Finding]
    files: list[str]
    checks_run: list[str]
    #: ``# ksel: noqa[...]`` entries whose rule RAN on this scan but no
    #: longer fires at that location — stale ledger entries (the gate
    #: warns; see dead_suppressions() below)
    dead_suppressions: list = dataclasses.field(default_factory=list)
    #: the parsed SourceModules of this scan — NOT serialized; lets the
    #: CLI hand the already-loaded tree to build_concurrency_report
    #: instead of re-parsing every file
    modules: list = dataclasses.field(default_factory=list, repr=False)

    @property
    def unsuppressed(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def exit_code(self) -> int:
        return 1 if self.unsuppressed else 0


_SKIP_DIRS = {
    "__pycache__", "build", "dist", "node_modules", "venv",
    "site-packages",
}


def _skip_part(part: str) -> bool:
    """Directory components the scan never descends into: caches, build
    output, virtualenvs (``kselect-lint .`` must not lint site-packages),
    and every dot-directory (.git, .venv, .tox, .claude, ...)."""
    return (
        part in _SKIP_DIRS
        or part.endswith(".egg-info")
        or (part.startswith(".") and part not in (".", ".."))
    )


def iter_python_files(paths) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                # judge only the components BELOW the given root, so a
                # scan rooted inside a dot-directory still works
                if not any(_skip_part(part) for part in f.relative_to(p).parts):
                    out.append(f)
    # dedupe, stable order
    seen, uniq = set(), []
    for f in out:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(f)
    return uniq


def load_module(path, root=None) -> SourceModule:
    path = pathlib.Path(path)
    try:
        rel = str(path.resolve().relative_to(pathlib.Path(root or ".").resolve()))
    except ValueError:
        rel = str(path)
    return SourceModule(str(path), rel, path.read_text())


def _selected(rule_id: str, select, ignore) -> bool:
    if select is not None and not any(rule_id.startswith(s) for s in select):
        return False
    if ignore is not None and any(rule_id.startswith(s) for s in ignore):
        return False
    return True


def run_analysis(
    paths,
    *,
    select=None,
    ignore=None,
    contracts: bool = True,
    root=None,
    mods=None,
) -> Report:
    """Run every selected rule (and, with ``contracts=True``, every jaxpr
    contract check) over ``paths``. Returns a :class:`Report`; the gate
    semantics are ``report.exit_code`` (1 iff any unsuppressed finding).

    ``mods`` is an optional pre-parsed :class:`SourceModule` list (the
    analysis/modcache.py shared set): when given, ``paths`` is not
    re-walked or re-parsed — the whole-repo gate tests hand the four
    passes ONE parsed tree. KSL000 (syntax errors) can only arise from
    the parse loop, so callers passing ``mods`` vouch the set parsed."""
    findings: list[Finding] = []
    checks_run: list[str] = []
    if mods is not None:
        mods = list(mods)
        files = [m.path for m in mods]
        return _run_rules(
            mods, files, findings, checks_run, select, ignore, contracts
        )
    files = iter_python_files(paths)
    mods = []
    for f in files:
        try:
            mods.append(load_module(f, root=root))
        except SyntaxError as e:
            # KSL000 honors --select/--ignore like any rule: an unparseable
            # vendored file is excludable (`--ignore KSL000`) without
            # dropping it from the scan paths — a noqa cannot apply since
            # the suppression table needs a parse
            if _selected("KSL000", select, ignore):
                findings.append(
                    Finding("KSL000", str(f), e.lineno or 1, f"syntax error: {e.msg}")
                )
    return _run_rules(mods, files, findings, checks_run, select, ignore, contracts)


def _run_rules(mods, files, findings, checks_run, select, ignore, contracts) -> Report:
    def emit(rule_id: str, mod: SourceModule, line: int, message: str):
        why = mod.suppression(rule_id, line)
        findings.append(
            Finding(
                rule_id,
                mod.relpath,
                line,
                message,
                suppressed=why is not None,
                justification=why or "",
            )
        )

    for rule_id, rule in sorted(_REGISTRY.items()):
        if not _selected(rule_id, select, ignore):
            continue
        checks_run.append(rule_id)
        for mod in mods:
            for line, message in rule.check_module(mod):
                emit(rule_id, mod, line, message)
        for mod, line, message in rule.check_tree(mods):
            emit(rule_id, mod, line, message)

    if contracts:
        from mpi_k_selection_tpu.analysis.jaxpr_checks import CONTRACT_CHECKS

        for check in CONTRACT_CHECKS:
            if not _selected(check.id, select, ignore):
                continue
            checks_run.append(check.id)
            findings.extend(check.run())

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    dead = _dead_suppressions(mods, findings, checks_run)
    return Report(findings, [str(f) for f in files], checks_run, dead, mods)


def _dead_suppressions(mods, findings, checks_run) -> list[dict]:
    """Stale ``# ksel: noqa[...]`` entries: the named rule RAN on this
    scan yet produced no (suppressed) finding at the suppression's
    location — the justification ledger is carrying a dead exception.
    Rules that were deselected are skipped (their silence proves
    nothing); so are non-KSL ids, which have no line-anchored findings
    to judge (contract checks deselect via ``--ignore`` instead)."""
    ran = set(checks_run)
    out: list[dict] = []
    for mod in mods:
        in_string = mod.string_literal_lines()
        live_lines = {
            (f.rule, f.line)
            for f in findings
            if f.path == mod.relpath and f.suppressed
        }
        live_rules = {rule for rule, _line in live_lines}
        for line, table in sorted(mod.line_noqa.items()):
            if line in in_string:
                continue  # noqa-shaped text inside a string literal
            for rule, why in sorted(table.items()):
                if rule not in ran or not rule.startswith("KSL"):
                    continue
                if (rule, line) not in live_lines:
                    out.append(
                        {
                            "path": mod.relpath,
                            "line": line,
                            "rule": rule,
                            "justification": why,
                            "scope": "line",
                        }
                    )
        for rule, why in sorted(mod.file_noqa.items()):
            if mod.file_noqa_lines.get(rule, 0) in in_string:
                continue
            if rule not in ran or not rule.startswith("KSL"):
                continue
            if rule not in live_rules:
                out.append(
                    {
                        "path": mod.relpath,
                        "line": mod.file_noqa_lines.get(rule, 1),
                        "rule": rule,
                        "justification": why,
                        "scope": "file",
                    }
                )
    return out
