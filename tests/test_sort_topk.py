"""sort_select and topk/batched_topk vs the NumPy oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.ops.sort import sort_select
from mpi_k_selection_tpu.ops.topk import batched_topk, topk
from mpi_k_selection_tpu.utils import datagen


def test_sort_select_matches_oracle():
    x = datagen.generate(4000, pattern="uniform", seed=1, dtype=np.int32)
    for k in (1, 2000, 4000):
        assert int(sort_select(jnp.asarray(x), k)) == int(seq.kselect_sort(x, k))


def test_partition_vs_sort_oracle():
    x = datagen.generate(5000, pattern="seqlike", seed=2, dtype=np.int32)
    for k in (1, 17, 2500, 5000):
        assert int(seq.kselect(x, k)) == int(seq.kselect_sort(x, k))


@pytest.mark.parametrize("largest", [True, False])
@pytest.mark.parametrize("dtype", [np.float32, np.int32, np.uint32])
def test_topk_values(largest, dtype):
    rng = np.random.default_rng(3)
    if np.dtype(dtype).kind == "f":
        x = rng.standard_normal(2000).astype(dtype)
    else:
        info = np.iinfo(dtype)
        x = rng.integers(info.min, info.max, size=2000, endpoint=True, dtype=dtype)
    vals, idx = topk(jnp.asarray(x), 37, largest=largest)
    want_vals, _ = seq.topk(x, 37, largest=largest)
    np.testing.assert_array_equal(np.asarray(vals), want_vals)
    # indices must point at the returned values
    np.testing.assert_array_equal(x[np.asarray(idx)], np.asarray(vals))


def test_batched_topk():
    x = datagen.generate(512, pattern="normal", seed=4, dtype=np.float32, batch=(8, 3))
    vals, idx = batched_topk(jnp.asarray(x), 8)
    want_vals, _ = seq.topk(x, 8)
    np.testing.assert_array_equal(np.asarray(vals), want_vals)
    np.testing.assert_array_equal(
        np.take_along_axis(x, np.asarray(idx), axis=-1), np.asarray(vals)
    )


def test_topk_chunked_matches_flat():
    x = datagen.generate(1 << 17, pattern="funiform", seed=5, dtype=np.float32)
    vflat, _ = topk(jnp.asarray(x), 128, method="flat")
    vchunk, ichunk = topk(jnp.asarray(x), 128, method="chunked")
    np.testing.assert_array_equal(np.asarray(vflat), np.asarray(vchunk))
    np.testing.assert_array_equal(x[np.asarray(ichunk)], np.asarray(vchunk))


def test_topk_k_equals_d():
    x = jnp.asarray([3.0, 1.0, 2.0], dtype=jnp.float32)
    vals, idx = topk(x, 3)
    np.testing.assert_array_equal(np.asarray(vals), [3.0, 2.0, 1.0])


def test_topk_duplicates():
    x = np.array([5, 5, 5, 1, 1, 9], dtype=np.int32)
    vals, _ = topk(jnp.asarray(x), 4)
    np.testing.assert_array_equal(np.asarray(vals), [9, 5, 5, 5])
    vals, _ = topk(jnp.asarray(x), 4, largest=False)
    np.testing.assert_array_equal(np.asarray(vals), [1, 1, 5, 5])


def test_topk_out_of_range():
    with pytest.raises(ValueError):
        topk(jnp.arange(4, dtype=jnp.float32), 5)


@pytest.mark.parametrize("method", ["threshold", "tournament"])
@pytest.mark.parametrize("largest", [True, False])
def test_topk_large_1d_methods(method, largest):
    # one ragged n + one block-aligned power-of-two n (the off-by-full-
    # block class: tail masks / pool reshapes when n % block == 0), and
    # the two edge ks — each (n, dtype, k) combo is a fresh jit trace
    # (k is static); the old 3-k matrix at 2x this n measured 18 s per
    # parametrization for no added coverage
    rng = np.random.default_rng(6)
    for n, ks in (((1 << 17) + 777, (1, 128)), (1 << 16, (128,))):
        for dtype in (np.float32, np.int32):
            x = (rng.standard_normal(n) * 100).astype(dtype)  # duplicate-heavy ints
            for k in ks:
                vals, idx = topk(jnp.asarray(x), k, largest=largest, method=method)
                want_vals, _ = seq.topk(x, k, largest=largest)
                np.testing.assert_array_equal(np.asarray(vals), want_vals)
                np.testing.assert_array_equal(x[np.asarray(idx)], np.asarray(vals))


@pytest.mark.parametrize("method", ["threshold", "tournament"])
def test_topk_1d_methods_all_equal(method):
    x = np.full((1 << 16) + 3, -7, np.int32)
    vals, idx = topk(jnp.asarray(x), 16, method=method)
    assert np.all(np.asarray(vals) == -7)
    i = np.asarray(idx)
    assert i.max() < x.size and len(set(i.tolist())) == 16


@pytest.mark.parametrize("method", ["threshold", "tournament"])
def test_topk_1d_methods_reject_2d(method):
    with pytest.raises(ValueError, match="1-D"):
        topk(jnp.zeros((4, 1 << 18), jnp.float32), 2, method=method)


def test_threshold_topk_f64_tpu_warns_once_per_path(monkeypatch):
    """ADVICE r5 #1 regression: host float64 1-D top-k via
    method='threshold' builds its own _Descent, bypassing the radix
    shells' exact f64 host-key route — on the TPU backend it must emit
    the one-time ~49-bit-approximation warning (exactly once), and the
    kselect-path warning must still fire afterwards: the two paths carry
    different advice, so neither may suppress the other."""
    import warnings

    import jax

    from mpi_k_selection_tpu.ops import histogram as hist_mod
    from mpi_k_selection_tpu.ops import radix as radix_mod
    from mpi_k_selection_tpu.utils import compat

    # fake the backend NAME only; force every histogram onto the XLA
    # scatter path so no TPU Pallas kernel is built on the CPU test host
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        hist_mod, "resolve_hist_method", lambda method, key_dtype=None: "scatter"
    )
    monkeypatch.setattr(radix_mod, "_f64_tpu_approx_warned", set())
    x = np.random.default_rng(11).standard_normal(1 << 12)
    with compat.enable_x64(True):
        xd = jnp.asarray(x)
        assert xd.dtype == jnp.float64
        with pytest.warns(UserWarning, match="threshold top-k"):
            topk(xd, 8, method="threshold")
        # exactly once per process for this path: a second call is silent
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            topk(xd, 8, method="threshold")
        # ...and the kselect advice is NOT suppressed by the top-k one
        with pytest.warns(UserWarning, match="bit-exact f64"):
            jax.jit(
                lambda: radix_mod.radix_select(x, 500, hist_method="scatter")
            )()
        # both advice variants are now recorded independently
        assert len(radix_mod._f64_tpu_approx_warned) == 2
