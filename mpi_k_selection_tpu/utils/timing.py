"""Wall-clock timing + structured result records.

The reference self-times with a single ``clock()`` pair
(``kth-problem-seq.c:30,35``) / ``MPI_Wtime()`` pair
(``TODO-kth-problem-cgm.c:76,279``), both excluding data generation, and
prints ``answer + seconds``. This module keeps that contract (time the solve,
not the generation) and extends it with the SURVEY.md §5 observability plan:
per-phase timing, repeat/median, elems/sec/chip, and a JSON-able record.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Callable

import jax


def _block(x):
    return jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready") else a, x
    )


def time_fn(fn: Callable[[], Any], *, repeats: int = 1, warmup: int = 0):
    """Time `fn` with device-sync semantics. Returns (best_seconds, last_result).

    Caveat (remote/tunneled accelerators): best-of-repeats on IDENTICAL
    inputs can read far below the true device time when the transport
    caches results (observed through the axon tunnel: a 64M top-k
    "measured" 0.15 ms vs ~4 ms real). On directly-attached hardware the
    numbers are sound; for tunnel-proof measurement use bench.py's
    differential perturb-chain methodology, which defeats caching by
    making every iteration's input depend on the previous output."""
    result = None
    for _ in range(warmup):
        result = _block(fn())
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        result = _block(fn())
        best = min(best, time.perf_counter() - t0)
    return best, result


class Deadline:
    """A monotonic deadline: ``Deadline.after(seconds)`` captures the
    clock ONCE here (KSL004: raw clocks live in utils/timing +
    utils/profiling only) and everyone downstream asks ``remaining()``/
    ``expired`` instead of reading clocks themselves. The serving layer
    (serve/batcher.py) threads one per request so waiters time out and
    the dispatch thread can fail expired queries fast without ever
    touching ``time`` itself."""

    __slots__ = ("_t1",)

    def __init__(self, t1: float):
        self._t1 = float(t1)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        s = float(seconds)
        if s <= 0:
            raise ValueError(f"deadline must be > 0 seconds, got {s}")
        return cls(time.monotonic() + s)

    def remaining(self) -> float:
        """Seconds left (clamped at 0.0 once expired)."""
        return max(0.0, self._t1 - time.monotonic())

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self._t1


@dataclasses.dataclass
class ResultRecord:
    """Structured run record (SURVEY.md §5 metrics/logging plan)."""

    answer: Any
    n: int
    k: int
    backend: str
    algorithm: str
    dtype: str
    seconds: float
    n_devices: int = 1
    rounds: int | None = None
    extra: dict = dataclasses.field(default_factory=dict)

    @property
    def elems_per_sec_per_chip(self) -> float:
        if self.seconds <= 0:
            return float("inf")
        return self.n / self.seconds / max(1, self.n_devices)

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["elems_per_sec_per_chip"] = self.elems_per_sec_per_chip
        if hasattr(d["answer"], "item"):
            d["answer"] = d["answer"].item()
        return json.dumps(d, default=str)

    def print_reference_style(self) -> None:
        # Mirrors the reference's per-program output contracts:
        # seq backend: "Solution found solution=%d \ntime: %f\n" (kth-problem-seq.c:37)
        # others:      "kth element=%d \ntime: %f\n"  (TODO-kth-problem-cgm.c:280)
        if self.backend == "seq":
            print(f"Solution found solution={self.answer} \ntime: {self.seconds:f}")
        else:
            print(f"kth element={self.answer} \ntime: {self.seconds:f}")
