"""Seeded fault plans — deterministic, replayable fault schedules.

The reference's failure model is ``MPI_Abort`` on any anomaly; hardening
the streaming/spill/serve vertical against real faults requires a way to
*produce* those faults deterministically: ad-hoc mocks drift from the
real failure surfaces, and random chaos that cannot be replayed from a
seed cannot be debugged or regression-gated. A :class:`FaultPlan` is a
frozen schedule — "fail occurrence *i* of site S on attempt *j* with
fault kind K" — that the runtime injector (faults/inject.py) executes at
the real hook points (chunk pull, staging ``device_put``, spill record
write/read, the serve dispatch loop). The same plan replays the same
faults, and :meth:`FaultPlan.seeded` derives one from a single integer,
so the chaos grid, the gauntlet and the CLI ``--chaos`` knob all speak
one seed.

No clocks, no real sleeping: the ``"stall"`` kind waits through the
injectable :class:`~mpi_k_selection_tpu.faults.sleeper.Sleeper` (KSL004
discipline extended to waiting — see faults/sleeper.py).
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: Every fault kind the injector can execute. Semantics:
#:
#: - ``"raise"``    — raise :class:`~mpi_k_selection_tpu.errors.
#:   TransientError` (the retryable class) at the hook point;
#: - ``"stall"``    — a slow producer/medium: sleep ``arg`` seconds via
#:   the injectable sleeper, then proceed normally;
#: - ``"corrupt"``  — a transient bad read: the spill reader raises
#:   SpillRecordError for the matching attempt only (a re-read sees the
#:   intact bytes — a flipped bit on the wire, not on the platter);
#: - ``"corrupt_disk"`` — flip one payload byte ON DISK (persistent): the
#:   real CRC32 check fails on this and every later read of the record;
#: - ``"truncate"`` — truncate the record file on disk (persistent): the
#:   real payload-size check fails from then on;
#: - ``"enospc"``   — raise ``OSError(errno.ENOSPC)`` at the write hook.
FAULT_KINDS = ("raise", "stall", "corrupt", "corrupt_disk", "truncate", "enospc")

#: The hook points fault specs can target:
#:
#: - ``"source"``       — pulling chunk ``index`` from a wrapped chunk
#:   source (faults/inject.py:wrap_chunk_source);
#: - ``"stage"``        — the ``index``-th staging ``device_put``
#:   (streaming/pipeline.py:stage_keys);
#: - ``"spill.write"``  — appending record ``index`` of a generation
#:   (streaming/spill.py:SpillWriter.append; per-generation record
#:   counts, so attempt *j* of record *i* is its write in the *j*-th
#:   generation — or re-run — that reaches it);
#: - ``"spill.read"``   — reading the record with chunk_index ``index``
#:   (streaming/spill.py:_read_record);
#: - ``"serve.dispatch"`` — the ``index``-th dispatch round of the query
#:   server's batcher loop (serve/batcher.py), OUTSIDE the per-group
#:   error isolation — the supervisor-restart path. Rounds are a global
#:   call sequence (a restart does not re-run a round), so only
#:   ``attempts=(0,)`` is meaningful here.
FAULT_SITES = ("source", "stage", "spill.write", "spill.read", "serve.dispatch")

#: Which kinds make sense at which site (validated at plan build time so
#: a typo fails at construction, not silently never-fires).
_SITE_KINDS = {
    "source": ("raise", "stall"),
    "stage": ("raise", "stall"),
    "spill.write": ("raise", "enospc"),
    "spill.read": ("raise", "corrupt", "corrupt_disk", "truncate"),
    "serve.dispatch": ("raise",),
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: occurrence ``index`` of ``site`` fails on
    each attempt number in ``attempts`` (0-based; the injector counts how
    many times that occurrence has been tried) with fault ``kind``.
    ``arg`` parameterizes the kind (stall seconds).

    The attempt counter spans the whole run: a chunk re-pulled by a
    retry, a record re-read by the recovery ladder, and a chunk replayed
    by a later radix pass all advance the same counter — so
    ``attempts=(0,)`` is "fail the first touch, recover on the next" and
    ``attempts=tuple(range(99))`` is "hard failure, exhaust any policy".
    """

    site: str
    index: int
    kind: str
    attempts: tuple = (0,)
    arg: float = 0.0

    def __post_init__(self):
        if self.site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; choose from {FAULT_SITES}"
            )
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; choose from {FAULT_KINDS}"
            )
        if self.kind not in _SITE_KINDS[self.site]:
            raise ValueError(
                f"fault kind {self.kind!r} does not apply at site "
                f"{self.site!r} (valid: {_SITE_KINDS[self.site]})"
            )
        if self.index < 0:
            raise ValueError(f"fault index must be >= 0, got {self.index}")
        atts = tuple(int(a) for a in self.attempts)
        if not atts or any(a < 0 for a in atts):
            raise ValueError(
                f"attempts must be a non-empty tuple of ints >= 0, got "
                f"{self.attempts!r}"
            )
        object.__setattr__(self, "attempts", atts)
        object.__setattr__(self, "index", int(self.index))
        object.__setattr__(self, "arg", float(self.arg))


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An immutable fault schedule. Build one explicitly from specs, or
    derive one from a seed (:meth:`seeded`) — either way the plan is pure
    data: executing it is the injector's job (faults/inject.py), so one
    plan can drive many runs (the bit-equality grid runs every
    devices x depth x spill x deferred combination under the SAME plan).
    """

    specs: tuple = ()
    seed: int | None = None

    def __post_init__(self):
        specs = tuple(self.specs)
        for s in specs:
            if not isinstance(s, FaultSpec):
                raise ValueError(f"FaultPlan specs must be FaultSpec, got {s!r}")
        object.__setattr__(self, "specs", specs)

    def for_site(self, site: str) -> tuple:
        return tuple(s for s in self.specs if s.site == site)

    @classmethod
    def seeded(
        cls,
        seed: int,
        *,
        n_chunks: int = 8,
        faults: int = 3,
        sites: tuple = ("source", "stage", "spill.read"),
        recoverable: bool = True,
        stall_seconds: float = 0.001,
    ) -> "FaultPlan":
        """A deterministic plan from one integer: ``faults`` specs drawn
        over ``sites``, each targeting an occurrence index in
        ``[0, n_chunks)`` with a kind valid at its site. With
        ``recoverable`` (the default) every spec fails a SINGLE attempt
        — first-touch transients a default RetryPolicy / the spill
        recovery ladder absorbs, which is what the bit-equality chaos
        grid wants; ``recoverable=False`` makes every spec hard (fails
        every attempt), the exhausted-policy form. Same seed, same plan
        — the replayability contract the chaos tests and ``--chaos``
        lean on."""
        rng = np.random.default_rng(int(seed))
        specs = []
        for _ in range(int(faults)):
            site = sites[int(rng.integers(len(sites)))]
            kinds = _SITE_KINDS[site]
            kind = kinds[int(rng.integers(len(kinds)))]
            index = int(rng.integers(max(1, int(n_chunks))))
            attempts = (0,) if recoverable else tuple(range(99))
            if kind == "stall":
                # a stall needs no recovery: keep it single-shot always
                attempts = (0,)
            specs.append(
                FaultSpec(
                    site=site,
                    index=index,
                    kind=kind,
                    attempts=attempts,
                    arg=stall_seconds if kind == "stall" else 0.0,
                )
            )
        return cls(specs=tuple(specs), seed=int(seed))
