"""Typed exceptions shared across the package.

The reference signals every failure as a process exit (``MPI_Abort``,
``TODO-kth-problem-cgm.c:58``); a library needs typed errors so callers can
distinguish "this machine cannot run it" from "the run failed".
"""

from __future__ import annotations


class NativeUnavailableError(RuntimeError):
    """The native (C++) runtime cannot be built/loaded on this machine —
    e.g. no C++ toolchain. Environmental, not a bug: harness code (bench.py)
    treats it as a tolerable skip, while any other exception from the native
    backend is a real failure."""


class SpillError(RuntimeError):
    """Misuse of the streaming spill store (streaming/spill.py): reading an
    empty/closed store, writing after commit, and similar lifecycle errors."""


class SpillRecordError(SpillError):
    """A spill record on disk failed validation — missing file, truncated
    header/payload, or a checksum/metadata mismatch. Raised BEFORE any key
    reaches a histogram: a corrupt spill cache must fail loudly, never feed
    the descent silently wrong survivors."""
