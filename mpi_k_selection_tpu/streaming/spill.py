"""Survivor spill store — the per-device on-disk chunk cache that lets the
out-of-core descent shrink geometrically instead of replaying the source.

The chunked descent (streaming/chunked.py) is a ``key_bits / radix_bits``-
pass walk, and without a cache EVERY pass re-streams the entire source:
a P-pass descent over an out-of-core input moves ~P·N key bytes across the
host->device boundary when only pass 0 actually needs all N. The reference
CGM's core perf idea is the opposite discipline — discard the partitions
that provably cannot hold the k-th element and recurse on a shrinking
window (``TODO-kth-problem-cgm.c`` L/E/G counts + window rebase). This
module is that discipline applied to the streaming axis:

- pass 0 TEES each chunk's encoded keys to a spill *generation* (written on
  the pipeline's producer thread, so the disk write overlaps device
  compute);
- every later pass reads the previous generation, filters each chunk to the
  surviving prefixes ON its owning device, and writes only the compacted
  survivors — ~1/2^radix_bits of the prior generation — as the next
  generation;
- total bytes streamed drop from ~P·N to ~N·(2 + 1/2^b + 1/4^b + ...), and
  one-shot (non-replayable) sources become first-class: passes >= 1 never
  touch the source.

Records are bucket-sized and keyed by ``(chunk_index, bucket, dtype,
device)`` — the :class:`~mpi_k_selection_tpu.streaming.pipeline.
StagingPool` key plus the chunk index — so a replay re-stages every chunk
onto the round-robin device that already compiled its bucket programs,
preserving the chunk->device determinism contract of the multi-device
ingest. Every record carries a CRC32 and a full metadata header; any
mismatch raises :class:`~mpi_k_selection_tpu.errors.SpillRecordError`
before a single key reaches a histogram (a corrupt cache fails loudly,
never answers wrong).

Disk bound: descents drop older generations eagerly, so an
internally-created store holds at most two generations at once —
~2·N·key_bytes worst case (adversarial duplicates), ~N·(1 + 1/2^b)
typically. A CALLER-owned store additionally keeps its pass-0 tee alive
for later calls, so its worst case is ~3·N·key_bytes (kept gen 0 + the
generation being read + the one being written), ~N typical.

Lifecycle: stores created internally by ``streaming_kselect{,_many}``
live in a ``ksel-spill-*`` temp directory and are removed on EVERY exit
path (success, consumer raise, producer raise — tests/conftest.py fails
any test that leaks one). Caller-owned stores (``spill=SpillStore(...)``,
or a sketch ``update_stream(..., spill=store)`` tee) keep their pass-0
generation so it can serve later calls (``refine``, the rank
certificate, a second descent); only descent-internal generations are
dropped.

This module is the ONE sanctioned file-writing surface under streaming/ —
lint rule KSL008 flags any other raw ``open``/``np.save``-class write
there, because a write that dodges the record keying, checksums and
cleanup discipline is exactly how a cache silently feeds a descent stale
or truncated survivors.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import struct
import tempfile
import zlib

import numpy as np

from mpi_k_selection_tpu.errors import SpillError, SpillRecordError
from mpi_k_selection_tpu.faults.inject import maybe_fault as _maybe_fault
from mpi_k_selection_tpu.obs import ledger as _ledger
from mpi_k_selection_tpu.resource_protocols import SPILL_DIR_PREFIX
from mpi_k_selection_tpu.streaming.pipeline import _bucket_elems

# SPILL_DIR_PREFIX (imported above): temp-directory prefix for
# internally-created stores; tests assert none outlive their call (the
# spill twin of pipeline.THREAD_NAME_PREFIX). Canonical value:
# resource_protocols.py (conftest + KSL020 registry).

#: The ``spill=`` knob's string modes (a SpillStore instance is also legal).
SPILL_MODES = ("auto", "off", "force")

_MAGIC = b"KSPILL1\x00"
_VERSION = 1
# magic, version, chunk_index, n_valid, bucket, device_slot,
# key dtype str, orig dtype str, payload crc32, payload nbytes
_HEADER = struct.Struct("<8sIqqqq8s8sIQ")


def validate_spill_mode(spill):
    """Normalize the ``spill`` knob: one of :data:`SPILL_MODES`, or an open
    :class:`SpillStore` to tee into / read from (caller-owned lifecycle)."""
    if isinstance(spill, SpillStore):
        if spill.closed:
            raise SpillError("spill store is closed")
        return spill
    if spill in SPILL_MODES:
        return spill
    raise ValueError(
        f"spill must be one of {SPILL_MODES} or a SpillStore, got {spill!r}"
    )


def _pack_dtype(dt) -> bytes:
    s = np.dtype(dt).str.encode("ascii")
    if len(s) > 8:  # pragma: no cover - no supported dtype exceeds '<u8'
        raise SpillError(f"dtype tag {s!r} exceeds the 8-byte record field")
    return s.ljust(8, b"\x00")


def _unpack_dtype(raw: bytes, path: str) -> np.dtype:
    try:
        return np.dtype(raw.rstrip(b"\x00").decode("ascii"))
    except (TypeError, UnicodeDecodeError) as e:
        raise SpillRecordError(f"spill record {path}: bad dtype tag {raw!r}") from e


@dataclasses.dataclass(frozen=True)
class SpillRecord:
    """On-disk metadata of one spilled chunk — the ``(chunk_index, bucket,
    dtype, device)`` key plus payload size/checksum. The header written to
    disk repeats all of it, and the reader cross-checks both."""

    path: str
    chunk_index: int
    n_valid: int
    bucket: int
    device_slot: int | None
    key_dtype: np.dtype
    orig_dtype: np.dtype
    crc32: int
    nbytes: int


@dataclasses.dataclass(frozen=True)
class SpillChunk:
    """One replayed chunk: already-encoded keys (host, key space) plus the
    staging metadata the pipeline needs to re-stage it onto the SAME
    round-robin slot that consumed it originally. ``streaming/chunked.py:
    _encode_chunk`` recognizes this type and skips re-encoding."""

    keys: np.ndarray
    orig_dtype: np.dtype
    device_slot: int | None
    chunk_index: int
    bucket: int


class SpillWriter:
    """Append-only writer for ONE spill generation. ``append`` is called
    from a single thread per pass (the pipeline's producer for the pass-0
    tee, the descent's consumer for the filtered survivor writes);
    ``commit``/``abort`` run after the pass's threads are joined."""

    def __init__(self, store: "SpillStore", index: int, path: str):
        self.store = store
        self.index = index
        self.path = path
        os.makedirs(path)
        self._records: list[SpillRecord] = []
        self._count = 0
        self._done = False

    def append(self, keys: np.ndarray, orig_dtype, device_slot=None) -> SpillRecord:
        """Write one chunk's encoded keys as a record. ``keys`` must be a
        host key-space array (the caller materializes device survivors);
        ``orig_dtype`` is the STREAM dtype the keys encode (recorded so a
        replay validates against the stream like any other chunk)."""
        if self._done:
            raise SpillError("spill generation already committed/aborted")
        # chaos hook, keyed by the record index WITHIN the generation
        # (ENOSPC, transient raise) — stable across recovery re-runs: a
        # re-run pass builds a fresh writer whose counts restart, so
        # re-appending record i advances the (site, i) ATTEMPT counter
        # instead of landing on a fresh index, which is what lets a plan
        # schedule both one-shot and hard write faults. Fires BEFORE
        # anything touches disk, so a recovered pass re-appends cleanly;
        # a real mid-write ENOSPC surfaces from the open/write below as
        # the same OSError class either way.
        _maybe_fault("spill.write", index=self._count)
        keys = np.ascontiguousarray(keys)
        if keys.ndim != 1:  # pragma: no cover - callers always ravel
            keys = keys.ravel()
        n = int(keys.shape[0])
        slot = -1 if device_slot is None else int(device_slot)
        rec_path = os.path.join(self.path, f"r{self._count:08d}.kspill")
        crc = zlib.crc32(keys.data) & 0xFFFFFFFF
        header = _HEADER.pack(
            _MAGIC,
            _VERSION,
            self._count,
            n,
            _bucket_elems(n),
            slot,
            _pack_dtype(keys.dtype),
            _pack_dtype(orig_dtype),
            crc,
            keys.nbytes,
        )
        with open(rec_path, "wb") as f:
            f.write(header)
            f.write(keys.data)
        rec = SpillRecord(
            path=rec_path,
            chunk_index=self._count,
            n_valid=n,
            bucket=_bucket_elems(n),
            device_slot=device_slot,
            key_dtype=np.dtype(keys.dtype),
            orig_dtype=np.dtype(orig_dtype),
            crc32=crc,
            nbytes=int(keys.nbytes),
        )
        self._records.append(rec)
        self._count += 1
        return rec

    def commit(self) -> "SpillGeneration":
        """Finalize: register the generation with the store and return it."""
        if self._done:
            raise SpillError("spill generation already committed/aborted")
        self._done = True
        gen = SpillGeneration(self.store, self.index, self.path, tuple(self._records))
        self.store._register(gen)
        return gen

    def abort(self) -> None:
        """Drop every record written so far (idempotent) — the unwind path
        when the pass feeding this generation raises mid-stream."""
        if self._done:
            return
        self._done = True
        shutil.rmtree(self.path, ignore_errors=True)


class SpillGeneration:
    """One committed generation: an ordered, replayable set of records.
    ``as_source()`` is a valid chunk source for every streaming entry
    point — each invocation re-reads (and re-validates) the records."""

    def __init__(self, store, index: int, path: str, records: tuple):
        self.store = store
        self.index = index
        self.path = path
        self.records = records
        self.dropped = False

    @property
    def nbytes(self) -> int:
        """Total payload bytes (the bytes a pass reading this gen streams)."""
        return sum(r.nbytes for r in self.records)

    @property
    def keys(self) -> int:
        return sum(r.n_valid for r in self.records)

    def iter_chunks(self, mmap: bool = False):
        """Yield every record as a :class:`SpillChunk`, validating headers,
        sizes and checksums — any mismatch raises
        :class:`~mpi_k_selection_tpu.errors.SpillRecordError`. With
        ``mmap`` the payload is served as a read-only ``np.memmap`` view
        (page-cache backed, checksummed in place) instead of a fresh heap
        copy — the deferred executor's replay mode, where most of each
        record's bytes are about to be filtered away on device anyway."""
        if self.dropped:
            raise SpillError(
                f"spill generation {self.index} was dropped (or its store "
                "closed); it can no longer serve as a chunk source"
            )
        for rec in self.records:
            yield _read_record(rec, mmap=mmap)

    def as_source(self, mmap: bool = False):
        """Zero-arg callable returning a fresh record iterator — the
        replayable chunk-source form streaming/chunked.py consumes."""
        if not mmap:
            return self.iter_chunks
        import functools

        return functools.partial(self.iter_chunks, mmap=True)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"SpillGeneration(index={self.index}, records={len(self.records)}, "
            f"keys={self.keys}, nbytes={self.nbytes})"
        )


def _read_record(rec: SpillRecord, mmap: bool = False) -> SpillChunk:
    # chaos hook, keyed by the record's chunk index: transient raises and
    # checksum blips fire here; the persistent kinds (corrupt_disk,
    # truncate) damage the file on disk and fall through, so the REAL
    # header/size/CRC validation below is what raises — the recovery
    # ladder (streaming/chunked.py:_recover_pass) is exercised against
    # the production error surface, not a simulated one.
    _maybe_fault("spill.read", index=rec.chunk_index, path=rec.path)
    try:
        f = open(rec.path, "rb")
    except OSError as e:
        raise SpillRecordError(f"spill record {rec.path}: unreadable ({e})") from e
    with f:
        head = f.read(_HEADER.size)
        if len(head) != _HEADER.size:
            raise SpillRecordError(
                f"spill record {rec.path}: truncated header "
                f"({len(head)} of {_HEADER.size} bytes)"
            )
        (
            magic, version, chunk_index, n_valid, bucket, slot,
            key_dt_raw, orig_dt_raw, crc, nbytes,
        ) = _HEADER.unpack(head)
        if magic != _MAGIC or version != _VERSION:
            raise SpillRecordError(
                f"spill record {rec.path}: bad magic/version "
                f"({magic!r}, {version})"
            )
        key_dt = _unpack_dtype(key_dt_raw, rec.path)
        orig_dt = _unpack_dtype(orig_dt_raw, rec.path)
        meta = (
            chunk_index, n_valid, bucket,
            None if slot < 0 else slot, key_dt, orig_dt, crc, nbytes,
        )
        want = (
            rec.chunk_index, rec.n_valid, rec.bucket,
            rec.device_slot, rec.key_dtype, rec.orig_dtype, rec.crc32, rec.nbytes,
        )
        if meta != want:
            raise SpillRecordError(
                f"spill record {rec.path}: header does not match the "
                f"writer's metadata (header {meta}, expected {want})"
            )
        if nbytes != n_valid * key_dt.itemsize:
            raise SpillRecordError(
                f"spill record {rec.path}: payload size {nbytes} != "
                f"{n_valid} x {key_dt.itemsize}-byte keys"
            )
        if not mmap:
            payload = f.read(nbytes)
            if len(payload) != nbytes:
                raise SpillRecordError(
                    f"spill record {rec.path}: truncated payload "
                    f"({len(payload)} of {nbytes} bytes)"
                )
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise SpillRecordError(
                    f"spill record {rec.path}: checksum mismatch (corrupt payload)"
                )
            keys = np.frombuffer(payload, dtype=key_dt)
    if mmap and n_valid == 0:  # pragma: no cover - writers skip empty chunks
        keys = np.empty((0,), key_dt)
    elif mmap:
        # read-only page-cache view of the payload (no heap copy); the
        # checksum still runs over EVERY payload byte before a single key
        # reaches a consumer — mmap changes residency, never the contract
        try:
            keys = np.memmap(  # read-only payload view inside the sanctioned spill module (KSL008 exempts spill.py; the staleness audit retired the old noqa)
                rec.path, dtype=key_dt, mode="r",
                offset=_HEADER.size, shape=(int(n_valid),),
            )
        except (OSError, ValueError) as e:
            raise SpillRecordError(
                f"spill record {rec.path}: truncated payload (mmap of "
                f"{nbytes} bytes failed: {e})"
            ) from e
        if (zlib.crc32(keys) & 0xFFFFFFFF) != crc:
            raise SpillRecordError(
                f"spill record {rec.path}: checksum mismatch (corrupt payload)"
            )
    return SpillChunk(
        keys=keys,
        orig_dtype=orig_dt,
        device_slot=None if slot < 0 else int(slot),
        chunk_index=int(chunk_index),
        bucket=int(bucket),
    )


class SpillStore:
    """A directory of spill generations plus the per-pass streaming log.

    Create one explicitly to own the lifecycle (tee a sketch's single
    stream pass, inspect ``pass_log`` after a descent, reuse gen 0 across
    calls), or let ``streaming_kselect{,_many}`` create and clean one up
    internally (``spill='force'``, or ``'auto'`` with a one-shot source).
    Context-manager protocol closes (removes) the directory.
    """

    def __init__(self, spill_dir: str | None = None):
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        self.root = tempfile.mkdtemp(prefix=SPILL_DIR_PREFIX, dir=spill_dir)
        self.generations: dict[int, SpillGeneration] = {}
        #: One dict per streamed pass of a spill-enabled descent:
        #: ``{"pass", "read", "keys_read", "bytes_read"[, "keys_written",
        #: "bytes_written"]}`` — the raw material of bench_streaming_oc's
        #: ``_spill`` record (pass_shrink_ratio).
        self.pass_log: list[dict] = []
        self._counter = 0
        self._closed = False

    @property
    def closed(self) -> bool:
        return self._closed

    def _check_open(self) -> None:
        if self._closed:
            raise SpillError("spill store is closed")

    def new_generation(self) -> SpillWriter:
        self._check_open()
        idx = self._counter
        self._counter += 1
        return SpillWriter(self, idx, os.path.join(self.root, f"gen-{idx:04d}"))

    def _register(self, gen: SpillGeneration) -> None:
        self._check_open()
        self.generations[gen.index] = gen
        # the on-disk byte book (obs/ledger.py): committed generations add
        # their payload bytes, drop/close subtracts them exactly once
        _ledger.LEDGER.adjust_bytes("spill", "disk", gen.nbytes)

    def latest_generation(self) -> SpillGeneration:
        """The newest committed generation — what a store-as-source read
        (``streaming_kselect(store, k)``, the certificate, ``refine``)
        streams from."""
        self._check_open()
        if not self.generations:
            raise SpillError(
                "spill store holds no committed generation; run a teeing "
                "pass first (streaming_kselect(..., spill=store) or "
                "RadixSketch.update_stream(..., spill=store))"
            )
        return self.generations[max(self.generations)]

    def drop_generation(self, gen: SpillGeneration) -> None:
        """Delete one generation's records (the eager disk-bound trim:
        at most two generations coexist during a descent)."""
        gen.dropped = True
        if self.generations.pop(gen.index, None) is not None:
            # pop-guarded so a double drop cannot double-subtract
            _ledger.LEDGER.adjust_bytes("spill", "disk", -gen.nbytes)
        shutil.rmtree(gen.path, ignore_errors=True)

    def close(self) -> None:
        """Remove the whole store directory. Idempotent; every generation
        becomes unreadable (``dropped``)."""
        if self._closed:
            return
        self._closed = True
        for gen in self.generations.values():
            gen.dropped = True
            _ledger.LEDGER.adjust_bytes("spill", "disk", -gen.nbytes)
        self.generations.clear()
        shutil.rmtree(self.root, ignore_errors=True)

    def __enter__(self) -> "SpillStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "closed" if self._closed else f"{len(self.generations)} gens"
        return f"SpillStore({self.root!r}, {state})"
