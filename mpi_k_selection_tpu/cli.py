"""Command-line driver: ``python -m mpi_k_selection_tpu`` (or ``kselect``).

The reference's entry points are two compiled binaries with every parameter a
compile-time constant (``kth-problem-seq.c:7,24``; ``TODO-kth-problem-cgm.c:
44-48`` — changing k meant recompiling, hence the ``~`` backup files). This
CLI is the configurable replacement mandated by the north star:
``--backend={seq,mpi,tpu}`` plus the full parameter surface, with defaults
matching the reference constants (config.py).

Examples::

    # reference sequential operating point (N=1e8, k=250) on the CPU oracle
    kselect --backend seq --n 100000000 --k 250

    # TPU radix select, median of 1B int32
    kselect --backend tpu --n 1000000000

    # distributed CGM parity algorithm over all devices
    kselect --backend tpu --algorithm cgm --n 16000000 --verify

    # top-k mode (MoE-router config from BASELINE.md)
    kselect --backend tpu --gen normal --dtype float32 --n 67108864 --topk 128

    # resident-dataset query server: load once, answer many clients
    # (POST /v1/query, GET /metrics; see docs/API.md "Serving")
    kselect serve --n 100000000 --dtype int32 --port 8080

    # continuous telemetry quantiles over an unbounded stream: one
    # exactly-bounded p50/p90/p99 sample per window advance
    # (docs/OBSERVABILITY.md "Continuous monitoring")
    kselect monitor --window 32 --emit-every 4 --buckets 100 --json
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from mpi_k_selection_tpu import config
from mpi_k_selection_tpu.utils import datagen
from mpi_k_selection_tpu.utils.timing import ResultRecord, time_fn
from mpi_k_selection_tpu.utils.x64 import maybe_x64

DTYPES = (
    "int32",
    "int64",
    "uint32",
    "float32",
    "float64",
    "float16",
    "int16",
    "bfloat16",
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kselect",
        description="TPU-native exact k-selection (capabilities of MPI-k-selection)",
    )
    p.add_argument("--backend", choices=("seq", "tpu", "mpi"), default="tpu")
    p.add_argument("--n", type=int, default=1 << 20, help="number of elements")
    p.add_argument(
        "--k", type=int, default=None,
        help="1-indexed rank (default: N/2, the reference's median operating point)",
    )
    p.add_argument("--gen", choices=datagen.PATTERNS, default="uniform")
    p.add_argument("--dtype", choices=DTYPES, default="int32")
    p.add_argument("--seed", type=int, default=config.DEFAULT_SEED)
    p.add_argument(
        "--algorithm", choices=("auto", "radix", "sort", "cgm"), default="auto",
        help="selection algorithm (tpu backend); cgm is the reference-parity protocol",
    )
    p.add_argument(
        "--distribute", choices=("auto", "never", "always"), default="auto",
        help="shard over all devices (tpu backend)",
    )
    p.add_argument(
        "--devices", type=int, default=None,
        help="mesh size cap; with --streaming, the number of chips the "
        "pipelined ingest stages chunks onto round-robin (default 1; "
        "answers bit-identical at every count)",
    )
    p.add_argument(
        "--num-procs", type=int, default=4,
        help="process count for the mpi backend (reference: mpirun -np P)",
    )
    p.add_argument(
        "--c", type=int, default=config.REFERENCE_C,
        help="CGM coarseness constant (mpi backend; TODO-kth-problem-cgm.c:44)",
    )
    p.add_argument("--topk", type=int, default=None, help="return top-k instead of k-th")
    p.add_argument(
        "--quantiles",
        default=None,
        help="comma-separated quantiles in [0,1] (e.g. 0.5,0.9,0.99): exact "
        "nearest-rank order statistics, amortized over one prepared pass",
    )
    p.add_argument("--smallest", action="store_true", help="top-k smallest instead of largest")
    p.add_argument(
        "--batch", type=int, default=None,
        help="batch rows for top-k: the input becomes shape (batch, n), "
        "i.e. batch INDEPENDENT rows of n elements each (total batch*n)",
    )
    p.add_argument(
        "--topk-method",
        choices=("auto", "flat", "chunked", "threshold", "tournament", "block"),
        default="auto",
        help="top-k algorithm (see ops/topk.py; block = the Pallas batched "
        "values kernel, 2-D float32 largest k<=8)",
    )
    p.add_argument(
        "--streaming",
        action="store_true",
        help="out-of-core mode: the input is generated and consumed in "
        "chunks of --chunk-elems and never materialized whole; exact k-th "
        "selection via the streaming subsystem (k-th mode only). Each chunk "
        "i is generated independently with seed+i, so structured --gen "
        "patterns (sequential/descending/seqlike) become per-chunk ramps "
        "and answers are NOT comparable to non-streaming runs at the same "
        "seed; --verify/--check stay self-consistent",
    )
    p.add_argument(
        "--chunk-elems", type=int, default=1 << 22,
        help="chunk size (elements) for --streaming",
    )
    p.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="--streaming ingest pipelining: number of chunks produced/"
        "encoded/staged ahead on a background thread (0 = fully "
        "synchronous, the correctness oracle; default 2 = double "
        "buffering). Answers are bit-identical at every depth",
    )
    p.add_argument(
        "--spill", choices=("auto", "off", "force"), default="auto",
        help="--streaming survivor spill store: tee pass-0 keys to disk "
        "and serve later passes from the geometrically-shrinking spilled "
        "survivors instead of replaying the source (auto = only for "
        "one-shot sources — the CLI's generated stream is replayable, so "
        "auto stays on the replay path; force = always spill; off = "
        "never). Answers are bit-identical in every mode",
    )
    p.add_argument(
        "--spill-dir", default=None,
        help="directory for --spill stores (default: the system temp dir); "
        "worst-case footprint ~2x the stream's key bytes (~3x for "
        "caller-owned stores that keep their pass-0 generation)",
    )
    p.add_argument(
        "--deferred", choices=("auto", "on", "off"), default="auto",
        help="--streaming per-chunk consumption discipline (the async "
        "executor, streaming/executor.py): auto/on (default) dispatch "
        "each staged chunk's survivor filters (collect, spill tee) and "
        "certificate counts as device-side fixed-shape programs that "
        "materialize host-side only when the p-wide FIFO window pops — "
        "multi-device collect/spill passes scale like the histogram "
        "passes; off = the historical eager gather at chunk-arrival "
        "time. Answers are bit-identical in every mode",
    )
    p.add_argument(
        "--fused", choices=("auto", "kernel", "xla", "off"), default="auto",
        help="--streaming single-read ingest tier: kernel = the "
        "hand-written single-sweep pallas program "
        "(ops/pallas/sweep_ingest.py — one GUARANTEED HBM read per "
        "staged bucket per pass; interpret-mode off TPU), xla = the "
        "one-XLA-program fusion (ops/pallas/fused_ingest.py — one "
        "dispatch), off = the unfused consumer bundle (the bit-for-bit "
        "oracle), auto (default) = kernel on TPU, xla elsewhere. "
        "Answers are bit-identical at every tier",
    )
    p.add_argument(
        "--width-schedule", default="off", metavar="auto|off|W0,W1,...",
        help="--streaming per-pass digit widths: off (default) = "
        "radix_bits every pass (the bit-for-bit oracle), auto = one WIDE "
        "first digit (up to 16 bits, int32-partial-safe) so the first "
        "spill generation shrinks to ~n/2^16 survivors, or an explicit "
        "comma-separated width list summing to the key width. Answers "
        "are bit-identical for every schedule",
    )
    p.add_argument(
        "--pack-spill", choices=("auto", "off"), default="off",
        help="--streaming prefix-packed spill records: auto stores only "
        "each survivor's still-unresolved low bits (bit-packed, "
        "per-segment CRC'd, format-versioned) and digit-segments the "
        "pass-0 tee so later passes read ONLY surviving segments; off "
        "(default) = the unpacked v1 records. Answers and replayed keys "
        "are bit-identical either way",
    )
    p.add_argument(
        "--ingest-workers", default=None, metavar="auto|N",
        help="--streaming host data-plane width: N > 1 (or auto = "
        "min(4, cores)) runs chunk encode, spill-tee packing and device "
        "staging on a pool of ksel-ingest-* workers behind a reorder "
        "sequencer that releases chunks strictly in stream order; 1 "
        "(default) = the single-producer plane. Answers, pass logs and "
        "spill records are bit-identical at every width",
    )
    p.add_argument(
        "--retry", choices=("default", "off"), default="default",
        help="--streaming resilience policies (faults/, docs/ROBUSTNESS.md): "
        "default = bounded retry (3 attempts, exponential backoff) for "
        "transient source/staging failures, pass re-runs from the previous "
        "spill generation, the corrupt-record re-read/rebuild ladder, and "
        "the ENOSPC spill downgrade; off = fail on the first fault (the "
        "pre-resilience behavior). Recovered answers are bit-identical",
    )
    p.add_argument(
        "--chaos", type=int, default=None, metavar="SEED",
        help="--streaming fault-injection harness (faults/): run the solve "
        "under a FaultPlan.seeded(SEED) — deterministic transient "
        "source/staging raises, spill-record corruption, stalls — and "
        "record what fired and what recovered in the result record's "
        "'chaos' entry. The same SEED replays the same faults; "
        "--verify/--check still hold, proving recovery changed no answer "
        "bit. Faults are injected on the FIRST touch of each chosen "
        "site/index, so with --repeats > 1 later repeats run fault-free",
    )
    p.add_argument("--repeats", type=int, default=1)
    p.add_argument("--verify", action="store_true", help="check against the seq oracle")
    p.add_argument(
        "--check",
        action="store_true",
        help="verify the answer's rank certificate (O(n) count, no oracle sort)",
    )
    p.add_argument("--json", action="store_true", help="emit a JSON result record")
    p.add_argument(
        "--profile", action="store_true", help="print per-phase wall timing"
    )
    p.add_argument(
        "--trace-dir",
        default=None,
        help="write a jax.profiler device trace (TensorBoard format) here",
    )
    p.add_argument(
        "--metrics-json",
        default=None,
        metavar="PATH",
        help="write the run's obs metrics registry (StagingPool hits/"
        "misses, pipeline stall seconds, in-flight window occupancy, "
        "chunks/bytes per ingest device, spilled bytes, per-phase wall "
        "time) as JSON to PATH; composes with --profile/--trace-dir. "
        "Counters and phase totals accumulate over ALL --repeats (the "
        "exported run.repeats gauge carries the divisor). See "
        "docs/OBSERVABILITY.md for the metric catalog (the registry "
        "also renders Prometheus text exposition programmatically)",
    )
    p.add_argument(
        "--trace-events",
        default=None,
        metavar="PATH",
        help="write host-thread spans (producer produce/encode/stage/"
        "spill vs consumer stall/pass/collect) as Chrome trace-event "
        "JSON to PATH — load in https://ui.perfetto.dev. Distinct from "
        "--trace-dir (XLA device ops); the two compose",
    )
    p.add_argument(
        "--debug-bundle",
        default=None,
        metavar="PATH",
        help="arm the flight recorder (obs/flight.py: a bounded ring of "
        "recent typed events + spans) and write the JSON debug bundle "
        "— events tail, metrics snapshot, process ledger, span tracks, "
        "fault section — to PATH at exit, success or failure. Terminal "
        "failures (RetryExhaustedError, unrecoverable spill damage) "
        "additionally auto-dump one ksel-flight-*.json bundle the "
        "moment they fire. See docs/OBSERVABILITY.md 'Flight recorder'",
    )
    return p


def _run_kth(args, x):
    from mpi_k_selection_tpu.backends import get_backend

    n = x.size
    k = args.k if args.k is not None else max(1, n // 2)
    if not 1 <= k <= n:
        raise SystemExit(f"error: k={k} out of range [1, {n}]")
    backend = get_backend(args.backend)
    rounds = None
    # record what actually runs, not what was asked for (seq always uses
    # partition/nth_element; the tpu backend resolves "auto" and may distribute)
    effective_algorithm = args.algorithm
    if args.backend == "seq":
        effective_algorithm = "partition"
        fn = lambda: backend.kselect(x, k)
    elif args.backend == "mpi":
        from mpi_k_selection_tpu.native import cgm_driver

        effective_algorithm = "cgm"
        fn = lambda: cgm_driver.kselect_full(x, k, num_procs=args.num_procs, c=args.c)[
            :2
        ]
    else:
        from mpi_k_selection_tpu import api as _api

        xd = _api.as_selection_array(x)
        effective_algorithm, distributed = backend.plan(
            n, args.algorithm, args.distribute
        )
        if effective_algorithm == "cgm":
            # CGM resolves through the same planner as radix; it carries a
            # per-run round count worth recording, so invoke it directly
            from mpi_k_selection_tpu.parallel import distributed_cgm_select, make_mesh

            mesh = make_mesh(args.devices)
            fn = lambda: distributed_cgm_select(xd, k, mesh=mesh, return_rounds=True)
            effective_algorithm = "cgm-distributed"
        else:
            if distributed:
                effective_algorithm = "radix-distributed"
            fn = lambda: backend.kselect(
                xd, k, algorithm=args.algorithm, distribute=args.distribute
            )
    seconds, answer = time_fn(fn, repeats=args.repeats, warmup=1 if args.backend == "tpu" else 0)
    if isinstance(answer, tuple):  # cgm returns (value, rounds)
        answer, rounds = answer
        rounds = int(np.asarray(rounds))
    answer = np.asarray(answer).item()
    record = ResultRecord(
        answer=answer,
        n=n,
        k=k,
        backend=args.backend,
        algorithm=effective_algorithm,
        dtype=args.dtype,
        seconds=seconds,
        n_devices=_device_count(args),
        rounds=rounds,
    )
    ok = True
    if args.verify:
        from mpi_k_selection_tpu.backends import seq

        want = np.asarray(seq.kselect(x, k)).item()
        ok = answer == want
        record.extra["oracle"] = want
        record.extra["exact_match"] = ok
    return record, ok


def _run_quantiles(args, x):
    import jax.numpy as jnp

    from mpi_k_selection_tpu.api import quantile_ranks
    from mpi_k_selection_tpu.backends import get_backend

    try:
        qs = [float(s) for s in args.quantiles.split(",") if s.strip()]
    except ValueError as e:
        raise SystemExit(f"error: bad --quantiles value: {e}") from e
    if args.backend != "tpu":
        raise SystemExit("error: --quantiles runs on the tpu backend")
    if args.algorithm not in ("auto", "radix"):
        raise SystemExit(
            f"error: --quantiles supports --algorithm auto|radix "
            f"(multi-rank selection is a radix-descent path), not "
            f"{args.algorithm!r}"
        )
    from mpi_k_selection_tpu import api as _api

    xd = _api.as_selection_array(x)
    backend = get_backend("tpu")
    # the backend owns the whole dispatch (plan_many + rank conversion +
    # mesh path); the CLI re-plans only to label the result record —
    # plan_many is pure, so the label always matches what executed
    fn = lambda: backend.quantiles(
        xd, qs, distribute=args.distribute, devices=args.devices
    )
    mesh = backend.plan_many(x.size, args.distribute, args.devices)
    algorithm = "quantiles-distributed" if mesh is not None else "quantiles"
    n_devices = mesh.size if mesh is not None else 1
    seconds, values = time_fn(fn, repeats=args.repeats, warmup=1)
    values = np.asarray(values)
    record = ResultRecord(
        answer=values.tolist(),
        n=x.size,
        k=0,
        backend=args.backend,
        algorithm=algorithm,
        dtype=args.dtype,
        seconds=seconds,
        n_devices=n_devices,
    )
    record.extra["quantiles"] = qs
    ok = True
    if args.verify:
        s = np.sort(x.ravel(), kind="stable")
        want = s[np.asarray(quantile_ranks(qs, x.size)) - 1]
        ok = np.array_equal(values, want)
        record.extra["exact_match"] = ok
    return record, ok


def _chunk_source(args):
    """Replayable chunk generator for --streaming: chunk i is
    ``datagen.generate(..., seed=seed+i)``, so the stream is deterministic
    and identical on every pass (the replay-stability contract of
    streaming/chunked.py) while no more than --chunk-elems elements ever
    exist at once."""
    n, chunk = args.n, args.chunk_elems

    def source():
        off = i = 0
        while off < n:
            m = min(chunk, n - off)
            yield datagen.generate(
                m, pattern=args.gen, seed=args.seed + i, dtype=args.dtype
            )
            off += m
            i += 1

    return source


def _parse_ingest_workers(raw):
    """``--ingest-workers`` arrives as a string (or None): keep ``auto``
    and None symbolic, convert digits to int, and let the pipeline's
    resolver reject everything else with its canonical message."""
    if raw is None or raw == "auto":
        return raw
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise SystemExit(
            f"error: --ingest-workers must be auto or an int, got {raw!r}"
        )


def _run_streaming(args, obs=None):
    from mpi_k_selection_tpu.api import kselect_streaming
    from mpi_k_selection_tpu.streaming.chunked import streaming_rank_certificate

    n = args.n
    if args.chunk_elems < 1:
        raise SystemExit("error: --chunk-elems must be >= 1")
    k = args.k if args.k is not None else max(1, n // 2)
    if not 1 <= k <= n:
        raise SystemExit(f"error: k={k} out of range [1, {n}]")
    from mpi_k_selection_tpu.streaming.pipeline import (
        resolve_ingest_workers,
        resolve_stream_devices,
        validate_pipeline_depth,
    )

    depth = validate_pipeline_depth(args.pipeline_depth)
    ingest_workers = _parse_ingest_workers(args.ingest_workers)
    try:
        n_workers = resolve_ingest_workers(ingest_workers)
    except (ValueError, TypeError) as e:
        raise SystemExit(f"error: {e}")
    # --width-schedule accepts the mode strings or a comma-separated
    # per-pass width list; validate eagerly so a typo is a clean
    # SystemExit instead of a mid-descent ValueError
    from mpi_k_selection_tpu.streaming.chunked import validate_width_schedule

    width_schedule = args.width_schedule
    if width_schedule not in ("auto", "off"):
        try:
            width_schedule = tuple(
                int(w) for w in width_schedule.split(",") if w.strip()
            )
        except ValueError:
            raise SystemExit(
                f"error: --width-schedule must be auto, off, or "
                f"comma-separated ints, got {args.width_schedule!r}"
            )
    try:
        validate_width_schedule(width_schedule)
    except ValueError as e:
        raise SystemExit(f"error: {e}")
    # --devices caps the round-robin ingest set (seq backend = host
    # histograms, no devices to spread over)
    devices = args.devices if args.backend != "seq" else None
    n_ingest = len(resolve_stream_devices(devices))
    source = _chunk_source(args)
    # the seq backend answers from host histograms; tpu streams chunks
    # through the device kernels (ops/histogram.py resolves the method)
    hist_method = "numpy" if args.backend == "seq" else "auto"
    # --profile: a DEDICATED PhaseTimer for the pipeline's produce/encode/
    # stage/stall phases — they run CONCURRENTLY with the solve phase, so
    # folding them into the solve timer would inflate its total past wall
    # time and skew every percentage in the report. --trace-events needs
    # the same timer (spans are timestamped by PhaseTimer; the recorder
    # is attached by the descent via the obs bundle), and --metrics-json
    # needs it too: the registry's phase.seconds{pipeline.stall} etc. are
    # collected FROM this timer by the descent's collect_runtime
    from mpi_k_selection_tpu.utils import profiling

    ptimer = (
        profiling.PhaseTimer()
        if args.profile or args.trace_events or args.metrics_json
        else None
    )
    # --spill=force with a single run routes through a CLI-owned store so
    # the per-pass streamed-bytes log rides the result record (and the
    # --check certificate replays the spilled keys instead of regenerating
    # the stream). With --repeats, each run tees a fresh generation into a
    # caller-owned store — pass the mode string instead, so every repeat
    # cleans up its own internal store. auto/off always pass the string
    # (the generated source is replayable, so auto = the replay path).
    from mpi_k_selection_tpu.streaming.spill import SpillStore

    spill_store = (
        SpillStore(args.spill_dir)
        if args.spill == "force" and args.repeats <= 1
        else None
    )
    # the try owns the store from the moment it exists: a failure while
    # ARMING the solve (FaultPlan seeding, a chaos-armed constructor)
    # used to strand the fresh ksel-spill-* dir — the store was built
    # before the try whose finally closes it (KSL020's first whole-repo
    # run caught this; tests/test_lifecycle.py holds the regression)
    try:
        # --chaos SEED: arm the seeded fault-injection harness around the
        # solve (faults/). The solve's source is wrapped so scheduled pulls
        # fail; --verify/--check below use the UNWRAPPED source, so the
        # exactness checks judge the RECOVERED answer against clean reads.
        import contextlib

        injector = None
        solve_source = source
        inject_ctx = contextlib.nullcontext()
        if args.chaos is not None:
            from mpi_k_selection_tpu.faults import FaultInjector, FaultPlan
            from mpi_k_selection_tpu.faults import inject as _arm

            nchunks_plan = max(1, -(-n // args.chunk_elems))
            injector = FaultInjector(
                FaultPlan.seeded(args.chaos, n_chunks=nchunks_plan), obs=obs
            )
            solve_source = injector.wrap_chunk_source(source)
            inject_ctx = _arm(injector)
        fn = lambda: kselect_streaming(
            solve_source, k, hist_method=hist_method, pipeline_depth=depth,
            timer=ptimer,
            devices=devices,
            spill=spill_store if spill_store is not None else args.spill,
            spill_dir=args.spill_dir,
            deferred=args.deferred,
            fused=args.fused,
            width_schedule=width_schedule,
            pack_spill=args.pack_spill,
            ingest_workers=ingest_workers,
            retry=args.retry,
            obs=obs,
        )
        with inject_ctx:
            seconds, answer = time_fn(fn, repeats=args.repeats, warmup=0)
        record = ResultRecord(
            answer=np.asarray(answer).item(),
            n=n,
            k=k,
            backend=args.backend,
            algorithm="streaming-chunked",
            dtype=args.dtype,
            seconds=seconds,
            # streaming: the devices actually staged onto, not the host total
            n_devices=n_ingest,
        )
        nchunks = -(-n // args.chunk_elems)
        record.extra["chunks"] = nchunks
        record.extra["chunk_elems"] = args.chunk_elems
        record.extra["pipeline_depth"] = depth
        record.extra["ingest_devices"] = n_ingest
        record.extra["spill"] = args.spill
        record.extra["deferred"] = args.deferred
        record.extra["fused"] = args.fused
        record.extra["width_schedule"] = (
            list(width_schedule)
            if isinstance(width_schedule, tuple)
            else width_schedule
        )
        record.extra["pack_spill"] = args.pack_spill
        # the RESOLVED pool width (auto pinned to this host's answer), so
        # a recorded run names the plane it actually used
        record.extra["ingest_workers"] = n_workers
        record.extra["retry"] = args.retry
        if injector is not None:
            record.extra["chaos"] = {
                "seed": args.chaos,
                "plan": [
                    {
                        "site": s.site, "index": s.index, "kind": s.kind,
                        "attempts": list(s.attempts),
                    }
                    for s in injector.plan.specs
                ],
                "fired": list(injector.fired),
            }
        if spill_store is not None:
            record.extra["spill_passes"] = list(spill_store.pass_log)
        if ptimer is not None and ptimer.phases:
            from mpi_k_selection_tpu.streaming.pipeline import ingest_hidden_frac

            # phases accumulate across --repeats while `seconds` is the best
            # single run: report per-repeat seconds so the two are comparable
            # (ingest_hidden_frac is a ratio of same-scale sums — unaffected)
            reps = max(1, args.repeats)
            record.extra["pipeline_phases"] = {
                name: {
                    "seconds": d["seconds"] / reps,
                    "calls": max(1, d["calls"] // reps),
                }
                for name, d in ptimer.as_dict().items()
            }
            hidden = ingest_hidden_frac(ptimer)
            if hidden is not None:
                record.extra["ingest_hidden_frac"] = round(hidden, 4)
        ok = True
        if args.verify:
            # the oracle NEEDS the whole array resident — only meaningful at
            # sizes where that is still possible; --check stays streaming
            from mpi_k_selection_tpu.backends import seq

            x = np.concatenate([np.ravel(c) for c in source()])
            want = np.asarray(seq.kselect(x, k)).item()
            ok = record.answer == want
            record.extra["oracle"] = want
            record.extra["exact_match"] = ok
        if args.check:
            # no timer here: the profile snapshot above covers the solve only
            # (the report is labeled "concurrent with solve"), and phases
            # recorded after it would be silently dropped anyway. With a
            # spill store in hand, the certificate replays the spilled gen-0
            # keys — the one-shot-friendly path — instead of regenerating.
            # the certificate pass shares only the TRACE channel: its spans
            # belong on the same timeline, but letting it share the metrics
            # registry would overwrite the SOLVE's phase gauges (its
            # collect_runtime snapshots a fresh timer) and additively
            # pollute the per-device chunk/byte counters --metrics-json
            # documents as the solve's
            cert_obs = None
            if obs is not None and obs.trace is not None:
                from mpi_k_selection_tpu import obs as obs_lib

                cert_obs = obs_lib.Observability(trace=obs.trace)
            # under --chaos, persistent disk faults (corrupt_disk,
            # truncate) may have damaged the CLI-owned store's gen-0
            # records — the SOLVE recovered by rebuilding from the
            # source, but a certificate replaying the damaged store
            # would (correctly) raise SpillRecordError; certify against
            # the clean source instead, which is also the stronger check
            cert_src = (
                spill_store
                if spill_store is not None and injector is None
                else source
            )
            less, leq = streaming_rank_certificate(
                cert_src,
                answer, pipeline_depth=depth, devices=devices,
                deferred=args.deferred, fused=args.fused,
                width_schedule=width_schedule, pack_spill=args.pack_spill,
                ingest_workers=ingest_workers,
                retry=args.retry, obs=cert_obs,
            )
            cert_ok = less < k <= leq
            record.extra["rank_certificate"] = [less, leq]
            record.extra["certificate_ok"] = cert_ok
            ok = ok and cert_ok
        return record, ok
    finally:
        if spill_store is not None:
            spill_store.close()


def _run_topk(args, x):
    k = args.topk
    if args.backend == "seq":
        from mpi_k_selection_tpu.backends import seq

        fn = lambda: seq.topk(x, k, largest=not args.smallest)[0]
    else:
        import jax.numpy as jnp

        from mpi_k_selection_tpu.ops.topk import topk as _topk

        xd = jnp.asarray(x)
        fn = lambda: _topk(xd, k, largest=not args.smallest, method=args.topk_method)[0]
    seconds, values = time_fn(fn, repeats=args.repeats, warmup=1 if args.backend != "seq" else 0)
    values = np.asarray(values)
    record = ResultRecord(
        answer=values.ravel()[:8].tolist(),
        n=x.size,
        k=k,
        backend=args.backend,
        algorithm="topk",
        dtype=args.dtype,
        seconds=seconds,
        n_devices=_device_count(args),
    )
    ok = True
    if args.verify:
        from mpi_k_selection_tpu.backends import seq

        want, _ = seq.topk(x, k, largest=not args.smallest)
        ok = np.array_equal(values, want)
        record.extra["exact_match"] = ok
    return record, ok


def _device_count(args) -> int:
    if args.backend == "seq":
        return 1
    if args.backend == "mpi":
        return args.num_procs
    import jax

    n = len(jax.devices())
    return min(n, args.devices) if args.devices else n


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kselect serve",
        description=(
            "resident-dataset query server: load/shard a dataset once, "
            "answer kselect/quantile/top-k/rank-certificate queries from "
            "many concurrent clients (POST /v1/query, GET /v1/datasets, "
            "GET /metrics, GET /healthz)"
        ),
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--port", type=int, default=8080,
        help="listen port (0 = ephemeral; see --port-file)",
    )
    p.add_argument(
        "--port-file", default=None, metavar="PATH",
        help="write the bound port here after listen (for --port 0 callers)",
    )
    p.add_argument(
        "--dataset-id", default="default",
        help="id the generated dataset registers under",
    )
    p.add_argument("--n", type=int, default=1 << 20, help="dataset elements")
    p.add_argument("--gen", choices=datagen.PATTERNS, default="uniform")
    p.add_argument("--dtype", choices=DTYPES, default="int32")
    p.add_argument("--seed", type=int, default=config.DEFAULT_SEED)
    p.add_argument(
        "--streaming", action="store_true",
        help="register the dataset as an out-of-core stream (sketched "
        "once at startup; exact-tier queries replay the generated chunk "
        "source) instead of a resident array",
    )
    p.add_argument(
        "--chunk-elems", type=int, default=1 << 22,
        help="chunk size (elements) for --streaming",
    )
    p.add_argument(
        "--no-sketch", action="store_true",
        help="skip the resident sketch (disables the sketch/auto fast "
        "tiers; every query runs exact)",
    )
    p.add_argument("--sketch-bits", type=int, default=4)
    p.add_argument("--sketch-levels", type=int, default=4)
    p.add_argument(
        "--batch-window", type=float, default=0.002, metavar="SECONDS",
        help="cross-request coalescing window: after a query arrives the "
        "dispatch thread waits this long for more against the same "
        "dataset and answers them with ONE shared-pass walk (0 = no "
        "coalescing; answers bit-identical either way)",
    )
    p.add_argument(
        "--max-batch", type=int, default=1024,
        help="coalesced-request ceiling per dispatch",
    )
    p.add_argument(
        "--warmup", action="store_true",
        help="pre-build the dataset's selection programs (cached sort, "
        "walk closure + its width-1 compile, sketch pin path) at "
        "registration, so the first client query excludes the compile "
        "wall (the ledger's serve.programs book proves it)",
    )
    p.add_argument(
        "--lanes", default="auto", metavar="N|auto",
        help="dispatch lanes: 'auto' (default) opens one supervised "
        "dispatch thread per distinct execution device; an integer "
        "folds devices onto N lanes (1 = the single-thread PR 7 "
        "batcher; answers bit-identical at every setting)",
    )
    p.add_argument(
        "--no-fast-path", action="store_true",
        help="route sketch-tier (and auto-pinned) answers through the "
        "dispatch lane instead of answering inline on the request "
        "thread — the bit-for-bit oracle for the default fast path",
    )
    p.add_argument(
        "--quit-after", type=int, default=None, metavar="N",
        help="serve N HTTP requests, then exit cleanly (smoke/testing; "
        "default: serve until interrupted)",
    )
    p.add_argument(
        "--latency-windows", type=int, default=0, metavar="BUCKETS",
        help="back the per-tier serve.latency_seconds histograms with a "
        "BUCKETS-deep sliding-window RadixSketch, so /metrics p50/p90/"
        "p99 become windowed quantiles with EXACT rank/value bounds "
        "(gauge series ksel_serve_latency_seconds_windowed{tier,"
        "quantile}) instead of fixed-bucket interpolation (0 = off, the "
        "default; see docs/OBSERVABILITY.md 'Continuous monitoring')",
    )
    p.add_argument(
        "--latency-advance-every", type=int, default=256, metavar="OBS",
        help="observations per latency window bucket (with "
        "--latency-windows; the window advances on observation counts, "
        "never clocks)",
    )
    p.add_argument(
        "--debug-bundle", default=None, metavar="PATH",
        help="arm the server's flight recorder (a bounded ring of recent "
        "serve events + request/walk spans; also live at GET "
        "/debug/bundle) and write the JSON debug bundle to PATH at "
        "shutdown; a dispatch-loop crash auto-dumps one the moment the "
        "supervisor restarts it (docs/OBSERVABILITY.md)",
    )
    return p


def serve_main(argv=None) -> int:
    """``kselect serve ...`` — build the server, register the generated
    dataset, run the HTTP front on THIS thread until interrupted (or
    ``--quit-after`` requests), then tear everything down: HTTP request
    threads joined, dispatch thread joined, exit 0."""
    args = build_serve_parser().parse_args(argv)
    from mpi_k_selection_tpu import obs as obs_lib
    from mpi_k_selection_tpu.serve import KSelectHTTPServer, KSelectServer

    x64_needed = args.dtype in ("int64", "float64")
    obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    latency_windows = (
        dict(
            window=args.latency_windows,
            advance_every=args.latency_advance_every,
        )
        if args.latency_windows
        else None
    )
    try:
        lanes = args.lanes if args.lanes == "auto" else int(args.lanes)
    except ValueError:
        raise SystemExit(
            f"error: --lanes must be 'auto' or an integer, got {args.lanes!r}"
        ) from None
    with maybe_x64(x64_needed):
        server = KSelectServer(
            window=args.batch_window, max_batch=args.max_batch, obs=obs,
            latency_windows=latency_windows,
            fast_path=not args.no_fast_path, lanes=lanes,
            flight=True if args.debug_bundle else None,
        )
        try:
            if args.streaming:
                if args.chunk_elems < 1:
                    raise SystemExit("error: --chunk-elems must be >= 1")
                server.add_dataset(
                    args.dataset_id,
                    source=_chunk_source(args),
                    warmup=args.warmup,
                    sketch=not args.no_sketch,
                    sketch_bits=args.sketch_bits,
                    sketch_levels=args.sketch_levels,
                )
            else:
                x = datagen.generate(
                    args.n, pattern=args.gen, seed=args.seed, dtype=args.dtype
                )
                server.add_dataset(
                    args.dataset_id,
                    x,
                    warmup=args.warmup,
                    sketch=not args.no_sketch,
                    sketch_bits=args.sketch_bits,
                    sketch_levels=args.sketch_levels,
                )
            httpd = KSelectHTTPServer((args.host, args.port), server)
            try:
                if args.port_file:
                    with open(args.port_file, "w") as f:
                        f.write(str(httpd.port))
                ds = server.list_datasets()[0]
                print(
                    f"serving dataset {args.dataset_id!r} "
                    f"(n={ds['n']}, dtype={ds['dtype']}, "
                    f"residency={ds['residency']}, sketch={ds['sketch']}) "
                    f"on http://{args.host}:{httpd.port} — POST /v1/query, "
                    "GET /v1/datasets, GET /metrics, GET /healthz",
                    flush=True,
                )
                if args.quit_after is not None:
                    for _ in range(args.quit_after):
                        httpd.handle_request()
                else:
                    httpd.serve_forever(poll_interval=0.2)
            except KeyboardInterrupt:
                pass
            finally:
                httpd.server_close()
        except (ValueError, RuntimeError) as e:
            raise SystemExit(f"error: {e}") from e
        finally:
            if args.debug_bundle and server.flight is not None:
                # through the server so the bundle carries the documented
                # `server` section (datasets, program-cache counters,
                # restarts) — a bare flight.dump would drop it.
                # best-effort: an unwritable PATH in this finally must
                # not replace the error (or SystemExit) in flight
                try:
                    server.dump_debug_bundle(
                        args.debug_bundle, reason="serve-shutdown"
                    )
                except OSError as write_err:
                    import sys

                    print(
                        f"warning: --debug-bundle {args.debug_bundle}: "
                        f"{write_err}",
                        file=sys.stderr,
                    )
            server.close()
    return 0


def build_monitor_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kselect monitor",
        description=(
            "continuous telemetry quantiles over an unbounded stream "
            "(mpi_k_selection_tpu/monitor/): a sliding ring of per-time-"
            "bucket RadixSketches — O(1) amortized window advance — "
            "emitting one multirank_p50_p90_p99 sample per advance, "
            "every value carrying EXACT rank/value bounds; --decay "
            "switches to the fixed-point exponential-decay aggregate"
        ),
    )
    p.add_argument(
        "--chunk-elems", type=int, default=1 << 16,
        help="elements per stream chunk (one chunk = one monitor tick)",
    )
    p.add_argument("--gen", choices=datagen.PATTERNS, default="uniform")
    p.add_argument("--dtype", choices=DTYPES, default="int32")
    p.add_argument("--seed", type=int, default=config.DEFAULT_SEED)
    p.add_argument(
        "--drift", type=float, default=0.0,
        help="per-chunk additive location drift of the synthetic stream "
        "(chunk i is shifted by round(drift * i)) — the windowed "
        "quantiles visibly track it",
    )
    p.add_argument(
        "--window", type=int, default=32,
        help="ring length in time buckets (the open bucket included)",
    )
    p.add_argument(
        "--emit-every", type=int, default=1, metavar="CHUNKS",
        help="chunks per time bucket: the window advances and one "
        "sample is emitted every this many chunks",
    )
    p.add_argument(
        "--decay", type=float, default=None,
        help="exponential decay per window advance, in (0, 1] "
        "(fixed-point count scaling, monitor/decay.py; decay=1.0 is "
        "bit-identical to the undecayed window; default: exact "
        "sliding window)",
    )
    p.add_argument(
        "--quantiles", default="0.5,0.9,0.99",
        help="comma-separated quantiles of the emitted stream "
        "(default p50/p90/p99)",
    )
    p.add_argument(
        "--buckets", type=int, default=None, metavar="N",
        help="stop after N emitted samples (default: run until "
        "interrupted — the stream is unbounded)",
    )
    p.add_argument("--sketch-bits", type=int, default=4)
    p.add_argument("--sketch-levels", type=int, default=4)
    p.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="ingest pipelining, as in --streaming (0 = synchronous; "
        "answers bit-identical at every depth)",
    )
    p.add_argument(
        "--devices", type=int, default=None,
        help="round-robin staged ingest across this many chips, as in "
        "--streaming (bit-identical at every count)",
    )
    p.add_argument(
        "--metrics-json", default=None, metavar="PATH",
        help="write the monitor's obs metrics registry (monitor.quantile"
        "{q=} gauges, ingest counters, phase seconds) as JSON to PATH "
        "at exit",
    )
    p.add_argument(
        "--prometheus-port", type=int, default=None, metavar="PORT",
        help="serve the registry's Prometheus text exposition on PORT "
        "(GET /metrics; 0 = ephemeral — see --port-file) for the whole "
        "run",
    )
    p.add_argument(
        "--port-file", default=None, metavar="PATH",
        help="write the bound Prometheus port here (for "
        "--prometheus-port 0 callers)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit one JSON object per sample (JSONL) instead of the "
        "human-readable line",
    )
    return p


def monitor_main(argv=None) -> int:
    """``kselect monitor ...`` — run the continuous quantile monitor
    over a synthetic (optionally drifting) chunk stream, one sample line
    per window advance, until the stream ends (``--buckets``) or the
    user interrupts. Exit 0 on clean shutdown (Ctrl-C included)."""
    import json as _json

    args = build_monitor_parser().parse_args(argv)
    if args.chunk_elems < 1:
        raise SystemExit("error: --chunk-elems must be >= 1")
    try:
        qs = [float(s) for s in args.quantiles.split(",") if s.strip()]
    except ValueError as e:
        raise SystemExit(f"error: bad --quantiles value: {e}") from e
    from mpi_k_selection_tpu import obs as obs_lib
    from mpi_k_selection_tpu.monitor import Monitor, start_metrics_server

    dtype = np.dtype(args.dtype)
    max_chunks = (
        None if args.buckets is None else args.buckets * args.emit_every
    )

    def source():
        i = 0
        while max_chunks is None or i < max_chunks:
            c = datagen.generate(
                args.chunk_elems, pattern=args.gen, seed=args.seed + i,
                dtype=args.dtype,
            )
            if args.drift:
                off = args.drift * i
                if np.issubdtype(dtype, np.integer):
                    off = int(round(off))
                c = (c + dtype.type(off)).astype(dtype, copy=False)
            yield c
            i += 1

    obs = None
    if args.metrics_json or args.prometheus_port is not None:
        obs = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    x64_needed = args.dtype in ("int64", "float64")
    exporter = None
    try:
        with maybe_x64(x64_needed):
            mon = Monitor(
                qs=qs, window=args.window, emit_every=args.emit_every,
                decay=args.decay, radix_bits=args.sketch_bits,
                levels=args.sketch_levels,
                pipeline_depth=args.pipeline_depth, devices=args.devices,
                obs=obs,
            )
            if args.prometheus_port is not None:
                exporter = start_metrics_server(
                    obs.metrics, port=args.prometheus_port
                )
                if args.port_file:
                    with open(args.port_file, "w") as f:
                        f.write(str(exporter.port))
            try:
                for s in mon.run(
                    source(), dtype, max_samples=args.buckets
                ):
                    line = (
                        _json.dumps(s.as_dict()) if args.json
                        else s.format_line()
                    )
                    print(line, flush=True)
            except KeyboardInterrupt:
                pass
    except (ValueError, RuntimeError, TypeError) as e:
        raise SystemExit(f"error: {e}") from e
    finally:
        if exporter is not None:
            exporter.close()
        if obs is not None and args.metrics_json:
            with open(args.metrics_json, "w") as f:
                f.write(obs.metrics.to_json(indent=2))
    return 0


def main(argv=None) -> int:
    # Honor JAX_PLATFORMS even on hosts whose site customization pins
    # jax_platforms at interpreter startup (config wins over the env var):
    # `JAX_PLATFORMS=cpu` + xla_force_host_platform_device_count is the
    # supported way to drive the distributed paths on a virtual mesh — the
    # analogue of running the reference under local mpirun (SURVEY.md §4).
    import os

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        import jax

        if jax.config.jax_platforms != env_platforms:
            jax.config.update("jax_platforms", env_platforms)
        if jax.default_backend() not in env_platforms.split(","):
            # config.update is a silent no-op once the backend initialized
            # (e.g. a programmatic caller touched jax.devices() first)
            print(
                f"warning: JAX_PLATFORMS={env_platforms} requested but the "
                f"jax backend is already initialized on "
                f"{jax.default_backend()!r}; running there",
                file=sys.stderr,
            )

    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        # subcommand: the long-lived query server (serve/), its own parser
        return serve_main(argv[1:])
    if argv and argv[0] == "monitor":
        # subcommand: continuous telemetry quantiles (monitor/)
        return monitor_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.batch and args.topk is None:
        raise SystemExit("error: --batch only applies to --topk mode")
    if args.topk is not None and args.backend == "mpi":
        raise SystemExit("error: the mpi backend does not support --topk")
    if args.check and args.topk is not None:
        raise SystemExit(
            "error: --check applies to k-th selection; use --verify for top-k"
        )
    if args.quantiles is not None and (args.topk is not None or args.check):
        raise SystemExit(
            "error: --quantiles is exclusive with --topk/--check; use --verify"
        )
    if args.streaming and (
        args.topk is not None or args.quantiles is not None or args.batch
    ):
        raise SystemExit(
            "error: --streaming supports k-th selection only "
            "(no --topk/--quantiles/--batch)"
        )
    if args.streaming and args.backend == "mpi":
        raise SystemExit("error: --streaming runs on the seq or tpu backend")
    x64_needed = args.dtype in ("int64", "float64")
    from mpi_k_selection_tpu.utils import profiling

    import contextlib

    # the obs bundle behind --metrics-json / --trace-events /
    # --debug-bundle (off = None, zero overhead): metrics collected by
    # the descent + _finish, spans recorded through the PhaseTimers on
    # whichever thread runs the phase, the flight ring retaining the
    # recent tail for the bundle
    obs = None
    if args.metrics_json or args.trace_events or args.debug_bundle:
        from mpi_k_selection_tpu import obs as obs_lib

        obs = obs_lib.Observability(
            metrics=obs_lib.MetricsRegistry() if args.metrics_json else None,
            trace=obs_lib.TraceRecorder() if args.trace_events else None,
            flight=True if args.debug_bundle else None,
        )
    from mpi_k_selection_tpu.obs import wiring as _wr

    # the trace channel, the flight ring, or the fan to both — pinning
    # obs.trace alone would leave --debug-bundle's spans section empty
    # whenever --trace-events is also on
    timer = profiling.PhaseTimer(recorder=_wr.span_recorder(obs))
    tracer = lambda: (
        profiling.trace(args.trace_dir)
        if args.trace_dir
        else contextlib.nullcontext()
    )
    try:
        with maybe_x64(x64_needed):
            if args.streaming:
                # chunks are generated INSIDE the solve (that is the point:
                # the whole array never exists); --check streams too
                with tracer(), timer.phase("solve"):
                    record, ok = _run_streaming(args, obs)
                return _finish(args, record, ok, timer, obs)
            with timer.phase("generate"):
                batch = (args.batch,) if args.batch else ()
                x = datagen.generate(
                    args.n, pattern=args.gen, seed=args.seed, dtype=args.dtype,
                    batch=batch,
                )
            with tracer(), timer.phase("solve"):
                if args.quantiles is not None:
                    record, ok = _run_quantiles(args, x)
                elif args.topk is not None:
                    record, ok = _run_topk(args, x)
                else:
                    record, ok = _run_kth(args, x)
            if args.check:
                with timer.phase("check"):
                    from mpi_k_selection_tpu.utils import debug

                    less, leq = debug.rank_certificate(x, record.answer)
                    cert_ok = int(less) < record.k <= int(leq)
                    record.extra["rank_certificate"] = [int(less), int(leq)]
                    record.extra["certificate_ok"] = cert_ok
                    ok = ok and cert_ok
    except (ValueError, RuntimeError) as e:
        # a failing run still writes its requested postmortem artifact
        # (terminal failures inside the descent ALSO auto-dumped one)
        _write_debug_bundle(args, None, obs, reason="cli-error", exc=e)
        raise SystemExit(f"error: {e}") from e
    return _finish(args, record, ok, timer, obs)


def _write_debug_bundle(args, record, obs, *, reason, exc=None) -> None:
    """--debug-bundle PATH: dump the flight ring's debug bundle
    (obs/flight.py) to PATH — called on both the success and the error
    exit, so a postmortem artifact always lands where asked. Best-effort
    like auto_dump: an unwritable PATH warns instead of masking the
    error in flight (or failing a run that actually succeeded)."""
    import sys

    path = getattr(args, "debug_bundle", None)
    if not path or obs is None or obs.flight is None:
        return
    extra = None
    if exc is not None:
        extra = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        obs.flight.dump(path, obs=obs, reason=reason, extra=extra)
    except OSError as write_err:
        print(
            f"warning: --debug-bundle {path}: {write_err}", file=sys.stderr
        )
        return
    if record is not None:
        record.extra["debug_bundle"] = path


def _finish(args, record, ok, timer, obs=None) -> int:
    """Shared result reporting (JSON or reference-style) + exit code."""
    _write_debug_bundle(args, record, obs, reason="cli")
    if obs is not None:
        if obs.metrics is not None:
            from mpi_k_selection_tpu.obs.metrics import collect_runtime

            # fold the driver-level phases (generate/solve/check) in on
            # top of whatever the descent already collected, and mark the
            # repeat count: counters/phase totals span ALL repeats, so a
            # per-run reading divides by this gauge
            collect_runtime(obs.metrics, timer=timer)
            obs.metrics.gauge("run.repeats").set(max(1, args.repeats))
            with open(args.metrics_json, "w") as f:
                f.write(obs.metrics.to_json(indent=2))
            record.extra["metrics_json"] = args.metrics_json
        if obs.trace is not None:
            obs.trace.write(args.trace_events)
            record.extra["trace_events"] = args.trace_events
    if args.profile:
        record.extra["phases"] = timer.as_dict()
    if args.json:
        print(record.to_json())
    else:
        record.print_reference_style()
        if args.verify:
            status = "exact match" if ok else "MISMATCH"
            print(f"oracle check: {status}")
        if args.check:
            status = "ok" if record.extra.get("certificate_ok") else "FAILED"
            print(f"rank certificate: {status}")
        if args.profile:
            print(timer.report())
            phases = record.extra.get("pipeline_phases")
            if phases:
                # reported separately so the solve report's total stays
                # wall-accurate: pipeline.* phases run on the producer
                # thread CONCURRENTLY with solve; descent.* phases are
                # the consumer side of the same overlap
                print("streaming phases (producer concurrent with solve, per repeat):")
                for name, d in sorted(
                    phases.items(), key=lambda kv: -kv[1]["seconds"]
                ):
                    print(
                        f"  {name:<24} {d['seconds'] * 1e3:10.3f} ms"
                        f"  ({d['calls']}x)"
                    )
                hidden = record.extra.get("ingest_hidden_frac")
                if hidden is not None:
                    print(f"  ingest_hidden_frac       {hidden:10.4f}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
