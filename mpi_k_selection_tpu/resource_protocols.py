"""Canonical resource-protocol registry — the ONE importable source of
truth for every leak-tracked resource family in this package.

Three enforcement layers key on the names below, and before this module
each kept its own copy — a new resource kind could be tracked at runtime
yet invisible statically (or vice versa) with no test noticing:

1. **runtime** — the tests/conftest.py leak fixtures match worker
   threads, spill temp dirs and flight-recorder files by these prefixes
   after every test;
2. **static** — the resource-lifecycle dataflow pass
   (analysis/lifecycle.py, rules KSL019-KSL021) proves every acquire
   reaches its release on every CFG path, with the SAME owner/prefix
   vocabulary;
3. **the owning modules** — streaming/pipeline.py, serve/batcher.py,
   monitor/monitor.py, streaming/spill.py and obs/flight.py re-export
   their prefix constants FROM here (their public names are unchanged),
   so a subsystem cannot drift its naming away from the fixtures.

Stdlib-only on purpose: the static pass must import this registry in
environments without jax (``kselect-lint --no-contracts``), and the
conftest reads it before the first jax import.
"""

from __future__ import annotations

#: Every package-owned leakable artifact carries this prefix; the
#: conftest straggler sweep matches the family, not an allowlist.
KSEL_PREFIX = "ksel-"

# -- worker-thread name prefixes (the KSL021 / conftest thread family) ------

#: streaming/pipeline.py ChunkPipeline producer threads.
PIPELINE_THREAD_PREFIX = "ksel-pipeline"
#: serve/ threads: the batcher's supervised dispatch thread, the HTTP
#: accept loop and per-request handlers.
SERVE_THREAD_PREFIX = "ksel-serve"
#: monitor/ metrics-server threads (accept loop + per-request handlers).
MONITOR_THREAD_PREFIX = "ksel-monitor"

THREAD_PREFIXES = (
    PIPELINE_THREAD_PREFIX,
    SERVE_THREAD_PREFIX,
    MONITOR_THREAD_PREFIX,
)

# -- on-disk artifact prefixes ----------------------------------------------

#: streaming/spill.py internally-created store directories.
SPILL_DIR_PREFIX = "ksel-spill-"
#: obs/flight.py debug-bundle temp files.
FLIGHT_FILE_PREFIX = "ksel-flight-"

#: The full leak-tracked prefix family (threads + disk artifacts).
RESOURCE_PREFIXES = THREAD_PREFIXES + (SPILL_DIR_PREFIX, FLIGHT_FILE_PREFIX)

# ---------------------------------------------------------------------------
# static lifecycle protocols (analysis/lifecycle.py)
#
# Each protocol names, for one resource family: the calls that ACQUIRE a
# tracked resource, the calls that RELEASE it, the calls/attributes that
# constitute a sanctioned OWNERSHIP TRANSFER (after which the owner's own
# lifecycle discipline — itself conftest-enforced — is responsible), and
# the class names the engine uses for isinstance() path narrowing.

# -- staged key buffers (KSL019): streaming/pipeline.py ---------------------

#: Calls whose result is a live StagedKeys ring slot.
STAGED_ACQUIRE_CALLS = frozenset({"stage_keys", "stage_device_keys"})
#: ``staged.release()`` — the ring-slot donation (idempotent).
STAGED_RELEASE_METHODS = frozenset({"release"})
#: ``release_staged(x)`` — the idempotent unwind helper (executor.py).
STAGED_RELEASE_FUNCS = frozenset({"release_staged"})
#: Method names whose call takes ownership of a staged buffer passed to
#: them: the executor/window FIFO (``push``) releases at bundle-finish
#: time; the pipeline queue (``put``/``_put``) hands the slot to the
#: consumer (ChunkPipeline.close() drains and releases unconsumed ones).
STAGED_OWNER_CALLS = frozenset({"push", "put", "_put"})
STAGED_TYPES = frozenset({"StagedKeys"})

# -- spill stores / writers / temp dirs (KSL020): streaming/spill.py --------

#: Constructors of caller-cleaned disk resources: a store (close()
#: removes its ksel-spill-* dir), a raw temp dir, or tempfile.mkdtemp.
SPILL_ACQUIRE_CALLS = frozenset(
    {"SpillStore", "SpillWriter", "TemporaryDirectory", "mkdtemp",
     # a store's generation writer: commit() hands its records to the
     # store, abort() drops them — one of the two must run on every path
     "new_generation"}
)
#: The cleanup surface: ``store.close()`` / ``writer.abort()`` /
#: ``writer.commit()`` (commit IS the writer's release — ownership of
#: the records passes to the store) / ``TemporaryDirectory.cleanup()`` /
#: ``store.drop_generation(...)``.
SPILL_RELEASE_METHODS = frozenset({"close", "abort", "commit", "cleanup"})
SPILL_RELEASE_FUNCS = frozenset()
SPILL_OWNER_CALLS = frozenset()
#: ``self.root = tempfile.mkdtemp(...)`` — the store owns its directory.
SPILL_OWNER_ATTRS = frozenset({"root"})
SPILL_TYPES = frozenset({"SpillStore", "SpillWriter", "TemporaryDirectory"})

# -- package worker threads (KSL021) ----------------------------------------

#: Only ``ksel-``-named threads are tracked (the conftest family); an
#: unstarted Thread object holds no OS resources, so the lifecycle
#: obligation arms at ``.start()``.
THREAD_ACQUIRE_CALLS = frozenset({"Thread"})
THREAD_RELEASE_METHODS = frozenset({"join"})
THREAD_RELEASE_FUNCS = frozenset()
THREAD_OWNER_CALLS = frozenset()
#: The conftest-recognized supervisor slots: attributes whose owners
#: join their threads on every close path (ChunkPipeline._thread,
#: QueryBatcher._thread, the HTTP servers' _serve_thread and tracked
#: _req_threads list in serve/http.py and monitor/monitor.py).
THREAD_OWNER_ATTRS = frozenset({"_thread", "_serve_thread", "_req_threads"})
THREAD_TYPES = frozenset({"Thread"})

# ---------------------------------------------------------------------------
# `# ksel: owner[<site>]` annotation vocabulary
#
# A declared ownership transfer must name one of these sites; naming
# anything else — or annotating a line where no tracked resource moves —
# is itself a finding (the guarded-by staleness contract applied to
# ownership). Keep descriptions current: the lifecycle report exports
# this table verbatim.

OWNER_SITES = {
    "InflightWindow": "the executor FIFO window releases at bundle finish",
    "StreamExecutor": "the stream executor owns staged-buffer lifetime",
    "ChunkPipeline": "the pipeline queue: close() drains and releases",
    "SpillStore": "the store owns committed generations (drop/close)",
    "supervisor": "a conftest-recognized thread supervisor joins it",
    "caller": "ownership returns to the caller (documented contract)",
}
