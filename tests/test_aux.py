"""Aux subsystems (SURVEY.md §5): profiling, validation, multihost mesh."""

import numpy as np
import pytest

from mpi_k_selection_tpu.utils import debug, profiling


def test_phase_timer_accumulates():
    t = profiling.PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b"):
        pass
    assert t.counts["a"] == 2 and t.counts["b"] == 1
    assert t.total >= 0 and "phase timing" in t.report()
    assert set(t.as_dict()) == {"a", "b"}


def test_device_memory_stats_shape():
    import jax

    stats = profiling.device_memory_stats()
    assert len(stats) == len(jax.devices()) and all("device" in s for s in stats)


def test_validate_input():
    debug.validate_input(np.arange(4), 2)
    with pytest.raises(ValueError, match="non-empty"):
        debug.validate_input(np.array([]), 1)
    with pytest.raises(ValueError, match="out of range"):
        debug.validate_input(np.arange(4), 5)
    with pytest.raises(ValueError, match="NaN"):
        debug.validate_input(np.array([1.0, np.nan]), 1)
    debug.validate_input(np.array([1.0, np.nan]), 1, allow_nan=True)


def test_rank_certificate_and_checked_kselect():
    rng = np.random.default_rng(0)
    x = rng.integers(0, 50, size=10_001, dtype=np.int32)  # duplicate-heavy
    for k in (1, 5_000, 10_001):
        v = debug.checked_kselect(x, k)
        less, leq = debug.rank_certificate(x, v)
        assert int(less) < k <= int(leq)
        assert int(v) == int(np.sort(x)[k - 1])


def test_checkify_kselect_reports_bad_k():
    import jax.numpy as jnp

    err, _ = debug.checkify_kselect(jnp.arange(16, dtype=jnp.int32), jnp.int32(0))
    with pytest.raises(Exception, match="k must be"):
        err.throw()
    err, v = debug.checkify_kselect(jnp.arange(1, 17, dtype=jnp.int32), jnp.int32(3))
    err.throw()
    assert int(v) == 3


def test_multihost_single_process_meshes():
    import jax

    from mpi_k_selection_tpu.parallel import multihost

    ndev = len(jax.devices())
    assert multihost.process_count() == 1
    assert multihost.process_index() == 0
    m = multihost.make_global_mesh()
    assert m.size == ndev
    h = multihost.make_hybrid_mesh()
    assert h.shape["hosts"] == 1 and h.shape["data"] == ndev


def test_cli_check_and_profile_flags(capsys):
    from mpi_k_selection_tpu import cli

    rc = cli.main(
        ["--backend", "seq", "--n", "5000", "--k", "77", "--check", "--profile"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "rank certificate: ok" in out and "phase timing" in out


def test_cli_topk_method_flag(capsys):
    from mpi_k_selection_tpu import cli

    rc = cli.main(
        ["--backend", "tpu", "--n", "300000", "--topk", "8", "--dtype", "float32",
         "--gen", "normal", "--topk-method", "threshold", "--verify"]
    )
    assert rc == 0
    assert "exact match" in capsys.readouterr().out
