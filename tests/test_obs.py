"""Descent telemetry (mpi_k_selection_tpu/obs/): the sinks-on == sinks-off
bit-identity grid, event-stream invariants, the metrics registry, the
cross-thread trace recorder, and PhaseTimer under concurrent
producer/consumer threads."""

import json
import threading

import numpy as np
import pytest

from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.obs.metrics import collect_runtime
from mpi_k_selection_tpu.streaming.chunked import (
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.streaming.pipeline import StagingPool
from mpi_k_selection_tpu.streaming.sketch import RadixSketch
from mpi_k_selection_tpu.streaming.spill import SpillStore
from mpi_k_selection_tpu.utils.profiling import PhaseTimer


def _chunks(rng, sizes=(5000, 4096, 2048, 4096, 1000), dtype=np.int32):
    return [
        rng.integers(-(2**31), 2**31 - 1, size=m, dtype=np.int64).astype(dtype)
        for m in sizes
    ]


def _oracle(chunks, k):
    return np.sort(np.concatenate([c.ravel() for c in chunks]), kind="stable")[
        k - 1
    ]


# ---------------------------------------------------------------------------
# bit-identity: sinks on vs off over the devices x depth x spill grid


@pytest.mark.parametrize("devices", [None, 2, 8])
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("spill", ["off", "force"])
def test_obs_bit_identical_grid(rng, devices, depth, spill):
    chunks = _chunks(rng)
    n = sum(c.size for c in chunks)
    k = n // 2
    want = int(_oracle(chunks, k))
    kw = dict(
        radix_bits=4, collect_budget=64, pipeline_depth=depth,
        devices=devices, spill=spill,
    )
    plain = int(streaming_kselect(chunks, k, **kw))
    o = obs_lib.Observability.collecting()
    instrumented = int(streaming_kselect(chunks, k, obs=o, **kw))
    assert plain == instrumented == want
    # the stream observed something real and structurally sound
    obs_lib.check_stream_invariants(o.events.events)
    assert len(o.events.of_kind("stream.pass")) >= 2


def test_obs_bit_identical_multirank_and_f64(rng):
    chunks = _chunks(rng, sizes=(3000, 2048, 1000), dtype=np.float64)
    n = sum(c.size for c in chunks)
    ks = [1, n // 3, n // 2, n]
    s = np.sort(np.concatenate(chunks), kind="stable")
    want = [s[k - 1] for k in ks]
    o = obs_lib.Observability.collecting()
    got = streaming_kselect_many(chunks, ks, radix_bits=4, collect_budget=32, obs=o)
    got_off = streaming_kselect_many(chunks, ks, radix_bits=4, collect_budget=32)
    assert [float(g) for g in got] == [float(g) for g in got_off] == [
        float(w) for w in want
    ]
    obs_lib.check_stream_invariants(o.events.events)
    # multi-rank: every pass event carries one survivor population per rank
    for e in o.events.of_kind("stream.pass"):
        if e.pass_index != "collect":
            assert len(e.survivors) == len(ks)


def test_obs_sketch_bit_identical(rng):
    chunks = _chunks(rng, sizes=(3000, 2000, 1024))
    o = obs_lib.Observability.collecting()
    sk = RadixSketch(np.int32).update_stream(chunks, devices=2, obs=o)
    sk_off = RadixSketch(np.int32).update_stream(chunks, devices=2)
    sk_seq = RadixSketch(np.int32)
    for c in chunks:
        sk_seq.update(c)
    assert sk == sk_off == sk_seq
    (ev,) = o.events.of_kind("sketch.pass")
    assert ev.chunks == len(chunks)
    assert ev.keys_read == sum(c.size for c in chunks)


# ---------------------------------------------------------------------------
# event-stream structure


def test_event_stream_spill_matches_pass_log(rng):
    chunks = _chunks(rng)
    n = sum(c.size for c in chunks)
    k = n // 2
    o = obs_lib.Observability.collecting()
    with SpillStore() as store:
        got = int(
            streaming_kselect(
                chunks, k, radix_bits=4, collect_budget=64, spill=store,
                pipeline_depth=2, devices=2, obs=o,
            )
        )
        log = list(store.pass_log)
    assert got == int(_oracle(chunks, k))
    obs_lib.check_stream_invariants(o.events.events, spill_pass_log=log)
    passes = o.events.of_kind("stream.pass")
    # later passes read the shrinking spill generations, not the source
    spill_reads = [e for e in passes if e.read_from == "spill"]
    assert spill_reads, "no pass read from the spill store"
    gens = o.events.of_kind("spill.generation")
    assert gens and gens[0].keys == n  # the pass-0 tee holds the stream
    # generation events mirror what the writer committed
    for g in gens:
        assert g.nbytes == g.keys * 4


def test_event_chunk_device_assignment_round_robin(rng):
    chunks = [
        rng.integers(0, 2**31 - 1, size=2048, dtype=np.int32) for _ in range(6)
    ]
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability.collecting()
    got = int(streaming_kselect(chunks, n // 2, pipeline_depth=2, devices=2, obs=o))
    assert got == int(_oracle(chunks, n // 2))
    pass0 = [
        c for c in o.events.of_kind("stream.chunk") if c.pass_index == 0
    ]
    assert [c.chunk_index for c in pass0] == list(range(6))
    assert [c.device_slot for c in pass0] == [0, 1, 0, 1, 0, 1]
    assert all(c.staged for c in pass0)
    assert sum(c.n for c in pass0) == n


def test_certificate_event(rng):
    chunks = _chunks(rng, sizes=(3000, 1024))
    n = sum(c.size for c in chunks)
    k = n // 2
    v = _oracle(chunks, k)
    o = obs_lib.Observability.collecting()
    less, leq = streaming_rank_certificate(chunks, v, devices=2, obs=o)
    assert less < k <= leq
    (ev,) = o.events.of_kind("certificate.pass")
    assert (ev.less, ev.leq) == (less, leq)
    assert ev.keys_read == n


def test_resident_and_streaming_quantiles_obs(rng):
    from mpi_k_selection_tpu import api

    o = obs_lib.Observability.collecting()
    x = rng.integers(0, 1000, size=50000, dtype=np.int32)
    got = int(api.kselect(x, 25000, obs=o))
    assert got == int(np.sort(x)[24999])
    (ev,) = o.events.of_kind("resident.select")
    assert ev.algorithm == "radix" and ev.n == 50000

    o2 = obs_lib.Observability.collecting()
    chunks = _chunks(rng, sizes=(4096, 2048))
    sq = api.StreamingQuantiles(np.int32, obs=o2)
    sq.update_stream(chunks)
    exact = sq.refine_quantiles([0.5], chunks)
    s = np.sort(np.concatenate(chunks), kind="stable")
    from mpi_k_selection_tpu.api import quantile_ranks

    (k50,) = quantile_ranks([0.5], sq.n)
    assert int(exact[0]) == int(s[k50 - 1])
    assert o2.events.of_kind("sketch.pass")
    assert o2.events.of_kind("stream.pass")


def test_events_as_dict_json_ready(rng):
    chunks = _chunks(rng, sizes=(2048, 1024))
    o = obs_lib.Observability.collecting()
    streaming_kselect(chunks, 17, obs=o)
    payload = json.dumps([e.as_dict() for e in o.events.events])
    kinds = {d["event"] for d in json.loads(payload)}
    assert "stream.pass" in kinds and "stream.chunk" in kinds


def test_invariant_checker_catches_violations():
    ev = obs_lib.StreamPassEvent(
        pass_index=0, resolved_bits=0, prefixes=(), chunks=1, keys_read=100,
        bytes_read=400, read_from="source", bucket_total=100, bucket_max=50,
        bucket_nonzero=3, survivors=(40,),
    )
    grown = obs_lib.StreamPassEvent(
        pass_index=1, resolved_bits=4, prefixes=(3,), chunks=1, keys_read=100,
        bytes_read=400, read_from="source", bucket_total=40, bucket_max=40,
        bucket_nonzero=1, survivors=(99,),  # grew past 40: impossible
    )
    with pytest.raises(AssertionError, match="grew past"):
        obs_lib.check_stream_invariants([ev, grown])
    with pytest.raises(AssertionError, match="no StreamPassEvent"):
        obs_lib.check_stream_invariants([])
    reordered = obs_lib.StreamPassEvent(
        pass_index=0, resolved_bits=8, prefixes=(1,), chunks=1, keys_read=40,
        bytes_read=160, read_from="source", bucket_total=40, bucket_max=40,
        bucket_nonzero=1, survivors=(10,),
    )
    with pytest.raises(AssertionError, match="strictly increasing"):
        obs_lib.check_stream_invariants([ev, grown, reordered][::2] + [ev])


# ---------------------------------------------------------------------------
# metrics registry


def test_metrics_counter_gauge_histogram_basics():
    reg = obs_lib.MetricsRegistry()
    c = reg.counter("hits")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("hits") is c  # get-or-create identity
    g = reg.gauge("frac")
    g.set(0.25)
    assert g.value == 0.25
    h = reg.histogram("occ", buckets=(1, 2, 4))
    for v in (0, 1, 2, 3, 9):
        h.observe(v)
    assert h.count == 5 and h.sum == 15 and h.min == 0 and h.max == 9
    assert h.cumulative() == [2, 3, 4, 5]
    assert h.mean == 3.0
    with pytest.raises(TypeError):
        reg.gauge("hits")  # type conflict on one name


def test_metrics_labels_and_prometheus_rendering():
    reg = obs_lib.MetricsRegistry()
    reg.counter("ingest.chunks", labels={"device": "0"}).inc(3)
    reg.counter("ingest.chunks", labels={"device": "1"}).inc(2)
    reg.gauge("stall.seconds").set(1.5)
    reg.histogram("occ", buckets=(1, 2)).observe(2)
    text = reg.render_prometheus()
    assert '# TYPE ksel_ingest_chunks counter' in text
    assert 'ksel_ingest_chunks{device="0"} 3' in text
    assert 'ksel_ingest_chunks{device="1"} 2' in text
    assert "ksel_stall_seconds 1.5" in text
    assert 'ksel_occ_bucket{le="2"} 1' in text
    assert 'ksel_occ_bucket{le="+Inf"} 1' in text
    assert "ksel_occ_sum 2" in text and "ksel_occ_count 1" in text
    # JSON exposition is valid and carries the same values
    snap = json.loads(reg.to_json())
    assert snap['ingest.chunks{device="0"}']["value"] == 3


def test_metrics_thread_safety():
    reg = obs_lib.MetricsRegistry()
    c = reg.counter("n")
    h = reg.histogram("h")

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(1)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000 and h.sum == 8000


def test_collect_runtime_mirrors_sources(rng):
    pool = StagingPool()
    a = pool.acquire(1024, np.uint32)
    pool.release(a)
    pool.acquire(1024, np.uint32)
    pool.acquire(512, np.uint32)
    timer = PhaseTimer()
    timer.record("pipeline.stall", 0.125)
    with SpillStore() as store:
        chunks = _chunks(rng, sizes=(2048, 1024))
        n = sum(c.size for c in chunks)
        streaming_kselect(
            chunks, n // 2, radix_bits=4, collect_budget=32, spill=store
        )
        reg = obs_lib.MetricsRegistry()
        collect_runtime(reg, staging_pool=pool, spill_store=store, timer=timer)
        log = list(store.pass_log)
    assert reg.counter("staging_pool.hits").value == pool.hits == 1
    assert reg.counter("staging_pool.misses").value == pool.misses == 2
    assert reg.counter("spill.passes").value == len(log)
    assert reg.counter("spill.bytes_read").value == sum(
        p["bytes_read"] for p in log
    )
    assert reg.counter("spill.keys_written").value == sum(
        p.get("keys_written", 0) for p in log
    )
    assert (
        reg.gauge("phase.seconds", labels={"phase": "pipeline.stall"}).value
        == 0.125
    )
    # idempotent: a second collection overwrites, not doubles
    collect_runtime(reg, staging_pool=pool, spill_store=store, timer=timer)
    assert reg.counter("staging_pool.misses").value == 2


def test_occupancy_sampled_on_pipelined_run(rng):
    chunks = [
        rng.integers(0, 2**31 - 1, size=2048, dtype=np.int32) for _ in range(6)
    ]
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability.collecting()
    streaming_kselect(chunks, n // 2, pipeline_depth=2, devices=2, obs=o)
    occ = o.metrics.histogram("inflight.occupancy")
    assert occ.count > 0
    assert 1 <= occ.max <= 2  # window is one slot per ingest device


# ---------------------------------------------------------------------------
# trace recorder + PhaseTimer concurrency (the cross-thread contract)


def test_trace_recorder_cross_thread_chrome_export():
    rec = obs_lib.TraceRecorder()
    timer = PhaseTimer(recorder=rec)

    def producer():
        for _ in range(3):
            with timer.phase("pipeline.produce"):
                pass

    t = threading.Thread(target=producer, name="ksel-test-producer")
    with timer.phase("pipeline.stall"):
        t.start()
        t.join()
    assert len(rec.spans) == 4
    assert len(rec.thread_ids()) == 2
    trace = json.loads(rec.to_json())
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == 4 and len({e["tid"] for e in xs}) == 2
    names = {m["args"]["name"] for m in metas}
    assert "ksel-test-producer" in names
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    # spans nest/overlap on a shared rebased timeline
    assert min(e["ts"] for e in xs) == 0


def test_streaming_trace_shows_producer_and_consumer_tracks(rng):
    chunks = _chunks(rng, sizes=(4096, 2048, 2048))
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability.collecting()
    streaming_kselect(chunks, n // 2, pipeline_depth=2, obs=o)
    trace = o.trace.to_chrome_trace()
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    by_tid = {}
    for e in xs:
        by_tid.setdefault(e["tid"], set()).add(e["name"])
    assert len(by_tid) >= 2  # producer + consumer tracks
    producer_names = set().union(
        *(v for v in by_tid.values() if "pipeline.produce" in v)
    )
    consumer_names = set().union(
        *(v for v in by_tid.values() if "descent.pass" in v)
    )
    assert "pipeline.encode" in producer_names
    assert "pipeline.stall" in consumer_names


def test_phase_timer_concurrent_accumulation():
    timer = PhaseTimer()
    iters, nthreads = 400, 8

    def work():
        for _ in range(iters):
            with timer.phase("shared"):
                pass
            timer.record("recorded", 0.001)

    threads = [threading.Thread(target=work) for _ in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # no lost updates: counts are exact under contention
    assert timer.counts["shared"] == iters * nthreads
    assert timer.counts["recorded"] == iters * nthreads
    assert timer.phases["recorded"] == pytest.approx(0.001 * iters * nthreads)
    d = timer.as_dict()
    assert d["shared"]["calls"] == iters * nthreads


def test_phase_timer_nested_phases_and_recorder_threads():
    rec = obs_lib.TraceRecorder()
    timer = PhaseTimer(recorder=rec)
    with timer.phase("outer"):
        with timer.phase("inner"):
            pass
        with timer.phase("inner"):
            pass
    assert timer.counts == {"inner": 2, "outer": 1}
    # nested spans: inner intervals sit inside outer's
    spans = {(" ".join([s.name]), s.t0, s.t1) for s in rec.spans}
    outer = next(s for s in rec.spans if s.name == "outer")
    for s in rec.spans:
        if s.name == "inner":
            assert outer.t0 <= s.t0 <= s.t1 <= outer.t1
    assert len(spans) == 3


def test_recorder_detached_from_caller_timer_after_run(rng):
    """An instrumented call attaches obs.trace to a caller-owned timer
    only for its own duration: later uninstrumented calls through the
    same timer must not keep feeding (and growing) the run's recorder."""
    chunks = _chunks(rng, sizes=(2048, 1024))
    timer = PhaseTimer()
    o = obs_lib.Observability.collecting()
    streaming_kselect(chunks, 17, timer=timer, obs=o)
    assert timer.recorder is None  # detached on exit
    n_spans = len(o.trace.spans)
    assert n_spans > 0
    streaming_kselect(chunks, 17, timer=timer)  # uninstrumented reuse
    with timer.phase("later"):
        pass
    assert len(o.trace.spans) == n_spans
    # a recorder the CALLER attached stays put (their wiring, their scope)
    rec = obs_lib.TraceRecorder()
    timer2 = PhaseTimer(recorder=rec)
    streaming_kselect(chunks, 17, timer=timer2, obs=o)
    assert timer2.recorder is rec


def test_observability_off_by_default_and_channels_independent(rng):
    chunks = _chunks(rng, sizes=(2048,))
    # metrics-only bundle: no sink, no recorder — nothing crashes
    o = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    got = int(streaming_kselect(chunks, 17, obs=o))
    assert got == int(_oracle(chunks, 17))
    assert o.events is None and o.trace is None
    assert o.metrics.as_dict()  # something was collected
    # events-only bundle
    o2 = obs_lib.Observability(events=obs_lib.ListSink())
    int(streaming_kselect(chunks, 17, obs=o2))
    assert len(o2.events) > 0
