"""Shared obs plumbing for the streaming descent's consumers.

The helpers every instrumented streaming loop needs — timer/recorder
attachment, the per-chunk ingest observation, the window-occupancy
histogram handle — live here (PUBLIC, in the obs package) rather than as
privates of ``streaming/chunked.py``: ``chunked``, ``sketch`` and any
future consumer (the resident query server) import one stable surface
instead of reaching into a sibling module's underscores.

Import direction: this module may import ``streaming/`` types lazily
(function-level) — ``streaming/chunked.py`` imports obs modules at load
time, so a module-level import back into ``streaming`` here would be a
cycle.
"""

from __future__ import annotations

from mpi_k_selection_tpu.obs.events import ChunkEvent, FaultEvent


def fault_event(
    obs, site: str, action: str, *, exc=None, fault_kind=None, index=None,
    attempt: int = 0, counter=None, labels=None,
):
    """The ONE FaultEvent emission shape (docs/ROBUSTNESS.md), shared by
    the injector (`action="inject"`), the retry policies, the descent's
    recovery ladder, and the serving layer — so the error-rendering
    convention (``"TypeName: message"``, empty for injections/sheds) and
    the event/metric pairing cannot drift between call sites. ``counter``
    (with optional ``labels``) names the metric to bump alongside the
    event; pure host observation, no-op when ``obs`` is None."""
    if obs is None:
        return
    obs.emit(
        FaultEvent(
            site=site,
            action=action,
            fault_kind=fault_kind,
            index=index,
            attempt=attempt,
            error="" if exc is None else f"{type(exc).__name__}: {exc}",
        )
    )
    if counter is not None and obs.metrics is not None:
        obs.metrics.counter(counter, labels=labels).inc()


def staged_slot(keys, devs):
    """Round-robin slot index of a staged chunk's device within the
    resolved device tuple (``None`` = host-resident, the uncommitted
    default-device path, or a device outside the pass set) — the ONE
    chunk->device mapping shared by the spill tee's record keying and
    the obs chunk events."""
    from mpi_k_selection_tpu.streaming.pipeline import StagedKeys

    if isinstance(keys, StagedKeys) and keys.device is not None:
        try:
            return devs.index(keys.device)
        except ValueError:  # pragma: no cover - device outside the pass set
            return None
    return None


class _FanoutHistogram:
    """Observes into several histograms at once — the aggregate
    ``inflight.occupancy`` plus its per-phase labeled twin, so the
    executor's collect/certificate/sketch windows stay separable from the
    histogram passes' without breaking the historical unlabeled series."""

    __slots__ = ("_hists",)

    def __init__(self, hists):
        self._hists = tuple(hists)

    def observe(self, value) -> None:
        for h in self._hists:
            h.observe(value)


def window_occupancy(obs, phase: str | None = None):
    """The InflightWindow occupancy handle when metrics are on: the
    unlabeled aggregate histogram, fanned out to
    ``inflight.occupancy{phase=...}`` when the caller names its executor
    phase (``descent`` | ``collect`` | ``certificate`` | ``sketch``) —
    the per-pass window utilization the deferred-executor before/after
    evidence reads (bench_streaming_oc's ``collect_hidden_frac``)."""
    if obs is None or obs.metrics is None:
        return None
    base = obs.metrics.histogram("inflight.occupancy")
    if phase is None:
        return base
    return _FanoutHistogram(
        (base, obs.metrics.histogram("inflight.occupancy", labels={"phase": phase}))
    )


def bucket_read(obs, phase: str, staged, programs: int = 1):
    """Count ``programs`` device-program dispatches consuming one staged
    bucket — sampled at DISPATCH time, so ``ingest.bucket_reads{phase}``
    (and its byte twin ``ingest.bucket_read_bytes{phase}``) measure the
    reads-per-pass multiplier the single-read ingest tiers collapse:
    an unfused spill pass reads each bucket for the histogram AND the
    tee (2 programs), an unfused collect pass once per spec; the fused
    program (phase ``"fused"``, either tier — the single-sweep kernel
    or the XLA fusion) reads it exactly once — and under the kernel
    tier the certificate pair (``certificate``: 2 -> 1) and the
    sketch's deep-fold + extremes pair (``sketch``: 2 -> 1) collapse
    too. ``phase`` partitions over the closed consumer set
    (``histogram`` | ``collect`` | ``tee`` | ``certificate`` |
    ``sketch`` | ``monitor`` | ``fused``).
    Byte counts
    are the PADDED bucket bytes (what the program actually sweeps), the
    same unit as ``ingest.staged_bytes`` — so ``bucket_read_bytes /
    staged_bytes`` is the per-pass read amplification. Pure host
    observation; no-op when metrics are off."""
    if obs is None or obs.metrics is None:
        return
    nbytes = (
        int(staged.data.shape[0]) * staged.data.dtype.itemsize * int(programs)
    )
    lab = {"phase": phase}
    obs.metrics.counter("ingest.bucket_reads", labels=lab).inc(int(programs))
    obs.metrics.counter("ingest.bucket_read_bytes", labels=lab).inc(nbytes)


def resolved_bits_gauge(obs, pass_label, bits) -> None:
    """Record the cumulative resolved-bit depth after a histogram pass:
    ``ingest.resolved_bits{pass}`` is how the adaptive width schedule's
    progress becomes observable — a wide pass 0 jumps the gauge to w₀
    where the fixed schedule would read ``radix_bits``, and the gap
    between consecutive passes IS the per-pass digit width. The ``pass``
    label set is closed by construction: labels are the descent's pass
    indices, at most ``total_bits / 1`` of them (64 for uint64) per run.
    Pure host observation; no-op when metrics are off."""
    if obs is None or obs.metrics is None:
        return
    obs.metrics.gauge(
        "ingest.resolved_bits",
        labels={"pass": str(pass_label)},  # ksel: noqa[KSL013] -- pass indices, bounded by key bits / min digit width
    ).set(int(bits))


def ingest_workers_gauge(obs, workers) -> None:
    """Record the RESOLVED ingest-pool width a streamed run is using:
    ``ingest.workers`` is how an ``ingest_workers="auto"`` caller learns
    what the knob resolved to on this host (and dashboards correlate a
    throughput change with the pool width that produced it). Unlabeled —
    one value per run, last-writer-wins across concurrent runs like every
    resolved-knob gauge. Pure host observation; no-op when metrics are
    off."""
    if obs is None or obs.metrics is None:
        return
    obs.metrics.gauge("ingest.workers").set(int(workers))


class _FanRecorder:
    """Forwards every finished span to several recorders (the trace
    recorder and the flight ring observe the same phases — neither
    replaces the other). Each target accepts the optional ``args``."""

    __slots__ = ("_targets",)

    def __init__(self, targets):
        self._targets = tuple(targets)

    def record(self, name, t0, t1, args=None) -> None:
        for r in self._targets:
            r.record(name, t0, t1, args)


def span_recorder(obs):
    """The recorder an instrumented run's PhaseTimer should feed: the
    trace channel, the flight ring, a fan-out to both, or ``None`` when
    neither is on."""
    if obs is None:
        return None
    targets = [r for r in (obs.trace, getattr(obs, "flight", None)) if r is not None]
    if not targets:
        return None
    if len(targets) == 1:
        return targets[0]
    return _FanRecorder(targets)


def attach_timer(obs, timer):
    """Resolve the (timer, recorder) wiring: with span recording on (the
    trace channel, the flight ring, or both), every phase needs a
    PhaseTimer to timestamp it — create one if the caller passed none,
    attach the recorder if the caller's timer has none.

    Returns ``(timer, restore)``. ``restore()`` detaches a recorder this
    call attached to a CALLER-owned timer — run it on every exit path,
    so a long-lived timer reused across later uninstrumented calls does
    not keep feeding spans into (and growing) this run's recorders.
    Timers created here, and timers whose recorder the caller set
    themselves, need no restore (a no-op is returned)."""
    recorder = span_recorder(obs)
    if recorder is None:
        return timer, lambda: None
    if timer is None:
        from mpi_k_selection_tpu.utils.profiling import PhaseTimer

        return PhaseTimer(recorder=recorder), lambda: None
    if timer.recorder is None:
        timer.recorder = recorder

        def _restore(t=timer):
            t.recorder = None

        return timer, _restore
    return timer, lambda: None


def chunk_event(obs, pass_index, chunk_index, keys, kdt, devs):
    """Emit one chunk's ingest observation (event + per-device counters).
    Pure host-int observation — called only when ``obs`` is on."""
    from mpi_k_selection_tpu.streaming.pipeline import StagedKeys

    staged = isinstance(keys, StagedKeys)
    slot = staged_slot(keys, devs)
    n = int(keys.size)
    nbytes = n * kdt.itemsize if kdt is not None else 0
    obs.emit(
        ChunkEvent(
            pass_index=pass_index,
            chunk_index=chunk_index,
            n=n,
            nbytes=nbytes,
            device_slot=slot,
            staged=staged,
        )
    )
    if obs.metrics is not None:
        # "default" = staged onto the uncommitted default device (the
        # single-slot path); "host" = never staged (host-exact routes,
        # depth-0 host chunks, device-resident chunks)
        dev = str(slot) if slot is not None else ("default" if staged else "host")
        lab = {"device": dev}
        obs.metrics.counter("ingest.chunks", labels=lab).inc()
        obs.metrics.counter("ingest.bytes", labels=lab).inc(nbytes)
        if staged:
            # the PADDED bucket bytes that landed on device — the
            # denominator of the bucket_read_bytes / staged_bytes read
            # amplification (see bucket_read above)
            obs.metrics.counter("ingest.staged_bytes").inc(
                int(keys.data.shape[0]) * keys.data.dtype.itemsize
            )
