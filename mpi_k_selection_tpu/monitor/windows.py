"""WindowedSketch — a sliding ring of per-time-bucket RadixSketches with
O(1) amortized window advance.

The core observation: RadixSketch merges are *elementwise int64 sums* —
associative AND commutative — so a sliding-window aggregate never needs
subtraction (which histogram counts would support, but min/max extremes
would not) or a full re-merge of the ring. The classic two-stack queue
aggregation applies verbatim:

- the **back** half collects freshly closed buckets with one running
  prefix aggregate (``fold_scaled(bucket, 1)`` per advance — one in-place
  merge);
- the **front** half holds older buckets with PRE-COMPUTED suffix
  aggregates (each entry stores the merge of itself and every younger
  front bucket), so evicting the oldest bucket is a pop;
- when the front empties, the back **flips** into it, computing the
  suffix aggregates in one linear sweep — amortized one merge per
  advance.

A full-window ``query()`` is then ``front_suffix + back_prefix +
current`` — two merges, independent of window length. Any narrower
suffix (``query(window=w)``) re-merges the newest ``w`` raw buckets
(O(w) merges — "arbitrary window re-aggregation"); either way the result
is a plain :class:`~mpi_k_selection_tpu.streaming.sketch.RadixSketch`,
so every answer carries the sketch's EXACT ``rank_bounds`` /
``value_bounds`` / ``rank_error_bound``, and — merge order being
bitwise-invariant — is bit-identical to a from-scratch merge of the same
live buckets (test-gridded in tests/test_monitor.py; re-proven by
``bench.py:bench_monitor``).

Time is whatever the caller advances on: the Monitor driver
(monitor/monitor.py) advances every ``emit_every`` chunks; the
windowed-histogram bridge (obs/windows.py) every ``advance_every``
observations. The sketch itself never reads a clock (KSL004).
"""

from __future__ import annotations

import numpy as np

from mpi_k_selection_tpu.streaming.sketch import RadixSketch


class WindowedSketch:
    """Sliding window of the last ``window`` time buckets (the open
    ``current`` bucket included), each an exact mergeable
    :class:`RadixSketch` over one dtype's stream.

    ``update``/``update_value`` fold into the current bucket;
    ``advance()`` closes it (evicting the oldest bucket once the ring is
    full — O(1) amortized sketch merges, see the module docstring) and
    opens a fresh one; ``query(window=w)`` returns the merged sketch of
    the newest ``w`` live buckets (default: all of them)."""

    #: Subclasses whose query() cannot use cached aggregates (the
    #: decayed window: weights shift every advance) set this False and
    #: advance() skips the two-stack maintenance entirely — the ring
    #: rotation alone is already O(1).
    _maintain_aggregates = True

    def __init__(self, dtype, *, window: int, radix_bits: int = 4, levels: int = 4):
        window = int(window)
        if window < 1:
            raise ValueError(f"window must be >= 1 bucket, got {window}")
        self.dtype = np.dtype(dtype)
        self.window = window
        self.radix_bits = int(radix_bits)
        self.levels = int(levels)
        #: completed window advances (the current bucket's epoch index)
        self.epoch = 0
        self.current = self._fresh()
        # two-stack queue over CLOSED buckets:
        # _front: [(bucket, suffix_aggregate)] — index 0 is the YOUNGEST
        #   front bucket, the END is the OLDEST (the stack top, popped at
        #   eviction); suffix_aggregate merges the entry with every
        #   younger front bucket.
        # _back: young closed buckets, oldest..newest; _back_agg is their
        #   running merge (None when empty).
        self._front: list[tuple[RadixSketch, RadixSketch]] = []
        self._back: list[RadixSketch] = []
        self._back_agg: RadixSketch | None = None

    def _fresh(self) -> RadixSketch:
        return RadixSketch(
            self.dtype, radix_bits=self.radix_bits, levels=self.levels
        )

    # -- accumulation ------------------------------------------------------

    def update(self, chunk) -> "WindowedSketch":
        """Fold one chunk into the current bucket."""
        self.current.update(chunk)
        return self

    def update_value(self, value) -> "WindowedSketch":
        """Fold one observation into the current bucket (the O(levels)
        scalar path — see :meth:`RadixSketch.update_value`)."""
        self.current.update_value(value)
        return self

    def advance(self) -> "WindowedSketch":
        """Close the current bucket and open a new one, evicting the
        oldest bucket once more than ``window - 1`` closed buckets are
        live. Amortized cost: O(1) sketch merges (one back fold, plus the
        amortized share of a front flip), independent of ``window``."""
        self._back.append(self.current)
        if self._maintain_aggregates:
            if self._back_agg is None:
                self._back_agg = self.current.copy()
            else:
                self._back_agg.fold_scaled(self.current, 1)
        while len(self._front) + len(self._back) > self.window - 1:
            self._evict_oldest()
        self.current = self._fresh()
        self.epoch += 1
        return self

    def _evict_oldest(self) -> None:
        if not self._front:
            # flip: back becomes the front, suffix aggregates computed in
            # one newest-to-oldest sweep (each entry's aggregate = itself
            # merged with the previous — younger — entry's aggregate)
            agg = None
            for b in reversed(self._back):
                if self._maintain_aggregates:
                    agg = b.copy() if agg is None else agg.merge(b)
                self._front.append((b, agg))
            self._back = []
            self._back_agg = None
        if self._front:
            self._front.pop()

    # -- queries -----------------------------------------------------------

    @property
    def n_live(self) -> int:
        """Live bucket count, the open current bucket included."""
        return len(self._front) + len(self._back) + 1

    def live_buckets(self) -> list[RadixSketch]:
        """The live buckets, oldest..newest (current last) — the raw
        operands a from-scratch merge of :meth:`query` would fold; the
        bit-identity tests and ``bench_monitor`` merge exactly these."""
        oldest_first = [b for b, _ in reversed(self._front)]
        return oldest_first + list(self._back) + [self.current]

    def _resolve_window(self, window) -> int:
        if window is None:
            return self.n_live
        window = int(window)
        if not 1 <= window <= self.window:
            raise ValueError(
                f"query window must be in [1, {self.window}] buckets, "
                f"got {window}"
            )
        return min(window, self.n_live)

    def query(self, window: int | None = None) -> RadixSketch:
        """Merged sketch of the newest ``window`` live buckets (default
        all) — a plain :class:`RadixSketch`, so ``quantile`` /
        ``rank_bounds`` / ``value_bounds`` / ``pin`` all apply with their
        exact-bound guarantees. Full-window queries cost O(1) merges (the
        cached two-stack aggregates); narrower suffixes re-merge their
        O(window) raw buckets. Bit-identical to a from-scratch fold of
        the same buckets in any order."""
        w = self._resolve_window(window)
        closed_needed = w - 1
        if self._maintain_aggregates and (
            closed_needed >= len(self._front) + len(self._back)
        ):
            # the full closed set: cached aggregates, O(1) merges
            out = self.current.copy()
            if self._back_agg is not None:
                out.fold_scaled(self._back_agg, 1)
            if self._front:
                out.fold_scaled(self._front[-1][1], 1)
            return out
        out = self.current.copy()
        take_back = min(closed_needed, len(self._back))
        for b in self._back[len(self._back) - take_back:]:
            out.fold_scaled(b, 1)
        for b, _ in self._front[: closed_needed - take_back]:
            out.fold_scaled(b, 1)
        return out

    def quantiles(self, qs, window: int | None = None):
        """Nearest-rank quantile values over the queried window (the
        merged sketch's :meth:`RadixSketch.quantiles`)."""
        return self.query(window).quantiles(qs)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"{type(self).__name__}(dtype={self.dtype}, window={self.window}, "
            f"epoch={self.epoch}, n_live={self.n_live})"
        )
