"""Async streaming executor (streaming/executor.py, ISSUE 8).

The contracts under test:

- **Bit-equality over the full grid**: devices {1, 2, max} x
  pipeline_depth {0, 2} x spill {off, force} x deferred {on, off} all
  return identical bits over heterogeneous (host + device + ragged +
  empty) chunk streams, and ``deferred="off"`` reproduces the
  pre-executor eager path.
- **Host-exact routes bypass deferral**: 64-bit keys without x64 and
  float64 (host key space) never stage, so nothing ever enters the
  deferred window.
- **Release discipline**: a consumer raise with bundles in flight leaks
  neither ``ksel-pipeline-*`` threads nor staged buffers (the autouse
  conftest fixtures enforce both; the tests also assert the live-staged
  counter directly).
- **The occupancy evidence**: on a multi-device deferred collect the
  p-wide window's ``inflight.occupancy{phase="collect"}`` mean is > 1
  (the r6 serialization retired), and the eager collect never samples it.
- **Honest collect accounting**: the terminal StreamPassEvent carries the
  per-spec survivor populations, held to the books by
  check_stream_invariants.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.errors import SpillRecordError
from mpi_k_selection_tpu.streaming import (
    SpillStore,
    StreamExecutor,
    collect_hidden_frac,
    live_staged_keys,
    resolve_deferred,
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.streaming import executor as ex_mod
from mpi_k_selection_tpu.streaming.pipeline import InflightWindow, stage_keys


def _chunks(rng, sizes=(4096, 1, 0, 2777, 4096), device_chunk=1):
    """Heterogeneous stream: host chunks, ragged sizes, an empty chunk,
    and `device_chunk` chunks already resident on a device."""
    out = [
        rng.integers(-(2**31), 2**31 - 1, size=s, dtype=np.int32)
        for s in sizes
    ]
    for i in range(device_chunk):
        out[i * 3] = jnp.asarray(out[i * 3])
    return out


def _oracle(chunks, ks):
    x = np.concatenate([np.asarray(c).ravel() for c in chunks])
    part = np.partition(x, [k - 1 for k in ks])
    return [int(part[k - 1]) for k in ks]


# ---------------------------------------------------------------------------
# the grid


@pytest.mark.parametrize("devices", [None, 2, 8])
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("spill", ["off", "force"])
@pytest.mark.parametrize("deferred", ["on", "off"])
def test_grid_bit_equality(rng, devices, depth, spill, deferred):
    chunks = _chunks(rng)
    n = sum(int(np.asarray(c).size) for c in chunks)
    ks = [1, n // 3, n // 2, n]
    want = _oracle(chunks, ks)
    got = streaming_kselect_many(
        chunks, ks, radix_bits=8, collect_budget=256,
        pipeline_depth=depth, devices=devices, spill=spill,
        deferred=deferred,
    )
    assert [int(g) for g in got] == want
    assert live_staged_keys() == 0


def test_deferred_default_matches_eager_f32(rng):
    chunks = [
        rng.standard_normal(s).astype(np.float32) for s in (3000, 1500, 700)
    ]
    n = sum(c.size for c in chunks)
    k = n // 2
    kw = dict(radix_bits=8, collect_budget=128, devices=8, pipeline_depth=2)
    a = streaming_kselect(chunks, k, deferred="on", **kw)
    b = streaming_kselect(chunks, k, deferred="off", **kw)
    c = streaming_kselect(chunks, k, pipeline_depth=0, radix_bits=8,
                          collect_budget=128)
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    assert np.asarray(a).tobytes() == np.asarray(c).tobytes()


def test_spill_generations_identical_across_deferred(rng):
    """The deferred tee writes the SAME per-pass survivor bytes as the
    eager tee (the multiset contract, visible in the pass_log)."""
    chunks = _chunks(rng, sizes=(4096, 2048, 4096), device_chunk=0)
    n = sum(c.size for c in chunks)
    logs = {}
    for deferred in ("on", "off"):
        with SpillStore() as store:
            streaming_kselect(
                chunks, n // 2, radix_bits=4, collect_budget=64,
                devices=8, pipeline_depth=2, spill=store, deferred=deferred,
            )
            logs[deferred] = [
                {kk: e[kk] for kk in ("pass", "keys_read", "keys_written")
                 if kk in e}
                for e in store.pass_log
            ]
    assert logs["on"] == logs["off"]


# ---------------------------------------------------------------------------
# host-exact routes bypass deferral


@pytest.mark.parametrize("dtype", [np.int64, np.float64])
def test_host_exact_routes_bypass_deferral(rng, dtype):
    """64-bit keys without x64 and f64 resolve to the host 'numpy' route:
    nothing stages, so nothing enters the deferred window — and the
    answers stay exact."""
    if np.dtype(dtype).kind == "f":
        chunks = [rng.standard_normal(s).astype(dtype) for s in (2000, 1000)]
    else:
        chunks = [
            rng.integers(-(2**62), 2**62, size=s, dtype=dtype)
            for s in (2000, 1000)
        ]
    n = sum(c.size for c in chunks)
    k = n // 2
    o = obs_lib.Observability.collecting()
    got = streaming_kselect(
        chunks, k, collect_budget=64, devices=8, pipeline_depth=2,
        deferred="on", obs=o,
    )
    assert np.asarray(got).tobytes() == np.asarray(
        np.sort(np.concatenate(chunks), kind="stable")[k - 1]
    ).tobytes()
    assert all(not e.staged for e in o.events.of_kind("stream.chunk"))
    occ = o.metrics.histogram("inflight.occupancy")
    assert occ.count == 0  # no bundle ever entered a window


# ---------------------------------------------------------------------------
# occupancy evidence


def test_multidevice_deferred_collect_occupancy_mean_above_one(rng):
    chunks = _chunks(rng, sizes=(4096,) * 6, device_chunk=0)
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability.collecting()
    streaming_kselect(
        chunks, n // 2, collect_budget=n, devices=8, pipeline_depth=2,
        deferred="on", obs=o,
    )
    occ = o.metrics.histogram(
        "inflight.occupancy", labels={"phase": "collect"}
    )
    assert occ.count > 0
    assert occ.mean > 1, (
        f"deferred multi-device collect sampled mean occupancy {occ.mean} "
        "— the window is degrading to serial"
    )
    frac = collect_hidden_frac(occ, 8)
    assert frac is not None and 0.0 < frac <= 1.0


def test_eager_collect_never_enters_the_window(rng):
    chunks = _chunks(rng, sizes=(4096,) * 6, device_chunk=0)
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability.collecting()
    streaming_kselect(
        chunks, n // 2, collect_budget=n, devices=8, pipeline_depth=2,
        deferred="off", obs=o,
    )
    occ = o.metrics.histogram(
        "inflight.occupancy", labels={"phase": "collect"}
    )
    assert occ.count == 0  # eager bundles skip the window entirely
    assert collect_hidden_frac(occ, 8) is None


# ---------------------------------------------------------------------------
# honest collect accounting


def test_collect_event_carries_honest_accounting(rng):
    chunks = _chunks(rng, sizes=(4096, 2048, 1024), device_chunk=0)
    n = sum(c.size for c in chunks)
    ks = [1, n // 4, n // 2, n]
    o = obs_lib.Observability.collecting()
    streaming_kselect_many(
        chunks, ks, radix_bits=4, collect_budget=64, devices=8,
        pipeline_depth=2, obs=o,
    )
    obs_lib.check_stream_invariants(o.events.events)
    passes = o.events.of_kind("stream.pass")
    coll = passes[-1]
    assert coll.pass_index == "collect"
    assert coll.survivors and len(coll.survivors) == len(coll.prefixes)
    assert all(s >= 1 for s in coll.survivors)
    assert coll.bucket_total == sum(coll.survivors)
    assert coll.bucket_max == max(coll.survivors)
    assert coll.bucket_total <= coll.keys_read
    # the collected populations are exactly the parked ranks' walked
    # bucket counts from the histogram passes
    assert coll.bucket_total <= passes[0].keys_read


# ---------------------------------------------------------------------------
# raise paths: no leaked threads, no leaked staged buffers


class _Boom(Exception):
    pass


def _raise_on_chunk(pass_index, chunk_index):
    def cb(event):
        if (
            event.kind == "stream.chunk"
            and event.pass_index == pass_index
            and event.chunk_index == chunk_index
        ):
            raise _Boom(f"injected at {pass_index}/{chunk_index}")

    return obs_lib.CallbackSink(cb)


@pytest.mark.parametrize("pass_index", [1, "collect"])
def test_consumer_raise_with_bundles_in_flight_releases_everything(
    rng, pass_index
):
    """A consumer-side raise mid-pass — after several deferred bundles
    are in flight on a multi-device window — must unwind cleanly: the
    executor aborts its pending bundles, the pipeline joins its producer
    and releases queued staged chunks, internal spill stores are removed
    (conftest enforces the thread/dir halves; the staged-buffer half is
    asserted here AND by its autouse fixture)."""
    chunks = _chunks(rng, sizes=(4096,) * 6, device_chunk=0)
    n = sum(c.size for c in chunks)
    base = live_staged_keys()
    o = obs_lib.Observability(events=_raise_on_chunk(pass_index, 3))
    with pytest.raises(_Boom):
        streaming_kselect(
            chunks, n // 2, radix_bits=4,
            collect_budget=64 if pass_index == 1 else n,
            devices=8, pipeline_depth=2, spill="force", deferred="on",
            obs=o,
        )
    assert live_staged_keys() == base


def test_certificate_raise_with_bundles_in_flight(rng):
    chunks = _chunks(rng, sizes=(4096,) * 6, device_chunk=0)
    base = live_staged_keys()
    o = obs_lib.Observability(events=_raise_on_chunk("certificate", 3))
    with pytest.raises(_Boom):
        streaming_rank_certificate(
            chunks, 0, devices=8, pipeline_depth=2, deferred="on", obs=o
        )
    assert live_staged_keys() == base


# ---------------------------------------------------------------------------
# the compaction program


def test_compaction_matches_numpy_filter(rng):
    kdt = np.dtype(np.uint32)
    keys = rng.integers(0, 2**32, size=3011, dtype=np.uint32)  # ragged: pads
    staged = stage_keys(keys)
    try:
        specs = [(8, int(keys[0] >> 24)), (16, int(keys[5] >> 16))]
        handle = ex_mod.dispatch_compaction(staged, specs, kdt, 32)
        got = ex_mod.materialize_compacted(handle, kdt)
    finally:
        staged.release()
    m = np.zeros(keys.shape, bool)
    for resolved, prefix in specs:
        m |= (keys >> np.uint32(32 - resolved)) == np.uint32(prefix)
    want = keys[m]
    assert got.dtype == kdt
    np.testing.assert_array_equal(got, want)  # order preserved, not just set


def test_compaction_empty_and_full(rng):
    kdt = np.dtype(np.uint32)
    keys = np.full(1000, 0xABCD1234, np.uint32)  # ragged -> padded bucket
    staged = stage_keys(keys)
    try:
        none = ex_mod.materialize_compacted(
            ex_mod.dispatch_compaction(staged, [(16, 0x1111)], kdt, 32), kdt
        )
        all_ = ex_mod.materialize_compacted(
            ex_mod.dispatch_compaction(staged, [(16, 0xABCD)], kdt, 32), kdt
        )
    finally:
        staged.release()
    assert none.size == 0
    np.testing.assert_array_equal(all_, keys)  # pads must NOT leak in


def test_certificate_deferred_pad_correction_at_key_zero(rng):
    """Pad lanes are key-space 0; a probe value whose key IS 0 (int32
    min) exercises both halves of the exact pad correction."""
    lo = -(2**31)
    chunks = [
        np.asarray([lo, lo, 5, -3], np.int32),
        rng.integers(lo, 2**31 - 1, size=777, dtype=np.int32),  # ragged
    ]
    for value in (lo, 0, 7):
        got_on = streaming_rank_certificate(
            chunks, value, devices=8, pipeline_depth=2, deferred="on"
        )
        got_off = streaming_rank_certificate(
            chunks, value, devices=8, pipeline_depth=2, deferred="off"
        )
        x = np.concatenate(chunks)
        want = (int(np.sum(x < value)), int(np.sum(x <= value)))
        assert got_on == got_off == want


# ---------------------------------------------------------------------------
# mmap spill replay


def test_mmap_spill_replay_bit_identical(rng):
    chunks = _chunks(rng, sizes=(4096, 2048), device_chunk=0)
    n = sum(c.size for c in chunks)
    with SpillStore() as store:
        # tee gen 0 via a forced spill descent, then read the store back
        # as a source under both executor modes
        want = int(streaming_kselect(chunks, n // 2, spill=store))
        got_mmap = int(streaming_kselect(store, n // 3, deferred="on"))
        got_read = int(streaming_kselect(store, n // 3, deferred="off"))
    x = np.concatenate(chunks)
    assert got_mmap == got_read == int(np.partition(x, n // 3 - 1)[n // 3 - 1])
    assert want == int(np.partition(x, n // 2 - 1)[n // 2 - 1])


def test_mmap_read_still_checksums(rng):
    import glob
    import os

    chunks = [rng.integers(0, 100, size=2048, dtype=np.int32)]
    with SpillStore() as store:
        streaming_kselect(chunks, 100, spill=store)
        recs = sorted(
            glob.glob(os.path.join(store.root, "gen-*", "r*.kspill"))
        )
        assert recs
        with open(recs[0], "r+b") as f:  # flip one payload byte
            f.seek(-1, os.SEEK_END)
            b = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(SpillRecordError, match="checksum"):
            streaming_kselect(store, 100, deferred="on")  # mmap route
        with pytest.raises(SpillRecordError, match="checksum"):
            streaming_kselect(store, 100, deferred="off")  # buffered route


# ---------------------------------------------------------------------------
# knob + helper units


def test_resolve_deferred():
    assert resolve_deferred("auto") is True
    assert resolve_deferred("on") is True
    assert resolve_deferred("off") is False
    assert resolve_deferred(True) is True
    assert resolve_deferred(False) is False
    with pytest.raises(ValueError, match="deferred"):
        resolve_deferred("sometimes")
    with pytest.raises(ValueError, match="deferred"):
        streaming_kselect([np.arange(4, dtype=np.int32)], 1, deferred=1.5)


def test_collect_hidden_frac_math():
    class H:
        count = 4
        mean = 3.0

    assert collect_hidden_frac(H(), 5) == pytest.approx(0.5)
    assert collect_hidden_frac(H(), 1) is None  # serial window
    assert collect_hidden_frac(None, 8) is None
    H.count = 0
    assert collect_hidden_frac(H(), 8) is None  # no samples

    class Full:
        count = 10
        mean = 9.0

    assert collect_hidden_frac(Full(), 8) == 1.0  # clamped


def test_inflight_window_clear_pending():
    done = []
    win = InflightWindow(4, done.append)
    for i in range(3):
        win.push(i)
    assert done == []
    assert win.clear_pending() == [0, 1, 2]
    assert list(win.drain()) == []
    assert done == []


def test_executor_eager_bundles_skip_window():
    class Eager:
        folded = []

        def dispatch(self, keys, kv):
            self.folded.append(int(kv.sum()))
            return None

        def finish(self, handle):  # pragma: no cover - never pending
            raise AssertionError("eager consumer must not be finished")

    class Occ:
        samples = []

        def observe(self, v):
            self.samples.append(v)

    ex = StreamExecutor([Eager()], window=8, occupancy=Occ())
    for i in range(5):
        ex.push(np.full(3, i, np.int64))
    ex.drain()
    assert Eager.folded == [0, 3, 6, 9, 12]
    assert Occ.samples == []


def test_streaming_quantiles_deferred_knob(rng):
    from mpi_k_selection_tpu.api import StreamingQuantiles

    with pytest.raises(ValueError, match="deferred"):
        StreamingQuantiles(np.float32, deferred="bogus")
    chunks = [rng.standard_normal(4000).astype(np.float32) for _ in range(3)]
    qs = (0.1, 0.5, 0.9)
    got = {}
    for deferred in ("on", "off"):
        sq = StreamingQuantiles(
            np.float32, devices=8, deferred=deferred
        ).update_stream(chunks)
        got[deferred] = [
            np.asarray(v).tobytes() for v in sq.refine_quantiles(qs, chunks)
        ]
    assert got["on"] == got["off"]


def test_cli_deferred_flag(capsys):
    import json

    from mpi_k_selection_tpu.cli import main

    for mode in ("on", "off"):
        rc = main([
            "--streaming", "--backend", "tpu", "--n", "40000",
            "--chunk-elems", "8192", "--devices", "2", "--verify", "--check",
            "--deferred", mode, "--json",
        ])
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["extra"]["exact_match"] is True
        assert rec["extra"]["certificate_ok"] is True
        assert rec["extra"]["deferred"] == mode
