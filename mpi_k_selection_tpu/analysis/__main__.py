"""``kselect-lint`` / ``python -m mpi_k_selection_tpu.analysis`` driver.

Exit codes: 0 clean (or everything suppressed), 1 unsuppressed findings,
2 usage error. The tier-1 gate (tests/test_analysis.py) runs the same
engine in-process and asserts exit code 0 over the whole repository.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kselect-lint",
        description=(
            "JAX-aware static analysis for the k-selection codebase: AST "
            "rules (KSLxxx) + jaxpr contract checks (KSCxxx). Rule catalog: "
            "docs/ANALYSIS.md."
        ),
    )
    p.add_argument("paths", nargs="*", default=["."], help="files/directories to scan")
    p.add_argument("--json", action="store_true", help="emit the JSON report")
    p.add_argument("--output", default=None, help="also write the JSON report here")
    p.add_argument(
        "--select", default=None,
        help="comma-separated rule-id prefixes to run (e.g. KSL001,KSC)",
    )
    p.add_argument(
        "--ignore", default=None,
        help="comma-separated rule-id prefixes to skip",
    )
    p.add_argument(
        "--no-contracts", action="store_true",
        help="skip the jaxpr contract checks (no jax import; pure AST lint)",
    )
    p.add_argument(
        "--concurrency-report", default=None, metavar="PATH",
        help=(
            "also write the thread-reachability call graph, the static "
            "lock-order graph (KSL016's), and the per-class guard "
            "inference as JSON to PATH"
        ),
    )
    p.add_argument(
        "--lifecycle-report", default=None, metavar="PATH",
        help=(
            "also write the resource-ownership graph (KSL019-021's "
            "acquire sites, release sites, escape edges, the owner-site "
            "registry, and the `# ksel: owner[...]` annotation ledger) "
            "as JSON to PATH"
        ),
    )
    p.add_argument(
        "--placement", action="store_true",
        help=(
            "run only the device-placement pass (KSL022-024 dataflow "
            "rules plus the KSC105 static<->runtime census contract "
            "unless --no-contracts)"
        ),
    )
    p.add_argument(
        "--placement-report", default=None, metavar="PATH",
        help=(
            "also write the placement census (the abstract lattice, "
            "per-module dispatch and crossing sites, the sanctioned-"
            "transfer registry, and the `# ksel: placed-on[...]` "
            "annotation ledger) as JSON to PATH"
        ),
    )
    p.add_argument(
        "--verbose", action="store_true",
        help="show suppressed findings in text output too",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from mpi_k_selection_tpu.analysis import (
        CONTRACT_CHECKS,
        all_rules,
        render_json,
        render_text,
        run_analysis,
    )

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}  {rule.title}")
        for check in CONTRACT_CHECKS:
            print(f"{check.id}  {check.title}")
        return 0

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    if args.placement:
        select = (select or []) + ["KSL022", "KSL023", "KSL024", "KSC105"]
    try:
        report = run_analysis(
            args.paths,
            select=select,
            ignore=ignore,
            contracts=not args.no_contracts,
        )
    except (OSError, RuntimeError) as e:
        print(f"kselect-lint: error: {e}", file=sys.stderr)
        return 2
    if args.concurrency_report:
        import json

        from mpi_k_selection_tpu.analysis.concurrency import (
            build_concurrency_report,
        )

        with open(args.concurrency_report, "w") as fh:
            json.dump(
                build_concurrency_report(args.paths, mods=report.modules),
                fh, indent=2, sort_keys=True,
            )
    if args.lifecycle_report:
        import json

        from mpi_k_selection_tpu.analysis.lifecycle import (
            build_lifecycle_report,
        )

        with open(args.lifecycle_report, "w") as fh:
            json.dump(
                build_lifecycle_report(args.paths, mods=report.modules),
                fh, indent=2, sort_keys=True,
            )
    if args.placement_report:
        import json

        from mpi_k_selection_tpu.analysis.placement import (
            build_placement_report,
        )

        with open(args.placement_report, "w") as fh:
            json.dump(
                build_placement_report(args.paths, mods=report.modules),
                fh, indent=2, sort_keys=True,
            )
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(render_json(report))
    print(render_json(report) if args.json else render_text(report, verbose=args.verbose))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
