"""Device-placement dataflow pass (KSL022-KSL024) + the KSC105
static<->runtime placement-census agreement contract.

Five layers of coverage, mirroring test_lifecycle.py:

- **rule fixtures** — positive/negative/annotation/stale-annotation/
  noqa sources per rule (dispatch-device mismatch KSL022, unsanctioned
  crossings KSL023, nondeterministic device choice KSL024);
- **lattice/engine units** — the join at top, the container round-trip
  (a FIFO keeps its pushed value's slot), the one-hop interprocedural
  return placement, and the loop-carried slot (bodies walked twice);
- **planted pre-fix shapes** — the exact ``devs if multi else None``
  conditional drop the first whole-repo run found live at four sites
  (chunked.py collect + certificate, sketch.py, monitor.py), caught,
  next to the fixed ``staged``-gated form proving clean;
- **runtime regressions** — the fixed paths for real: an explicitly
  requested single device now stages committed (``device_slot == 0``,
  not the silent host fold), and serve's ``add_stream`` builds its
  resident sketch through the streaming layer with the dataset's own
  staging knobs, bit-identical to the host fold it replaced;
- **the gate** — zero KSL022-024 findings repo-wide off the shared
  parsed-module set (analysis/modcache.py), the placement graph
  exported package-relative and cwd-independent, every shipped
  ``# ksel: placed-on[...]`` annotation live, KSC105 registered and
  clean, and the four whole-repo scans inside the declared wall budget.
"""

import json
import pathlib
import textwrap
import time

import numpy as np
import pytest

from mpi_k_selection_tpu import resource_protocols as rp
from mpi_k_selection_tpu.analysis import run_analysis, shared_modules
from mpi_k_selection_tpu.analysis.__main__ import main as lint_main
from mpi_k_selection_tpu.analysis.placement import (
    HOST,
    NONE,
    UNKNOWN,
    Placement,
    build_placement_report,
    join,
    untargeted_puts,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = "mpi_k_selection_tpu"


def _lint_source(tmp_path, source, name=f"{PKG}/streaming/mod.py", **kwargs):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    kwargs.setdefault("contracts", False)
    return run_analysis([f], **kwargs)


def _rules_hit(report):
    return {f.rule for f in report.unsuppressed}


def _hits(report, rule):
    return [f for f in report.unsuppressed if f.rule == rule]


# ---------------------------------------------------------------------------
# KSL022 — dispatch-device mismatch / conditional placement drop


KSL022_DROP_POSITIVE = """
    def run(source, devices, depth):
        devs = resolve_stream_devices(devices)
        multi = len(devs) > 1 and depth > 0
        return _key_chunk_stream(source, devices=devs if multi else None)
"""

KSL022_DROP_NEGATIVE = """
    def run(source, devices, depth):
        devs = resolve_stream_devices(devices)
        staged = depth > 0 and devices is not None
        return _key_chunk_stream(source, devices=devs if staged else None)
"""

KSL022_MISMATCH_POSITIVE = """
    def run(chunk, devices):
        devs = resolve_stream_devices(devices)
        a = stage_keys(chunk, devs[0])
        b = stage_keys(chunk, devs[1])
        return masked_radix_histogram(a, b)
"""

KSL022_MISMATCH_NEGATIVE = """
    def run(chunk, devices):
        devs = resolve_stream_devices(devices)
        a = stage_keys(chunk, devs[0])
        b = stage_keys(chunk, devs[0])
        return masked_radix_histogram(a, b)
"""


def test_ksl022_conditional_drop_positive(tmp_path):
    report = _lint_source(tmp_path, KSL022_DROP_POSITIVE, select=["KSL022"])
    (hit,) = _hits(report, "KSL022")
    assert "depends on the placement itself" in hit.message


def test_ksl022_conditional_drop_negative(tmp_path):
    report = _lint_source(tmp_path, KSL022_DROP_NEGATIVE, select=["KSL022"])
    assert _hits(report, "KSL022") == []


def test_ksl022_dispatch_mismatch_positive(tmp_path):
    report = _lint_source(
        tmp_path, KSL022_MISMATCH_POSITIVE, select=["KSL022"]
    )
    (hit,) = _hits(report, "KSL022")
    assert "different" in hit.message and "slot" in hit.message


def test_ksl022_dispatch_mismatch_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL022_MISMATCH_NEGATIVE, select=["KSL022"]
    )
    assert _hits(report, "KSL022") == []


def test_ksl022_out_of_scope_module_ignored(tmp_path):
    # the pass covers the streaming/serve/monitor/ops/parallel vertical;
    # a module elsewhere (obs/, analysis/) is not judged
    report = _lint_source(
        tmp_path, KSL022_DROP_POSITIVE, name=f"{PKG}/obs/mod.py",
        select=["KSL022"],
    )
    assert _hits(report, "KSL022") == []


def test_ksl022_placed_on_annotation_overrides(tmp_path):
    src = """
        def run(source, devices, depth):
            devs = resolve_stream_devices(devices)
            multi = len(devs) > 1 and depth > 0
            return _key_chunk_stream(source, devices=devs if multi else None)  # ksel: placed-on[devs] -- window sizing quirk, audited
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    assert _hits(report, "KSL022") == []


def test_ksl022_stale_placed_on_annotation(tmp_path):
    src = """
        def run(x):
            y = x + 1  # ksel: placed-on[devs[0]] -- nothing places here
            return y
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    (hit,) = _hits(report, "KSL022")
    assert "stale" in hit.message


def test_ksl022_noqa_suppresses_with_justification(tmp_path):
    src = """
        def run(source, devices, depth):
            devs = resolve_stream_devices(devices)
            multi = len(devs) > 1 and depth > 0
            return _key_chunk_stream(source, devices=devs if multi else None)  # ksel: noqa[KSL022] -- legacy shape under migration
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    assert _hits(report, "KSL022") == []
    (sup,) = [f for f in report.findings if f.suppressed]
    assert sup.rule == "KSL022" and sup.justification


# ---------------------------------------------------------------------------
# KSL023 — unsanctioned host<->device crossings


KSL023_POSITIVE = """
    def push(x, d):
        return jax.device_put(x, device=d)
"""


def test_ksl023_positive(tmp_path):
    report = _lint_source(tmp_path, KSL023_POSITIVE, select=["KSL023"])
    (hit,) = _hits(report, "KSL023")
    assert "sanctioned" in hit.message


def test_ksl023_sanctioned_site_negative(tmp_path):
    # the same crossing inside streaming/pipeline.py (the registered
    # staging boundary) is sanctioned
    report = _lint_source(
        tmp_path, KSL023_POSITIVE, name=f"{PKG}/streaming/pipeline.py",
        select=["KSL023"],
    )
    assert _hits(report, "KSL023") == []


def test_ksl023_device_get_positive(tmp_path):
    src = """
        def pull(x):
            return jax.device_get(x)
    """
    report = _lint_source(
        tmp_path, src, name=f"{PKG}/serve/mod.py", select=["KSL023"]
    )
    (hit,) = _hits(report, "KSL023")
    assert "device_get" in hit.message


def test_sanctioned_registry_carries_written_reasons():
    assert rp.SANCTIONED_TRANSFER_SITES
    for site, why in rp.SANCTIONED_TRANSFER_SITES.items():
        assert "/" in site and site.endswith(".py"), site
        assert len(why) > 10, (site, why)


# ---------------------------------------------------------------------------
# KSL024 — nondeterministic device choice


KSL024_CLOCK_POSITIVE = """
    def pick(chunk, devices):
        devs = resolve_stream_devices(devices)
        return stage_keys(chunk, devs[int(time.monotonic()) % 2])
"""

KSL024_SET_POSITIVE = """
    def pick(chunk, devices):
        devs = resolve_stream_devices(devices)
        return stage_keys(chunk, next(iter(set(devs))))
"""

KSL024_NEGATIVE = """
    def pick(chunk, devices, j):
        devs = resolve_stream_devices(devices)
        return stage_keys(chunk, devs[j % len(devs)])
"""


def test_ksl024_clock_positive(tmp_path):
    report = _lint_source(tmp_path, KSL024_CLOCK_POSITIVE, select=["KSL024"])
    hits = _hits(report, "KSL024")
    assert hits and "time.monotonic" in hits[0].message


def test_ksl024_unordered_set_positive(tmp_path):
    report = _lint_source(tmp_path, KSL024_SET_POSITIVE, select=["KSL024"])
    hits = _hits(report, "KSL024")
    assert hits and "iteration order" in hits[0].message


def test_ksl024_pure_round_robin_negative(tmp_path):
    report = _lint_source(tmp_path, KSL024_NEGATIVE, select=["KSL024"])
    assert _hits(report, "KSL024") == []


# ---------------------------------------------------------------------------
# lattice / engine units


def test_join_lattice_laws():
    d0 = Placement("device", slot="devs[0]")
    d1 = Placement("device", slot="devs[1]")
    assert join(UNKNOWN, d0) == d0  # unknown is bottom
    assert join(NONE, d0) == d0  # optimistic none fold
    assert join(d0, d0) == d0
    top = join(d0, d1)  # two slots meet at top
    assert top.kind == "top" and "devs[0]" in top.reason
    assert join(top, d0).kind == "top"  # top absorbs
    assert join(HOST, d0).kind == "top"  # host vs placed conflicts


def test_engine_container_round_trip(tmp_path):
    # the FIFO keeps the pushed value's slot: popping it back and
    # dispatching against a DIFFERENT slot is a mismatch
    src = """
        def run(chunk, devices, q):
            devs = resolve_stream_devices(devices)
            q.push(stage_keys(chunk, devs[0]))
            held = q.pop()
            other = stage_keys(chunk, devs[1])
            return masked_radix_histogram(held, other)
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    (hit,) = _hits(report, "KSL022")
    assert "different" in hit.message


def test_engine_interprocedural_one_hop(tmp_path):
    # a module-local function returning a placed value seeds its callers
    src = """
        def pick(devices):
            devs = resolve_stream_devices(devices)
            return devs[0]

        def run(chunk, devices):
            a = stage_keys(chunk, pick(devices))
            b = stage_keys(chunk, resolve_stream_devices(devices)[1])
            return masked_radix_histogram(a, b)
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    (hit,) = _hits(report, "KSL022")
    assert "different" in hit.message


def test_engine_loop_carried_slot(tmp_path):
    # the slot placed in iteration j is visible at iteration j+1's top
    # (bodies are walked twice so loop-carried placements converge)
    src = """
        def run(chunks, devices):
            devs = resolve_stream_devices(devices)
            prev = None
            for chunk in chunks:
                if prev is not None:
                    masked_radix_histogram(prev, stage_keys(chunk, devs[1]))
                prev = stage_keys(chunk, devs[0])
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    assert _hits(report, "KSL022"), "loop-carried slot not seen"


def test_ksl007_shim_delegates_to_placement_source_model(tmp_path):
    # satellite: KSL007 keeps its id/fixtures but its source model IS
    # untargeted_puts — one placement vocabulary, not two
    from mpi_k_selection_tpu.analysis.core import load_module

    f = tmp_path / "streaming" / "stage.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "def f(x, d):\n"
        "    a = jax.device_put(x)\n"
        "    b = jax.device_put(x, device=d)\n"
        "    return a, b\n"
    )
    mod = load_module(f, root=tmp_path)
    assert [(line, name) for line, name in untargeted_puts(mod)] == [
        (2, "jax.device_put")
    ]
    report = run_analysis([f], contracts=False, select=["KSL007"])
    (hit,) = _hits(report, "KSL007")
    assert hit.line == 2 and "device" in hit.message


# ---------------------------------------------------------------------------
# planted pre-fix shapes (the first whole-repo run's live findings)


def test_planted_multi_gated_host_fold_caught(tmp_path):
    # the EXACT shape that was live at chunked.py (collect+certificate),
    # sketch.py and monitor.py: staging gated on the resolved tuple's
    # length, so an explicitly requested single device host-folded
    src = """
        def update_stream(self, source, pipeline_depth, devices):
            devs = resolve_stream_devices(devices)
            multi = len(devs) > 1 and pipeline_depth > 0
            with _key_chunk_stream(
                source, pipeline_depth=pipeline_depth,
                hist_method="scatter" if multi else None,
                devices=devs if multi else None,
            ) as kc:
                for keys, _ in kc:
                    fold(keys)
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    assert _hits(report, "KSL022"), "pre-fix host-fold shape not caught"


def test_planted_shape_fixed_form_clean(tmp_path):
    src = """
        def update_stream(self, source, pipeline_depth, devices):
            devs = resolve_stream_devices(devices)
            staged = pipeline_depth > 0 and devices is not None
            with _key_chunk_stream(
                source, pipeline_depth=pipeline_depth,
                hist_method="scatter" if staged else None,
                devices=devs if staged else None,
            ) as kc:
                for keys, _ in kc:
                    fold(keys)
    """
    report = _lint_source(tmp_path, src, select=["KSL022"])
    assert _hits(report, "KSL022") == []


# ---------------------------------------------------------------------------
# runtime regressions for the fixed paths


def _chunks(n=4, size=512, seed=3):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, 1 << 31, size, dtype=np.int64).astype(np.int32)
        for _ in range(n)
    ]


def test_runtime_explicit_single_device_stages_committed():
    # pre-fix: devices=1 fell through `multi` to the host fold
    # (device_slot None); the caller asked for a placement and silently
    # got the default. Post-fix every chunk stages committed on slot 0.
    from mpi_k_selection_tpu.obs import Observability
    from mpi_k_selection_tpu.streaming.sketch import RadixSketch

    chunks = _chunks()
    obs = Observability.collecting()
    sk = RadixSketch(np.dtype(np.int32))
    sk.update_stream(chunks, pipeline_depth=2, devices=1, obs=obs)
    evs = obs.events.of_kind("stream.chunk")
    assert len(evs) == len(chunks)
    for ev in evs:
        assert ev.staged, ev
        assert ev.device_slot == 0, ev
    # and the staged fold is bit-identical to the host fold
    ref = RadixSketch(np.dtype(np.int32))
    for c in chunks:
        ref.update(c)
    assert sk == ref


def test_runtime_default_single_slot_path_unchanged():
    # devices=None stays the uncommitted default path — the fix extends
    # staging to EXPLICIT single devices only
    from mpi_k_selection_tpu.obs import Observability
    from mpi_k_selection_tpu.streaming.sketch import RadixSketch

    chunks = _chunks(seed=5)
    obs = Observability.collecting()
    sk = RadixSketch(np.dtype(np.int32))
    sk.update_stream(chunks, pipeline_depth=2, obs=obs)
    evs = obs.events.of_kind("stream.chunk")
    assert len(evs) == len(chunks)
    assert all(ev.device_slot is None for ev in evs)


def test_runtime_collect_pass_explicit_single_device():
    from mpi_k_selection_tpu.obs import Observability
    from mpi_k_selection_tpu.streaming.chunked import streaming_kselect

    chunks = _chunks(seed=11)
    flat = np.concatenate(chunks)
    k = 37
    obs = Observability.collecting()
    out = streaming_kselect(
        chunks, k, pipeline_depth=2, devices=1, spill="off", obs=obs
    )
    assert np.asarray(out) == np.partition(flat, k - 1)[k - 1]
    staged_evs = [
        ev for ev in obs.events.of_kind("stream.chunk") if ev.staged
    ]
    assert staged_evs and all(ev.device_slot == 0 for ev in staged_evs)


def test_runtime_add_stream_builds_sketch_through_streaming_layer():
    # serve's add_stream used to host-fold chunk by chunk regardless of
    # the dataset's held staging knobs; it now runs ONE update_stream
    # pass with them, bit-identical to the host reference
    import jax

    from mpi_k_selection_tpu.serve.registry import DatasetRegistry
    from mpi_k_selection_tpu.streaming.sketch import RadixSketch

    chunks = _chunks(seed=13)
    devices = 2 if len(jax.devices()) >= 2 else 1
    reg = DatasetRegistry()
    ds = reg.add_stream(
        "d1", chunks, pipeline_depth=2, devices=devices
    )
    assert ds.n == sum(c.size for c in chunks)
    assert ds.dtype == np.dtype(np.int32)
    assert ds.stream_kwargs["devices"] == devices
    ref = RadixSketch(np.dtype(np.int32))
    for c in chunks:
        ref.update(c)
    assert ds.sketch == ref


def test_runtime_add_stream_empty_source_still_raises():
    from mpi_k_selection_tpu.serve.errors import QueryError
    from mpi_k_selection_tpu.serve.registry import DatasetRegistry

    reg = DatasetRegistry()
    with pytest.raises(QueryError):
        reg.add_stream("empty", [np.asarray([], np.int32)])


# ---------------------------------------------------------------------------
# THE GATE: zero findings repo-wide, off the shared parsed-module set


def test_placement_rules_clean_repo_wide():
    report = run_analysis(
        [REPO / PKG], root=REPO, contracts=False,
        select=["KSL022", "KSL023", "KSL024"],
        mods=shared_modules([REPO / PKG], root=REPO),
    )
    assert report.unsuppressed == [], [
        f.render() for f in report.unsuppressed
    ]


def test_placement_gate_whole_repo(tmp_path):
    report = build_placement_report(
        [REPO / PKG], root=REPO, mods=shared_modules([REPO / PKG], root=REPO)
    )
    art = json.dumps(report, indent=2, sort_keys=True)
    (tmp_path / "kselect_placement.json").write_text(art)
    try:  # best-effort /tmp mirror (shared-host permission hazard)
        pathlib.Path("/tmp/kselect_placement.json").write_text(art)
    except OSError:
        pass
    pl = report["placements"]
    # the graph is populated and package-relative (cwd-independent)
    assert "streaming/pipeline.py" in pl
    assert "streaming/executor.py" in pl
    assert all(p.split("/", 1)[0] in (
        "streaming", "serve", "monitor", "ops", "parallel"
    ) for p in pl)
    # the staging boundary's crossings are all sanctioned
    boundary = pl["streaming/pipeline.py"]["crossing_sites"]
    assert boundary and all(s["sanctioned"] for s in boundary)
    # dispatch sites exist with the executor's vocabulary
    ex_calls = {
        s["call"] for s in pl["streaming/executor.py"]["dispatch_sites"]
    }
    assert ex_calls & rp.DISPATCH_CALLS
    # every shipped `# ksel: placed-on[...]` annotation is LIVE
    for a in report["annotations"]:
        assert a["used"] and a["justification"], a
    # the exported vocabulary IS the registry
    assert report["sanctioned_transfers"] == dict(
        rp.SANCTIONED_TRANSFER_SITES
    )
    assert report["rules"] == ["KSL022", "KSL023", "KSL024"]


def test_placement_report_cli_cwd_independent(tmp_path, monkeypatch):
    out = tmp_path / "pl.json"
    monkeypatch.chdir(tmp_path)
    rc = lint_main(
        [
            str(REPO / PKG / "streaming" / "pipeline.py"),
            "--no-contracts",
            "--placement-report", str(out),
        ]
    )
    assert rc == 0
    data = json.loads(out.read_text())
    assert "streaming/pipeline.py" in data["placements"]
    assert data["sanctioned_transfers"] == dict(
        rp.SANCTIONED_TRANSFER_SITES
    )


def test_placement_selector_flag(capsys):
    rc = lint_main(
        [str(REPO / PKG / "streaming"), "--placement", "--no-contracts"]
    )
    assert rc == 0
    outp = capsys.readouterr().out
    assert "KSL022" in outp and "KSL024" in outp


# ---------------------------------------------------------------------------
# the shared parsed-module cache + the declared wall budget


def test_shared_modules_cache_identity():
    a = shared_modules([REPO / PKG], root=REPO)
    b = shared_modules([REPO / PKG], root=REPO)
    assert a is b  # the four gates literally share one parsed set
    assert any(m.relpath.endswith("streaming/pipeline.py") for m in a)


def test_analysis_gates_within_declared_wall_budget():
    # the four whole-repo dataflow scans (ast, concurrency, lifecycle,
    # placement) off ONE shared parsed set, against the declared ceiling
    from mpi_k_selection_tpu.analysis.modcache import (
        ANALYSIS_GATE_WALL_BUDGET_S,
    )

    mods = shared_modules([REPO / PKG], root=REPO)
    t0 = time.perf_counter()  # ksel: noqa[KSL004] -- wall budget, no device work timed
    for select in (
        ["KSL"],
        ["KSL015", "KSL016", "KSL017"],
        ["KSL019", "KSL020", "KSL021"],
        ["KSL022", "KSL023", "KSL024"],
    ):
        run_analysis(
            [REPO / PKG], root=REPO, contracts=False, select=select,
            mods=mods,
        )
    elapsed = time.perf_counter() - t0  # ksel: noqa[KSL004] -- wall budget, no device work timed
    assert elapsed < ANALYSIS_GATE_WALL_BUDGET_S, (
        f"four whole-repo scans took {elapsed:.1f}s, budget "
        f"{ANALYSIS_GATE_WALL_BUDGET_S}s"
    )


def test_run_analysis_mods_matches_parse_loop():
    mods = shared_modules([REPO / PKG], root=REPO)
    with_mods = run_analysis(
        [REPO / PKG], root=REPO, contracts=False,
        select=["KSL022", "KSL023", "KSL024"], mods=mods,
    )
    without = run_analysis(
        [REPO / PKG], root=REPO, contracts=False,
        select=["KSL022", "KSL023", "KSL024"],
    )
    assert [f.render() for f in with_mods.findings] == [
        f.render() for f in without.findings
    ]
    assert sorted(with_mods.files) == sorted(str(f) for f in without.files)


def test_shared_modules_raises_on_syntax_error(tmp_path):
    from mpi_k_selection_tpu.analysis import modcache

    (tmp_path / "bad.py").write_text("def broken(:\n")
    with pytest.raises(SyntaxError):
        shared_modules([tmp_path])
    modcache.clear()


# ---------------------------------------------------------------------------
# KSC105 — static<->runtime placement-census agreement


def test_ksc105_registered():
    from mpi_k_selection_tpu.analysis.jaxpr_checks import CONTRACT_CHECKS

    ids = {c.id for c in CONTRACT_CHECKS}
    assert "KSC105" in ids


def test_ksc105_agreement_clean():
    # the full contract: unsanctioned static crossings, KSC104-traced
    # modules statically crossing-free, the dispatch vocabulary live,
    # and the recorded device_slot streams on the devices {1,2} x spill
    # {off,force} grid matching the round-robin prediction with replay
    # landing on recorded slots bit-identically
    from mpi_k_selection_tpu.analysis.placement import (
        _check_placement_agreement,
    )

    findings = _check_placement_agreement()
    assert findings == [], [f.render() for f in findings]


def test_ksc105_slot_stream_multi_device_round_robin():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from mpi_k_selection_tpu.analysis.placement import _slot_stream_findings

    assert _slot_stream_findings(2, False) == []
    assert _slot_stream_findings(2, True) == []
