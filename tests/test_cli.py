"""CLI driver: backends, algorithms, top-k mode, verify, JSON output."""

import json

import numpy as np
import pytest

from mpi_k_selection_tpu.cli import main


def test_seq_backend_verify(capsys):
    rc = main(["--backend", "seq", "--n", "10000", "--k", "250", "--verify"])
    out = capsys.readouterr().out
    assert rc == 0
    # the sequential program's distinct output contract (kth-problem-seq.c:37)
    assert "Solution found solution=" in out and "exact match" in out


def test_tpu_backend_reference_output(capsys):
    rc = main(["--backend", "tpu", "--n", "20000", "--k", "100", "--distribute", "never"])
    out = capsys.readouterr().out
    assert rc == 0
    # the CGM program's output contract (TODO-kth-problem-cgm.c:280)
    assert "kth element=" in out


def test_tpu_backend_json(capsys):
    rc = main(
        ["--backend", "tpu", "--n", "65536", "--verify", "--json", "--distribute", "never"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["n"] == 65536
    assert rec["k"] == 32768  # default: median (N/2)
    assert rec["extra"]["exact_match"] is True


def test_cgm_algorithm(capsys):
    rc = main(
        ["--backend", "tpu", "--algorithm", "cgm", "--n", "32768", "--verify", "--json"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["extra"]["exact_match"] is True


def test_topk_mode(capsys):
    rc = main(
        [
            "--backend", "tpu", "--gen", "normal", "--dtype", "float32",
            "--n", "4096", "--topk", "16", "--verify", "--json",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["algorithm"] == "topk" and rec["extra"]["exact_match"] is True


def test_batched_topk_mode(capsys):
    rc = main(
        [
            "--backend", "tpu", "--gen", "funiform", "--dtype", "float32",
            "--n", "1024", "--batch", "8", "--topk", "4", "--verify", "--json",
        ]
    )
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["extra"]["exact_match"] is True


def test_k_out_of_range():
    with pytest.raises(SystemExit):
        main(["--backend", "seq", "--n", "100", "--k", "0"])


def test_reference_operating_point(capsys):
    # k=250 at small n, seq oracle — the kth-problem-seq.c:24 operating point
    rc = main(["--backend", "seq", "--n", "100000", "--k", "250", "--json"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    x = np.sort(
        __import__("mpi_k_selection_tpu.utils.datagen", fromlist=["generate"]).generate(
            100000, pattern="uniform", seed=0, dtype=np.int32
        )
    )
    assert rec["answer"] == int(x[249])


def test_float16_dtype(capsys):
    rc = main(
        ["--backend", "tpu", "--gen", "funiform", "--dtype", "float16",
         "--n", "20000", "--k", "500", "--verify", "--json", "--distribute", "never"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert json.loads(out.strip().splitlines()[-1])["extra"]["exact_match"] is True


def test_cli_quantiles(capsys):
    from mpi_k_selection_tpu.cli import main

    rc = main(
        ["--backend", "tpu", "--n", "100000", "--quantiles", "0.5,0.9,0.99",
         "--seed", "5", "--verify"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "exact match" in out


def test_cli_quantiles_bad_combo():
    from mpi_k_selection_tpu.cli import main

    with pytest.raises(SystemExit, match="exclusive"):
        main(["--quantiles", "0.5", "--topk", "8"])
    with pytest.raises(SystemExit, match="tpu backend"):
        main(["--backend", "seq", "--quantiles", "0.5", "--n", "1000"])


def test_cli_quantiles_distributed(monkeypatch):
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from mpi_k_selection_tpu.cli import main

    rc = main(
        ["--backend", "tpu", "--n", "100000", "--quantiles", "0.25,0.75",
         "--distribute", "always", "--seed", "6", "--verify", "--json"]
    )
    assert rc == 0


def test_cli_quantiles_devices_cap_auto_falls_back_single(capsys):
    from mpi_k_selection_tpu.cli import main

    rc = main(
        ["--backend", "tpu", "--n", "50000", "--quantiles", "0.5",
         "--devices", "1", "--seed", "3", "--verify"]
    )
    assert rc == 0
    assert "exact match" in capsys.readouterr().out


def test_cli_quantiles_devices_cap_always_errors():
    # distribute='always' capped below 2 devices raises (the reference's
    # world_size >= 2 abort), no silent single-chip fallback
    import pytest

    from mpi_k_selection_tpu.cli import main

    with pytest.raises(SystemExit, match="needs >= 2 devices"):
        main(
            ["--backend", "tpu", "--n", "50000", "--quantiles", "0.5",
             "--distribute", "always", "--devices", "1", "--seed", "3"]
        )


def test_cli_quantiles_rejects_non_radix_algorithm():
    from mpi_k_selection_tpu.cli import main

    with pytest.raises(SystemExit, match="radix"):
        main(["--quantiles", "0.5", "--algorithm", "sort", "--n", "1000"])


def test_cli_metrics_json_and_trace_events_streaming(tmp_path, capsys):
    """--metrics-json / --trace-events (ISSUE 6): a streaming run writes a
    parseable metrics registry snapshot and a perfetto-loadable Chrome
    trace with producer AND consumer thread tracks, composing with
    --profile, without changing the answer or exit code."""
    mpath = tmp_path / "metrics.json"
    tpath = tmp_path / "trace.json"
    rc = main([
        "--streaming", "--backend", "tpu", "--n", "40000",
        "--chunk-elems", "8192", "--verify", "--profile", "--json",
        "--metrics-json", str(mpath), "--trace-events", str(tpath),
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["extra"]["exact_match"] is True
    assert rec["extra"]["metrics_json"] == str(mpath)
    assert rec["extra"]["trace_events"] == str(tpath)
    metrics = json.loads(mpath.read_text())
    # the catalog's load-bearing entries are present with sane values
    assert metrics["staging_pool.misses"]["type"] == "counter"
    assert metrics["inflight.occupancy"]["type"] == "histogram"
    stall = metrics['phase.seconds{phase="pipeline.stall"}']
    assert stall["type"] == "gauge" and stall["value"] >= 0
    solve = metrics['phase.seconds{phase="solve"}']
    assert solve["value"] > 0  # the driver timer folded in at _finish
    trace = json.loads(tpath.read_text())
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert xs and len({e["tid"] for e in xs}) >= 2  # producer + consumer
    names = {e["name"] for e in xs}
    assert "pipeline.produce" in names and "descent.pass" in names


def test_cli_metrics_json_alone_carries_pipeline_phases(tmp_path, capsys):
    """--metrics-json WITHOUT --profile/--trace-events must still export
    the pipeline/descent phase gauges its help text promises (the timer
    exists for the registry, not only for the report)."""
    mpath = tmp_path / "m.json"
    rc = main([
        "--streaming", "--backend", "tpu", "--n", "40000",
        "--chunk-elems", "8192", "--json", "--metrics-json", str(mpath),
    ])
    assert rc == 0
    json.loads(capsys.readouterr().out)
    metrics = json.loads(mpath.read_text())
    assert metrics['phase.seconds{phase="pipeline.stall"}']["value"] >= 0
    assert metrics['phase.seconds{phase="descent.pass"}']["value"] > 0


def test_cli_metrics_json_resident_mode(tmp_path, capsys):
    """The flags also work outside --streaming: the driver phases
    (generate/solve) land in the registry and the trace."""
    mpath = tmp_path / "m.json"
    tpath = tmp_path / "t.json"
    rc = main([
        "--backend", "tpu", "--n", "30000", "--distribute", "never", "--json",
        "--metrics-json", str(mpath), "--trace-events", str(tpath),
    ])
    assert rc == 0
    json.loads(capsys.readouterr().out)
    metrics = json.loads(mpath.read_text())
    assert metrics['phase.seconds{phase="solve"}']["value"] > 0
    trace = json.loads(tpath.read_text())
    assert {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"} >= {
        "generate", "solve",
    }


def test_cli_check_does_not_clobber_solve_metrics(tmp_path, capsys):
    """--check shares only the TRACE channel with the solve: the written
    metrics registry must describe the solve's pipeline phases (not get
    overwritten by the certificate pass's fresh timer), while the trace
    still shows the certificate span on the shared timeline."""
    mpath = tmp_path / "m.json"
    tpath = tmp_path / "t.json"
    rc = main([
        "--streaming", "--backend", "tpu", "--n", "40000",
        "--chunk-elems", "8192", "--check", "--profile", "--json",
        "--metrics-json", str(mpath), "--trace-events", str(tpath),
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    metrics = json.loads(mpath.read_text())
    want_calls = rec["extra"]["pipeline_phases"]["pipeline.produce"]["calls"]
    got_calls = metrics['phase.calls{phase="pipeline.produce"}']["value"]
    assert got_calls == want_calls  # the SOLVE's counts, not the check's
    trace = json.loads(tpath.read_text())
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert "certificate.pass" in names  # still traced, same timeline
    # per-device chunk counters match an identical run WITHOUT --check:
    # the certificate's chunks must not additively pollute the registry
    mpath2 = tmp_path / "m2.json"
    rc = main([
        "--streaming", "--backend", "tpu", "--n", "40000",
        "--chunk-elems", "8192", "--profile", "--json",
        "--metrics-json", str(mpath2),
    ])
    assert rc == 0
    capsys.readouterr()
    metrics2 = json.loads(mpath2.read_text())

    def _chunk_totals(m):
        return {
            name: v["value"] for name, v in m.items()
            if name.startswith("ingest.chunks{")
        }

    assert _chunk_totals(metrics) == _chunk_totals(metrics2)
