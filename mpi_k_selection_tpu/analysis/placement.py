"""Device-placement dataflow pass (KSL022-KSL024) + the KSC105
static<->runtime placement-census agreement contract.

The CGM discipline this package ports (every processor touches exactly
its own partition) appears here as three runtime conventions: staged
chunk *j* commits to ``devices[j % p]``, a bucket's programs dispatch on
the bucket's OWN device, and spill replay re-stages every record onto
its recorded slot. Until this pass, all of that was enforced only at
runtime (KSC104's host-transfer census, the recorded ``device_slot``
streams) plus one shallow syntactic rule (KSL007). This module proves
the discipline statically, the way lifecycle.py proves release-on-every-
path: an abstract **placement lattice** per value,

    ``unknown``      no information (bottom; joins absorb it)
    ``none``         explicitly no placement (the uncommitted default)
    ``host``         a host-side value (device_get / np.asarray result)
    ``device(slot)`` committed to one slot expression
    ``slots``        a resolved device tuple (resolve_stream_devices)
    ``round-robin``  slots indexed by chunk position (``devs[j % p]``)
    ``inherited``    a device chunk's own committed device
    ``top``          conflicting placements met (the finding state)

seeded at the known placement sources (``stage_keys``/
``stage_device_keys``, ``jax.device_put(..., device=)``,
``resolve_stream_devices``, ``.device`` reads), propagated through
assignments, one interprocedural hop (module-local functions returning
placed values — the same discipline lifecycle.py applies to
acquire-returning functions) and container round-trips (a FIFO window /
pipeline queue keeps the slot its pushed value carried). Loop bodies are
walked twice so loop-carried slots converge.

The rules:

- **KSL022** — dispatch-device mismatch: one program dispatch consuming
  buckets from two different slots, a conflicting (``top``) placement
  reaching a dispatch, or a resolved device tuple dropped under a
  condition that depends on the tuple itself (``devs if len(devs) > 1
  else None`` — the silent single-device host-fallback bug class; gate
  on the placement-independent knob instead). Also carries the
  ``# ksel: placed-on[...]`` stale-annotation audit.
- **KSL023** — unsanctioned transfer: a host<->device crossing call at
  a module outside ``resource_protocols.SANCTIONED_TRANSFER_SITES`` —
  the static, path-sensitive generalization of KSL007 (which delegates
  its source model here and keeps only its streaming/ scope).
- **KSL024** — placement nondeterminism: a device-target expression
  data-dependent on a clock, thread identity, randomness or set/dict
  iteration order. Device choice must be a pure function of chunk
  index, an explicit knob or a recorded slot, or spill replay cannot
  re-stage deterministically — this rule makes replay determinism a
  proved property instead of a convention.

Declared intent rides ``# ksel: placed-on[<slot-expr>] -- why`` on the
site line: it overrides the pass's verdict there, is exported to the
report ledger, and is itself audited — an annotation on a line carrying
no dispatch, crossing or device-target expression is a finding (the
owner[]/guarded-by[] staleness discipline applied to placement).

**KSC105** closes the loop with the runtime: the static census must
agree with KSC104's traced programs (a module whose programs KSC104
proves crossing-free may not contain a static crossing site), and the
recorded ``device_slot`` event streams on the devices {1, 2} x spill
{off, force} grid must match the round-robin prediction, with spill
replay landing every chunk back on its recorded slot bit-identically.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

from mpi_k_selection_tpu import resource_protocols as _rp
from mpi_k_selection_tpu.analysis.ast_rules import (
    _function_defs,
    dotted_name,
)
from mpi_k_selection_tpu.analysis.concurrency import _in_package, _pkg_relpath
from mpi_k_selection_tpu.analysis.core import Finding, Rule, SourceModule, register
from mpi_k_selection_tpu.analysis.jaxpr_checks import contract

_PKG = "mpi_k_selection_tpu"


def _scoped_relpath(mod: SourceModule) -> str:
    """``streaming/pipeline.py``-style path (the package segment
    stripped) — the key form of ``SANCTIONED_TRANSFER_SITES`` and the
    join key against KSC104's census module paths."""
    rel = _pkg_relpath(mod)
    return rel.split("/", 1)[1] if rel.startswith(_PKG + "/") else rel

# ---------------------------------------------------------------------------
# the lattice

_PLACED_KINDS = frozenset({"device", "slots", "round-robin", "inherited"})
_DROPPABLE = frozenset({"none", "host", "unknown"})


@dataclasses.dataclass(frozen=True)
class Placement:
    """One lattice point. ``slot`` is the source text of the slot (or
    tuple) expression; ``reason`` explains a ``top``."""

    kind: str
    slot: str = ""
    reason: str = ""

    def show(self) -> str:
        return f"{self.kind}({self.slot})" if self.slot else self.kind


UNKNOWN = Placement("unknown")
NONE = Placement("none")
HOST = Placement("host")
INHERITED = Placement("inherited")


def join(a: Placement, b: Placement) -> Placement:
    """Lattice join. ``unknown`` is bottom and ``none`` (no explicit
    placement) folds optimistically into a placed value — the
    *conditional* drop of a placed value is judged separately with the
    condition in hand (see ``_FunctionPlacement._merge_cond``), so the
    plain join stays optimistic and the pass stays quiet on the
    sanctioned depth-gated host paths."""
    if a == b:
        return a
    for x, y in ((a, b), (b, a)):
        if x.kind == "top":
            return x
        if x.kind == "unknown":
            return y
    for x, y in ((a, b), (b, a)):
        if x.kind == "none":
            return y
    return Placement("top", reason=f"{a.show()} vs {b.show()}")


# ---------------------------------------------------------------------------
# `# ksel: placed-on[<slot-expr>] -- why` annotations

_PLACED_RE = re.compile(
    r"#\s*ksel:\s*placed-on\[(?P<slot>[^\]]+)\]"
    r"(?:\s*--\s*(?P<why>.*\S))?"
)


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# ---------------------------------------------------------------------------
# the per-function engine


class _FunctionPlacement:
    """Abstract interpretation of one function body over the placement
    lattice. ``record=False`` is the pass-1 walk that only computes the
    function's return placement (the interprocedural seed); pass 2
    re-runs with the module's placed-returning functions in ``extra``
    and records sites + findings."""

    def __init__(self, owner: "_ModulePlacement", fn, extra, record: bool):
        self.o = owner
        self.fn = fn
        self.extra = extra
        self.record = record
        self.env: dict[str, Placement] = {}
        self.defs: dict[str, ast.expr] = {}
        self.return_placement = UNKNOWN

    def run(self):
        self._seq(self.fn.body)
        return self

    # -- statements ---------------------------------------------------------

    def _seq(self, body):
        for st in body:
            self._stmt(st)

    def _stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # analyzed as their own functions
        if isinstance(st, ast.Assign):
            v = self._eval(st.value)
            for t in st.targets:
                self._bind(t, v, st.value)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._bind(st.target, self._eval(st.value), st.value)
        elif isinstance(st, ast.AugAssign):
            self._eval(st.value)  # x += e never re-places x
        elif isinstance(st, (ast.Return,)):
            if st.value is not None:
                self.return_placement = join(
                    self.return_placement, self._eval(st.value)
                )
        elif isinstance(st, ast.Expr):
            self._eval(st.value)
        elif isinstance(st, ast.If):
            self._eval(st.test)
            before = dict(self.env)
            self._seq(st.body)
            after_body = self.env
            self.env = dict(before)
            self._seq(st.orelse)
            after_else = self.env
            merged = {}
            for name in set(after_body) | set(after_else):
                merged[name] = self._merge_cond(
                    after_body.get(name, UNKNOWN),
                    after_else.get(name, UNKNOWN),
                    st.test,
                )
            self.env = merged
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._eval(st.iter)
            tv = UNKNOWN
            if isinstance(st.iter, ast.Name):  # iterating a container
                tv = self.env.get(st.iter.id + "@contents", UNKNOWN)
            for _sweep in (0, 1):  # twice: loop-carried slots converge
                self._bind(st.target, tv, None)
                self._seq(st.body)
            self._seq(st.orelse)
        elif isinstance(st, ast.While):
            self._eval(st.test)
            for _sweep in (0, 1):
                self._seq(st.body)
            self._seq(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                v = self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, v, item.context_expr)
            self._seq(st.body)
        elif isinstance(st, ast.Try):
            self._seq(st.body)
            for h in st.handlers:
                if h.name:
                    self.env[h.name] = UNKNOWN
                self._seq(h.body)
            self._seq(st.orelse)
            self._seq(st.finalbody)
        elif isinstance(st, (ast.Raise, ast.Assert, ast.Delete)):
            for c in ast.iter_child_nodes(st):
                if isinstance(c, ast.expr):
                    self._eval(c)
        # pass/break/continue/global/import: nothing placed moves

    def _bind(self, target, placement: Placement, value_node):
        if isinstance(target, ast.Name):
            self.env[target.id] = placement
            if value_node is not None:
                self.defs[target.id] = value_node
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = (
                value_node.elts
                if isinstance(value_node, (ast.Tuple, ast.List))
                and len(value_node.elts) == len(target.elts)
                else None
            )
            for i, t in enumerate(target.elts):
                if elts is not None:
                    self._bind(t, self._eval(elts[i]), elts[i])
                else:
                    self._bind(t, UNKNOWN, None)
        elif isinstance(target, ast.Attribute):
            d = dotted_name(target)
            if d:
                self.env[d] = placement
        elif isinstance(target, ast.Starred):
            self._bind(target.value, UNKNOWN, None)
        # subscript targets don't re-place their base

    # -- conditional drops ---------------------------------------------------

    def _merge_cond(self, a: Placement, b: Placement, test) -> Placement:
        """Join two branch placements under ``test``. Dropping a placed
        value to None/host on one branch is sanctioned only when the
        condition is placement-independent (a depth knob, the raw
        ``devices`` argument) — a condition that depends on the resolved
        placement itself (``len(devs) > 1``) is the single-device
        silent-host-fallback bug class and joins to ``top``."""
        placed, other = (a, b) if a.kind in _PLACED_KINDS else (b, a)
        if (
            placed.kind in _PLACED_KINDS
            and other.kind in _DROPPABLE
            and other.kind != "unknown"
            and test is not None
            and self._cond_depends_on_placed(test)
        ):
            return Placement(
                "top",
                slot=placed.slot,
                reason=(
                    f"resolved placement {placed.show()} is dropped under a "
                    "condition that depends on the placement itself — gate "
                    "the host path on a placement-independent knob "
                    "(pipeline depth, the raw devices argument) instead"
                ),
            )
        return join(a, b)

    def _cond_depends_on_placed(self, test) -> bool:
        for name in _names_in(test):
            if self.env.get(name, UNKNOWN).kind in _PLACED_KINDS:
                return True
            rhs = self.defs.get(name)  # one hop: `multi = len(devs) > 1 ...`
            if rhs is not None:
                for m in _names_in(rhs):
                    if self.env.get(m, UNKNOWN).kind in _PLACED_KINDS:
                        return True
        return False

    # -- expressions ---------------------------------------------------------

    def _txt(self, node) -> str:
        seg = self.o.mod.segment(node)
        if seg:
            return " ".join(seg.split())
        try:
            return ast.unparse(node)
        except Exception:  # pragma: no cover - unparse is total on exprs
            return "<expr>"

    def _eval(self, node) -> Placement:
        if node is None:
            return UNKNOWN
        if isinstance(node, ast.Constant):
            return NONE if node.value is None else UNKNOWN
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Attribute):
            self._eval(node.value)
            d = dotted_name(node)
            if d and d in self.env:
                return self.env[d]
            if node.attr == "device":  # StagedKeys.device / array.device
                return Placement("device", slot=self._txt(node))
            return UNKNOWN
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value)
            self._eval(node.slice)
            if base.kind == "slots":
                self._check_nondet(node.slice, "slot index")
                idx = self._txt(node.slice)
                kind = "round-robin" if "%" in idx else "device"
                return Placement(kind, slot=self._txt(node))
            return UNKNOWN
        if isinstance(node, ast.IfExp):
            self._eval(node.test)
            return self._merge_cond(
                self._eval(node.body), self._eval(node.orelse), node.test
            )
        if isinstance(node, (ast.Tuple, ast.List)):
            placed = [
                p for p in (self._eval(e) for e in node.elts)
                if p.kind in _PLACED_KINDS
            ]
            # a tuple carries its single placed element's slot through a
            # container round-trip; a mixed tuple (the devs tuple itself)
            # is not a placement conflict
            if placed and all(p == placed[0] for p in placed):
                return placed[0]
            return UNKNOWN
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self._eval(v)
            return UNKNOWN
        for c in ast.iter_child_nodes(node):
            if isinstance(c, ast.expr):
                self._eval(c)
        return UNKNOWN

    def _kwnode(self, node, *names):
        for kw in node.keywords:
            if kw.arg in names:
                return kw.value
        return None

    def _call(self, node: ast.Call) -> Placement:
        name = dotted_name(node.func) or ""
        last = name.rsplit(".", 1)[-1]
        argp = [self._eval(a) for a in node.args]
        kwp = {}
        for kw in node.keywords:
            v = self._eval(kw.value)
            if kw.arg:
                kwp[kw.arg] = v

        if last in _rp.SLOT_RESOLVER_CALLS:
            return Placement("slots", slot=self._txt(node))
        if last in _rp.INHERIT_STAGE_CALLS:
            return INHERITED
        if last in _rp.STAGE_CALLS:
            tgt = (
                node.args[1]
                if len(node.args) > 1
                else self._kwnode(node, "device")
            )
            return self._target(tgt)
        if name in _rp.TRANSFER_PUT_CALLS:
            self._site_crossing(node, name)
            tgt = (
                node.args[1]
                if len(node.args) > 1
                else self._kwnode(node, *sorted(_rp.PUT_TARGET_KWARGS))
            )
            if tgt is None:
                return NONE  # uncommitted put — KSL007's subject
            return self._target(tgt)
        if name in _rp.CROSSING_CALLS or last in ("device_get", "copy_to_host_async"):
            self._site_crossing(node, name or last)
            return HOST
        if last in _rp.DISPATCH_CALLS:
            self._site_dispatch(node, last, argp, kwp, mismatch=True)
            return UNKNOWN
        if last in _rp.DEVICE_THREADING_CALLS:
            self._site_dispatch(node, last, argp, kwp, mismatch=False)
            return UNKNOWN
        if isinstance(node.func, ast.Attribute):
            recv = dotted_name(node.func.value)
            if recv:  # container round-trips keep the pushed slot
                if last in ("push", "put", "_put", "append", "appendleft", "add"):
                    if argp:
                        key = recv + "@contents"
                        self.env[key] = join(self.env.get(key, UNKNOWN), argp[0])
                    return UNKNOWN
                if last in ("pop", "popleft", "get", "drain", "peek"):
                    return self.env.get(recv + "@contents", UNKNOWN)
        if last in self.extra:  # the one interprocedural hop
            return self.extra[last]
        return UNKNOWN

    def _target(self, tgt_node) -> Placement:
        """Placement of a device-target expression (stage_keys' device
        argument, a device_put target, a threading call's devices=)."""
        if tgt_node is None:
            return NONE
        self._check_nondet(tgt_node, "device target")
        p = self._eval(tgt_node)
        if p.kind in _PLACED_KINDS or p.kind == "top":
            return p
        if isinstance(tgt_node, ast.Constant) and tgt_node.value is None:
            return NONE
        return Placement("device", slot=self._txt(tgt_node))

    # -- site checks ---------------------------------------------------------

    def _site_crossing(self, node, name):
        if not self.record:
            return
        self.o.note_site(node.lineno)
        rel = _scoped_relpath(self.o.mod)
        sanctioned = rel in _rp.SANCTIONED_TRANSFER_SITES
        self.o.crossing_sites.append(
            {"line": node.lineno, "call": name, "sanctioned": sanctioned}
        )
        if not sanctioned:
            self.o.emit(
                node.lineno,
                "KSL023",
                f"`{name}` host<->device crossing at {rel}, which is not a "
                "sanctioned transfer site — route the transfer through the "
                "staging boundary (streaming/pipeline.py) or register the "
                "module in resource_protocols.SANCTIONED_TRANSFER_SITES "
                "with a written reason",
            )

    def _site_dispatch(self, node, name, argp, kwp, *, mismatch: bool):
        if not self.record:
            return
        self.o.note_site(node.lineno)
        operands = list(argp)
        for k in ("device", "devices"):
            if k in kwp:
                operands.append(kwp[k])
        slots = sorted(
            {p.slot for p in operands if p.kind in ("device", "round-robin")}
        )
        self.o.dispatch_sites.append(
            {
                "line": node.lineno,
                "call": name,
                "kind": "dispatch" if mismatch else "threading",
                "slots": slots,
            }
        )
        if mismatch and len(slots) > 1:
            self.o.emit(
                node.lineno,
                "KSL022",
                f"`{name}` dispatch consumes operands placed on different "
                f"slots ({', '.join(slots)}) — one program dispatch, one "
                "device; thread the bucket's own slot",
            )
        for p in operands:
            if p.kind == "top":
                self.o.emit(
                    node.lineno,
                    "KSL022",
                    f"`{name}` consumes a conflicting placement: {p.reason}",
                )

    def _check_nondet(self, expr, context: str):
        if not self.record:
            return
        self.o.note_site(getattr(expr, "lineno", self.fn.lineno))

        def scan(e, hop_left):
            for sub in ast.walk(e):
                if isinstance(sub, ast.Call):
                    dn = dotted_name(sub.func) or ""
                    ln = dn.rsplit(".", 1)[-1]
                    if dn in _rp.NONDET_PLACEMENT_CALLS or (
                        ln in ("get_ident", "current_thread", "urandom",
                               "uuid1", "uuid4")
                    ):
                        self.o.emit(
                            getattr(sub, "lineno", expr.lineno),
                            "KSL024",
                            f"{context} depends on `{dn or ln}` — device "
                            "choice must be a pure function of chunk index, "
                            "an explicit knob or a recorded slot, or spill "
                            "replay cannot re-stage deterministically",
                        )
                    elif dn in _rp.UNORDERED_CONSTRUCTORS:
                        self.o.emit(
                            getattr(sub, "lineno", expr.lineno),
                            "KSL024",
                            f"{context} drawn from a `{dn}` — set/dict "
                            "iteration order is no contract; a device index "
                            "must come from an ordered, recorded source",
                        )
                elif isinstance(sub, ast.Name) and hop_left:
                    rhs = self.defs.get(sub.id)
                    if rhs is not None and rhs is not e:
                        scan(rhs, hop_left - 1)

        scan(expr, 1)


# ---------------------------------------------------------------------------
# the per-module analyzer


class _ModulePlacement:
    def __init__(self, mod: SourceModule):
        self.mod = mod
        self.findings: set[tuple[int, str, str]] = set()
        self.dispatch_sites: list[dict] = []
        self.crossing_sites: list[dict] = []
        self.annotations: dict[int, tuple[str, str]] = {}
        self.site_lines: set[int] = set()
        in_string = mod.string_literal_lines()
        for i, text in enumerate(mod.lines, start=1):
            if i in in_string:
                continue
            m = _PLACED_RE.search(text)
            if m:
                self.annotations[i] = (
                    m.group("slot").strip(),
                    (m.group("why") or "").strip(),
                )

    def note_site(self, line: int):
        self.site_lines.add(line)

    def emit(self, line: int, rule: str, message: str):
        if line in self.annotations:
            return  # declared placement overrides; audited below
        self.findings.add((line, rule, message))

    def run(self) -> "_ModulePlacement":
        fns = [
            fn for defs in _function_defs(self.mod.tree).values() for fn in defs
        ]
        returns: dict[str, Placement] = {}
        for fn in fns:  # pass 1: placed-returning functions
            eng = _FunctionPlacement(self, fn, {}, record=False).run()
            if eng.return_placement.kind in _PLACED_KINDS:
                returns[fn.name] = eng.return_placement
        for fn in fns:  # pass 2: sites + findings, with the hop seeded
            _FunctionPlacement(self, fn, returns, record=True).run()
        self._audit_annotations()
        return self

    def _audit_annotations(self):
        for line, (slot, _why) in sorted(self.annotations.items()):
            if line not in self.site_lines:
                self.findings.add(
                    (
                        line,
                        "KSL022",
                        f"stale `# ksel: placed-on[{slot}]`: no dispatch, "
                        "crossing or device-target expression on this line "
                        "— placement annotations must sit on the site they "
                        "sanction",
                    )
                )


_CACHE: dict[int, _ModulePlacement] = {}


def analyze_placement(mod: SourceModule) -> _ModulePlacement:
    key = id(mod)
    hit = _CACHE.get(key)
    if hit is None:
        if len(_CACHE) > 4096:  # pragma: no cover - bound, not a policy
            _CACHE.clear()
        hit = _CACHE[key] = _ModulePlacement(mod).run()
    return hit


# ---------------------------------------------------------------------------
# scope + the KSL007 source model

_SCOPED_PACKAGES = ("streaming", "serve", "monitor", "ops", "parallel")


def _in_scope(mod: SourceModule) -> bool:
    if not _in_package(mod):
        return False
    return _scoped_relpath(mod).split("/", 1)[0] in _SCOPED_PACKAGES


def untargeted_puts(mod: SourceModule):
    """``(line, call_name)`` for every ``jax.device_put`` lacking an
    explicit device/sharding target — THE placement-source model KSL007
    gates on (defined here so one placement vocabulary exists, not two:
    the same ``resource_protocols`` names seed the dataflow pass)."""
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and dotted_name(node.func) in _rp.TRANSFER_PUT_CALLS
            and len(node.args) < 2
            and not any(
                kw.arg in _rp.PUT_TARGET_KWARGS for kw in node.keywords
            )
        ):
            yield node.lineno, dotted_name(node.func)


# ---------------------------------------------------------------------------
# the rules


class _PlacementRule(Rule):
    def check_module(self, mod: SourceModule):
        if not _in_scope(mod):
            return
        result = analyze_placement(mod)
        for line, rule, message in sorted(result.findings):
            if rule == self.id:
                yield line, message


@register
class DispatchDeviceMismatch(_PlacementRule):
    id = "KSL022"
    title = "program dispatch with mismatched or dropped device placement"
    rationale = (
        "The streaming discipline is one bucket, one slot, one program: "
        "staged chunk j commits to devices[j % p] and every program the "
        "bucket feeds dispatches on that slot. A dispatch consuming "
        "operands from two slots forces XLA to insert a silent cross-"
        "device copy mid-pass (the exact transfer KSC104's census "
        "forbids); a resolved device tuple dropped under a condition "
        "that depends on the tuple itself (`devs if len(devs) > 1 else "
        "None`) silently host-folds an explicitly requested single "
        "device — the caller asked for a placement and got the default. "
        "Declared intent rides `# ksel: placed-on[<slot>]` and is "
        "audited for staleness like owner[]/guarded-by[]."
    )


@register
class UnsanctionedTransfer(_PlacementRule):
    id = "KSL023"
    title = "host<->device crossing outside the sanctioned transfer registry"
    rationale = (
        "Every legitimate host<->device crossing in the streaming/serve/"
        "monitor/ops/parallel vertical lives at a named site: the "
        "staging boundary (streaming/pipeline.py), the mesh-sharding "
        "registrations (parallel/), the DCN device_get (multihost). A "
        "crossing anywhere else is how mid-pass transfers sneak in — "
        "the static, path-sensitive generalization of KSL007, keyed on "
        "resource_protocols.SANCTIONED_TRANSFER_SITES so the registry "
        "is one importable table, not a rule's private list."
    )


@register
class PlacementNondeterminism(_PlacementRule):
    id = "KSL024"
    title = "device choice data-dependent on a nondeterministic source"
    rationale = (
        "Spill replay re-stages every record onto its recorded slot, and "
        "recovery is bit-identical only because device choice is a pure "
        "function of chunk index, explicit knobs and recorded slots. A "
        "device target derived from a clock, thread identity, randomness "
        "or set/dict iteration order makes placement unreproducible: the "
        "replay lands on different chips than the pass it replays, "
        "recompiles every bucket program, and the flight recorder's "
        "device_slot stream stops describing reality."
    )


# ---------------------------------------------------------------------------
# the exported placement graph


def build_placement_report(paths, root=None, mods=None) -> dict:
    """The placement graph the ``--placement-report`` flag exports:
    per-module dispatch/threading/crossing sites, the annotation ledger
    (with justifications), the sanctioned-transfer registry and the
    lattice vocabulary — package-relative, cwd-independent."""
    from mpi_k_selection_tpu.analysis.core import iter_python_files, load_module

    if mods is None:
        mods = []
        for f in iter_python_files(paths):
            try:
                mods.append(load_module(f, root=root))
            except SyntaxError:
                continue
    placements: dict[str, dict] = {}
    annotations: list[dict] = []
    for mod in mods:
        if not _in_scope(mod):
            continue
        result = analyze_placement(mod)
        rel = _scoped_relpath(mod)
        if result.dispatch_sites or result.crossing_sites:
            placements[rel] = {
                "dispatch_sites": sorted(
                    result.dispatch_sites, key=lambda s: s["line"]
                ),
                "crossing_sites": sorted(
                    result.crossing_sites, key=lambda s: s["line"]
                ),
            }
        for line, (slot, why) in sorted(result.annotations.items()):
            annotations.append(
                {
                    "path": rel,
                    "line": line,
                    "slot": slot,
                    "justification": why,
                    "used": line in result.site_lines,
                }
            )
    return {
        "lattice": [
            "unknown", "none", "host", "device(slot)", "slots",
            "round-robin", "inherited", "top",
        ],
        "placements": placements,
        "annotations": annotations,
        "sanctioned_transfers": dict(_rp.SANCTIONED_TRANSFER_SITES),
        "rules": ["KSL022", "KSL023", "KSL024"],
    }


# ---------------------------------------------------------------------------
# KSC105 — static<->runtime placement-census agreement


def _static_census():
    """(crossing sites by package-relative module, dispatch-call names
    the pass saw) over the installed package — the static half of
    KSC105."""
    pkg_root = pathlib.Path(__file__).resolve().parent.parent
    from mpi_k_selection_tpu.analysis.core import iter_python_files, load_module

    crossings: dict[str, list[dict]] = {}
    dispatch_names: set[str] = set()
    for f in iter_python_files([pkg_root]):
        try:
            mod = load_module(f, root=pkg_root.parent)
        except SyntaxError:
            continue
        if not _in_scope(mod):
            continue
        result = analyze_placement(mod)
        rel = _scoped_relpath(mod)
        if result.crossing_sites:
            crossings[rel] = list(result.crossing_sites)
        dispatch_names.update(
            s["call"] for s in result.dispatch_sites if s["kind"] == "dispatch"
        )
        # a dispatch core passed BY REFERENCE (into jax.jit / a dispatch
        # wrapper) is a live vocabulary use too — operand agreement only
        # applies at direct calls, but the name has not drifted
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Name, ast.Attribute)):
                name = (dotted_name(node) or "").rsplit(".", 1)[-1]
                if name in _rp.DISPATCH_CALLS:
                    dispatch_names.add(name)
    return crossings, dispatch_names


def _slot_stream_findings(devices: int, force_spill: bool) -> list:
    """Run one small staged sketch pass and check the recorded
    ``device_slot`` stream against the round-robin prediction; with
    ``force_spill`` also replay the spill generation and check the
    replay re-stages every chunk onto its recorded slot with a
    bit-identical fold."""
    import numpy as np

    from mpi_k_selection_tpu.obs import Observability
    from mpi_k_selection_tpu.streaming.sketch import RadixSketch
    from mpi_k_selection_tpu.streaming.spill import SpillStore

    here = "mpi_k_selection_tpu/analysis/placement.py"
    findings: list[Finding] = []
    rng = np.random.default_rng(7)
    chunks = [
        rng.integers(0, 1 << 31, 1024, dtype=np.int64).astype(np.int32)
        for _ in range(4)
    ]

    def run(source, store=None):
        obs = Observability.collecting()
        sk = RadixSketch(np.dtype(np.int32))
        sk.update_stream(
            source, pipeline_depth=2, devices=devices, spill=store, obs=obs
        )
        evs = obs.events.of_kind("stream.chunk")
        return sk, evs

    store = SpillStore() if force_spill else None
    try:
        sk, evs = run(chunks, store=store)
        if len(evs) != len(chunks):
            findings.append(
                Finding(
                    "KSC105", here, 0,
                    f"devices={devices} spill={force_spill}: expected "
                    f"{len(chunks)} stream.chunk events, saw {len(evs)}",
                )
            )
        for ev in evs:
            want = ev.chunk_index % devices
            if ev.device_slot != want:
                findings.append(
                    Finding(
                        "KSC105", here, 0,
                        f"devices={devices} spill={force_spill}: chunk "
                        f"{ev.chunk_index} recorded device_slot="
                        f"{ev.device_slot}, round-robin predicts {want} — "
                        "the runtime slot stream disagrees with the static "
                        "placement model",
                    )
                )
        if force_spill:
            sk2, evs2 = run(store)
            if [e.device_slot for e in evs2] != [e.device_slot for e in evs]:
                findings.append(
                    Finding(
                        "KSC105", here, 0,
                        f"devices={devices}: spill replay re-dealt the slots "
                        f"({[e.device_slot for e in evs2]} vs recorded "
                        f"{[e.device_slot for e in evs]}) — replay must "
                        "re-stage every record onto its recorded slot",
                    )
                )
            if sk2 != sk:
                findings.append(
                    Finding(
                        "KSC105", here, 0,
                        f"devices={devices}: spill replay's sketch fold is "
                        "not bit-identical to the teeing pass",
                    )
                )
    finally:
        if store is not None:
            store.close()
    return findings


@contract(
    "KSC105",
    "static placement census agrees with traced programs and recorded slots",
    "The placement pass predicts WHERE crossings and dispatches happen; "
    "KSC104 proves the streaming programs carry no mid-pass crossing and "
    "the runtime records each staged chunk's device_slot. The three views "
    "must agree: a module whose programs KSC104 traces as crossing-free "
    "may not contain a static crossing site, every static crossing must "
    "be sanctioned, and the recorded slot streams on the devices {1,2} x "
    "spill {off,force} grid must match the round-robin prediction with "
    "replay landing on recorded slots (the KSL016/lockorder discipline "
    "applied to placement).",
)
def _check_placement_agreement() -> list:
    findings: list[Finding] = []
    crossings, dispatch_names = _static_census()
    for rel, sites in sorted(crossings.items()):
        for site in sites:
            if not site["sanctioned"]:
                findings.append(
                    Finding(
                        "KSC105", rel, site["line"],
                        f"static census: `{site['call']}` crossing at an "
                        "unsanctioned site survives to the contract layer",
                    )
                )
    # KSC104 agreement: its traced program modules must be statically
    # crossing-free (their zero-mid-pass-crossing claim is a runtime
    # census; this is its static twin over the same modules), and every
    # dispatch-family name must be SEEN by the pass somewhere — a
    # registry name no call site uses means the vocabulary drifted
    from mpi_k_selection_tpu.analysis.jaxpr_checks import _census_cases

    census_rels = set()
    for case in _census_cases():
        census_rels.add(case[0].split("mpi_k_selection_tpu/", 1)[-1])
    for rel in sorted(census_rels):
        if rel in crossings:
            findings.append(
                Finding(
                    "KSC105", rel, crossings[rel][0]["line"],
                    "KSC104 traces this module's programs as crossing-free, "
                    "but the static placement census finds a host<->device "
                    "crossing site in it — the two censuses disagree",
                )
            )
    for name in sorted(_rp.DISPATCH_CALLS - dispatch_names):
        findings.append(
            Finding(
                "KSC105", "mpi_k_selection_tpu/resource_protocols.py", 0,
                f"DISPATCH_CALLS registers `{name}` but the placement pass "
                "sees no call site for it — the dispatch vocabulary has "
                "drifted from the code (remove the name or fix the scan)",
            )
        )
    # runtime agreement on the devices {1,2} x spill {off,force} grid
    import jax

    grid = [1] + ([2] if len(jax.devices()) >= 2 else [])
    for devices in grid:
        for force_spill in (False, True):
            findings.extend(_slot_stream_findings(devices, force_spill))
    return findings
