"""Tracing / profiling subsystem (SURVEY.md §5).

The reference's entire observability story is one wall-clock pair per run
(``clock()`` at ``kth-problem-seq.c:30,35``; ``MPI_Wtime()`` at
``TODO-kth-problem-cgm.c:76,279``). This module is the framework-grade
replacement:

- :class:`PhaseTimer` — named per-phase wall timing (callers sync devices
  with ``block_until_ready``/``np.asarray`` where relevant), the
  "per-round timing" SURVEY.md §5 calls for; renders a report and a dict.
  Thread-safe: the pipelined streaming descent accumulates producer-thread
  phases (produce/encode/stage/spill) and consumer-thread phases
  (stall, per-pass merges) into ONE timer concurrently. An optional
  ``recorder`` (obs/trace.py:TraceRecorder) receives every finished
  ``(name, t0, t1)`` phase on its own thread — the ONE bridge from this
  module's clocks (KSL004: raw clocks live only here and in
  utils/timing.py) to the cross-thread Chrome-trace export.
- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable device trace (XLA op/kernel level), when available.
- :func:`device_memory_stats` — HBM usage snapshot per device.

Used by the CLI via ``--profile`` / ``--trace-dir`` / ``--trace-events``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field

import jax


@dataclass
class PhaseTimer:
    """Accumulates named phase durations: ``with timer.phase('sort'): ...``

    ``recorder`` (optional) gets ``record(name, t0, t1)`` for every
    finished phase, called on the thread that ran it — so one timer
    shared across the pipeline's producer and consumer yields correctly
    thread-attributed spans.
    """

    phases: dict = field(default_factory=dict)  # ksel: guarded-by[_lock]
    counts: dict = field(default_factory=dict)  # ksel: guarded-by[_lock]
    recorder: object = None
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    @contextlib.contextmanager
    def phase(self, name: str, args: dict | None = None):
        """``args`` (optional) is span context forwarded to the recorder
        (e.g. the serve walk's trace ids) — it never touches the phase
        accumulation, so the timing books are args-blind. Recorders
        without an args parameter keep working: the 3-arg call is used
        whenever no args were given."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            with self._lock:
                self.phases[name] = self.phases.get(name, 0.0) + (t1 - t0)
                self.counts[name] = self.counts.get(name, 0) + 1
            if self.recorder is not None:
                if args is None:
                    self.recorder.record(name, t0, t1)
                else:
                    self.recorder.record(name, t0, t1, args)

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + seconds
            self.counts[name] = self.counts.get(name, 0) + 1

    @property
    def total(self) -> float:
        with self._lock:
            return sum(self.phases.values())

    def as_dict(self) -> dict:
        with self._lock:
            return {
                name: {"seconds": s, "calls": self.counts[name]}
                for name, s in self.phases.items()
            }

    def report(self) -> str:
        # snapshot under the lock: a producer thread landing a phase
        # mid-report would otherwise tear this iteration (KSL015)
        with self._lock:
            phases = dict(self.phases)
            counts = dict(self.counts)
        total = sum(phases.values()) or 1.0
        lines = ["phase timing:"]
        for name, s in sorted(phases.items(), key=lambda kv: -kv[1]):
            lines.append(
                f"  {name:<24} {s * 1e3:10.3f} ms  {100 * s / total:5.1f}%"
                f"  ({counts[name]}x)"
            )
        lines.append(f"  {'total':<24} {total * 1e3:10.3f} ms")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: str):
    """Device-level trace via jax.profiler (TensorBoard format). No-op if the
    profiler is unavailable on this platform."""
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - platform-dependent
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass


def device_memory_stats() -> list[dict]:
    """Per-device memory snapshot (bytes in use / limit when reported)."""
    out = []
    for d in jax.devices():
        stats = {}
        try:
            stats = dict(d.memory_stats() or {})
        except Exception:  # pragma: no cover - backend-dependent
            pass
        out.append(
            {
                "device": str(d),
                "bytes_in_use": stats.get("bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
        )
    return out
