"""Distributed top-k over a device mesh.

The reference returns only the single k-th order statistic; top-k is the
north-star extension (BASELINE.md configs). The distributed form follows the
same communication shape as the rest of the framework (SURVEY.md §3.2 —
"O(p) scalars per round, no element redistribution"): each shard computes
its local top-k on-device, then one ``all_gather`` moves just ``k`` candidate
values per device (not the data), and a replicated final top-k over the
``P*k`` candidates yields the exact global result — valid because the global
top-k is a subset of the union of per-shard top-k sets.

Communication: one all-gather of ``P*k`` elements total, independent of N —
the analogue of the reference's medians gather (``TODO-kth-problem-cgm.c:
135-136``), generalized from 1 scalar to k per rank.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import numpy as np

from mpi_k_selection_tpu.ops.topk import topk as local_topk
from mpi_k_selection_tpu.parallel import mesh as mesh_lib
from mpi_k_selection_tpu.utils import compat, debug as _debug, dtypes as _dt


def _pad_with_losers(x, multiple: int, largest: bool):
    """Pad to a shard multiple with order-extreme *losers* (order-minimum for
    largest-k, order-maximum for smallest-k), so sentinels can never displace
    a real element from any shard's local top-k."""
    n = x.shape[0]
    rem = n % multiple
    if rem == 0:
        return x, n
    kdt = np.dtype(_dt.key_dtype(x.dtype))
    key = np.array(0 if largest else ~np.uint64(0)).astype(kdt)
    sentinel = _dt.from_sortable_bits(jnp.full((multiple - rem,), key, kdt), x.dtype)
    return jnp.concatenate([x, sentinel]), n


@functools.lru_cache(maxsize=64)
def _jitted_topk(mesh, k, largest, method):
    """Cached jitted sharded program per (mesh, config) — see parallel/radix.py."""
    axis = mesh.axis_names[0]

    def shard_fn(xs):
        vals, idx = local_topk(xs.ravel(), k, largest=largest, method=method)
        shard = jax.lax.axis_index(axis).astype(jnp.int32)
        # global index = shard offset + local index (balanced equal shards)
        gidx = idx.astype(jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
        gidx = gidx + shard.astype(gidx.dtype) * xs.shape[0]
        cand_v = jax.lax.all_gather(vals, axis).reshape(-1)  # (P*k,)
        cand_i = jax.lax.all_gather(gidx, axis).reshape(-1)
        top_v, pos = local_topk(cand_v, k, largest=largest)
        return top_v, cand_i[pos]

    # check_vma=False: outputs derive only from all_gather results so they
    # are replicated by construction, but the jitted local_topk inside the
    # body defeats static replication inference (same situation as cgm.py)
    fn = compat.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(axis),), out_specs=(P(), P()), check_vma=False
    )
    return jax.jit(fn)


def _remap_sentinel_indices(x, n, vals, idx):
    """Repair indices pointing at padding slots (>= n).

    A padding sentinel can enter the result only by *tying* a real element at
    the dtype's order-extreme value (it is a loser otherwise), and since
    n >= k there are always at least as many real occurrences of that value
    as result slots holding it — so each bad slot can be remapped to a
    distinct real occurrence. Rare path: runs on host, O(n) scan.
    """
    idx_np = np.asarray(idx).copy()
    bad = np.flatnonzero(idx_np >= n)
    if bad.size == 0:
        return idx
    vals_np = np.asarray(vals)
    xh = np.asarray(x)
    # Match on raw bit patterns, not ==: a sentinel tie means *key* equality,
    # and to_sortable_bits is a bit-level bijection, so key equality is raw
    # bit equality. For float dtypes the sentinel's payload is a NaN, where
    # == would never match; bit matching handles every dtype uniformly.
    udt = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[xh.dtype.itemsize]
    xb = xh.view(udt)
    vb = vals_np.view(udt)
    for v in np.unique(vb[bad]):
        occ = np.flatnonzero(xb == v)
        taken = set(idx_np[(vb == v) & (idx_np < n)].tolist())
        free = iter(i for i in occ.tolist() if i not in taken)
        fallback = int(occ[0]) if occ.size else n - 1
        for slot in bad[vb[bad] == v]:
            idx_np[slot] = next(free, fallback)
    return jnp.asarray(idx_np, dtype=idx.dtype)


def distributed_topk(x, k: int, *, largest: bool = True, mesh=None, method: str = "auto"):
    """Exact global top-k of sharded 1-D ``x``. Returns replicated
    ``(values, global_indices)`` sorted by rank.

    Exact in both values and indices: when n is not a multiple of the mesh
    size and the input contains the dtype's order-extreme value, a padding
    sentinel can tie a real element into the result — such indices are
    remapped to a real occurrence of the tied value before returning.
    """
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)
    x = jnp.ravel(jnp.asarray(x))
    n = x.shape[0]
    _debug.check_concrete_k(k, n)
    if k > n // mesh.size:
        # per-shard top-k cannot exceed the shard size; tiny inputs are not
        # worth distributing anyway
        raise ValueError(
            f"k={k} exceeds the shard size {n // mesh.size}; "
            "use the single-chip ops.topk for k this large"
        )
    xp, _ = _pad_with_losers(x, mesh.size, largest)
    xs = jax.device_put(xp, NamedSharding(mesh, P(mesh.axis_names[0])))
    vals, idx = _jitted_topk(mesh, int(k), bool(largest), method)(xs)
    if xp.shape[0] != n:
        idx = _remap_sentinel_indices(x, n, vals, idx)
    return vals, idx
