"""TPU backend (``--backend=tpu``) — JAX/XLA execution.

Single-chip selection dispatches to the radix/sort ops (ops/); when more than
one device is visible and the input is large, selection runs sharded over a
1-D device mesh via the distributed radix path (parallel/), which replaces
the reference's MPI scatter/iterate/gather protocol
(``TODO-kth-problem-cgm.c:103-293``) with XLA collectives over ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu import api

NAME = "tpu"


def kselect(x, k: int, *, algorithm: str = "auto", distribute: str = "auto", **kwargs):
    """Exact k-th smallest (1-indexed). ``distribute`` in {auto, never, always}."""
    n_dev = len(jax.devices())
    n = np.asarray(x).size if not hasattr(x, "size") else x.size
    use_mesh = {
        "auto": n_dev > 1 and n >= 1 << 20 and n % n_dev == 0,
        "never": False,
        "always": n_dev > 1,
    }[distribute]
    if use_mesh:
        from mpi_k_selection_tpu.parallel import radix as pradix

        return pradix.distributed_radix_select(jnp.asarray(x), k, **kwargs)
    return api.kselect(jnp.asarray(x), k, algorithm=algorithm, **kwargs)


def topk(x, k: int, *, largest: bool = True, **kwargs):
    from mpi_k_selection_tpu.ops.topk import topk as _topk

    return _topk(jnp.asarray(x), k, largest=largest, **kwargs)


def median(x, **kwargs):
    x = jnp.asarray(x)
    return kselect(x, max(1, x.size // 2), **kwargs)
