"""Tier-1 membership audit.

The tier-1 gate runs ``pytest -m 'not slow'``. A test file whose tests all
carry an implicit skip (bad collection, module-level gating, a forgotten
``pytestmark``) silently falls out of that gate without anyone noticing.
This audit closes the hole: every ``tests/test_*.py`` file must either
contribute at least one collected test to the ``-m 'not slow'`` selection
or contain an explicit ``pytest.mark.slow`` opt-out.
"""

import pathlib
import re
import subprocess
import sys

TESTS_DIR = pathlib.Path(__file__).resolve().parent


def test_every_test_file_is_tier1_or_explicitly_slow():
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "--collect-only",
            "-q",
            "-m",
            "not slow",
            "--continue-on-collection-errors",
            "-p",
            "no:cacheprovider",
            str(TESTS_DIR),
        ],
        capture_output=True,
        text=True,
        cwd=TESTS_DIR.parent,
    )
    collected = {
        pathlib.Path(line.split("::")[0]).name
        for line in out.stdout.splitlines()
        if "::" in line
    }
    assert collected, f"tier-1 collection produced nothing:\n{out.stdout}\n{out.stderr}"
    offenders = [
        f.name
        for f in sorted(TESTS_DIR.glob("test_*.py"))
        if f.name not in collected
        and not re.search(r"pytest\.mark\.slow\b", f.read_text())
    ]
    assert not offenders, (
        "test files neither collected under tier-1 (-m 'not slow') nor "
        f"explicitly slow-marked: {offenders}"
    )
