"""Pallas batched top-k (values) kernel — BASELINE config 4's hot path.

Replaces XLA's TopK for the batched shape (B x D float32, k <= 8, the
beam-search / vocab top-k config: B=4096, D=32768, k=8). XLA's integer-key
TopK runs ~2.4 ms there; this pipeline measures ~1.1-1.3 ms on v5e
(exp_btopk.py records the full design-space measurements: streaming floor
0.51 ms, full insert-chain 3.5 ms, depth-8 + in-kernel fold 1.7 ms,
depth-3 + rescue ~1.2 ms — the variant below).

Design (VPU-shaped, not a port of any CPU/GPU heap scheme):

1. **Depth-3 insert chain** (`_chain3_kernel`): the (bb, bd) tile is viewed
   as (bb, bd/128, 128) sublane slabs; each slab streams through a 3-deep
   compare-insert chain kept per (row, lane) in the output block, which the
   d-grid revisits as an accumulator. 6 VPU ops/element — the whole reason
   this beats both XLA TopK and a full 8-deep chain (16 ops/element,
   measured 2x slower end-to-end).
2. **Bitonic lane fold** (`_fold3_kernel`): the per-lane sorted-3 columns
   (padded to sorted-8 with -inf) are merged across lanes by halving:
   winners of (a_i, b_{7-i}) form a bitonic sequence, cleaned by a 3-stage
   network — 7 fold levels turn (3, 128) candidates/row into the row's
   top-8 IF no lane hid a 4th member of the true top-8. The same kernel
   emits a per-row suspect flag: some lane's 3rd-kept value > the folded
   8th value.
3. **Bounded rescue**: suspect rows (a lane holding >= 4 of the row's top
   8 — P ~ C(8,4)/128^3 per row, ~1e-3 per 4096-row batch for random data;
   adversarial stride-128 layouts can force it) are re-solved exactly by
   ``lax.top_k`` on a gathered <= ``rescue_rows`` subset; if even that
   budget overflows, one ``lax.cond`` falls back to full ``lax.top_k``.
   Exactness therefore never depends on the data distribution.

Exactness proof of the non-suspect case (by value, duplicates included):
with no suspect lane, every hidden element is <= its lane's 3rd-kept
<= t8_hat (the folded 8th value), so all row values > t8_hat are among the
candidates; if the true 8th value were > t8_hat, the >= 8 values above
t8_hat would all be candidates and the folded 8th would exceed t8_hat —
contradiction. Hence the candidate top-8 equals the true top-8 by value.

Values only: the chain carries no positions (indices would double the ops).
ops/topk.py pairs these values with indices from the XLA path; when the
caller uses only values (vocab pruning, thresholds, beam scores against a
bound), XLA dead-code-eliminates the index path and the kernel's speed is
the call's speed.

Reference anchor: the reference has no batched dimension at all (one
IntVector, ``vector.h:7-11``); this is north-star scope (BASELINE.md
config 4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128
_DEPTH = 3  # candidates kept per (row, lane); see suspect-rate analysis


def _ce(a, b):
    """Descending compare-exchange."""
    return jnp.maximum(a, b), jnp.minimum(a, b)


def _chain3_kernel(x_ref, c_ref, *, bd):
    j = pl.program_id(1)
    slabs = bd // LANES
    bb = x_ref.shape[0]

    @pl.when(j == 0)
    def _():
        c_ref[:] = jnp.full_like(c_ref, -jnp.inf)

    x = x_ref[:].reshape(bb, slabs, LANES)
    regs = [c_ref[i * bb:(i + 1) * bb, :] for i in range(_DEPTH)]
    for s in range(slabs):
        t = x[:, s, :]
        for i in range(_DEPTH):
            ri = regs[i]
            regs[i] = jnp.maximum(ri, t)
            t = jnp.minimum(ri, t)
    c_ref[:] = jnp.concatenate(regs, axis=0)


def _lane_fold_top8(regs, bb):
    """Merge 8 per-lane sorted-descending columns across the lane axis.

    At each fold the left/right lane halves hold independent sorted-8 runs
    per lane; ``max(a_i, b_{7-i})`` yields a bitonic sequence containing
    the merged top-8, cleaned by compare-exchanges at strides 4, 2, 1.
    Returns 8 ``(bb, 1)`` arrays — the fold target's top-8, sorted.
    """
    w = regs[0].shape[1] // 2
    while w >= 1:
        a = [r[:, :w] for r in regs]
        b = [r[:, w:2 * w] for r in regs]
        m = [jnp.maximum(a[i], b[7 - i]) for i in range(8)]
        for (i, j) in ((0, 4), (1, 5), (2, 6), (3, 7)):
            m[i], m[j] = _ce(m[i], m[j])
        for (i, j) in ((0, 2), (1, 3), (4, 6), (5, 7)):
            m[i], m[j] = _ce(m[i], m[j])
        for (i, j) in ((0, 1), (2, 3), (4, 5), (6, 7)):
            m[i], m[j] = _ce(m[i], m[j])
        regs = m
        w //= 2
    return regs


def _fold3_kernel(c_ref, o_ref, s_ref, *, bb):
    neg = jnp.full((bb, LANES), -jnp.inf, jnp.float32)
    regs = [c_ref[i * bb:(i + 1) * bb, :] for i in range(_DEPTH)]
    lane3 = regs[-1]
    top = _lane_fold_top8(regs + [neg] * (8 - _DEPTH), bb)
    o_ref[:] = jnp.concatenate(top, axis=1)
    t8 = top[7]  # (bb, 1): the folded 8th value
    # NaN anywhere in a lane floods that lane's registers (max/min both
    # propagate NaN), so isnan(lane3) catches every contaminated row and
    # routes it to the exact lax.top_k rescue — without this, `lane3 > t8`
    # is False for NaN and the flood would return silently wrong values
    suspect = jnp.logical_or(lane3 > t8, jnp.isnan(lane3))
    s = jnp.where(suspect, jnp.float32(1), jnp.float32(0))
    w = LANES // 2
    while w >= 1:  # lane-axis max: any suspect lane flags the row
        s = jnp.maximum(s[:, :w], s[:, w:2 * w])
        w //= 2
    s_ref[:] = s


def _pick_block(size, options):
    for o in options:
        if size % o == 0:
            return o
    return None


def batched_topk_supported(shape, dtype, k) -> bool:
    """Static dispatch test for :func:`pallas_batched_topk_values`."""
    if pltpu is None or len(shape) != 2 or jnp.dtype(dtype) != jnp.float32:
        return False
    b, d = shape
    if not 1 <= k <= 8:
        return False
    if _pick_block(b, (512, 256, 128, 64)) is None:
        return False
    # d must split into whole (>= 1024)-wide column blocks of whole slabs,
    # and give each lane enough depth for the suspect analysis to pay
    return d % 1024 == 0 and d >= 4096


@functools.partial(jax.jit, static_argnames=("k", "rescue_rows", "interpret"))
def pallas_batched_topk_values(
    x: jax.Array,
    k: int,
    *,
    rescue_rows: int = 64,
    interpret: bool | None = None,
) -> jax.Array:
    """Exact top-k VALUES (sorted descending) per row of 2-D float32 ``x``.

    Use :func:`batched_topk_supported` to gate dispatch; out-of-envelope
    shapes should take the XLA paths in ops/topk.py.
    """
    if pltpu is None:
        raise NotImplementedError(
            "the pallas batched top-k kernel is not available in this build"
        )
    if not batched_topk_supported(x.shape, x.dtype, k):
        raise ValueError(
            f"unsupported batched-topk shape {x.shape} dtype {x.dtype} k={k}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, D = x.shape
    bb = _pick_block(B, (512, 256, 128, 64))
    bd = _pick_block(D, (2048, 1024))
    nb, nd = B // bb, D // bd
    rescue_rows = min(rescue_rows, B)

    with jax.enable_x64(False):
        cand = pl.pallas_call(
            functools.partial(_chain3_kernel, bd=bd),
            grid=(nb, nd),
            in_specs=[
                pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec(
                (_DEPTH * bb, LANES), lambda i, j: (i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct(
                (_DEPTH * B, LANES), jnp.float32, vma=jax.typeof(x).vma
            ),
            interpret=interpret,
        )(x)
        top, susp = pl.pallas_call(
            functools.partial(_fold3_kernel, bb=bb),
            grid=(nb,),
            in_specs=[
                pl.BlockSpec(
                    (_DEPTH * bb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                )
            ],
            out_specs=[
                pl.BlockSpec((bb, 8), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((bb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, 8), jnp.float32, vma=jax.typeof(x).vma),
                jax.ShapeDtypeStruct((B, 1), jnp.float32, vma=jax.typeof(x).vma),
            ],
            interpret=interpret,
        )(cand)

    sflag = susp[:, 0] > 0
    nsusp = jnp.sum(sflag.astype(jnp.int32))
    # bounded exact rescue: lax.top_k over the <= rescue_rows gathered rows
    sval, sidx = jax.lax.top_k(sflag.astype(jnp.int32), rescue_rows)
    rtop, _ = jax.lax.top_k(x[sidx], 8)
    fixed = jnp.where(sval[:, None] > 0, rtop, top[sidx])
    top = top.at[sidx].set(fixed)

    def full_fallback(_):
        v, _ = jax.lax.top_k(x, 8)
        return v

    top = jax.lax.cond(nsusp <= rescue_rows, lambda _: top, full_fallback, 0)
    return top[:, :k]
