"""Python driver for the native multi-process CGM runtime (mpi backend)."""

from __future__ import annotations

import numpy as np

from mpi_k_selection_tpu import config


def kselect_full(x, k: int, *, num_procs: int = 4, c: int | None = None):
    """Exact k-th smallest (1-indexed) via the native forked-rank CGM runtime.

    Returns ``(answer, rounds, elapsed_s, found_early)``. ``c`` is the CGM
    coarseness constant (reference default 500, ``TODO-kth-problem-cgm.c:44``).
    """
    from mpi_k_selection_tpu.native import loader

    lib = loader.get_lib()
    if lib is None:
        from mpi_k_selection_tpu.errors import NativeUnavailableError

        raise NativeUnavailableError(
            "the native runtime is unavailable (no C++ compiler?); "
            "build it with `python -m mpi_k_selection_tpu.native.build`"
        )
    x = np.asarray(x)
    if x.dtype != np.int32:
        raise ValueError(
            f"the mpi backend operates on int32 (reference C int), got {x.dtype}"
        )
    if c is None:
        c = config.REFERENCE_C
    answer, rounds, elapsed, found = lib.cgm_kselect(x, k, num_procs=num_procs, c=c)
    return np.int32(answer), rounds, elapsed, found


def kselect(x, k: int, *, num_procs: int = 4, c: int | None = None, **_ignored):
    """Like :func:`kselect_full` but returns just the answer."""
    answer, _, _, _ = kselect_full(x, k, num_procs=num_procs, c=c)
    return answer
