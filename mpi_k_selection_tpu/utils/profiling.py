"""Tracing / profiling subsystem (SURVEY.md §5).

The reference's entire observability story is one wall-clock pair per run
(``clock()`` at ``kth-problem-seq.c:30,35``; ``MPI_Wtime()`` at
``TODO-kth-problem-cgm.c:76,279``). This module is the framework-grade
replacement:

- :class:`PhaseTimer` — named per-phase wall timing (callers sync devices
  with ``block_until_ready``/``np.asarray`` where relevant), the
  "per-round timing" SURVEY.md §5 calls for; renders a report and a dict.
- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable device trace (XLA op/kernel level), when available.
- :func:`device_memory_stats` — HBM usage snapshot per device.

Used by the CLI via ``--profile`` / ``--trace-dir``.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@dataclass
class PhaseTimer:
    """Accumulates named phase durations: ``with timer.phase('sort'): ...``"""

    phases: dict = field(default_factory=dict)
    counts: dict = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.phases[name] = self.phases.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def record(self, name: str, seconds: float) -> None:
        self.phases[name] = self.phases.get(name, 0.0) + seconds
        self.counts[name] = self.counts.get(name, 0) + 1

    @property
    def total(self) -> float:
        return sum(self.phases.values())

    def as_dict(self) -> dict:
        return {
            name: {"seconds": s, "calls": self.counts[name]}
            for name, s in self.phases.items()
        }

    def report(self) -> str:
        total = self.total or 1.0
        lines = ["phase timing:"]
        for name, s in sorted(self.phases.items(), key=lambda kv: -kv[1]):
            lines.append(
                f"  {name:<24} {s * 1e3:10.3f} ms  {100 * s / total:5.1f}%"
                f"  ({self.counts[name]}x)"
            )
        lines.append(f"  {'total':<24} {total * 1e3:10.3f} ms")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: str):
    """Device-level trace via jax.profiler (TensorBoard format). No-op if the
    profiler is unavailable on this platform."""
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - platform-dependent
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass


def device_memory_stats() -> list[dict]:
    """Per-device memory snapshot (bytes in use / limit when reported)."""
    out = []
    for d in jax.devices():
        stats = {}
        try:
            stats = dict(d.memory_stats() or {})
        except Exception:  # pragma: no cover - backend-dependent
            pass
        out.append(
            {
                "device": str(d),
                "bytes_in_use": stats.get("bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
        )
    return out
