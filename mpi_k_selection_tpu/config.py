"""Framework defaults, carrying over the reference's compile-time constants.

The reference has no config system at all — every parameter is a compile-time
constant and changing one means recompiling (SURVEY.md §5: ``SIZE_OF_SAMPLES``
at ``kth-problem-seq.c:7``, ``MAX_NUMBERS``/``k``/``c`` at
``TODO-kth-problem-cgm.c:44-48``; the ``~`` backup files exist precisely
because ``k`` was edited between runs). Here they become defaults of a real
CLI/config surface (cli.py).
"""

REFERENCE_N = 100_000_000  # SIZE_OF_SAMPLES (kth-problem-seq.c:7) == MAX_NUMBERS (TODO-…:46)
REFERENCE_K_SEQ = 250  # kth-problem-seq.c:24
REFERENCE_K_CGM = 150  # TODO-kth-problem-cgm.c:48
REFERENCE_C = 500  # CGM coarseness constant c (TODO-kth-problem-cgm.c:44)

# The CGM program aborts unless world_size >= 2 (TODO-kth-problem-cgm.c:56-59).
MIN_DEVICES_DISTRIBUTED = 2

DEFAULT_RADIX_BITS = 8
DEFAULT_SEED = 0
