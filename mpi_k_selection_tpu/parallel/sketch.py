"""Sharded RadixSketch construction — per-shard histograms merged by psum.

The sketch's merge is an elementwise sum (streaming/sketch.py), so building
one over a device-sharded array is a single shard_map: every shard counts
its local DEEPEST-level histogram with the same ops/histogram.py primitive
the selects use, one ``lax.psum`` merges the counts (the shallower pyramid
is derived host-side by reshape-sum) — the exact analogue
of the reference CGM's ``MPI_Allreduce`` of per-rank counts
(``TODO-kth-problem-cgm.c:190``), except the reduced object here IS the
final queryable summary. The replicated result is lifted into a host
:class:`RadixSketch`, interchangeable (bitwise) with one accumulated by
sequential ``update`` calls over the same data.

Multi-host: on a process-spanning mesh the psum above already rides DCN
between slices, so the device path needs nothing extra. The host-exact
fallback routes (64-bit-no-x64, f64-on-TPU) only ever see local data —
:func:`dcn_merge_sketch` finishes those with ONE ``process_allgather`` of
the packed deepest-level counts (32-bit lanes, so x64-off processes
cannot truncate them; single-process jobs are the degenerate identity).

The STREAMING twin of this merge lives in ``streaming/sketch.py:
RadixSketch.update_stream(devices=p)``: same deepest-level device
histograms, same int32-partial -> host-int64 ``_fold_deep_histogram``
discipline, but the partials arrive per staged chunk (round-robin over
the ingest devices, merged in chunk order) instead of per shard through a
psum — for data that is never resident as one sharded array.

Exact refinement of EITHER sketch (``RadixSketch.refine``) needs a
second read of the data. A sketch built here has it by construction (the
sharded array is resident); a streamed sketch over a one-shot source
does not — there, ``update_stream(..., spill=SpillStore(...))`` tees the
single pass's encoded keys to the survivor spill store
(streaming/spill.py), and ``refine(store, k)`` runs the sketch-seeded
descent entirely from disk, shrinking the spilled generation
geometrically pass over pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
from mpi_k_selection_tpu.parallel import mesh as mesh_lib
from mpi_k_selection_tpu.streaming.sketch import RadixSketch
from mpi_k_selection_tpu.utils import compat
from mpi_k_selection_tpu.utils import dtypes as _dt


def _mesh_spans_processes(mesh) -> bool:
    """True when ``mesh`` includes devices owned by more than one process —
    the regime where host-side accumulation only ever saw LOCAL data and a
    DCN merge must finish the job."""
    procs = {d.process_index for d in np.asarray(mesh.devices).ravel()}
    return len(procs) > 1


def _split_u32(a: np.ndarray) -> np.ndarray:
    """Pack a nonnegative int64/uint64 vector into a ``(2, n)`` uint32
    lo/hi-word array — the DCN wire format: 32-bit lanes survive the
    device round-trip of ``process_allgather`` bit-exactly with x64 OFF,
    where shipping int64 directly would be silently truncated (the KSL002
    class this repository guards everywhere else)."""
    u = a.astype(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    return np.stack([lo, hi])


def _join_u32(packed: np.ndarray) -> np.ndarray:
    """Inverse of :func:`_split_u32`: ``(2, n)`` uint32 -> uint64."""
    lo, hi = packed
    return lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32))


def _pack_sketch_payload(sk: RadixSketch) -> np.ndarray:
    """One process's DCN payload: ``[deep histogram..., n, has_data,
    min_key, max_key]`` as uint64. ``has_data`` masks the extremes of
    processes that saw an empty local stream."""
    deep = sk.hists[-1]
    payload = np.empty((deep.size + 4,), np.uint64)
    payload[: deep.size] = deep.astype(np.uint64)
    payload[deep.size] = np.uint64(sk.n)
    payload[deep.size + 1] = np.uint64(sk.n > 0)
    payload[deep.size + 2] = (
        np.uint64(0) if sk._min_key is None else np.uint64(sk._min_key)
    )
    payload[deep.size + 3] = (
        np.uint64(0) if sk._max_key is None else np.uint64(sk._max_key)
    )
    return payload


def _unpack_gathered_payloads(gathered: np.ndarray, like: RadixSketch) -> RadixSketch:
    """Fold every process's packed payload row into a fresh sketch shaped
    ``like`` (empty-process rows contribute nothing, including to the
    extremes)."""
    nbuckets = like.hists[-1].size
    out = RadixSketch(like.dtype, radix_bits=like.radix_bits, levels=like.levels)
    kmin = kmax = None
    for packed in gathered:  # one (2, len) uint32 row pair per process
        row = _join_u32(packed)
        n_p = int(row[nbuckets])
        if not int(row[nbuckets + 1]):
            continue
        out._fold_deep_histogram(row[:nbuckets].astype(np.int64))
        out.n += n_p
        pmin = out.kdt.type(row[nbuckets + 2])
        pmax = out.kdt.type(row[nbuckets + 3])
        kmin = pmin if kmin is None else min(kmin, pmin)
        kmax = pmax if kmax is None else max(kmax, pmax)
    out._min_key, out._max_key = kmin, kmax
    return out


def dcn_merge_sketch(sk: RadixSketch) -> RadixSketch:
    """Merge per-process host-accumulated sketches across a multi-process
    job with ONE ``process_allgather`` (utils/compat.py) of the packed
    deepest-level arrays — ``RadixSketch.merge`` is an elementwise int64
    sum, so the allgather-of-levels IS the merge; the shallower pyramid is
    re-derived from the merged deepest level (bitwise identical, as in
    :func:`distributed_sketch`). Single-process jobs return ``sk``
    unchanged (the degenerate identity).

    Payloads ship as uint32 lo/hi words (see :func:`_split_u32`) so
    x64-off processes cannot truncate counts; extremes travel in key
    space, masked per process by the ``has_data`` slot."""
    if jax.process_count() == 1:
        return sk
    gathered = np.asarray(
        compat.process_allgather(_split_u32(_pack_sketch_payload(sk)))
    )
    return _unpack_gathered_payloads(gathered, sk)


def distributed_sketch(
    x,
    *,
    mesh=None,
    radix_bits: int = 4,
    levels: int = 4,
    hist_method: str = "scatter",
) -> RadixSketch:
    """Build a :class:`RadixSketch` of device-resident ``x`` over ``mesh``
    (all devices by default): one psum-merged deepest-level histogram pass,
    shallower levels derived host-side.

    ``hist_method`` defaults to ``"scatter"``: the deepest level needs
    ``2**resolution_bits`` buckets, beyond the Pallas kernels' digit-width
    sweet spot — scatter handles any bucket count. A non-multiple-of-mesh
    tail is folded in host-side (sentinel padding would corrupt the top
    bucket's count, unlike selection where sentinels are rank-safe).
    """
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)
    xh = x if hasattr(x, "dtype") else np.asarray(x)
    dtype = np.dtype(xh.dtype)  # BEFORE any device cast can narrow it
    sk = RadixSketch(dtype, radix_bits=radix_bits, levels=levels)
    spans = _mesh_spans_processes(mesh)
    if dtype.itemsize == 8 and not jax.config.jax_enable_x64:
        # jnp.asarray would silently truncate 64-bit host input to 32 bits
        # (wrong counts, wrong sketch dtype) — the same hole
        # streaming/chunked.py:resolve_stream_hist guards; accumulate
        # host-side instead: exact, and no x64 mode flip required. On a
        # process-spanning mesh each process only folded its LOCAL data,
        # so one DCN allgather finishes the merge
        sk.update(np.ravel(np.asarray(xh)))
        return dcn_merge_sketch(sk) if spans else sk
    x = jnp.ravel(jnp.asarray(x))
    if dtype == np.float64 and jax.default_backend() == "tpu":
        # TPU f64 device keys are the ~49-bit approximation
        # (utils/dtypes.py:f64_raw_bits), which would break the bitwise
        # host-parity contract — accumulate host-side instead, exact
        # w.r.t. the (already storage-truncated) device contents; DCN-merge
        # per-process accumulations as above
        sk.update(np.asarray(x))
        return dcn_merge_sketch(sk) if spans else sk
    n = x.shape[0]
    nmain = n - n % mesh.size
    axis = mesh.axis_names[0]
    total_bits = sk.total_bits

    if nmain:

        def shard_fn(xs):
            u = _dt.to_sortable_bits(xs.ravel())
            # ONE kernel + one psum: only the deepest level is counted on
            # device; the shallower pyramid is derived host-side from the
            # merged int64 counts (RadixSketch._fold_deep_histogram), which
            # is bitwise identical and cuts device reads and collective
            # traffic by ~levels x
            local = masked_radix_histogram(
                u,
                shift=total_bits - levels * radix_bits,
                radix_bits=levels * radix_bits,
                prefix=None,
                method=hist_method,
                count_dtype=jnp.int32,  # exact: segment < 2^31 elements
            )
            # extremes in KEY space (not value space): bitwise identical to
            # the host sketch's update() extremes for every stream, NaN and
            # -0.0/+0.0 included, where value-space min/max diverge from the
            # keys' total order
            return (
                jax.lax.psum(local, axis),
                jax.lax.pmin(jnp.min(u), axis),
                jax.lax.pmax(jnp.max(u), axis),
            )

        # NOTE: on a process-spanning mesh the psum below already reduces
        # over EVERY device in the mesh — ICI within a slice, DCN across —
        # so the merged counts come back globally complete and need no
        # extra process merge (dcn_merge_sketch is for the host-accumulated
        # fallback routes above, where no collective ever ran)
        fn = jax.jit(
            compat.shard_map(shard_fn, mesh=mesh, in_specs=(P(axis),), out_specs=P())
        )
        # the psum reduces int32 counts across shards: cap each call's total
        # population below 2^31 so the merged counts cannot wrap, and
        # accumulate segments host-side in int64 (the same discipline as
        # streaming/chunked.py's per-chunk histograms)
        seg = ((1 << 31) - 1) // mesh.size * mesh.size
        kmin = kmax = None
        for off in range(0, nmain, seg):
            xs = mesh_lib.shard_1d(x[off : off + min(seg, nmain - off)], mesh)
            deep, dmin, dmax = fn(xs)
            sk._fold_deep_histogram(np.asarray(deep).astype(np.int64))
            smin = sk.kdt.type(np.asarray(dmin))
            smax = sk.kdt.type(np.asarray(dmax))
            kmin = smin if kmin is None else min(kmin, smin)
            kmax = smax if kmax is None else max(kmax, smax)
        sk.n = nmain
        sk._min_key, sk._max_key = kmin, kmax
    if nmain != n:
        sk.update(np.asarray(x[nmain:]))
    return sk
