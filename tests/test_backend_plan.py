"""tpu-backend plan(): algorithm/distribution resolution is explicit.

Regression tests for the review finding that an explicit ``algorithm='sort'``
was silently replaced by distributed radix when a mesh was available.
"""

import numpy as np
import pytest

from mpi_k_selection_tpu.backends import tpu as tpu_backend


def test_plan_explicit_sort_never_distributes():
    algo, dist = tpu_backend.plan(1 << 22, "sort", "auto")
    assert algo == "sort" and not dist


def test_plan_explicit_sort_with_always_is_error():
    with pytest.raises(ValueError, match="no distributed path"):
        tpu_backend.plan(1 << 22, "sort", "always")


def test_plan_auto_large_distributes_on_mesh():
    algo, dist = tpu_backend.plan(1 << 23, "auto", "auto")
    assert algo == "radix" and dist  # conftest provides 8 virtual devices


def test_plan_auto_small_single_chip():
    algo, dist = tpu_backend.plan(1 << 10, "auto", "never")
    assert algo == "sort" and not dist


def test_explicit_sort_runs_sort(rng):
    x = rng.integers(0, 1000, size=1 << 20, dtype=np.int32)
    got = int(tpu_backend.kselect(x, 1234, algorithm="sort"))
    assert got == int(np.sort(x)[1233])


def test_datagen_narrow_dtype_clips_not_wraps():
    from mpi_k_selection_tpu.utils import datagen

    x = datagen.generate(100_000, pattern="sequential", dtype=np.int16)
    assert x.max() == np.iinfo(np.int16).max  # clipped, no sawtooth
    assert np.all(np.diff(x.astype(np.int64)) >= 0)  # still monotone


def test_plan_cgm_is_distributed():
    algo, dist = tpu_backend.plan(1 << 22, "cgm", "auto")
    assert algo == "cgm" and dist
    algo, dist = tpu_backend.plan(1 << 10, "cgm", "always")
    assert algo == "cgm" and dist


def test_plan_cgm_never_is_error():
    with pytest.raises(ValueError, match="no single-chip path"):
        tpu_backend.plan(1 << 22, "cgm", "never")


def test_backend_kselect_cgm_dispatch(rng):
    x = rng.integers(0, 10_000, size=1 << 14, dtype=np.int32)
    got = int(tpu_backend.kselect(x, 4321, algorithm="cgm"))
    assert got == int(np.sort(x)[4320])


def test_tpu_backend_kselect_many_planned_dispatch(rng):
    from mpi_k_selection_tpu.backends import tpu as tpu_backend

    x = rng.integers(-(2**31), 2**31, size=2_100_000, dtype=np.int32)
    ks_q = np.array([1, 1_050_000, 2_100_000])
    want = np.sort(x, kind="stable")[ks_q - 1]
    # auto: distributes on the virtual mesh (n divisible check may keep it
    # single-device; either path must be exact)
    got = np.asarray(tpu_backend.kselect_many(x, ks_q))
    np.testing.assert_array_equal(got, want)
    got = np.asarray(tpu_backend.kselect_many(x, ks_q, distribute="always"))
    np.testing.assert_array_equal(got, want)
    got = np.asarray(tpu_backend.quantiles(x, [0.5, 0.99], distribute="always"))
    s = np.sort(x, kind="stable")
    from mpi_k_selection_tpu.api import quantile_ranks
    np.testing.assert_array_equal(got, s[np.asarray(quantile_ranks([0.5, 0.99], x.size)) - 1])


def test_plan_auto_distributes_non_divisible_n():
    # the padding path (pad_to_multiple) makes ragged N shardable; auto must
    # not silently fall back to single-chip for n % n_dev != 0
    algo, dist = tpu_backend.plan((1 << 20) + 5, "auto", "auto")
    assert algo == "radix" and dist


def test_backend_auto_distributes_and_matches_oracle_ragged(rng):
    n = (1 << 20) + 5
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int32)
    got = int(tpu_backend.kselect(x, n // 2))  # auto + auto on the 8-dev mesh
    assert got == int(np.sort(x, kind="stable")[n // 2 - 1])


def test_plan_always_single_device_raises():
    with pytest.raises(ValueError, match="needs >= 2 devices"):
        tpu_backend.plan(1 << 22, "radix", "always", n_dev=1)


def test_plan_many_respects_devices_cap():
    # gate must be evaluated against the capped device count: a cap of 1
    # falls back to single-device under auto...
    assert tpu_backend.plan_many(1 << 22, "auto", devices=1) is None
    # ...and raises under always (require_distributed semantics)
    with pytest.raises(ValueError, match="needs >= 2 devices"):
        tpu_backend.plan_many(1 << 22, "always", devices=1)
    mesh = tpu_backend.plan_many(1 << 22, "auto", devices=4)
    assert mesh is not None and mesh.size == 4


def test_kselect_many_scalar_k_returns_scalar(rng):
    from mpi_k_selection_tpu import api

    x = rng.integers(0, 1 << 20, size=100_000, dtype=np.int32)
    out = api.kselect_many(x, 50_000)
    assert out.shape == ()
    assert int(out) == int(np.sort(x)[49_999])
    out_small = api.kselect_many(x[:1000], 17)
    assert out_small.shape == ()
    # backend path (distributed on the virtual mesh) honors the same contract
    big = rng.integers(0, 1 << 20, size=(1 << 20) + 3, dtype=np.int32)
    out_b = tpu_backend.kselect_many(big, 12345)
    assert out_b.shape == ()
    assert int(out_b) == int(np.sort(big)[12344])


def test_kselect_many_warns_on_ignored_radix_kwargs(rng):
    from mpi_k_selection_tpu import api

    x = rng.integers(0, 100, size=1000, dtype=np.int32)
    with pytest.warns(UserWarning, match="sort path"):
        api.kselect_many(x, [1, 500], radix_bits=8)


def test_plan_always_sort_keeps_specific_error_on_single_device():
    # the distributability error must win over the device-count error
    with pytest.raises(ValueError, match="no distributed path"):
        tpu_backend.plan(1 << 22, "sort", "always", n_dev=1)
    # cgm surfaces the device-count error at plan time
    with pytest.raises(ValueError, match="needs >= 2 devices"):
        tpu_backend.plan(1 << 22, "cgm", "always", n_dev=1)
