"""Top-k selection (values + indices), single and batched.

The reference only ever returns the single k-th order statistic; top-k (the
full set of k extreme elements) is the north-star extension covering the
BASELINE.md configs "Single-chip top-k: N=64M float32, k=128 (MoE router
logits)" and "Batched top-k: B=4096 x D=32768 float32, k=8 (beam-search /
vocab top-k)".

Implementation notes:

- ``lax.top_k`` is the XLA baseline (operates on the last axis; leading axes
  batch for free, so batched_topk is the same code path).
- ``smallest``-k and unsigned dtypes are handled via the order-preserving
  key transforms in utils/dtypes.py: build signed keys whose descending order
  equals the requested order, top_k the keys, gather the original values.
- ``method="chunked"`` is the two-stage large-D variant: split the last axis
  into C chunks, take top-k per chunk (parallel, small sorts), then top-k of
  the C*k candidates. For D >> k this does ~D + C*k work per row instead of
  a single large-D top_k, and it is how the Pallas block kernel decomposes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu.utils import dtypes as _dt


def _signed_keys(x: jax.Array, largest: bool) -> jax.Array:
    """Keys whose *descending* signed order equals the requested value order."""
    dtype = np.dtype(x.dtype)
    if largest and (jnp.issubdtype(dtype, jnp.signedinteger) or dtype.kind == "f"):
        return x  # lax.top_k compares these natively
    u = _dt.to_sortable_bits(x)
    kdt = u.dtype
    bits = _dt.key_bits(dtype)
    if not largest:
        u = ~u
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    signed = np.dtype(f"int{bits}")
    return jax.lax.bitcast_convert_type(u ^ msb, signed)


@functools.partial(jax.jit, static_argnames=("k", "largest", "method", "num_chunks"))
def topk(
    x: jax.Array,
    k: int,
    *,
    largest: bool = True,
    method: str = "auto",
    num_chunks: int | None = None,
):
    """Top-k along the last axis. Returns ``(values, indices)`` sorted by rank.

    ``largest=False`` returns the k smallest (ascending). Leading axes batch.
    """
    d = x.shape[-1]
    if not 1 <= k <= d:
        raise ValueError(f"k={k} out of range for last axis of size {d}")
    keys = _signed_keys(x, largest)
    if method == "auto":
        method = "chunked" if d >= 1 << 16 and d >= 64 * k else "flat"
    if method == "flat":
        _, idx = jax.lax.top_k(keys, k)
    elif method == "chunked":
        c = num_chunks or _pick_num_chunks(d, k)
        if c <= 1 or d % c:
            _, idx = jax.lax.top_k(keys, k)
        else:
            sub = d // c
            kk = keys.reshape(*keys.shape[:-1], c, sub)
            subvals, subidx = jax.lax.top_k(kk, min(k, sub))
            base = jnp.arange(c, dtype=subidx.dtype)[:, None] * sub
            cand_idx = (subidx + base).reshape(*keys.shape[:-1], -1)
            cand_vals = subvals.reshape(*keys.shape[:-1], -1)
            _, pos = jax.lax.top_k(cand_vals, k)
            idx = jnp.take_along_axis(cand_idx, pos, axis=-1)
    else:
        raise ValueError(f"unknown topk method {method!r}")
    values = jnp.take_along_axis(x, idx, axis=-1)
    return values, idx


def _pick_num_chunks(d: int, k: int) -> int:
    """Largest power-of-two chunk count with chunk size >= max(256, 2k)."""
    c = 1
    while d % (c * 2) == 0 and d // (c * 2) >= max(256, 2 * k):
        c *= 2
    return c


def batched_topk(x: jax.Array, k: int, **kwargs):
    """Alias for :func:`topk` on ``(..., D)`` arrays (BASELINE batched config)."""
    return topk(x, k, **kwargs)
