"""Typed errors of the resident-dataset query server (serve/).

The serving layer fronts many concurrent clients, so its failures must be
distinguishable without string matching: the HTTP front maps each class to
a status code (registry misses are 404s, malformed queries 400s, a closed
server 503, an expired deadline 504, a shed query 503 + ``Retry-After``)
and the in-process API lets callers catch exactly the case they can
handle. All inherit :class:`ServeError` so "anything the server raised"
is one except clause.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class for every serving-layer error."""


class DatasetNotFoundError(ServeError):
    """No dataset registered under the requested id (HTTP 404)."""


class DatasetExistsError(ServeError):
    """A dataset id was registered twice. Resident shards are immutable —
    replacing data under a live id would race in-flight queries; drop the
    id first, then add the new data."""


class QueryError(ServeError, ValueError):
    """A malformed or unanswerable query: unknown tier/op, out-of-range
    rank or quantile, a sketch tier against a dataset with no resident
    sketch, top-k against a stream-resident dataset (HTTP 400)."""


class ServerClosedError(ServeError):
    """The server (or its dispatch thread) has been closed; no further
    queries are accepted and queued ones are failed with this (HTTP 503)."""


class DeadlineExceededError(ServeError):
    """The request's deadline expired before its answer materialized
    (HTTP 504). Raised on the REQUEST thread by ``PendingQuery.wait``
    when the wait times out, and set by the dispatch thread when it drops
    an already-expired query before executing it (fail fast: a dead
    client's walk would only delay live ones)."""


class ServerOverloadedError(ServeError):
    """Admission control shed this query: the dispatch queue is at its
    configured depth bound, so queueing would only grow latency without
    bound (HTTP 503 with a ``Retry-After`` header). ``retry_after`` is
    the suggested client backoff in seconds."""

    def __init__(self, message: str, *, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = float(retry_after)


class DispatchCrashedError(ServeError):
    """The batcher's dispatch loop crashed while this query was in
    flight; the supervisor restarted the loop (``serve.dispatch_restarts``
    counts it) and failed ONLY the in-flight batch with this — queued and
    future queries are unaffected (HTTP 500)."""
