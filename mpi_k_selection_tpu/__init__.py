"""TPU-native k-selection framework.

A brand-new framework with the capabilities of the reference
``laertispappas/MPI-k-selection`` (a C/MPI CGM k-selection project), rebuilt
idiomatically for TPU on JAX/XLA/Pallas:

- exact 1-indexed k-th-element selection over large int/float arrays
  (reference semantics: sort ascending, answer = element ``k-1`` —
  ``kth-problem-seq.c:32-33``)
- top-k and batched top-k
- a sequential CPU oracle backend (``seq``), a multi-process CGM backend over
  a native shared-memory collectives runtime (``mpi``), and the TPU backend
  (``tpu``) built on radix-select histograms + XLA collectives over a device
  mesh (replacing the reference's MPI_Scatterv/Gather/Bcast/Allreduce protocol,
  ``TODO-kth-problem-cgm.c:103-293``).

Public API::

    import mpi_k_selection_tpu as ks
    ks.kselect(x, k)              # exact k-th smallest (1-indexed), any backend
    ks.kselect_many(x, ks_list)   # multi-rank selection, one prepared pass
    ks.quantiles(x, [.5, .9, .99])# exact nearest-rank order statistics
    ks.topk(x, k)                 # top-k values (and indices)
    ks.distributed_kselect(x, k)  # sharded over a jax.sharding.Mesh
    ks.kselect_streaming(src, k)  # out-of-core exact selection over chunks
    ks.StreamingQuantiles(dtype)  # mergeable online-quantile sketch + refine
    ks.Observability.collecting() # descent telemetry bundle (obs= kwarg):
                                  # events + metrics + trace, off by default

Full reference: docs/API.md; telemetry: docs/OBSERVABILITY.md.
"""

from mpi_k_selection_tpu.version import __version__
from mpi_k_selection_tpu.buffer import DeviceVector
from mpi_k_selection_tpu.ops.sort import sort_select
from mpi_k_selection_tpu.ops.radix import radix_select
from mpi_k_selection_tpu.ops.topk import topk, batched_topk
from mpi_k_selection_tpu.api import (
    StreamingQuantiles,
    batched_kselect,
    batched_median,
    kselect,
    kselect_many,
    kselect_streaming,
    median,
    quantiles,
)
from mpi_k_selection_tpu.parallel import (
    distributed_kselect,
    distributed_radix_select,
    distributed_cgm_select,
    distributed_sketch,
    distributed_topk,
)
from mpi_k_selection_tpu.obs import Observability
from mpi_k_selection_tpu.serve import KSelectServer
from mpi_k_selection_tpu.streaming import RadixSketch
from mpi_k_selection_tpu.monitor import Monitor, WindowedSketch

__all__ = [
    "__version__",
    "DeviceVector",
    "kselect",
    "kselect_many",
    "kselect_streaming",
    "StreamingQuantiles",
    "RadixSketch",
    "WindowedSketch",
    "Monitor",
    "KSelectServer",
    "Observability",
    "quantiles",
    "median",
    "batched_kselect",
    "batched_median",
    "sort_select",
    "radix_select",
    "topk",
    "batched_topk",
    "distributed_kselect",
    "distributed_radix_select",
    "distributed_cgm_select",
    "distributed_sketch",
    "distributed_topk",
]
