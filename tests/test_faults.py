"""Fault-injection harness + resilience policies (mpi_k_selection_tpu/faults/).

Three layers of coverage:

- **harness units** — seeded plan determinism, spec validation, the
  injectable sleeper, retry policy arithmetic, injector lifecycle;
- **streaming recovery** — the seeded chaos grid (plans x devices x
  spill x deferred, recovered answers bit-identical to fault-free runs),
  the spill re-read/rebuild ladder, the one-shot gen-0 anchor, the
  ENOSPC downgrade, and typed raises when policies are exhausted — with
  the autouse conftest fixtures asserting no leaked threads, staged
  buffers, or spill dirs on EVERY injected-fault path;
- **serve hardening** — deadlines (waiter timeout + dispatch-side fast
  fail, HTTP 504), queue-depth admission control (503 + Retry-After),
  supervised dispatch restarts, and graceful drain.
"""

import json
import threading
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

from mpi_k_selection_tpu import faults
from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.errors import (
    RetryExhaustedError,
    SpillCapacityError,
    SpillRecordError,
    TransientError,
)
from mpi_k_selection_tpu.streaming.chunked import (
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)


def _chunks(sizes=(5000, 4096, 2048, 4096, 1024), dtype=np.int32, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(-(2**31), 2**31 - 1, size=m, dtype=np.int64).astype(dtype)
        for m in sizes
    ]


CHUNKS = _chunks()
X = np.concatenate(CHUNKS)
K = X.size // 2
WANT = int(np.sort(X, kind="stable")[K - 1])
KW = dict(radix_bits=4, collect_budget=64)


def _policy(**kw):
    kw.setdefault("sleeper", faults.VirtualSleeper())
    return faults.RetryPolicy(**kw)


# ---------------------------------------------------------------------------
# harness units


def test_seeded_plan_deterministic():
    a = faults.FaultPlan.seeded(42, n_chunks=6, faults=5)
    b = faults.FaultPlan.seeded(42, n_chunks=6, faults=5)
    assert a == b and a.seed == 42 and len(a.specs) == 5
    c = faults.FaultPlan.seeded(43, n_chunks=6, faults=5)
    assert a != c


def test_seeded_plan_recoverable_vs_hard():
    soft = faults.FaultPlan.seeded(1, recoverable=True)
    assert all(s.attempts == (0,) for s in soft.specs)
    hard = faults.FaultPlan.seeded(1, recoverable=False)
    assert all(
        s.attempts == (0,) if s.kind == "stall" else len(s.attempts) > 10
        for s in hard.specs
    )


@pytest.mark.parametrize(
    "bad",
    [
        dict(site="nope", index=0, kind="raise"),
        dict(site="source", index=0, kind="nope"),
        dict(site="source", index=0, kind="enospc"),  # kind/site mismatch
        dict(site="spill.write", index=0, kind="corrupt"),
        dict(site="source", index=-1, kind="raise"),
        dict(site="source", index=0, kind="raise", attempts=()),
        dict(site="source", index=0, kind="raise", attempts=(-1,)),
    ],
)
def test_fault_spec_validation(bad):
    with pytest.raises(ValueError):
        faults.FaultSpec(**bad)


def test_fault_plan_rejects_non_specs():
    with pytest.raises(ValueError):
        faults.FaultPlan(specs=("not a spec",))


def test_virtual_sleeper_records_without_blocking():
    vs = faults.VirtualSleeper()
    vs.sleep(1000.0)  # would hang a real sleeper
    vs.sleep(0.5)
    assert vs.slept == [1000.0, 0.5] and vs.total == 1000.5


def test_resolve_sleeper():
    assert faults.resolve_sleeper(None) is faults.DEFAULT_SLEEPER
    vs = faults.VirtualSleeper()
    assert faults.resolve_sleeper(vs) is vs
    with pytest.raises(ValueError):
        faults.resolve_sleeper(42)


def test_retry_policy_backoff_bounded():
    p = faults.RetryPolicy(backoff_base=0.1, backoff_max=0.35)
    assert p.backoff(1) == pytest.approx(0.1)
    assert p.backoff(2) == pytest.approx(0.2)
    assert p.backoff(3) == pytest.approx(0.35)  # capped
    assert p.backoff(10) == pytest.approx(0.35)
    with pytest.raises(ValueError):
        faults.RetryPolicy(max_attempts=0)


def test_resolve_retry_forms():
    assert faults.resolve_retry(None) is faults.DEFAULT_RETRY
    assert faults.resolve_retry("default") is faults.DEFAULT_RETRY
    assert faults.resolve_retry("off") is None
    assert faults.resolve_retry(False) is None
    p = _policy()
    assert faults.resolve_retry(p) is p
    with pytest.raises(ValueError):
        faults.resolve_retry("sometimes")


def test_retry_call_recovers_then_exhausts():
    vs = faults.VirtualSleeper()
    p = faults.RetryPolicy(max_attempts=3, backoff_base=0.25, sleeper=vs)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError("blip")
        return "ok"

    assert faults.retry_call(flaky, p, site="t") == "ok"
    assert vs.slept == [0.25, 0.5]  # exponential, through the sleeper

    def always():
        raise TransientError("down")

    with pytest.raises(RetryExhaustedError) as ei:
        faults.retry_call(always, p, site="t")
    assert ei.value.site == "t" and ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, TransientError)


def test_retry_call_non_retryable_propagates():
    def boom():
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        faults.retry_call(boom, _policy(), site="t")


def test_inject_rejects_rewiring_prebuilt_injector():
    # silently dropping sleeper=/obs= would de-virtualize sleeps and
    # lose every inject event — must fail loudly
    inj = faults.FaultInjector(faults.FaultPlan())
    with pytest.raises(ValueError, match="pre-built injector"):
        with faults.inject(inj, sleeper=faults.VirtualSleeper()):
            pass  # pragma: no cover
    assert faults.active_injector() is None
    with faults.inject(inj) as armed:  # no rewiring: fine
        assert armed is inj


def test_inject_lifecycle_and_nesting():
    plan = faults.FaultPlan()
    assert faults.active_injector() is None
    with faults.inject(plan) as inj:
        assert faults.active_injector() is inj
        with pytest.raises(RuntimeError):
            with faults.inject(plan):
                pass  # pragma: no cover
    assert faults.active_injector() is None
    # disarmed on the raise path too
    with pytest.raises(KeyError):
        with faults.inject(plan):
            raise KeyError("x")
    assert faults.active_injector() is None


def test_injector_stall_uses_sleeper():
    vs = faults.VirtualSleeper()
    plan = faults.FaultPlan(
        (faults.FaultSpec("source", 0, "stall", arg=0.7),)
    )
    inj = faults.FaultInjector(plan, sleeper=vs)
    assert inj.maybe_fault("source", 0) is not None
    assert vs.slept == [0.7]
    assert inj.maybe_fault("source", 0) is None  # attempt 1: clean
    assert inj.fired == [
        {"site": "source", "kind": "stall", "index": 0, "attempt": 0}
    ]


# ---------------------------------------------------------------------------
# resilient source


def test_resilient_source_mid_pass_repull():
    vs = faults.VirtualSleeper()
    p = faults.RetryPolicy(sleeper=vs)
    plan = faults.FaultPlan((faults.FaultSpec("source", 2, "raise"),))
    with faults.inject(plan, sleeper=vs) as inj:
        src = faults.resilient_source(
            inj.wrap_chunk_source(lambda: iter(CHUNKS)), p
        )
        got = list(src())
    assert len(got) == len(CHUNKS)
    assert all(np.array_equal(a, b) for a, b in zip(got, CHUNKS))
    assert len(vs.slept) == 1  # one backoff for one transient


def test_resilient_source_exhausts_typed():
    p = _policy()
    plan = faults.FaultPlan(
        (faults.FaultSpec("source", 1, "raise", attempts=tuple(range(99))),)
    )
    with faults.inject(plan) as inj:
        src = faults.resilient_source(
            inj.wrap_chunk_source(lambda: iter(CHUNKS)), p
        )
        with pytest.raises(RetryExhaustedError) as ei:
            list(src())
    assert ei.value.site == "source"


def test_resilient_source_non_retryable_propagates():
    def bad():
        yield CHUNKS[0]
        raise KeyError("not transient")

    src = faults.resilient_source(lambda: bad(), _policy())
    with pytest.raises(KeyError):
        list(src())


def test_resilient_source_detects_shrunken_repull():
    state = {"calls": 0}

    def drifting():
        state["calls"] += 1
        if state["calls"] == 1:
            yield CHUNKS[0]
            yield CHUNKS[1]
            raise TransientError("blip")
        # the re-pull yields FEWER chunks than already consumed
        yield CHUNKS[0]

    src = faults.resilient_source(lambda: drifting(), _policy())
    it = src()
    assert np.array_equal(next(it), CHUNKS[0])
    assert np.array_equal(next(it), CHUNKS[1])
    with pytest.raises(RuntimeError, match="not replay-stable"):
        next(it)


# ---------------------------------------------------------------------------
# the seeded chaos grid (ISSUE acceptance): recovered == fault-free bits


@pytest.mark.parametrize("seed", [11, 22, 33])
@pytest.mark.parametrize("devices", [1, 2])
@pytest.mark.parametrize("spill", ["off", "force"])
@pytest.mark.parametrize("deferred", ["on", "off"])
def test_chaos_grid_bit_identical(seed, devices, spill, deferred):
    plan = faults.FaultPlan.seeded(seed, n_chunks=len(CHUNKS), faults=3)
    vs = faults.VirtualSleeper()
    with faults.inject(plan, sleeper=vs) as inj:
        src = inj.wrap_chunk_source(lambda: iter(CHUNKS))
        got = streaming_kselect_many(
            src, [K // 2, K], spill=spill, devices=devices,
            deferred=deferred, retry=_policy(), **KW,
        )
    want = [
        int(np.sort(X, kind="stable")[K // 2 - 1]),
        WANT,
    ]
    assert [int(v) for v in got] == want, (
        f"seed={seed} devices={devices} spill={spill} deferred={deferred} "
        f"fired={inj.fired}"
    )


def test_chaos_grid_float32_leg():
    fchunks = _chunks(dtype=np.float32, seed=3)
    fx = np.concatenate(fchunks)
    fk = fx.size // 3
    want = np.sort(fx, kind="stable")[fk - 1]
    plan = faults.FaultPlan.seeded(5, n_chunks=len(fchunks), faults=3)
    with faults.inject(plan, sleeper=faults.VirtualSleeper()) as inj:
        got = streaming_kselect(
            inj.wrap_chunk_source(lambda: iter(fchunks)), fk,
            spill="force", retry=_policy(), **KW,
        )
    assert np.asarray(got).tobytes() == np.asarray(want).tobytes()


# ---------------------------------------------------------------------------
# the spill recovery ladder


def test_recover_pass_retries_retryable_oserror_subclasses():
    # ConnectionError/TimeoutError ARE OSError subclasses: the ENOSPC
    # rung must dispatch on errno, never intercept them away from the
    # pass-level transient retry
    from mpi_k_selection_tpu.streaming.chunked import _recover_pass

    calls = []

    def run(src, tee):
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient network failure")
        return "ok"

    got = _recover_pass(
        run, policy=_policy(), reading_spill=False, fallback=None,
        on_enospc=lambda e: (_ for _ in ()).throw(AssertionError("wrong rung")),
        obs=None, site="t",
    )
    assert got == "ok" and len(calls) == 3


def test_transient_record_error_rereads_once():
    o = obs_lib.Observability.collecting()
    plan = faults.FaultPlan((faults.FaultSpec("spill.read", 1, "corrupt"),))
    with faults.inject(plan, obs=o) as inj:
        got = int(
            streaming_kselect(
                CHUNKS, K, spill="force", retry=_policy(), obs=o, **KW
            )
        )
    assert got == WANT
    assert inj.fired and inj.fired[0]["kind"] == "corrupt"
    actions = [e.action for e in o.events.of_kind("fault")]
    assert "reread" in actions and "rebuild" not in actions


def test_persistent_corruption_rebuilds_from_source():
    o = obs_lib.Observability.collecting()
    plan = faults.FaultPlan(
        (faults.FaultSpec("spill.read", 0, "corrupt_disk"),)
    )
    with faults.inject(plan, obs=o):
        got = int(
            streaming_kselect(
                CHUNKS, K, spill="force", retry=_policy(), obs=o, **KW
            )
        )
    assert got == WANT
    actions = [e.action for e in o.events.of_kind("fault")]
    assert "reread" in actions and "rebuild" in actions
    # the recovery counters rode the registry
    assert (
        o.metrics.counter(
            "faults.recovered", labels={"site": "spill.read", "action": "rebuild"}
        ).value
        == 1
    )


def test_truncation_rebuilds_from_source():
    plan = faults.FaultPlan((faults.FaultSpec("spill.read", 2, "truncate"),))
    with faults.inject(plan):
        got = int(
            streaming_kselect(CHUNKS, K, spill="force", retry=_policy(), **KW)
        )
    assert got == WANT


def test_one_shot_falls_back_to_gen0_anchor():
    # attempt 1 of record-index 0 is the SECOND generation's read (record
    # indices restart per generation): gen 1 corrupt, gen 0 intact —
    # the consumed stream's only rebuild source is the gen-0 tee
    plan = faults.FaultPlan(
        (faults.FaultSpec("spill.read", 0, "corrupt_disk", attempts=(1,)),)
    )
    o = obs_lib.Observability.collecting()
    with faults.inject(plan, obs=o):
        got = int(
            streaming_kselect(
                iter(list(CHUNKS)), K, retry=_policy(), obs=o, **KW
            )
        )
    assert got == WANT
    assert "rebuild" in [e.action for e in o.events.of_kind("fault")]


def test_one_shot_gen0_corruption_raises_typed():
    # gen 0 is the consumed stream's ONLY copy: damage to it is
    # unrecoverable and must raise the typed record error (never answer
    # wrong) — with no leaked threads/buffers/dirs (autouse fixtures)
    plan = faults.FaultPlan(
        (faults.FaultSpec("spill.read", 1, "corrupt_disk"),)
    )
    with faults.inject(plan):
        with pytest.raises(SpillRecordError):
            streaming_kselect(iter(list(CHUNKS)), K, retry=_policy(), **KW)


def test_record_error_without_retry_policy_still_ladders():
    # the re-read/rebuild ladder is spill-shaped, not policy-shaped: it
    # works even with retry="off" (only TRANSIENT re-runs need a policy)
    plan = faults.FaultPlan(
        (faults.FaultSpec("spill.read", 0, "corrupt_disk"),)
    )
    with faults.inject(plan):
        got = int(
            streaming_kselect(CHUNKS, K, spill="force", retry="off", **KW)
        )
    assert got == WANT


def test_enospc_degrades_auto_spill():
    o = obs_lib.Observability.collecting()
    # record 0, attempt 1: the SECOND generation that writes its first
    # record (gen 0 tees cleanly at attempt 0) — the degradable window
    plan = faults.FaultPlan(
        (faults.FaultSpec("spill.write", 0, "enospc", attempts=(1,)),)
    )
    with faults.inject(plan, obs=o), warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        got = int(
            streaming_kselect(
                iter(list(CHUNKS)), K, retry=_policy(), obs=o, **KW
            )
        )
    assert got == WANT
    assert any("ENOSPC" in str(x.message) for x in w)
    assert "degrade" in [e.action for e in o.events.of_kind("fault")]
    # the degraded (writer-less) passes still log: one pass_log entry per
    # streamed pass, snapshotted into the registry while the store was open
    assert o.metrics.counter("spill.passes").value == len(
        o.events.of_kind("stream.pass")
    )


def test_enospc_in_force_mode_raises_typed():
    plan = faults.FaultPlan(
        (faults.FaultSpec("spill.write", 0, "enospc", attempts=(1,)),)
    )
    with faults.inject(plan):
        with pytest.raises(SpillCapacityError):
            streaming_kselect(CHUNKS, K, spill="force", retry=_policy(), **KW)


def test_enospc_teeing_gen0_raises_typed():
    plan = faults.FaultPlan((faults.FaultSpec("spill.write", 0, "enospc"),))
    with faults.inject(plan):
        with pytest.raises(SpillCapacityError, match="generation 0"):
            streaming_kselect(CHUNKS, K, spill="force", retry=_policy(), **KW)


def test_hard_spill_write_fault_exhausts_typed():
    # attempts semantics at the spill.write site: record indices are
    # per-generation, so a hard spec keeps firing across pass re-runs
    # until the pass-level budget exhausts (never a silent recovery)
    plan = faults.FaultPlan(
        (
            faults.FaultSpec(
                "spill.write", 0, "raise", attempts=tuple(range(1, 99))
            ),
        )
    )
    pol = faults.RetryPolicy(max_attempts=2, sleeper=faults.VirtualSleeper())
    with faults.inject(plan):
        with pytest.raises(RetryExhaustedError):
            streaming_kselect(CHUNKS, K, spill="force", retry=pol, **KW)


def test_stage_fault_retries_in_place():
    plan = faults.FaultPlan((faults.FaultSpec("stage", 1, "raise"),))
    with faults.inject(plan) as inj:
        got = int(streaming_kselect(CHUNKS, K, retry=_policy(), **KW))
    assert got == WANT
    assert inj.fired and inj.fired[0]["site"] == "stage"


def test_stage_fault_exhausts_typed_without_leaks():
    plan = faults.FaultPlan(
        (faults.FaultSpec("stage", 1, "raise", attempts=tuple(range(99))),)
    )
    pol = faults.RetryPolicy(max_attempts=2, sleeper=faults.VirtualSleeper())
    with faults.inject(plan):
        with pytest.raises(RetryExhaustedError) as ei:
            streaming_kselect(CHUNKS, K, retry=pol, **KW)
    # the staging retry exhausts in place on the producer; the consumer
    # may also re-run the pass under ITS transient budget before the
    # final typed raise — either way the terminal error is typed
    assert ei.value.site in ("stage", "pass 0")


def test_retry_off_fails_on_first_transient():
    plan = faults.FaultPlan((faults.FaultSpec("source", 1, "raise"),))
    with faults.inject(plan) as inj:
        with pytest.raises(TransientError):
            streaming_kselect(
                inj.wrap_chunk_source(lambda: iter(CHUNKS)), K,
                retry="off", **KW,
            )


def test_consumer_raise_with_stalled_producer_leaks_nothing():
    # a consumer-side raise while the producer is slowed by an injected
    # stall: close() must join the thread and release the chunk
    # abandoned mid-put — the autouse fixtures (threads, staged buffers,
    # spill dirs) are the assertion here
    from mpi_k_selection_tpu.streaming import chunked as _ck
    from mpi_k_selection_tpu.streaming import executor as _ex

    plan = faults.FaultPlan(
        (faults.FaultSpec("source", 2, "stall", arg=0.05),)
    )
    with faults.inject(plan) as inj:  # REAL sleeper: the stall blocks
        src = inj.wrap_chunk_source(lambda: iter(CHUNKS))
        with pytest.raises(KeyError):
            with _ck._key_chunk_stream(
                src, pipeline_depth=2, hist_method="auto"
            ) as kc:
                keys = None
                try:
                    keys, _ = next(iter(kc))
                    raise KeyError("consumer bug mid-stream")
                finally:
                    # the chunk IN HAND is the consumer's to release —
                    # the same discipline every pass body follows in its
                    # except path; everything still queued or mid-put is
                    # the pipeline's close() sweep's job
                    _ex.release_staged(keys)


def test_certificate_recovers_transient_source_fault():
    plan = faults.FaultPlan((faults.FaultSpec("source", 2, "raise"),))
    clean_less, clean_leq = streaming_rank_certificate(CHUNKS, WANT)
    with faults.inject(plan) as inj:
        less, leq = streaming_rank_certificate(
            inj.wrap_chunk_source(lambda: iter(CHUNKS)), WANT,
            retry=_policy(),
        )
    assert (less, leq) == (clean_less, clean_leq)
    assert less < K <= leq


def test_stream_invariants_hold_through_recovery():
    from mpi_k_selection_tpu.streaming.spill import SpillStore

    o = obs_lib.Observability.collecting()
    plan = faults.FaultPlan(
        (
            faults.FaultSpec("source", 1, "raise"),
            faults.FaultSpec("spill.read", 0, "corrupt_disk"),
        )
    )
    with SpillStore() as store:
        with faults.inject(plan, obs=o) as inj:
            got = int(
                streaming_kselect(
                    inj.wrap_chunk_source(lambda: iter(CHUNKS)), K,
                    spill=store, retry=_policy(), obs=o, **KW,
                )
            )
        log = list(store.pass_log)
    assert got == WANT
    # the event-stream contract holds with recovery attempts interleaved,
    # INCLUDING the entry-for-entry pass_log bytes cross-check: rebuilt
    # passes (and the collect) must log the successful attempt's ACTUAL
    # read, not the scheduled generation's
    obs_lib.check_stream_invariants(o.events.events, spill_pass_log=log)
    assert any(e["pass"] == "collect" for e in log)
    assert (
        o.metrics.counter("faults.injected", labels={"site": "source"}).value
        >= 1
    )


def test_resilient_source_budget_resets_per_incident():
    # isolated recoverable transients on DIFFERENT chunks must never
    # accumulate into an exhaustion: only consecutive failures around
    # one incident share a budget
    p = faults.RetryPolicy(max_attempts=2, sleeper=faults.VirtualSleeper())
    plan = faults.FaultPlan(
        (
            faults.FaultSpec("source", 0, "raise"),
            faults.FaultSpec("source", 2, "raise"),
            faults.FaultSpec("source", 4, "raise"),
        )
    )
    with faults.inject(plan) as inj:
        src = faults.resilient_source(
            inj.wrap_chunk_source(lambda: iter(CHUNKS)), p
        )
        got = list(src())
    assert len(got) == len(CHUNKS)
    assert all(np.array_equal(a, b) for a, b in zip(got, CHUNKS))
    assert len(inj.fired) == 3  # every scheduled transient actually fired


# ---------------------------------------------------------------------------
# serve hardening: deadlines, admission control, supervision, drain


from mpi_k_selection_tpu.serve import (  # noqa: E402 - grouped with their tests
    DeadlineExceededError,
    DispatchCrashedError,
    KSelectServer,
    ServerOverloadedError,
    start_http_server,
)
from mpi_k_selection_tpu.serve.batcher import PendingQuery  # noqa: E402
from mpi_k_selection_tpu.utils.timing import Deadline  # noqa: E402


def test_deadline_unit():
    d = Deadline.after(30.0)
    assert not d.expired and 0.0 < d.remaining() <= 30.0
    z = Deadline(0.0)  # epoch-past monotonic instant
    assert z.expired and z.remaining() == 0.0
    with pytest.raises(ValueError):
        Deadline.after(0.0)


class _Blocker:
    """Parks the dispatch thread until released, so queue/deadline
    behavior is observable deterministically."""

    def __init__(self, srv, dataset="d"):
        self.srv = srv
        self.entered = threading.Event()
        self.release = threading.Event()
        ds = srv.registry.get(dataset)
        self.pending = srv.batcher.submit(
            PendingQuery(dataset, "op", ds=ds, run=self._run)
        )

    def _run(self):
        self.entered.set()
        self.release.wait(10.0)
        return None

    def done(self):
        self.release.set()
        self.pending.wait()


@pytest.fixture
def served():
    o = obs_lib.Observability.collecting()
    srv = KSelectServer(max_queue_depth=2, retry_after=0.25, obs=o)
    srv.add_dataset("d", np.arange(1000, dtype=np.int32))
    yield srv, o
    srv.close()


def test_deadline_waiter_times_out(served):
    srv, o = served
    b = _Blocker(srv)
    b.entered.wait(5.0)
    try:
        with pytest.raises(DeadlineExceededError):
            srv.kselect("d", 5, tier="exact", deadline=0.05)
    finally:
        b.done()
    assert o.metrics.counter("serve.deadline_exceeded").value == 1
    assert "deadline" in [e.action for e in o.events.of_kind("fault")]


def test_default_deadline_applies():
    srv = KSelectServer(default_deadline=0.05)
    srv.add_dataset("d", np.arange(64, dtype=np.int32))
    try:
        b = _Blocker(srv)
        b.entered.wait(5.0)
        try:
            with pytest.raises(DeadlineExceededError):
                srv.kselect("d", 5, tier="exact")
        finally:
            b.done()
        # an explicit generous deadline overrides the tight default
        ans = srv.kselect("d", 5, tier="exact", deadline=30.0)
        assert int(ans.value) == 4
    finally:
        srv.close()


def test_dispatch_drops_expired_before_running(served):
    srv, o = served
    b = _Blocker(srv)
    b.entered.wait(5.0)
    ran = []
    ds = srv.registry.get("d")
    expired = srv.batcher.submit(
        PendingQuery(
            "d", "op", ds=ds, run=lambda: ran.append(1),
            deadline=Deadline(0.0),
        )
    )
    b.done()
    with pytest.raises(DeadlineExceededError):
        expired.wait()
    assert ran == []  # never executed
    assert o.metrics.counter("serve.deadline_exceeded").value == 1


def test_admission_control_sheds_with_retry_after(served):
    srv, o = served
    b = _Blocker(srv)
    b.entered.wait(5.0)
    ds = srv.registry.get("d")
    try:
        admitted = []
        with pytest.raises(ServerOverloadedError) as ei:
            for _ in range(10):
                admitted.append(
                    srv.batcher.submit(
                        PendingQuery("d", "op", ds=ds, run=lambda: 1)
                    )
                )
        assert ei.value.retry_after == 0.25
        assert len(admitted) == 2  # max_queue_depth
    finally:
        b.done()
        for item in admitted:
            item.wait()
    srv.collect_metrics()
    assert o.metrics.counter("serve.load_shed").value >= 1
    assert "shed" in [e.action for e in o.events.of_kind("fault")]


def test_supervisor_restarts_after_dispatch_crash(served):
    srv, o = served
    plan = faults.FaultPlan((faults.FaultSpec("serve.dispatch", 0, "raise"),))
    with faults.inject(plan):
        with pytest.raises(DispatchCrashedError):
            srv.kselect("d", 5, tier="exact")
    # the loop restarted in place: later queries answer normally
    ans = srv.kselect("d", 5, tier="exact")
    assert int(ans.value) == 4
    assert srv.batcher.restarts == 1
    srv.collect_metrics()
    assert o.metrics.counter("serve.dispatch_restarts").value == 1
    assert "restart" in [e.action for e in o.events.of_kind("fault")]


def test_graceful_drain_on_close():
    srv = KSelectServer()
    srv.add_dataset("d", np.arange(128, dtype=np.int32))
    results = []
    ds = srv.registry.get("d")
    pendings = [
        srv.batcher.submit(
            PendingQuery("d", "op", ds=ds, run=lambda i=i: results.append(i))
        )
        for i in range(8)
    ]
    srv.close()  # drain: queued work finishes before the join
    for p in pendings:
        p.wait()
    assert sorted(results) == list(range(8))


def test_http_deadline_and_shed_mapping(served):
    srv, o = served
    with start_http_server(srv) as h:
        url = f"http://127.0.0.1:{h.port}/v1/query"

        def post(body):
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(), method="POST"
            )
            return urllib.request.urlopen(req, timeout=10)

        # normal query with a generous deadline
        r = post({"dataset": "d", "op": "kselect", "k": 3, "deadline_ms": 60000})
        assert r.status == 200
        assert json.loads(r.read())["answers"][0]["value"] == 2
        # bad deadlines -> 400 (incl. the non-finite values stdlib json
        # happily parses, and bools float() would accept as 1.0 ms)
        for bad in (-5, float("nan"), float("inf"), True):
            with pytest.raises(urllib.error.HTTPError) as ei:
                post(
                    {"dataset": "d", "op": "kselect", "k": 3, "deadline_ms": bad}
                )
            assert ei.value.code == 400, bad
        # expired deadline -> 504 (block the dispatcher so it cannot win)
        b = _Blocker(srv)
        b.entered.wait(5.0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                post(
                    {
                        "dataset": "d",
                        "op": "kselect",
                        "k": 3,
                        "tier": "exact",
                        "deadline_ms": 30,
                    }
                )
            assert ei.value.code == 504
            # overload -> 503 with Retry-After: fill the queue to its
            # bound (the expired query above may still occupy a slot)
            ds = srv.registry.get("d")
            for _ in range(2):
                try:
                    srv.batcher.submit(
                        PendingQuery("d", "op", ds=ds, run=lambda: 1)
                    )
                except ServerOverloadedError:
                    break
            with pytest.raises(urllib.error.HTTPError) as ei:
                post({"dataset": "d", "op": "kselect", "k": 3, "tier": "exact"})
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After") == "1"
        finally:
            b.done()


# ---------------------------------------------------------------------------
# CLI --chaos


def test_cli_chaos_flag_end_to_end(capsys):
    from mpi_k_selection_tpu.cli import main

    rc = main(
        [
            "--streaming", "--n", "60000", "--chunk-elems", "8000",
            "--chaos", "2", "--spill", "force", "--verify", "--check",
            "--json",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["extra"]["exact_match"] is True
    assert rec["extra"]["certificate_ok"] is True
    assert rec["extra"]["chaos"]["seed"] == 2
    assert rec["extra"]["chaos"]["plan"]


def test_cli_retry_off_parses(capsys):
    from mpi_k_selection_tpu.cli import main

    rc = main(
        [
            "--streaming", "--n", "20000", "--chunk-elems", "5000",
            "--retry", "off", "--json",
        ]
    )
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["extra"]["retry"] == "off"
