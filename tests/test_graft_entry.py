"""The driver-facing entry points: single-chip compile + multi-chip gauntlet.

Runs the gauntlet *in-process* (conftest provides the 8-device virtual CPU
mesh, so dryrun_multichip takes its fast path); the subprocess bootstrap is
exercised by running __graft_entry__ from a plain interpreter.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    assert out.shape == ()


def test_dryrun_gauntlet_inprocess(monkeypatch):
    import __graft_entry__ as g

    # the config-5 case (N=2^27 int64) is driver-run territory: ~2.5 min on
    # one CPU core. The fast cases (incl. pallas-under-sharding) all run.
    monkeypatch.setenv("_MPIKSEL_GAUNTLET_SKIP_SLOW", "1")
    g.dryrun_multichip(8)  # asserts internally across the case matrix
