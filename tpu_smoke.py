"""TPU smoke test — Mosaic-compiled Pallas kernel correctness on real hardware.

The unit suite exercises the Pallas kernels in interpret mode on CPU
(tests/test_pallas.py); this script is the repeatable artifact that proves
the *compiled* kernels — 32-bit and 64-bit, packed SWAR and compare
variants, prefix-free and prefixed, ragged and tile-aligned — produce
oracle-exact histograms and selections on an actual TPU (VERDICT.md round-1
item 6). Run it directly on a TPU-attached host:

    python tpu_smoke.py

Exit code 0 = every case exact. On a non-TPU host it exits 0 with a skip
notice (the interpret path is already covered by the unit suite).

Reference parity anchor: these kernels are the TPU replacement for the
reference's hot local compute — the per-shard ``qsort``
(``TODO-kth-problem-cgm.c:115``) and linear L/E/G counting sweep
(``:175-185``) — so this is the analogue of running the reference binaries
on real silicon rather than under an emulator.
"""

from __future__ import annotations

import sys

import numpy as np


def _hist_oracle(keys, shift, radix_bits, prefix):
    keys = np.asarray(keys, np.uint64)
    nb = 1 << radix_bits
    digits = (keys >> np.uint64(shift)) & np.uint64(nb - 1)
    active = np.ones(keys.shape, bool)
    if prefix is not None:
        active = (keys >> np.uint64(shift + radix_bits)) == np.uint64(prefix)
    return np.bincount(digits[active].astype(np.int64), minlength=nb)


def main() -> int:
    import jax
    import jax.numpy as jnp

    if jax.default_backend() == "cpu":
        print("tpu_smoke: no TPU attached; compiled-kernel smoke skipped")
        return 0

    from mpi_k_selection_tpu.ops.pallas.histogram import (
        pallas_radix_histogram,
        pallas_radix_histogram64,
    )
    from mpi_k_selection_tpu.ops.radix import radix_select
    from mpi_k_selection_tpu.utils.x64 import enable_x64

    rng = np.random.default_rng(42)
    failures = []

    def check(label, got, want):
        ok = np.array_equal(np.asarray(got), np.asarray(want))
        print(f"  {'ok ' if ok else 'FAIL'} {label}")
        if not ok:
            failures.append(label)

    # --- 32-bit kernel: shapes x prefix cases x variants, compiled ---
    print("32-bit histogram kernel (Mosaic-compiled):")
    for n in (12345, 1 << 20, (1 << 22) + 77):  # ragged, aligned, multi-grid+tail
        keys = rng.integers(0, 2**32, size=n, dtype=np.uint32)
        kd = jax.device_put(jnp.asarray(keys))
        for shift, rb, prefix in ((28, 4, None), (24, 4, 7), (0, 4, 2**27 - 5),
                                  (24, 8, None), (16, 8, 129)):
            for packed in (True, False):
                got = pallas_radix_histogram(
                    kd, shift=shift, radix_bits=rb, prefix=prefix,
                    packed=packed, interpret=False,
                )
                check(
                    f"n={n} shift={shift} rb={rb} prefix={prefix} packed={packed}",
                    got, _hist_oracle(keys, shift, rb, prefix),
                )

    # adversarial skew at the production block size (SWAR drain path)
    skew = np.full(300_000, 0x12345678, dtype=np.uint32)
    got = pallas_radix_histogram(
        jax.device_put(jnp.asarray(skew)), shift=24, radix_bits=4,
        prefix=jnp.uint32(1), interpret=False,
    )
    check("adversarial skew (drain)", got, _hist_oracle(skew, 24, 4, 1))

    # --- 64-bit two-plane kernel, compiled (needs x64) ---
    print("64-bit histogram kernel (Mosaic-compiled):")
    with enable_x64():
        for n in (54321, 1 << 20):
            keys = rng.integers(0, 2**64, size=n, dtype=np.uint64)
            kd = jax.device_put(jnp.asarray(keys))
            for shift, rb, prefix in ((60, 4, None), (56, 4, 9), (32, 4, 3**10),
                                      (28, 4, 11), (0, 4, 2**50 + 17)):
                for packed in (True, False):
                    got = pallas_radix_histogram64(
                        kd, shift=shift, radix_bits=rb, prefix=prefix,
                        packed=packed, interpret=False,
                    )
                    check(
                        f"n={n} shift={shift} rb={rb} prefix={prefix} packed={packed}",
                        got, _hist_oracle(keys, shift, rb, prefix),
                    )

    # --- end-to-end compiled selections over the kernel ---
    print("radix_select end-to-end (compiled kernels):")
    x32 = rng.integers(-(2**31), 2**31, size=2_000_003, dtype=np.int32)
    for k in (1, 1_000_000, 2_000_003):
        got = int(radix_select(jax.device_put(jnp.asarray(x32)), k))
        check(f"int32 k={k}", got, int(np.sort(x32)[k - 1]))
    xf = rng.standard_normal(1_000_000).astype(np.float32)
    got = float(radix_select(jax.device_put(jnp.asarray(xf)), 500_000))
    check("float32 median", got, float(np.sort(xf)[499_999]))
    # the compare-per-bucket variant end-to-end (its interpret-mode e2e was
    # retired from the CPU suite in r5 — each descent pass cost a multi-
    # second interpret trace; compiled it is one cheap run)
    xc = rng.integers(-(2**31), 2**31, size=300_001, dtype=np.int32)
    got = int(radix_select(
        jax.device_put(jnp.asarray(xc)), 150_000, hist_method="pallas_compare"
    ))
    check("int32 pallas_compare e2e", got, int(np.sort(xc)[149_999]))
    for dt in (np.float16, jnp.bfloat16):
        xh = (rng.standard_normal(300_001) * 8).astype(dt)
        got = radix_select(jax.device_put(jnp.asarray(xh)), 150_000)
        want = np.sort(np.asarray(xh), kind="stable")[149_999]
        check(f"{np.dtype(dt).name} median", np.asarray(got)[()], want)
    with enable_x64():
        # n > 2^20: the production cutover gate (ops/radix.py:cutover_passes)
        # is OPEN, so the compiled path includes the collect ladder — the
        # round-3 smoke's n=1,000,000 sat just below the gate and never ran
        # the cutover the headline numbers depend on (VERDICT r3 weak #5)
        n64 = (1 << 21) + 4097
        x64v = rng.integers(-(2**62), 2**62, size=n64, dtype=np.int64)
        xd64 = jax.device_put(jnp.asarray(x64v))
        for k in (123_456, n64 // 2, n64):
            got = int(radix_select(xd64, k))
            check(f"int64 k={k} (cutover path)", got, int(np.sort(x64v)[k - 1]))
        # float64/uint64: the remaining claimed dtypes (docs/API.md), e2e on
        # chip. float64 goes in as a HOST array: TPU f64 storage truncates
        # to ~49 bits at device_put (measured), so the exact path view-casts
        # the bits on host and selects in u64 key space on device
        # (ops/radix.py:_f64_tpu_host_keys)
        xf64 = rng.standard_normal(n64).astype(np.float64)
        xf64[: n64 // 2] = -np.abs(xf64[: n64 // 2])
        got = float(radix_select(xf64, n64 // 2))
        check("float64 median (host-exact path)", got,
              float(np.sort(xf64)[n64 // 2 - 1]))
        # device-resident f64: exact w.r.t. the device's (truncated) contents
        xdev = jax.device_put(jnp.asarray(xf64))
        got = float(radix_select(xdev, n64 // 2))
        check("float64 median (device contents)", got,
              float(np.sort(np.asarray(xdev))[n64 // 2 - 1]))
        xu64 = rng.integers(0, 2**64, size=n64, dtype=np.uint64)
        got = int(radix_select(jax.device_put(jnp.asarray(xu64)), n64 // 3))
        check("uint64 k=n/3 (cutover path)", got, int(np.sort(xu64)[n64 // 3 - 1]))
        # multi-rank through the 64-bit multi-prefix kernels (lo-plane
        # variant included: passes below shift 32 run
        # _hist_kernel64_multi_packed compiled)
        from mpi_k_selection_tpu.ops.radix import radix_select_many

        ksq = np.array([1, n64 // 2, n64 - 7])
        got_m = np.asarray(radix_select_many(xd64, ksq, cutover=None))
        check("int64 select_many (multi kernels, all passes)",
              got_m, np.sort(x64v)[ksq - 1])

    # --- top-k paths over the compiled kernels (r5: both return indices) ---
    print("topk end-to-end (compiled kernels):")
    from mpi_k_selection_tpu.ops.topk import topk

    # threshold via-counts: the pallas_tau_counts kernel + tile collect
    xtf = rng.standard_normal((1 << 21) + 123).astype(np.float32)
    xtd = jax.device_put(jnp.asarray(xtf))
    v, i = topk(xtd, 128, method="threshold")
    order = np.argsort(-xtf, kind="stable")[:128]
    check("threshold 2M f32 k=128 values", v, xtf[order])
    check("threshold 2M f32 k=128 indices", i, order)
    v, i = topk(xtd, 64, method="threshold", largest=False)
    order_s = np.argsort(xtf, kind="stable")[:64]
    check("threshold smallest k=64 indices", i, order_s)
    # block kernel + streaming index recovery at a reduced batched shape
    xb = rng.standard_normal((256, 8192)).astype(np.float32)
    xbd = jax.device_put(jnp.asarray(xb))
    v, i = topk(xbd, 8, method="block")
    rv, ri = jax.lax.top_k(xbd, 8)
    check("block 256x8192 k=8 values", v, rv)
    check("block 256x8192 k=8 indices", i, ri)
    # r5 widened envelope: depth-4/fold-16 band (k=9 exercises the slice)
    for kk in (9, 16):
        v, i = topk(xbd, kk, method="block")
        rv, ri = jax.lax.top_k(xbd, kk)
        check(f"block k={kk} values", v, rv)
        check(f"block k={kk} indices", i, ri)
    # bf16 (in-kernel f32 upcast; values bitwise-exact bf16)
    xb16 = jnp.asarray(xb).astype(jnp.bfloat16)
    for kk in (8, 16):  # both depth bands in bf16
        v, i = topk(xb16, kk, method="block")
        rv, ri = jax.lax.top_k(xb16, kk)
        check(f"block bf16 k={kk} values", np.asarray(v).view(np.uint16),
              np.asarray(rv).view(np.uint16))
        check(f"block bf16 k={kk} indices", i, ri)

    # --- multi-device staged ingest (the bench_streaming_oc multi-device
    # config at smoke scale): chunks staged round-robin over every chip,
    # answers bit-identical across devices {1, all} x depth {0, 2} and
    # exact vs the host oracle — so MULTICHIP runs record the streaming
    # round-robin path on real silicon, not only the virtual CPU mesh ---
    ndev = len(jax.devices())
    if ndev > 1:
        print(f"streaming multi-device ingest ({ndev} chips):")
        from mpi_k_selection_tpu.streaming import (
            streaming_kselect,
            streaming_rank_certificate,
        )

        chunk = 1 << 19
        nchunks = 9  # odd count: the round robin wraps unevenly
        sn = chunk * nchunks
        rng_chunks = [
            np.random.default_rng(100 + i).integers(
                -(2**31), 2**31 - 1, size=chunk, dtype=np.int32
            )
            for i in range(nchunks)
        ]
        sk = sn // 2
        want_s = int(np.sort(np.concatenate(rng_chunks), kind="stable")[sk - 1])
        got_sync = int(streaming_kselect(rng_chunks, sk, pipeline_depth=0))
        check("streaming sync oracle", got_sync, want_s)
        for dv in (1, ndev):
            got_d = int(streaming_kselect(rng_chunks, sk, pipeline_depth=2, devices=dv))
            check(f"streaming devices={dv} bit-identical", got_d, want_s)
        less, leq = streaming_rank_certificate(rng_chunks, want_s, devices=ndev)
        check("streaming multi-device certificate", less < sk <= leq, True)

    # --- survivor spill store (the bench_streaming_oc _spill config at
    # smoke scale, ISSUE 5): spill=force bit-identical to spill=off at
    # devices {1, all}, a one-shot generator served end-to-end, and the
    # per-pass streamed bytes shrinking geometrically on real silicon ---
    print("streaming survivor spill store:")
    from mpi_k_selection_tpu.streaming import (
        SpillStore,
        streaming_kselect as _sp_ksel,
    )

    sp_chunks = [
        np.random.default_rng(300 + i).integers(
            -(2**31), 2**31 - 1, size=1 << 17, dtype=np.int32
        )
        for i in range(9)
    ]
    sp_n = sum(c.size for c in sp_chunks)
    sp_k = sp_n // 2
    sp_kw = dict(radix_bits=4, collect_budget=512)
    want_sp = int(_sp_ksel(sp_chunks, sp_k, spill="off", **sp_kw))
    sp_devgrid = (1, ndev) if ndev > 1 else (1,)
    for dv in sp_devgrid:
        for deferred in ("on", "off"):
            got_sp = int(
                _sp_ksel(
                    sp_chunks, sp_k, spill="force", devices=dv,
                    deferred=deferred, **sp_kw,
                )
            )
            check(
                f"spill=force devices={dv} deferred={deferred} bit-identical",
                got_sp, want_sp,
            )
    got_os = int(_sp_ksel(iter(sp_chunks), sp_k, **sp_kw))  # spill=auto
    check("spill one-shot generator", got_os, want_sp)
    with SpillStore() as sp_store:
        _sp_ksel(sp_chunks, sp_k, spill=sp_store, **sp_kw)
        reads = [
            p["bytes_read"] for p in sp_store.pass_log
            if isinstance(p["pass"], int) and p["pass"] >= 1
        ]
        shrink_ok = len(reads) >= 2 and all(
            b <= a / (1 << 3) for a, b in zip(reads, reads[1:])
        )
        check("spill passes shrink geometrically", shrink_ok, True)

    # --- adaptive width schedule + prefix-packed spill (ISSUE 19, the
    # bench_streaming_oc width_pack config at smoke scale): the wide
    # pass-0 digit + segment-pruned packed replay must be bit-identical
    # on real silicon at devices {1, all}, stream <= 1.2 * n key bytes
    # total (the legacy spill path pays ~2x), and write strictly fewer
    # physical than logical bytes — the silicon validation the
    # width_schedule default flip waits on (ROADMAP) ---
    print("adaptive width schedule + packed spill:")
    for dv in sp_devgrid:
        for ws, ps in (("auto", "auto"), ("auto", "off"), ("off", "auto")):
            got_wp = int(
                _sp_ksel(
                    sp_chunks, sp_k, spill="force", devices=dv,
                    width_schedule=ws, pack_spill=ps, **sp_kw,
                )
            )
            check(
                f"width_schedule={ws} pack_spill={ps} devices={dv} "
                "bit-identical",
                got_wp, want_sp,
            )
    with SpillStore() as wp_store:
        got_wp = int(
            _sp_ksel(
                sp_chunks, sp_k, spill=wp_store,
                width_schedule="auto", pack_spill="auto", **sp_kw,
            )
        )
        check("width+pack spill-store bit-identical", got_wp, want_sp)
        wp_log = list(wp_store.pass_log)
    wp_streamed = sum(p["bytes_read"] for p in wp_log)
    wp_disk_w = sum(p.get("disk_bytes_written") or 0 for p in wp_log)
    wp_logical_w = sum(p.get("bytes_written") or 0 for p in wp_log)
    wp_ratio = wp_streamed / (sp_n * 4)
    check("width+pack bytes streamed <= 1.2 n key bytes", wp_ratio <= 1.2, True)
    check(
        "packed writes below logical",
        wp_logical_w > 0 and wp_disk_w < wp_logical_w, True,
    )
    print(
        f"    bytes_streamed / (n * key_bytes) = {round(wp_ratio, 4)}; "
        f"disk_bytes_ratio = "
        f"{round(wp_disk_w / wp_logical_w, 4) if wp_logical_w else None}"
    )

    # --- parallel host data plane (ISSUE 20, the bench_streaming_oc
    # _workers config at smoke scale): the ingest pool must be invisible
    # to the answer on real silicon — workers {1, 2, auto} x devices
    # {1, all} bit-identical on the packed-spill descent, and the pooled
    # leg's wall reported next to workers=1 (TPU hosts have the cores
    # the CPU-mesh CI box lacks, so this is where workers_speedup is
    # load-bearing) ---
    print("parallel host data plane (ingest pool):")
    from mpi_k_selection_tpu.streaming.pipeline import (
        encode_hidden_frac as _ehf,
        resolve_ingest_workers as _riw,
    )
    from mpi_k_selection_tpu.utils.profiling import PhaseTimer as _PT
    from mpi_k_selection_tpu.utils.timing import time_fn as _pw_time_fn

    pw_auto = _riw("auto")
    pw_walls = {}
    for dv in sp_devgrid:
        for wk in (1, 2, "auto"):
            pw_t = _PT()
            pw_secs, got_pw = _pw_time_fn(
                lambda dv=dv, wk=wk, pw_t=pw_t: int(
                    _sp_ksel(
                        sp_chunks, sp_k, spill="force", devices=dv,
                        width_schedule="auto", pack_spill="auto",
                        ingest_workers=wk, timer=pw_t, **sp_kw,
                    )
                )
            )
            pw_walls[(dv, wk)] = (pw_secs, pw_t)
            check(
                f"ingest_workers={wk} devices={dv} bit-identical",
                got_pw, want_sp,
            )
    pw_s1 = pw_walls[(sp_devgrid[-1], 1)][0]
    pw_sa, pw_ta = pw_walls[(sp_devgrid[-1], "auto")]
    pw_hidden = _ehf(pw_ta)
    print(
        f"    workers=1 {round(pw_s1, 4)}s vs auto({pw_auto}) "
        f"{round(pw_sa, 4)}s; workers_speedup = "
        f"{round(pw_s1 / pw_sa, 4) if pw_sa else None}; "
        f"encode_hidden_frac = "
        f"{round(pw_hidden, 4) if pw_hidden is not None else None}"
    )

    # the spill-pass device_scaling the ROADMAP sweep item needs: the
    # deferred spill descent's wall at devices {1, all} (+ the eager
    # wall at devices=all as the before/after) — on real silicon these
    # are the numbers that show the r6 consumer serialization retired
    # (CPU-mesh devices share one core, so only TPU values are load-
    # bearing). time_fn blocks on the result: device-sync semantics.
    if ndev > 1:
        from mpi_k_selection_tpu.utils.timing import time_fn as _time_fn

        spill_walls = {}
        for label, dv, deferred in (
            ("devices=1 deferred", 1, "on"),
            (f"devices={ndev} deferred", ndev, "on"),
            (f"devices={ndev} eager", ndev, "off"),
        ):
            secs, _ = _time_fn(
                lambda dv=dv, deferred=deferred: _sp_ksel(
                    sp_chunks, sp_k, spill="force", devices=dv,
                    deferred=deferred, **sp_kw,
                )
            )
            spill_walls[label] = round(secs, 4)
        d1 = spill_walls["devices=1 deferred"]
        dp = spill_walls[f"devices={ndev} deferred"]
        print(
            f"    spill-pass walls: {spill_walls} -> device_scaling "
            f"{round(d1 / dp, 3) if dp else None}"
        )

    # --- single-read ingest tiers (ISSUEs 11 + 13): ONE device program
    # per staged bucket per pass vs the unfused bundle, at devices
    # {1, all} — bit-equality of BOTH fusion tiers on real silicon, the
    # read-amplification counters (bucket_read_bytes / staged_bytes:
    # <= 1.0 kernel, ~1.0 xla), and the kernel-vs-xla-vs-unfused walls.
    # The kernel wall is THE number this leg exists for: the compiled
    # sweep kernel's guaranteed-one-HBM-read bandwidth factor, which the
    # CPU CI (dispatch counts only) cannot measure and the ROADMAP
    # records as unrecorded ---
    print("single-read ingest tiers (sweep kernel / xla fusion / unfused):")
    from mpi_k_selection_tpu.obs import (
        MetricsRegistry as _fu_Reg,
        Observability as _fu_Obs,
    )
    from mpi_k_selection_tpu.utils.timing import time_fn as _fu_time_fn

    for dv in sp_devgrid:
        for mode in ("kernel", "xla"):
            got_fu = int(
                _sp_ksel(
                    sp_chunks, sp_k, spill="force", devices=dv, fused=mode,
                    **sp_kw,
                )
            )
            check(f"fused={mode} devices={dv} bit-identical", got_fu, want_sp)
    fu_walls = {}
    fu_amp = {}
    for mode in ("kernel", "xla", "off"):
        o = _fu_Obs(metrics=_fu_Reg())
        secs, _ = _fu_time_fn(
            lambda mode=mode, o=o: _sp_ksel(
                sp_chunks, sp_k, spill="force",
                devices=ndev if ndev > 1 else 1, fused=mode, obs=o, **sp_kw,
            )
        )
        fu_walls[mode] = round(secs, 4)
        read = staged = 0
        for m in o.metrics.metrics():
            if m.name == "ingest.bucket_read_bytes":
                read += m.value
            elif m.name == "ingest.staged_bytes":
                staged += m.value
        fu_amp[mode] = round(read / staged, 3) if staged else None
    check("kernel read amplification <= 1.0", fu_amp["kernel"] is not None
          and fu_amp["kernel"] <= 1.0, True)
    check("xla read amplification ~1.0", fu_amp["xla"] is not None
          and fu_amp["xla"] <= 1.1, True)
    print(
        f"    ingest-tier walls: {fu_walls} -> fused_speedup "
        f"{round(fu_walls['off'] / fu_walls['kernel'], 3) if fu_walls['kernel'] else None}"
        f", kernel_vs_xla "
        f"{round(fu_walls['xla'] / fu_walls['kernel'], 3) if fu_walls['kernel'] else None}"
        f"; read_amplification kernel={fu_amp['kernel']} "
        f"xla={fu_amp['xla']} unfused={fu_amp['off']}"
    )

    # --- seeded chaos recovery (ISSUE 9 follow-on (c), ROADMAP): the
    # spill descent under a seeded FaultPlan on real chips — CPU CI
    # proves the recovered BITS; this leg records the recovery TIMING:
    # the fault-free wall vs the chaos wall (stalls virtualized, so the
    # delta is real recovery work: re-pulls, re-reads, pass rebuilds)
    # plus the fault/recovery counters, alongside the obs snapshot below
    print("streaming chaos recovery (seeded fault injection):")
    from mpi_k_selection_tpu import faults as _faults
    from mpi_k_selection_tpu import obs as _ch_obs_lib
    from mpi_k_selection_tpu.utils.timing import time_fn as _ch_time_fn

    ch_kw = dict(spill="force", devices=ndev if ndev > 1 else 1, **sp_kw)
    clean_s, _ = _ch_time_fn(lambda: _sp_ksel(sp_chunks, sp_k, **ch_kw))
    ch_vs = _faults.VirtualSleeper()
    ch_obs = _ch_obs_lib.Observability.collecting()
    ch_plan = _faults.FaultPlan(
        (
            _faults.FaultSpec("source", 2, "raise"),
            _faults.FaultSpec("stage", 1, "raise"),
            _faults.FaultSpec("spill.read", 0, "corrupt_disk"),
            _faults.FaultSpec("source", 4, "stall", arg=0.001),
        )
    )
    with _faults.inject(ch_plan, sleeper=ch_vs, obs=ch_obs) as ch_inj:
        chaos_s, got_chaos = _ch_time_fn(
            lambda: _sp_ksel(
                ch_inj.wrap_chunk_source(lambda: iter(sp_chunks)), sp_k,
                retry=_faults.RetryPolicy(sleeper=ch_vs), obs=ch_obs,
                **ch_kw,
            )
        )
    check("chaos recovered bit-identical", int(got_chaos), want_sp)
    check("chaos plan fired >= 3 sites", len(ch_inj.fired) >= 3, True)
    ch_counters = {
        f"{m.name}{dict(m.labels) if m.labels else ''}": m.value
        for m in ch_obs.metrics.metrics()
        if m.name.startswith("faults.")
    }
    ch_actions = sorted(
        {
            e.action
            for e in ch_obs.events.of_kind("fault")
            if e.action != "inject"
        }
    )
    print(
        f"    recovery walls: fault-free {round(clean_s, 4)}s vs chaos "
        f"{round(chaos_s, 4)}s (overhead "
        f"{round(chaos_s / clean_s - 1, 3) if clean_s else None}, "
        f"virtual backoff {round(ch_vs.total, 4)}s excluded); "
        f"fired={list(ch_inj.fired)} actions={ch_actions}"
    )
    print(f"    fault counters: {ch_counters}")

    # --- continuous monitoring (ISSUE 10): the windowed quantile ring
    # over the spill chunks on real silicon — ring re-aggregation must
    # stay bit-identical to a from-scratch merge with device-staged
    # ingest underneath, and the exact bounds must bracket the true
    # window quantiles
    print("continuous monitoring (windowed quantiles):")
    from mpi_k_selection_tpu.monitor import Monitor as _Monitor
    from mpi_k_selection_tpu.streaming.sketch import (
        RadixSketch as _MonSketch,
    )

    mon = _Monitor(
        window=4, devices=ndev if ndev > 1 else None, pipeline_depth=2
    )
    mon_samples = list(mon.run(list(sp_chunks), np.int32))
    check("monitor emitted one sample per chunk", len(mon_samples), 9)
    mon_scratch = _MonSketch(np.int32)
    for b in mon.ws.live_buckets():
        mon_scratch.fold_scaled(b, 1)
    check(
        "monitor ring bit-identical to from-scratch merge",
        mon.ws.query() == mon_scratch, True,
    )
    import math as _math

    mon_live = np.sort(
        np.concatenate(sp_chunks[-4:]), kind="stable"
    )
    last_mon = mon_samples[-1]
    mon_ok = all(
        vlo <= mon_live[max(1, _math.ceil(q * mon_live.size)) - 1] <= vhi
        for q, (vlo, vhi) in zip(last_mon.qs, last_mon.value_bounds)
    )
    check("monitor bounds bracket true window quantiles", mon_ok, True)

    # --- obs snapshot (ISSUE 6): one instrumented pipelined streaming run
    # whose record carries the numbers the ROADMAP TPU-validation sweep
    # needs — in-flight window occupancy, ingest_hidden_frac, per-pass
    # bytes — so the next TPU run closes that item with data, not just
    # wall clocks. Sinks on must not change the answer (checked here too).
    print("streaming obs snapshot (occupancy / hidden-frac / per-pass bytes):")
    import json as _json

    from mpi_k_selection_tpu import obs as _obs_lib
    from mpi_k_selection_tpu.streaming import (
        SpillStore as _ObsSpillStore,
        streaming_kselect as _obs_ksel,
    )
    from mpi_k_selection_tpu.streaming.pipeline import (
        ingest_hidden_frac as _hidden_frac,
    )
    from mpi_k_selection_tpu.utils.profiling import PhaseTimer as _PhaseTimer

    ob_chunks = [
        np.random.default_rng(500 + i).integers(
            -(2**31), 2**31 - 1, size=1 << 17, dtype=np.int32
        )
        for i in range(8)
    ]
    ob_n = sum(c.size for c in ob_chunks)
    ob_k = ob_n // 2
    ob_kw = dict(radix_bits=4, collect_budget=512)
    want_ob = int(_obs_ksel(ob_chunks, ob_k, **ob_kw))
    # ledger reading (ISSUE 14): snapshot the process ProgramLedger
    # around the instrumented run, so the same silicon run records
    # per-site compile walls and per-device peak staging bytes next to
    # the occupancy snapshot below
    ob_led0 = _obs_lib.LEDGER.snapshot()
    o = _obs_lib.Observability.collecting()
    ob_timer = _PhaseTimer(recorder=o.trace)
    with _ObsSpillStore() as ob_store:
        got_ob = int(
            _obs_ksel(
                ob_chunks, ob_k, spill=ob_store, pipeline_depth=2,
                devices=ndev if ndev > 1 else None, timer=ob_timer, obs=o,
                **ob_kw,
            )
        )
        ob_log = list(ob_store.pass_log)
    check("obs sinks on bit-identical", got_ob, want_ob)
    try:
        _obs_lib.check_stream_invariants(o.events.events, spill_pass_log=ob_log)
        inv_ok = True
    except AssertionError as e:  # pragma: no cover - diagnosed via stdout
        print(f"    invariant failure: {e}")
        inv_ok = False
    check("obs event invariants", inv_ok, True)
    trace_json = o.trace.to_json()
    parsed = _json.loads(trace_json)
    check("obs chrome trace parses", bool(parsed["traceEvents"]), True)
    occ = o.metrics.histogram("inflight.occupancy")
    occ_coll = o.metrics.histogram(
        "inflight.occupancy", labels={"phase": "collect"}
    )
    from mpi_k_selection_tpu.streaming import (
        collect_hidden_frac as _coll_frac,
    )

    chf = _coll_frac(occ_coll, ndev if ndev > 1 else 1)
    hidden_ob = _hidden_frac(ob_timer)
    snapshot = {
        "occupancy_mean": round(occ.mean, 3) if occ.count else None,
        "occupancy_max": occ.max,
        "collect_occupancy_mean": (
            round(occ_coll.mean, 3) if occ_coll.count else None
        ),
        "collect_hidden_frac": round(chf, 4) if chf is not None else None,
        "ingest_hidden_frac": (
            round(hidden_ob, 4) if hidden_ob is not None else None
        ),
        "bytes_per_pass": [
            (e.pass_index, e.bytes_read)
            for e in o.events.of_kind("stream.pass")
        ],
        "chunks_per_device": {
            dict(m.labels).get("device", "?"): m.value
            for m in o.metrics.metrics()
            if m.name == "ingest.chunks"
        },
        "trace_threads": len(o.trace.thread_ids()),
    }
    print(f"  obs snapshot: {snapshot}")
    ob_led = _obs_lib.snapshot_delta(ob_led0, _obs_lib.LEDGER.snapshot())
    ledger_snapshot = {
        "compiles": ob_led["compiles"],
        "recompiles": ob_led["recompiles"],
        "compile_seconds_by_site": {
            site: d["compile_seconds"]
            for site, d in ob_led["sites"].items()
        },
        "device_bytes_peak": ob_led["device_bytes_peak"],
    }
    print(f"  ledger snapshot: {ledger_snapshot}")

    # --- resident-dataset query server (serve/): in-process smoke — a
    # mixed query burst across tiers over two datasets (spread int32 =
    # unpinnable, constant int32 = always pinned), asserting the
    # tier-auto escalation count and bit-equality with direct
    # api.kselect on real silicon, so the next TPU run records serving
    # numbers alongside the streaming sweep ---
    print("resident-dataset query server:")
    import threading as _sv_threading

    from mpi_k_selection_tpu import api as _sv_api
    from mpi_k_selection_tpu import obs as _sv_obs
    from mpi_k_selection_tpu.serve import KSelectServer as _KSelectServer

    sv_obs = _sv_obs.Observability(
        events=_sv_obs.ListSink(), metrics=_sv_obs.MetricsRegistry()
    )
    sv_spread = rng.integers(-(2**31), 2**31 - 1, size=1 << 20, dtype=np.int32)
    sv_flat = np.full(1 << 16, 424242, np.int32)
    sv_ks = [1 + (i * 65537) % sv_spread.size for i in range(24)]
    sv_want = {k: int(np.asarray(_sv_api.kselect(sv_spread, k))) for k in sv_ks}
    with _KSelectServer(window=0.002, obs=sv_obs) as sv_srv:
        sv_srv.add_dataset("spread", sv_spread)
        sv_srv.add_dataset("flat", sv_flat)
        sv_results: dict = {}
        sv_flat_tiers: list = []
        sv_lock = _sv_threading.Lock()

        def sv_client(ks_shard):
            # mixed burst: exact + auto ranks on the spread dataset,
            # auto (always pinned) on the constant one
            for k in ks_shard:
                a_exact = sv_srv.kselect("spread", k, tier="exact")
                a_auto = sv_srv.kselect("spread", k, tier="auto")
                a_flat = sv_srv.kselect("flat", 1 + k % sv_flat.size, tier="auto")
                with sv_lock:
                    sv_results[k] = (int(a_exact.value), int(a_auto.value))
                    sv_flat_tiers.append((a_flat.tier, int(a_flat.value)))

        sv_threads = [
            _sv_threading.Thread(target=sv_client, args=(sv_ks[i::8],))
            for i in range(8)
        ]
        for t in sv_threads:
            t.start()
        for t in sv_threads:
            t.join()
        check(
            "serve exact tier bit-equality vs api.kselect",
            all(sv_results[k][0] == sv_want[k] for k in sv_ks),
            True,
        )
        check(
            "serve auto tier escalates to the same bits",
            all(sv_results[k][1] == sv_want[k] for k in sv_ks),
            True,
        )
        check(
            "serve auto pinned on the constant dataset",
            all(t == ("sketch", 424242) for t in sv_flat_tiers),
            True,
        )
        # every auto query on the spread dataset escalated; none on flat
        esc = sv_obs.metrics.counter("serve.tier_escalations").value
        check("serve tier-auto escalation count", esc, len(sv_ks))
        sv_sketch = sv_srv.kselect("spread", sv_ks[0], tier="sketch")
        v_lo, v_hi = sv_sketch.value_bounds
        check(
            "serve sketch bounds bracket the exact answer",
            bool(v_lo <= sv_want[sv_ks[0]] <= v_hi),
            True,
        )
        sv_width = sv_obs.metrics.histogram("serve.batch_width").as_dict()
        sv_cache = sv_srv.collect_metrics().as_dict()
        print(
            "  serve snapshot: "
            f"batch_width={{count: {sv_width['count']}, "
            f"mean: {round(sv_width['mean'], 2) if sv_width['count'] else None}, "
            f"max: {sv_width['max']}}}, "
            f"program_cache={{hits: {sv_cache['serve.program_cache.hits']['value']}, "
            f"misses: {sv_cache['serve.program_cache.misses']['value']}}}, "
            f"escalations={esc}"
        )
        # per-device dispatch-lane occupancy (ISSUE 18): each resident
        # dataset routes to the lane of its execution device, so on a
        # multi-chip host this prints one row per device that saw work
        print(f"  serve lanes: {sv_srv.batcher.lane_summary()}")

    # --- registration-time warmup (ISSUE 18): the cold-vs-warm
    # first-query split on real silicon — a warmed dataset's first exact
    # query must run with ZERO on-path compiles (the compile wall moved
    # into add_dataset), while the cold control pays it on the request ---
    sv_fq_timer = _PhaseTimer()
    sv_fq_books = {}
    for sv_leg, sv_warm, sv_extra in (("cold", False, 4099), ("warm", True, 8209)):
        sv_x = rng.integers(
            -(2**31), 2**31 - 1, size=(1 << 17) + sv_extra, dtype=np.int32
        )
        sv_k = 1 + sv_x.size // 3
        sv_v_ref = int(np.asarray(_sv_api.kselect(sv_x, sv_k)))
        with _KSelectServer() as sv_fq_srv:
            sv_fq_srv.add_dataset("fq", sv_x, warmup=sv_warm)
            sv_led0 = _obs_lib.LEDGER.snapshot()
            with sv_fq_timer.phase(sv_leg):
                sv_a = int(sv_fq_srv.kselect("fq", sv_k, tier="exact").value)
            sv_fq_books[sv_leg] = _obs_lib.snapshot_delta(
                sv_led0, _obs_lib.LEDGER.snapshot()
            )["sites"].get("serve.programs", {}).get("compiles", 0)
        check(f"serve {sv_leg} first query bit-equality", sv_a, sv_v_ref)
    check("serve warmed first query on-path compiles", sv_fq_books["warm"], 0)
    sv_fq = sv_fq_timer.as_dict()
    print(
        "  serve first-query split: "
        f"cold={sv_fq['cold']['seconds']:.3f}s "
        f"({sv_fq_books['cold']} on-path compiles), "
        f"warm={sv_fq['warm']['seconds']:.3f}s "
        f"({sv_fq_books['warm']} on-path compiles)"
    )

    if failures:
        print(f"tpu_smoke: {len(failures)} FAILURES")
        return 1
    print("tpu_smoke: all cases exact")
    return 0


if __name__ == "__main__":
    sys.exit(main())
