"""Top-k selection (values + indices), single and batched.

The reference only ever returns the single k-th order statistic; top-k (the
full set of k extreme elements) is the north-star extension covering the
BASELINE.md configs "Single-chip top-k: N=64M float32, k=128 (MoE router
logits)" and "Batched top-k: B=4096 x D=32768 float32, k=8 (beam-search /
vocab top-k)".

Implementation notes:

- ``lax.top_k`` is the XLA baseline (operates on the last axis; leading axes
  batch for free, so batched_topk is the same code path).
- ``smallest``-k and unsigned dtypes are handled via the order-preserving
  key transforms in utils/dtypes.py: build signed keys whose descending order
  equals the requested order, top_k the keys, gather the original values.
- ``method="chunked"`` is the two-stage large-D variant: split the last axis
  into C chunks, take top-k per chunk (parallel, small sorts), then top-k of
  the C*k candidates. For D >> k this does ~D + C*k work per row instead of
  a single large-D top_k, and it is how the Pallas block kernel decomposes.
- ``method="tournament"`` is the multi-round variant for huge 1-D inputs:
  ``lax.top_k`` gets its speed from batch parallelism across rows, so a
  single giant row is its worst case. Each round reshapes the candidate
  pool into (rows, sub) and keeps the per-row top-k, shrinking the pool by
  ~sub/k until one cheap flat top-k finishes (~3x faster than flat at
  N=64M on a v5e).
- ``method="threshold"`` is the production 1-D path: the k-th largest value
  is found by radix descent (the Pallas histogram kernel, ops/radix.py),
  then the k winners are collected by a cumsum-rank gather — all streaming,
  no giant sort anywhere. ~10x faster than flat at N=64M, k=128 on a v5e.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu.utils import dtypes as _dt


def _signed_keys(x: jax.Array, largest: bool):
    """``(keys, native)``: keys whose *descending* signed order equals the
    requested value order, and whether they are ``x`` itself (native)."""
    dtype = np.dtype(x.dtype)
    if largest and (jnp.issubdtype(dtype, jnp.signedinteger) or dtype.kind == "f"):
        # lax.top_k compares these natively — but on TPU the float TopK
        # path is ~3.5x slower than integer TopK (measured 5.8 vs 3.0 ms at
        # 4096x32768 f32 k=8), so floats take the order-preserving integer
        # bitcast below there; one elementwise pass buys a faster sort
        if not (dtype.kind == "f" and jax.default_backend() == "tpu"):
            return x, True
    u = _dt.to_sortable_bits(x)
    kdt = u.dtype
    bits = _dt.key_bits(dtype)
    if not largest:
        u = ~u
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    signed = np.dtype(f"int{bits}")
    return jax.lax.bitcast_convert_type(u ^ msb, signed), False


def _decode_keys(kv: jax.Array, dtype, largest: bool) -> jax.Array:
    """Inverse of the non-native :func:`_signed_keys` transform: signed keys
    back to values of ``dtype``. Lets the flat/chunked paths return values
    straight from ``lax.top_k``'s own output instead of a
    ``take_along_axis`` gather — the batched (B, k)-from-(B, d) gather
    lowers catastrophically on TPU (measured 135 ms for 32K indices at
    4096x32768, ~25x the whole top-k)."""
    dtype = np.dtype(dtype)
    bits = _dt.key_bits(dtype)
    kdt = np.dtype(f"uint{bits}")
    u = jax.lax.bitcast_convert_type(kv, kdt)
    msb = u.dtype.type(np.uint64(1) << np.uint64(bits - 1))
    u = u ^ msb
    if not largest:
        u = ~u
    return _dt.from_sortable_bits(u, dtype)


@functools.partial(jax.jit, static_argnames=("k", "largest", "method", "num_chunks"))
def topk(
    x: jax.Array,
    k: int,
    *,
    largest: bool = True,
    method: str = "auto",
    num_chunks: int | None = None,
):
    """Top-k along the last axis. Returns ``(values, indices)`` sorted by rank.

    ``largest=False`` returns the k smallest (ascending). Leading axes batch.
    """
    d = x.shape[-1]
    if not 1 <= k <= d:
        raise ValueError(f"k={k} out of range for last axis of size {d}")
    from mpi_k_selection_tpu.ops.pallas.topk import (
        batched_topk_supported,
        pallas_batched_topk_values,
    )

    if method == "auto":
        if (
            x.ndim == 2
            and largest
            and jax.default_backend() == "tpu"
            and batched_topk_supported(x.shape, x.dtype, k)
        ):
            # the Pallas depth-3-chain + lane-fold + rescue kernel
            # (ops/pallas/topk.py) + the streaming index recovery
            # (_block_topk_indices): ~1.1 ms values-only, ~4.3 ms with
            # indices at the BASELINE batched config (v5e) vs XLA's 5.7 ms
            # values-only and ~138 ms with indices consumed (lax.top_k
            # lowers to a variadic sort once its index output is used —
            # measured at this shape, any dtype). Auto is therefore the
            # right dispatch for BOTH values-only and index consumers.
            method = "block"
        elif x.ndim == 1 and d >= 1 << 18 and d >= 64 * k and d < 2**31:
            method = "threshold"
        elif d >= 1 << 16 and d >= 64 * k and jax.default_backend() != "tpu":
            # chunked wins ~90x over lax.top_k on CPU; on TPU the XLA TopK
            # custom call is already strong and chunked LOSES 3-9x at every
            # measured batched shape (see bench history) — use flat there
            method = "chunked"
        else:
            method = "flat"
    # the flat/chunked paths take values straight from lax.top_k's output
    # (key-decoded when the keys are transformed) — the batched (B, k)
    # take_along_axis gather lowers catastrophically on TPU (see
    # _decode_keys); the 1-D threshold/tournament paths produce indices
    # only, and a 1-D gather of k elements is cheap
    if method == "block":
        if x.ndim != 2 or not largest:
            raise ValueError("block method applies to 2-D inputs, largest=True")
        values = pallas_batched_topk_values(x, k)
        # indices from the streaming threshold-recovery pass (r5) — NOT a
        # second full XLA TopK (whose index path lowers to a 137 ms-class
        # program at this shape, measured) and NOT via the signed-key
        # transform: _signed_keys is a full read+write pass of x that
        # lax.cond would hoist out of the fallback branch and run every
        # call. Tie order matches lax.top_k: slots sort (value desc,
        # position asc), so values[i] == x[row, idx[i]] elementwise.
        # Values-only callers still pay only the kernel: XLA DCEs the
        # whole index recovery.
        idx = _block_topk_indices(x, values, k)
        return values, idx
    keys, native = _signed_keys(x, largest)
    if method == "threshold":
        if x.ndim != 1:
            raise ValueError("threshold method applies to 1-D inputs")
        idx = _threshold_topk_indices(x, k, largest)
        return jnp.take_along_axis(x, idx, axis=-1), idx
    if method == "tournament":
        if x.ndim != 1:
            raise ValueError("tournament method applies to 1-D inputs")
        idx = _tournament_topk_indices(keys, k)
        return jnp.take_along_axis(x, idx, axis=-1), idx
    if method == "flat":
        kv, idx = jax.lax.top_k(keys, k)
    elif method == "chunked":
        c = num_chunks or _pick_num_chunks(d, k)
        if c <= 1 or d % c:
            kv, idx = jax.lax.top_k(keys, k)
        else:
            sub = d // c
            kk = keys.reshape(*keys.shape[:-1], c, sub)
            subvals, subidx = jax.lax.top_k(kk, min(k, sub))
            base = jnp.arange(c, dtype=subidx.dtype)[:, None] * sub
            cand_idx = (subidx + base).reshape(*keys.shape[:-1], -1)
            cand_vals = subvals.reshape(*keys.shape[:-1], -1)
            kv, pos = jax.lax.top_k(cand_vals, k)
            idx = jnp.take_along_axis(cand_idx, pos, axis=-1)
    else:
        raise ValueError(f"unknown topk method {method!r}")
    values = kv if native else _decode_keys(kv, x.dtype, largest)
    return values, idx


def _block_topk_indices_from_values(
    x: jax.Array, values: jax.Array, k: int, *, block: int = 128
):
    """Per-row indices pairing the block kernel's sorted VALUES with their
    positions in ``x`` — the index half of ``method="block"`` (VERDICT r4
    item 1; the reference's own search primitives return positions,
    ``/root/reference/vector.c:220-235``).

    The batched :func:`_threshold_topk_indices` scheme: with the row's k-th
    value ``tau`` known from the kernel, ONE streaming compare pass yields
    per-(row, block) counts of ``> tau`` and ``== tau`` (both reductions
    fuse into a single read of x, ~0.7 ms at 4096x32768); tiny cumsums
    over the ``nb = D/block`` blocks locate, for each output slot, its
    block and rank within it; the <= k candidate blocks per row are
    extracted with ``take_along_axis`` along the SMALL nb axis —
    contiguous ``block``-wide slices, which lower fine on TPU, unlike the
    (B, k)-from-(B, D) per-element gather (135 ms measured, see
    :func:`_decode_keys`) — and the slot's element is found by
    within-block running rank (r <= k always, since target ranks are
    <= k). Gather extraction copies bits verbatim, so rows containing
    ±inf recover exactly (an earlier one-hot-matmul extraction was
    rejected: 0*inf = NaN pollution, and default-precision bf16 products
    broke the == tau match — measured 7.5e-3 error).

    Comparisons run in sortable-key space (uint32 total order, the
    transform fusing into the count reduction), matching ``lax.top_k``'s
    comparator for ±0.0. Collection order is strict-winners-then-ties,
    each by position; the final permutation into (key desc, position asc)
    — ``lax.top_k``'s tie rule — is computed by pairwise ranks + a
    one-hot scatter over the k axis: (B, k, k) elementwise work, no top_k
    call, no gather.

    Returns ``(idx, ok)``: ``ok`` requires every slot matched, no NaN
    among the kernel values, and a strict count consistent with a
    total-order tau (see the guard comments); failing rows take the
    caller's bounded rescue, so exactness never depends on the data.
    """
    B, D = x.shape
    nb = D // block
    cdt = jnp.int32
    # ALL comparisons run in sortable-key space (uint32 total order): f32
    # `==`/`>` treat -0.0 and +0.0 as equal while lax.top_k's comparator
    # ranks -0.0 strictly below +0.0, so value-space counting returned the
    # wrong index at a signed-zero k-boundary. The key transform is
    # elementwise and fuses into the streaming count reduction — no extra
    # pass over x.
    tauk = _dt.to_sortable_bits(values[:, k - 1])  # the k-th value's key
    xb = x.reshape(B, nb, block)
    ub = _dt.to_sortable_bits(xb)
    t3 = tauk[:, None, None]
    bgt = jnp.sum((ub > t3).astype(cdt), axis=2)  # (B, nb)
    beq = jnp.sum((ub == t3).astype(cdt), axis=2)
    ogt = jnp.cumsum(bgt, axis=1)
    oeq = jnp.cumsum(beq, axis=1)
    g = ogt[:, -1:]  # strict-winner count; <= k-1 for a true total-order tau
    j = jnp.arange(k, dtype=cdt)[None, :]
    strict = j < g  # slot j collects a strict winner, else a tau tie
    target = jnp.where(strict, j + 1, j - g + 1)  # 1-based rank sought
    # slot's block: how many block-cumulatives fall short of its rank
    ocmp = jnp.where(strict[..., None], ogt[:, None, :], oeq[:, None, :])
    blk = jnp.sum((ocmp < target[..., None]).astype(cdt), axis=2)
    blk = jnp.clip(blk, 0, nb - 1)
    arange_nb = jnp.arange(nb, dtype=cdt)[None, None, :]
    prev = jnp.sum(
        jnp.where(arange_nb == (blk - 1)[..., None], ocmp, 0), axis=2
    )  # cumulative before the slot's block (0 for block 0)
    r = target - prev  # 1-based rank within the block (<= k)
    # gather RAW f32 blocks and key-transform only the (B, k, block)
    # extract: gathering from ub would give it a non-reduce consumer and
    # force XLA to materialize the full-size key tensor (~1.2 ms measured)
    rows = jnp.take_along_axis(xb, blk[..., None], axis=1)
    urows = _dt.to_sortable_bits(rows)
    t2 = tauk[:, None, None]
    m = jnp.where(strict[..., None], urows > t2, urows == t2)
    within = jnp.cumsum(m.astype(cdt), axis=2)
    hit = m & (within == r[..., None])  # one-hot along the block (or empty)
    found = jnp.any(hit, axis=2)
    local = jnp.argmax(hit, axis=2).astype(cdt)
    idx = blk * block + local  # (B, k), strict-then-ties by position
    # candidate j's key: verbatim from the extracted block at its hit
    wkeys = jnp.sum(jnp.where(hit, urows, 0), axis=2)
    # pairwise ranks: beats[b, i, j] <=> candidate j outranks candidate i
    wi = wkeys[:, :, None]
    wj = wkeys[:, None, :]
    tj = jnp.arange(k, dtype=cdt)
    beats = (wj > wi) | ((wj == wi) & (tj[None, :, None] > tj[None, None, :]))
    rank = jnp.sum(beats.astype(cdt), axis=2)  # (B, k) final slot of cand i
    idx = jnp.sum(
        jnp.where(rank[:, :, None] == tj[None, None, :], idx[:, :, None], 0),
        axis=1,
    )
    # rescue guards beyond per-slot `found`:
    # - NaN among the kernel's values: tau may still be finite+matchable
    #   (duplicated boundary value), in which case every slot "finds" a
    #   tie and the NaN winner's index is silently dropped — rescue.
    # - g > k-1: impossible under a correct total-order tau, but the
    #   kernel's f32 max/min can emit the WRONG-SIGN zero for tau at a
    #   signed-zero boundary, inflating the strict count and making the
    #   position-ordered collection miss later, larger winners — rescue.
    ok = (
        jnp.all(found, axis=1)
        & ~jnp.any(jnp.isnan(values), axis=1)
        & (g[:, 0] <= k - 1)
    )
    return idx, ok


def _block_topk_indices(x: jax.Array, values: jax.Array, k: int, rescue_rows: int = 64):
    """Index half of ``method="block"`` with the same bounded-rescue shape
    as the values kernel: rows the streaming recovery could not resolve
    (rows holding NaN; anything else adversarial) are re-solved exactly by
    ``lax.top_k`` over a gathered <= ``rescue_rows`` subset, and one
    ``lax.cond`` falls back to the full XLA path if even that overflows.
    The fallback's comparison keys are built INSIDE the branch: as a cond
    operand they would be hoisted and their full read+write pass of x
    would run on every call."""
    B = x.shape[0]
    rescue_rows = min(rescue_rows, B)
    idx, ok = _block_topk_indices_from_values(x, values, k)
    bad = ~ok
    nbad = jnp.sum(bad.astype(jnp.int32))
    sval, sidx = jax.lax.top_k(bad.astype(jnp.int32), rescue_rows)
    _, ridx = jax.lax.top_k(x[sidx], k)  # NaNs rank first, like the kernel
    fixed = jnp.where(sval[:, None] > 0, ridx, idx[sidx])
    idx = idx.at[sidx].set(fixed)

    def full_fallback(_):
        fkeys, _ = _signed_keys(x, True)
        _, fidx = jax.lax.top_k(fkeys, k)
        return fidx

    return jax.lax.cond(
        nbad <= rescue_rows, lambda _: idx, full_fallback, 0
    )


def _threshold_topk_indices(x: jax.Array, k: int, largest: bool) -> jax.Array:
    """Indices of the k extreme elements of 1-D ``x`` via radix threshold +
    cumsum-rank gather. Exact under duplicates: all strict winners are taken,
    then earliest-position ties of the threshold value fill the rest.

    r5 fast path (VERDICT r4 item 3): ONE prepared tile set serves both the
    tau select and the winner collect — `_Descent` is built here and the
    descent runs on it via `_select_key_on_prep`, then the per-subblock
    winner counts come from the streaming `pallas_tau_counts` kernel over
    the SAME tiles. The previous structure ran `radix_select` (which built
    and threw away its own tiles), re-derived ``to_sortable_bits(x)`` (a
    second full read+write pass), padded/reshaped a third full-size copy,
    and swept it with jnp block counts — ~5.9 ms at the 64M f32 k=128
    BASELINE config vs ≤3.5 ms targeted here.
    """
    from mpi_k_selection_tpu.ops.radix import (
        _Descent,
        _select_key_on_prep,
        _warn_f64_tpu_approx,
    )

    # this path builds its own _Descent, bypassing the radix shells' exact
    # f64-on-TPU host-key route — threshold top-k over float64 on TPU always
    # runs the documented ~49-bit key approximation, so emit the same
    # one-time warning the kselect paths do (ADVICE r5 #1; the helper
    # no-ops for every other dtype/backend pair), with advice specific to
    # this path: unlike k-th selection, there is no eager-exact escape
    _warn_f64_tpu_approx(
        x,
        advice=(
            "The threshold top-k index pass always runs in device key "
            "space — the exact eager host-key route applies to k-th "
            "selection, not top-k (see docs/API.md). "
        ),
    )

    n = x.shape[0]
    xr = x.ravel()
    prep = _Descent(xr, None, "auto", 32768)
    # threshold rank in TRUE key space: k-th largest == (n-k+1)-th smallest
    tau_rank = (n - k + 1) if largest else k
    # rank in the descent's count dtype (select_count_dtype(n), sized at
    # _Descent build): an implicit int32 asarray would wrap for n >= 2^31
    tauk = _select_key_on_prep(prep, n, jnp.asarray(tau_rank, prep.cdt))
    if (
        prep.count_tiles is not None
        and prep.tiles is not None
        and len(prep.tiles) == 1
        and np.dtype(prep.kdt) == np.dtype(np.uint32)
        and jax.default_backend() == "tpu"  # interpret-mode pallas off-TPU
        # would be slower than the jnp sweep below and bloat test time
    ):
        return _threshold_indices_via_counts(prep, tauk, k, largest)
    # fallback (off-TPU / 64-bit keys / odd geometry): jnp block sweep on
    # the mirrored key view, as before. prep.u is already the sortable-key
    # view when the descent took the non-raw path — reuse it instead of a
    # second full transform pass
    u = prep.u if prep.u is not None else _dt.to_sortable_bits(x)
    tau = tauk
    if not largest:
        u = ~u  # mirror the order so "largest key" means "requested extreme"
        tau = ~tau
    # Collect winners without a full-length cumsum (26 ms at 64M on a v5e —
    # slower than the whole radix descent). Instead: one streaming pass of
    # per-block (gt, eq) counts, tiny cumsums over the blocks, then for each
    # of the k output slots gather just its block and rank within it.
    cdt = jnp.int32  # n < 2^31 enforced by the auto dispatch / caller
    block = 32768
    nb = -(-n // block)
    up = jnp.pad(u, (0, nb * block - n)).reshape(nb, block)
    valid = jax.lax.broadcasted_iota(cdt, (nb, block), 0) * block + jax.lax.broadcasted_iota(cdt, (nb, block), 1) < n
    bgt = jnp.sum((up > tau) & valid, axis=1, dtype=cdt)
    beq = jnp.sum((up == tau) & valid, axis=1, dtype=cdt)
    ogt = jnp.cumsum(bgt)
    oeq = jnp.cumsum(beq)
    g = ogt[-1]
    jj = jnp.arange(k, dtype=cdt)
    strict = jj < g
    target = jnp.where(strict, jj + 1, jj - g + 1)  # 1-based rank sought
    b = jnp.where(strict, jnp.searchsorted(ogt, target), jnp.searchsorted(oeq, target))
    b = jnp.clip(b, 0, nb - 1).astype(cdt)
    prev = jnp.where(
        b > 0, jnp.where(strict, ogt[b - 1], oeq[b - 1]), jnp.zeros_like(target)
    )
    r = target - prev  # 1-based rank within the block
    rows = up[b]  # (k, block) — only k blocks are ever touched
    cols = jax.lax.broadcasted_iota(cdt, (k, block), 1)
    rvalid = cols < (n - b[:, None] * block)
    m = jnp.where(strict[:, None], rows > tau, rows == tau) & rvalid
    within = jnp.cumsum(m.astype(cdt), axis=1)
    local = jnp.argmax((within == r[:, None]) & m, axis=1).astype(cdt)
    idx = b * block + local
    # order the k winners by rank (tiny top_k over k elements)
    _, pos = jax.lax.top_k(u[idx], k)
    return idx[pos]


def _threshold_indices_via_counts(prep, tauk, k: int, largest: bool):
    """Winner collect of :func:`_threshold_topk_indices` on the select's own
    prepared tiles: the ``pallas_tau_counts`` kernel streams the tiles ONCE
    producing per-128-element-row counts of keys strictly beyond tau and
    equal to tau; rank searches route each winner slot to its row; one
    (k, 128) row gather + within-row running rank finds the element. All
    comparisons in uint32 key space (total order — ties, ±0.0, NaN all
    behave exactly like the select itself). Exactness: tau comes from the
    exact descent on the same tiles, so strict count g <= k-1 and the tie
    pool holds >= k-g members — every slot resolves, no rescue needed."""
    from mpi_k_selection_tpu.ops.pallas.histogram import pallas_tau_counts
    from mpi_k_selection_tpu.ops.radix import _rank_block_search

    cdt = prep.cdt
    key_op, key_xor = prep.count_key
    cgt, ceq = pallas_tau_counts(
        tau_key=tauk.astype(jnp.uint32),
        tiles=prep.count_tiles,
        orig_n=prep.tiles_n,
        key_op=key_op,
        key_xor=key_xor,
        largest=largest,
        count_dtype=cdt,
        block_rows=min(prep.block_rows, 4096),
    )
    ogt = jnp.cumsum(cgt)
    oeq = jnp.cumsum(ceq)
    g = ogt[-1]  # strict winners; <= k-1 by definition of the k-th rank
    jj = jnp.arange(k, dtype=cdt)
    strict = jj < g
    target = jnp.where(strict, jj + 1, jj - g + 1)  # 1-based rank sought
    bg = _rank_block_search(ogt, target)
    be = _rank_block_search(oeq, target)
    b = jnp.where(strict, bg, be).astype(cdt)
    bm1 = jnp.maximum(b - 1, 0)
    prev = jnp.where(
        b > 0, jnp.where(strict, ogt[bm1], oeq[bm1]), jnp.zeros_like(target)
    )
    r = target - prev  # 1-based rank within row b
    rows = prep.tiles[0][b]  # (k, 128) whole-row gather — lowers well
    keys = prep.key_of(rows) if prep.key_of is not None else rows
    beyond = (keys > tauk) if largest else (keys < tauk)
    m = jnp.where(strict[:, None], beyond, keys == tauk)
    pos = b[:, None] * 128 + jnp.arange(128, dtype=cdt)[None, :]
    m = jnp.logical_and(m, pos < prep.tiles_n)
    within = jnp.cumsum(m.astype(cdt), axis=1)
    local = jnp.argmax(jnp.logical_and(within == r[:, None], m), axis=1)
    idx = b * 128 + local.astype(cdt)
    # order the k winners by requested rank: top_k over the winners' keys
    # (mirrored for smallest-k), signed-biased for the int comparator;
    # ties keep candidate order == position order, lax.top_k's rule
    wkey = jnp.take_along_axis(keys, local[:, None], axis=1)[:, 0]
    skey = wkey if largest else ~wkey
    skey = jax.lax.bitcast_convert_type(skey ^ jnp.uint32(1 << 31), jnp.int32)
    _, order = jax.lax.top_k(skey, k)
    return idx[order]


def _tournament_topk_indices(keys: jax.Array, k: int) -> jax.Array:
    """Indices of the k largest signed keys of 1-D ``keys`` via batched
    reduction rounds. Exact: every round keeps each row's full top-k, and the
    global top-k survives per-row top-k by the subset property."""
    d = keys.shape[0]
    sub = 1024
    while sub < 4 * k:  # rows must be enough larger than k to shrink the pool
        sub *= 2
    idx = None
    finish = max(1 << 16, sub)
    while d > finish:
        rows = d // sub
        main = rows * sub
        vals, sidx = jax.lax.top_k(keys[:main].reshape(rows, sub), k)
        base = jnp.arange(rows, dtype=sidx.dtype)[:, None] * sub
        cand = (sidx + base).reshape(-1)
        if main < d:  # ragged tail rides along as extra candidates
            cand = jnp.concatenate([cand, jnp.arange(main, d, dtype=cand.dtype)])
        idx = cand if idx is None else idx[cand]
        keys = jnp.concatenate([vals.reshape(-1), keys[main:]]) if main < d else vals.reshape(-1)
        d = keys.shape[0]
    _, pos = jax.lax.top_k(keys, k)
    return pos if idx is None else idx[pos]


def _pick_num_chunks(d: int, k: int) -> int:
    """Largest power-of-two chunk count with chunk size >= max(256, 2k)."""
    c = 1
    while d % (c * 2) == 0 and d // (c * 2) >= max(256, 2 * k):
        c *= 2
    return c


def batched_topk(x: jax.Array, k: int, **kwargs):
    """Alias for :func:`topk` on ``(..., D)`` arrays (BASELINE batched config)."""
    return topk(x, k, **kwargs)
