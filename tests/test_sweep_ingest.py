"""Single-sweep Pallas ingest kernel (ops/pallas/sweep_ingest.py + the
three-tier ``fused`` knob, ISSUE 13).

The contracts under test:

- **Bit-equality over the full grid**: devices {1, 2, max} x
  pipeline_depth {0, 2} x spill {off, force} x fused {kernel, xla, off}
  return identical bits over heterogeneous (host + device + ragged +
  empty) chunk streams and the one-shot tee — ``fused="off"`` (the
  unfused bundle) and ``"xla"`` (PR 11's one-program fusion) are the
  bit-for-bit oracles of the kernel tier.
- **Kernel vs numpy oracle**: the sweep program's histogram, per-spec
  compactions, tee payload, certificate pair and sketch fold + extremes
  equal the host filters — and the compaction BUFFERS are bit-identical
  to the XLA tier's ``compact_core`` (front-packed survivors in chunk
  order, zeros after), not just the materialized prefixes.
- **One program per staged bucket**: under the kernel tier the sketch
  consumer's ``ingest.bucket_reads{phase="sketch"}`` drops to exactly 1
  per staged bucket (2 on the xla tier) and the certificate's
  ``phase="certificate"`` to 1 (2 deferred pair otherwise).
- **Graceful fallback**: buckets outside the kernel's support matrix
  (sub-lane-tile buckets, non-4-byte key spaces) ride the XLA tier per
  bucket with identical answers.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.ops.pallas import fused_ingest as fi
from mpi_k_selection_tpu.ops.pallas import sweep_ingest as si
from mpi_k_selection_tpu.streaming import (
    RadixSketch,
    live_staged_keys,
    resolve_fused,
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.streaming import executor as ex_mod
from mpi_k_selection_tpu.streaming.pipeline import stage_keys


def _chunks(rng, sizes=(4096, 1, 0, 2777, 4096), device_chunk=1):
    out = [
        rng.integers(-(2**31), 2**31 - 1, size=s, dtype=np.int32)
        for s in sizes
    ]
    for i in range(device_chunk):
        out[i * 3] = jnp.asarray(out[i * 3])
    return out


def _oracle(chunks, ks):
    x = np.concatenate([np.asarray(c).ravel() for c in chunks])
    part = np.partition(x, [k - 1 for k in ks])
    return [int(part[k - 1]) for k in ks]


def _phase_reads(o, phase):
    total = 0
    for m in o.metrics.metrics():
        if m.name == "ingest.bucket_reads" and dict(m.labels).get(
            "phase"
        ) == phase:
            total += m.value
    return total


# ---------------------------------------------------------------------------
# the grid


@pytest.mark.parametrize("devices", [None, 2, 8])
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("spill", ["off", "force"])
def test_grid_bit_equality_kernel_tier(rng, devices, depth, spill):
    """The kernel tier against the oracle over the heterogeneous stream
    (the xla/off legs of the same grid live in test_fused_ingest.py)."""
    chunks = _chunks(rng)
    n = sum(int(np.asarray(c).size) for c in chunks)
    ks = [1, n // 3, n // 2, n]
    want = _oracle(chunks, ks)
    got = streaming_kselect_many(
        chunks, ks, radix_bits=8, collect_budget=256,
        pipeline_depth=depth, devices=devices, spill=spill, fused="kernel",
    )
    assert [int(g) for g in got] == want
    assert live_staged_keys() == 0


def test_three_tiers_bit_identical_f32(rng):
    chunks = [
        rng.standard_normal(s).astype(np.float32) for s in (3000, 1500, 700)
    ]
    n = sum(c.size for c in chunks)
    k = n // 2
    kw = dict(radix_bits=8, collect_budget=128, devices=8, pipeline_depth=2,
              spill="force")
    legs = {
        mode: streaming_kselect(chunks, k, fused=mode, **kw)
        for mode in ("kernel", "xla", "off")
    }
    sync = streaming_kselect(chunks, k, pipeline_depth=0, radix_bits=8,
                             collect_budget=128)
    want = {np.asarray(v).tobytes() for v in legs.values()}
    assert want == {np.asarray(sync).tobytes()}


def test_one_shot_tee_kernel_tier(rng):
    """A consumed generator under spill='auto': the kernel-tier tee must
    anchor the same gen-0 bytes and the descent the same answer."""
    chunks = [rng.integers(-1000, 1000, size=s, dtype=np.int32)
              for s in (3000, 2000, 1000)]
    n = sum(c.size for c in chunks)
    k = n // 2
    want = _oracle(chunks, [k])[0]
    got = streaming_kselect(
        (c for c in chunks), k, radix_bits=4, collect_budget=128,
        fused="kernel",
    )
    assert int(got) == want


def test_spill_generations_identical_across_tiers(rng):
    """All three tiers write the SAME per-pass survivor bytes (the
    multiset contract, visible in the pass_log)."""
    from mpi_k_selection_tpu.streaming import SpillStore

    chunks = _chunks(rng, sizes=(4096, 2048, 4096), device_chunk=0)
    n = sum(c.size for c in chunks)
    logs = {}
    for fused in ("kernel", "xla", "off"):
        with SpillStore() as store:
            streaming_kselect(
                chunks, n // 2, radix_bits=4, collect_budget=64,
                devices=8, pipeline_depth=2, spill=store, fused=fused,
            )
            logs[fused] = [
                {kk: e[kk] for kk in ("pass", "keys_read", "keys_written")
                 if kk in e}
                for e in store.pass_log
            ]
    assert logs["kernel"] == logs["xla"] == logs["off"]


# ---------------------------------------------------------------------------
# the sweep program vs the numpy oracle (and the XLA tier's buffers)


def test_sweep_program_matches_numpy_oracle(rng):
    kdt = np.dtype(np.uint32)
    keys = rng.integers(0, 2**32, size=3011, dtype=np.uint32)  # ragged: pads
    staged = stage_keys(keys)
    try:
        assert si.sweep_supported(staged, kdt, radix_bits=8, sketch_bits=16)
        prefixes = sorted({int(keys[0] >> 24), int(keys[7] >> 24)})
        collect_specs = [(8, int(keys[0] >> 24)), (16, int(keys[5] >> 16))]
        vkey = int(keys[100])
        hist, collect, tee, cert, sketch = si.dispatch_sweep_ingest(
            staged, kdt=kdt, total_bits=32, shift=16, radix_bits=8,
            hist_prefixes=prefixes, collect_specs=collect_specs,
            tee_specs=collect_specs, vkey=vkey, sketch_bits=16,
        )
        hist = np.asarray(hist)
        # histogram: over the WHOLE padded bucket (pad keys are key-space
        # 0 — the executor's finish subtracts them; here we include them)
        padded = np.zeros(staged.data.shape[0], np.uint32)
        padded[: keys.size] = keys
        assert hist.dtype == np.int32
        for i, p in enumerate(prefixes):
            up = padded >> np.uint32(24)
            dig = (padded >> np.uint32(16)) & np.uint32(0xFF)
            np.testing.assert_array_equal(
                hist[i],
                np.bincount(
                    dig[up == np.uint32(p)].astype(np.int64), minlength=256
                ),
            )
        # per-spec compactions: pad excluded, chunk order preserved — and
        # the full BUFFER bit-identical to the XLA tier's compact_core
        union = np.zeros(keys.shape, bool)
        for (resolved, prefix), part in zip(collect_specs, collect):
            got = ex_mod.materialize_compacted(part, kdt)
            m = (keys >> np.uint32(32 - resolved)) == np.uint32(prefix)
            union |= m
            assert got.dtype == kdt
            np.testing.assert_array_equal(got, keys[m])
            ref_buf, ref_cnt = fi.compact_core(
                staged.data, np.int32(staged.n_valid),
                np.asarray([32 - resolved], kdt), np.asarray([prefix], kdt),
            )
            np.testing.assert_array_equal(
                np.asarray(part[0]), np.asarray(ref_buf)
            )
            assert int(part[1]) == int(ref_cnt)
        np.testing.assert_array_equal(
            ex_mod.materialize_compacted(tee, kdt), keys[union]
        )
        # certificate: pad-exact in kernel (no host correction needed)
        assert int(cert[0]) == int(np.count_nonzero(keys < vkey))
        assert int(cert[1]) == int(np.count_nonzero(keys <= vkey))
        # sketch: deep fold counts pads (the consumer's exact bucket-0
        # subtraction), extremes mask them to the identities
        deep, kmin, kmax = sketch
        deep = np.asarray(deep).astype(np.int64)
        deep[0] -= staged.pad
        np.testing.assert_array_equal(
            deep,
            np.bincount(
                (keys >> np.uint32(16)).astype(np.int64), minlength=1 << 16
            ),
        )
        assert int(np.asarray(kmin)) == int(keys.min())
        assert int(np.asarray(kmax)) == int(keys.max())
    finally:
        staged.release()
    assert live_staged_keys() == 0


def test_sweep_multi_block_grid(rng):
    """A bucket spanning several grid steps: the cross-tile running
    offsets and accumulators must stitch exactly (2^18 elems -> grid 4 at
    the default 512-row tile)."""
    kdt = np.dtype(np.uint32)
    keys = rng.integers(0, 2**32, size=200_000, dtype=np.uint32)
    staged = stage_keys(keys)
    try:
        specs = [(4, int(keys[0] >> 28)), (8, int(keys[3] >> 24))]
        vkey = int(keys[5])
        hist, collect, tee, cert, sketch = si.dispatch_sweep_ingest(
            staged, kdt=kdt, total_bits=32, shift=24, radix_bits=4,
            hist_prefixes=[int(keys[0] >> 28)], collect_specs=specs,
            tee_specs=specs, vkey=vkey, sketch_bits=16,
        )
        union = np.zeros(keys.shape, bool)
        for (resolved, prefix), part in zip(specs, collect):
            m = (keys >> np.uint32(32 - resolved)) == np.uint32(prefix)
            union |= m
            np.testing.assert_array_equal(
                ex_mod.materialize_compacted(part, kdt), keys[m]
            )
        np.testing.assert_array_equal(
            ex_mod.materialize_compacted(tee, kdt), keys[union]
        )
        assert int(cert[0]) == int(np.count_nonzero(keys < vkey))
        assert int(cert[1]) == int(np.count_nonzero(keys <= vkey))
        deep = np.asarray(sketch[0]).astype(np.int64)
        deep[0] -= staged.pad
        np.testing.assert_array_equal(
            deep,
            np.bincount(
                (keys >> np.uint32(16)).astype(np.int64), minlength=1 << 16
            ),
        )
        assert int(np.asarray(sketch[1])) == int(keys.min())
        assert int(np.asarray(sketch[2])) == int(keys.max())
    finally:
        staged.release()


# ---------------------------------------------------------------------------
# one program per staged bucket: the read accounting


def test_sketch_bucket_reads_drop_to_one_under_kernel(rng):
    """The tentpole's closed gap: the sketch consumer was the last
    2-programs-per-staged-bucket consumer; the kernel tier folds the deep
    histogram and the extremes into ONE sweep program — and the folded
    pyramid is bit-identical across tiers and to sequential update."""
    chunks = [rng.standard_normal(4000).astype(np.float32) for _ in range(3)]
    seq = RadixSketch(np.float32)
    for c in chunks:
        seq.update(c)
    sketches = {}
    reads = {}
    staged_counts = {}
    for fused in ("kernel", "xla"):
        o = obs_lib.Observability.collecting()
        sk = RadixSketch(np.float32).update_stream(
            chunks, devices=2, pipeline_depth=2, fused=fused, obs=o
        )
        sketches[fused] = sk
        reads[fused] = _phase_reads(o, "sketch")
        ev = [e for e in o.events.of_kind("sketch.pass")]
        staged_counts[fused] = ev[0].staged_chunks
    assert sketches["kernel"] == sketches["xla"] == seq
    assert staged_counts["kernel"] == staged_counts["xla"] == len(chunks)
    # exactly ONE program per staged bucket under the kernel tier; the
    # xla tier keeps the historical deep-fold + extremes pair
    assert reads["kernel"] == staged_counts["kernel"]
    assert reads["xla"] == 2 * staged_counts["xla"]
    assert live_staged_keys() == 0


def test_certificate_bucket_reads_parity(rng):
    """phase="certificate" accounting: 1 program per staged bucket under
    the kernel tier, the deferred pair (2) on the xla tier — counts
    bit-identical to each other and the eager oracle."""
    chunks = [rng.integers(-(2**31), 2**31 - 1, size=s, dtype=np.int32)
              for s in (4096, 2777, 4096)]
    x = np.concatenate(chunks)
    v = int(x[len(x) // 2])
    got = {}
    reads = {}
    for fused in ("kernel", "xla"):
        o = obs_lib.Observability.collecting()
        got[fused] = streaming_rank_certificate(
            chunks, v, pipeline_depth=2, devices=2, fused=fused, obs=o
        )
        reads[fused] = _phase_reads(o, "certificate")
    eager = streaming_rank_certificate(
        chunks, v, pipeline_depth=2, devices=2, deferred="off"
    )
    want = (int(np.count_nonzero(x < v)), int(np.count_nonzero(x <= v)))
    assert got["kernel"] == got["xla"] == eager == want
    assert reads["kernel"] == len(chunks)
    assert reads["xla"] == 2 * len(chunks)


def test_descent_read_amplification_one_under_kernel(rng):
    """Every staged key dispatched to exactly one program per pass:
    bucket_read_bytes == staged_bytes, with only histogram (pass 0) and
    fused phases present."""
    chunks = _chunks(rng, sizes=(4096, 2048, 4096), device_chunk=0)
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability.collecting()
    streaming_kselect(
        chunks, n // 2, radix_bits=4, collect_budget=64, devices=2,
        pipeline_depth=2, spill="force", fused="kernel", obs=o,
    )
    read = staged = 0
    phases = set()
    for m in o.metrics.metrics():
        if m.name == "ingest.bucket_read_bytes":
            read += m.value
            phases.add(dict(m.labels).get("phase"))
        elif m.name == "ingest.staged_bytes":
            staged += m.value
    assert read == staged
    assert "fused" in phases
    assert not {"tee", "collect"} & phases


# ---------------------------------------------------------------------------
# support matrix and fallback


def test_sub_tile_buckets_fall_back_to_xla_tier(rng):
    """Chunks below one (1, 128) lane tile stage into sub-128 buckets the
    kernel cannot tile — the kernel tier must answer identically through
    the per-bucket XLA fallback."""
    chunks = [rng.integers(-1000, 1000, size=s, dtype=np.int32)
              for s in (60, 50, 40)]
    n = sum(c.size for c in chunks)
    k = n // 2
    want = _oracle(chunks, [k])[0]
    got = streaming_kselect(
        chunks, k, radix_bits=8, collect_budget=16, devices=2,
        pipeline_depth=2, spill="force", fused="kernel",
    )
    assert int(got) == want
    assert live_staged_keys() == 0


def test_sweep_supported_matrix():
    base = live_staged_keys()
    small = stage_keys(np.arange(60, dtype=np.uint32))
    big = stage_keys(np.arange(4096, dtype=np.uint32))
    try:
        kdt = np.dtype(np.uint32)
        assert not si.sweep_supported(small, kdt)
        assert si.sweep_supported(big, kdt)
        # non-4-byte key spaces ride the XLA tier
        assert not si.sweep_supported(big, np.dtype(np.uint16))
        assert not si.sweep_supported(big, np.dtype(np.uint64))
        # digit widths / sketch depths beyond the kernel's accumulators
        assert not si.sweep_supported(big, kdt, radix_bits=9)
        assert si.sweep_supported(big, kdt, radix_bits=8)
        assert not si.sweep_supported(big, kdt, sketch_bits=21)
        assert si.sweep_supported(big, kdt, sketch_bits=20)
        # non-pow2 lane multiples (768 rows: the 512-row tile would not
        # divide them) are outside the staging contract — the gate must
        # route them to the XLA tier, and the core must raise rather
        # than silently sweep a truncated grid
        from mpi_k_selection_tpu.streaming.pipeline import StagedKeys

        odd = StagedKeys(jnp.zeros(768 * 128, jnp.uint32), 768 * 128)
        assert not si.sweep_supported(odd, kdt)
        with pytest.raises(ValueError, match="does not divide"):
            si.dispatch_sweep_ingest(
                odd, kdt=kdt, total_bits=32, shift=24, radix_bits=8,
                hist_prefixes=[0], collect_specs=[], tee_specs=[],
            )
    finally:
        small.release()
        big.release()
    assert live_staged_keys() == base


def test_uint64_sketch_keeps_two_program_path(rng):
    """A 64-bit key space (x64 off -> host-exact route never stages; here
    via the supported-matrix gate) must not break the sketch fold."""
    chunks = [rng.integers(-(2**62), 2**62, size=2000, dtype=np.int64)]
    seq = RadixSketch(np.int64)
    for c in chunks:
        seq.update(c)
    sk = RadixSketch(np.int64).update_stream(
        chunks, devices=2, pipeline_depth=2, fused="kernel"
    )
    assert sk == seq


# ---------------------------------------------------------------------------
# knob + surface units


def test_resolve_fused_tiers():
    import jax

    assert resolve_fused("kernel") == "kernel"
    assert resolve_fused("xla") == "xla"
    assert resolve_fused("off") is False
    assert resolve_fused(False) is False
    # "auto" mirrors hist_method="auto": the kernel tier on TPU backends,
    # the XLA fusion elsewhere (the kernel only interprets off-TPU)
    want_auto = "kernel" if jax.default_backend() == "tpu" else "xla"
    assert resolve_fused("auto") == want_auto
    assert resolve_fused(True) == want_auto
    with pytest.raises(ValueError, match="fused"):
        resolve_fused("sometimes")


def test_validate_fused_no_backend_probe(rng):
    from mpi_k_selection_tpu.streaming import validate_fused

    # normalizes without resolving "auto" (no jax backend probe)
    assert validate_fused("auto") == "auto"
    assert validate_fused(True) == "auto"
    assert validate_fused(False) == "off"
    assert validate_fused("kernel") == "kernel"
    with pytest.raises(ValueError, match="fused"):
        validate_fused("kernle")
    # the eager (deferred="off") route forces the unfused bundle but
    # must still reject a typo'd knob instead of silently riding it
    chunks = [rng.integers(-1000, 1000, size=1000, dtype=np.int32)]
    with pytest.raises(ValueError, match="fused"):
        streaming_kselect(chunks, 500, deferred="off", fused="kernle")
    with pytest.raises(ValueError, match="fused"):
        streaming_rank_certificate(chunks, 0, deferred="off", fused="kernle")
    from mpi_k_selection_tpu.api import StreamingQuantiles

    with pytest.raises(ValueError, match="fused"):
        StreamingQuantiles(np.int32, fused="kernle")


def test_consumer_tier_validation():
    kdt = np.dtype(np.uint32)
    with pytest.raises(ValueError, match="tier"):
        ex_mod.FusedIngestConsumer(
            collect=object(), kdt=kdt, total_bits=32, tier="bogus"
        )
    with pytest.raises(ValueError, match="tier"):
        ex_mod.CountLessLeqConsumer(
            np.uint32(5), kdt, deferred=True, fused="bogus"
        )


def test_streaming_quantiles_kernel_tier(rng):
    from mpi_k_selection_tpu.api import StreamingQuantiles

    chunks = [rng.standard_normal(4000).astype(np.float32) for _ in range(3)]
    qs = (0.1, 0.5, 0.9)
    got = {}
    for fused in ("kernel", "xla"):
        sq = StreamingQuantiles(
            np.float32, devices=8, fused=fused
        ).update_stream(chunks)
        got[fused] = [
            np.asarray(v).tobytes() for v in sq.refine_quantiles(qs, chunks)
        ]
    assert got["kernel"] == got["xla"]


def test_cli_fused_kernel_leg(capsys):
    import json

    from mpi_k_selection_tpu.cli import main

    rc = main([
        "--streaming", "--backend", "tpu", "--n", "40000",
        "--chunk-elems", "8192", "--devices", "2", "--verify", "--check",
        "--spill", "force", "--fused", "kernel", "--json",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["extra"]["exact_match"] is True
    assert rec["extra"]["certificate_ok"] is True
    assert rec["extra"]["fused"] == "kernel"
