"""Order-preserving bit transforms for radix selection.

Radix select works on unsigned keys whose numeric order equals the order of
the original values. This module maps every supported dtype to such keys and
back, so one selection kernel serves int8/16/32/64, uint*, bfloat16,
float16/32/64.

The reference operates only on C ``int`` (``vector.h:7-11``); supporting the
wider dtype set is part of the north-star scope (BASELINE.json configs use
int32, int64 and float32).

Transform rules (classic radix-sort tricks):
- signed int  -> flip the sign bit: ``u = bits(x) ^ MSB``
- unsigned    -> identity
- float       -> if sign bit set, flip all bits; else set the sign bit.
  This orders -inf < ... < -0.0 < +0.0 < ... < +inf < +NaN, matching
  ``np.sort`` for NaN-free data (NaNs with the sign bit clear sort last like
  NumPy; negative-NaN bit patterns sort first — documented deviation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# dtype -> (unsigned key dtype, total bits)
_KEY_INFO = {
    np.dtype(np.int8): (np.uint8, 8),
    np.dtype(np.uint8): (np.uint8, 8),
    np.dtype(np.int16): (np.uint16, 16),
    np.dtype(np.uint16): (np.uint16, 16),
    np.dtype(np.int32): (np.uint32, 32),
    np.dtype(np.uint32): (np.uint32, 32),
    np.dtype(np.int64): (np.uint64, 64),
    np.dtype(np.uint64): (np.uint64, 64),
    np.dtype(np.float16): (np.uint16, 16),
    np.dtype(jnp.bfloat16): (np.uint16, 16),
    np.dtype(np.float32): (np.uint32, 32),
    np.dtype(np.float64): (np.uint64, 64),
}


def key_dtype(dtype) -> np.dtype:
    """Unsigned key dtype used for radix passes over `dtype`."""
    dtype = np.dtype(dtype)
    if dtype not in _KEY_INFO:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    return np.dtype(_KEY_INFO[dtype][0])


def key_bits(dtype) -> int:
    """Total number of key bits for `dtype`."""
    dtype = np.dtype(dtype)
    if dtype not in _KEY_INFO:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    return _KEY_INFO[dtype][1]


def key_fold(dtype):
    """In-kernel form of :func:`to_sortable_bits` for raw-bits kernel tiles.

    Returns ``("xor", C)`` when ``key == raw_bits ^ C`` (every integer
    dtype: C is the sign-bit mask for signed, 0 for unsigned) — the fold is
    *free* in the histogram kernels because a logical shift distributes over
    xor (``(raw ^ C) >> s == (raw >> s) ^ (C >> s)``), so C folds into the
    kernel's existing xor constant. Returns ``("float",)`` for
    float32/float64, whose sign-dependent transform costs two VPU ops in
    kernel. Returns None for sub-32-bit dtypes, which are widened on the
    host side anyway (the widening copy subsumes the transform).

    Why this exists: materializing ``to_sortable_bits(x)`` before the Pallas
    kernels is a full extra read+write of the array per select (the kernels
    are opaque custom calls, so XLA cannot fuse the transform into them —
    measured 1.63 ms of a 7.5 ms select at N=2^27 on v5e). Feeding raw bits
    and folding the transform into the kernel removes that pass entirely.
    """
    dtype = np.dtype(dtype)
    if dtype not in _KEY_INFO:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    kdt, bits = _KEY_INFO[dtype]
    if bits < 32:
        return None
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return ("xor", 0)
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return ("xor", 1 << (bits - 1))
    return ("float",)


def _require_x64(dtype):
    if np.dtype(dtype).itemsize == 8 and not jax.config.jax_enable_x64:
        raise ValueError(
            f"{np.dtype(dtype)} selection requires 64-bit mode; enable it via "
            "jax.config.update('jax_enable_x64', True) or the "
            "jax.experimental.enable_x64() context manager"
        )


def to_sortable_bits(x: jax.Array) -> jax.Array:
    """Map `x` to unsigned keys with the same ordering."""
    dtype = np.dtype(x.dtype)
    kdt, bits = _KEY_INFO.get(dtype, (None, None))
    if kdt is None:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    _require_x64(dtype)
    kdt = np.dtype(kdt)
    msb = np.array(1, dtype=np.uint64) << np.uint64(bits - 1)
    msb = kdt.type(msb)
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return x
    u = jax.lax.bitcast_convert_type(x, kdt)
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return u ^ msb
    # floating point
    all_ones = kdt.type(~np.uint64(0) >> np.uint64(64 - bits))
    neg = (u >> kdt.type(bits - 1)) != kdt.type(0)
    return jnp.where(neg, u ^ all_ones, u | msb)


def from_sortable_bits(u: jax.Array, dtype) -> jax.Array:
    """Inverse of :func:`to_sortable_bits`."""
    dtype = np.dtype(dtype)
    kdt, bits = _KEY_INFO.get(dtype, (None, None))
    if kdt is None:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    kdt = np.dtype(kdt)
    u = u.astype(kdt)
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return u
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return jax.lax.bitcast_convert_type(u ^ msb, dtype)
    all_ones = kdt.type(~np.uint64(0) >> np.uint64(64 - bits))
    neg = (u & msb) == kdt.type(0)  # keys below MSB came from negative floats
    raw = jnp.where(neg, u ^ all_ones, u & ~msb)
    return jax.lax.bitcast_convert_type(raw, dtype)
