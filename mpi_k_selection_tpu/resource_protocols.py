"""Canonical resource-protocol registry — the ONE importable source of
truth for every leak-tracked resource family in this package.

Three enforcement layers key on the names below, and before this module
each kept its own copy — a new resource kind could be tracked at runtime
yet invisible statically (or vice versa) with no test noticing:

1. **runtime** — the tests/conftest.py leak fixtures match worker
   threads, spill temp dirs and flight-recorder files by these prefixes
   after every test;
2. **static** — the resource-lifecycle dataflow pass
   (analysis/lifecycle.py, rules KSL019-KSL021) proves every acquire
   reaches its release on every CFG path, with the SAME owner/prefix
   vocabulary;
3. **the owning modules** — streaming/pipeline.py, serve/batcher.py,
   monitor/monitor.py, streaming/spill.py and obs/flight.py re-export
   their prefix constants FROM here (their public names are unchanged),
   so a subsystem cannot drift its naming away from the fixtures.

Stdlib-only on purpose: the static pass must import this registry in
environments without jax (``kselect-lint --no-contracts``), and the
conftest reads it before the first jax import.
"""

from __future__ import annotations

#: Every package-owned leakable artifact carries this prefix; the
#: conftest straggler sweep matches the family, not an allowlist.
KSEL_PREFIX = "ksel-"

# -- worker-thread name prefixes (the KSL021 / conftest thread family) ------

#: streaming/pipeline.py ChunkPipeline producer threads.
PIPELINE_THREAD_PREFIX = "ksel-pipeline"
#: Parallel host data plane: ingest-pool encode/stage workers
#: (streaming/pipeline.py ``ksel-ingest-<pipeline>-<w>``) and the spill
#: replay decode pool (streaming/spill.py ``ksel-ingest-decode-*``).
INGEST_THREAD_PREFIX = "ksel-ingest"
#: serve/ threads: the per-device dispatch-lane threads (serve/lanes.py
#: names each lane's supervised QueryBatcher thread
#: ``ksel-serve-lane-<key>-dispatch-*``; a standalone batcher keeps
#: ``ksel-serve-dispatch-*``), the HTTP accept loop and per-request
#: handlers.
SERVE_THREAD_PREFIX = "ksel-serve"
#: monitor/ metrics-server threads (accept loop + per-request handlers).
MONITOR_THREAD_PREFIX = "ksel-monitor"

THREAD_PREFIXES = (
    PIPELINE_THREAD_PREFIX,
    INGEST_THREAD_PREFIX,
    SERVE_THREAD_PREFIX,
    MONITOR_THREAD_PREFIX,
)

# -- on-disk artifact prefixes ----------------------------------------------

#: streaming/spill.py internally-created store directories.
SPILL_DIR_PREFIX = "ksel-spill-"
#: obs/flight.py debug-bundle temp files.
FLIGHT_FILE_PREFIX = "ksel-flight-"

#: The full leak-tracked prefix family (threads + disk artifacts).
RESOURCE_PREFIXES = THREAD_PREFIXES + (SPILL_DIR_PREFIX, FLIGHT_FILE_PREFIX)

# ---------------------------------------------------------------------------
# static lifecycle protocols (analysis/lifecycle.py)
#
# Each protocol names, for one resource family: the calls that ACQUIRE a
# tracked resource, the calls that RELEASE it, the calls/attributes that
# constitute a sanctioned OWNERSHIP TRANSFER (after which the owner's own
# lifecycle discipline — itself conftest-enforced — is responsible), and
# the class names the engine uses for isinstance() path narrowing.

# -- staged key buffers (KSL019): streaming/pipeline.py ---------------------

#: Calls whose result is a live StagedKeys ring slot.
STAGED_ACQUIRE_CALLS = frozenset({"stage_keys", "stage_device_keys"})
#: ``staged.release()`` — the ring-slot donation (idempotent).
STAGED_RELEASE_METHODS = frozenset({"release"})
#: ``release_staged(x)`` — the idempotent unwind helper (executor.py).
STAGED_RELEASE_FUNCS = frozenset({"release_staged"})
#: Method names whose call takes ownership of a staged buffer passed to
#: them: the executor/window FIFO (``push``) releases at bundle-finish
#: time; the pipeline queue (``put``/``_put``) hands the slot to the
#: consumer (ChunkPipeline.close() drains and releases unconsumed ones).
STAGED_OWNER_CALLS = frozenset({"push", "put", "_put"})
STAGED_TYPES = frozenset({"StagedKeys"})

# -- spill stores / writers / temp dirs (KSL020): streaming/spill.py --------

#: Constructors of caller-cleaned disk resources: a store (close()
#: removes its ksel-spill-* dir), a raw temp dir, or tempfile.mkdtemp.
SPILL_ACQUIRE_CALLS = frozenset(
    {"SpillStore", "SpillWriter", "TemporaryDirectory", "mkdtemp",
     # a store's generation writer: commit() hands its records to the
     # store, abort() drops them — one of the two must run on every path.
     # The prefix-packed (format v2) writer is THIS SAME surface:
     # new_generation(pack_specs=...) / (pack_digit_bits=...) returns the
     # same SpillWriter, its bit-pack buffers are plain numpy arrays
     # (no tracked resource), and every packed record still reaches disk
     # only through the writer's one sanctioned append/commit path — so
     # KSL008/KSL020 see the v2 path with no extra protocol entries
     "new_generation"}
)
#: The cleanup surface: ``store.close()`` / ``writer.abort()`` /
#: ``writer.commit()`` (commit IS the writer's release — ownership of
#: the records passes to the store) / ``TemporaryDirectory.cleanup()`` /
#: ``store.drop_generation(...)``.
SPILL_RELEASE_METHODS = frozenset({"close", "abort", "commit", "cleanup"})
SPILL_RELEASE_FUNCS = frozenset()
SPILL_OWNER_CALLS = frozenset()
#: ``self.root = tempfile.mkdtemp(...)`` — the store owns its directory.
SPILL_OWNER_ATTRS = frozenset({"root"})
SPILL_TYPES = frozenset({"SpillStore", "SpillWriter", "TemporaryDirectory"})

# -- package worker threads (KSL021) ----------------------------------------

#: Only ``ksel-``-named threads are tracked (the conftest family); an
#: unstarted Thread object holds no OS resources, so the lifecycle
#: obligation arms at ``.start()``.
THREAD_ACQUIRE_CALLS = frozenset({"Thread"})
THREAD_RELEASE_METHODS = frozenset({"join"})
THREAD_RELEASE_FUNCS = frozenset()
THREAD_OWNER_CALLS = frozenset()
#: The conftest-recognized supervisor slots: attributes whose owners
#: join their threads on every close path (ChunkPipeline._thread,
#: QueryBatcher._thread, the HTTP servers' _serve_thread and tracked
#: _req_threads list in serve/http.py and monitor/monitor.py, and the
#: LaneDispatcher's _lanes map in serve/lanes.py — each lane is a whole
#: QueryBatcher whose close() joins its own _thread).
#: ``_workers`` is the ingest-pool family: ChunkPipeline's worker list
#: (close() joins every entry) and the spill decode pool's thread list
#: (the reader generator's finally joins them on every exit path).
THREAD_OWNER_ATTRS = frozenset(
    {"_thread", "_serve_thread", "_req_threads", "_lanes", "_workers"}
)
THREAD_TYPES = frozenset({"Thread"})

# ---------------------------------------------------------------------------
# `# ksel: owner[<site>]` annotation vocabulary
#
# A declared ownership transfer must name one of these sites; naming
# anything else — or annotating a line where no tracked resource moves —
# is itself a finding (the guarded-by staleness contract applied to
# ownership). Keep descriptions current: the lifecycle report exports
# this table verbatim.

OWNER_SITES = {
    "InflightWindow": "the executor FIFO window releases at bundle finish",
    "StreamExecutor": "the stream executor owns staged-buffer lifetime",
    "ChunkPipeline": "the pipeline queue: close() drains and releases",
    "SpillStore": "the store owns committed generations (drop/close)",
    "supervisor": "a conftest-recognized thread supervisor joins it",
    "caller": "ownership returns to the caller (documented contract)",
}

# ---------------------------------------------------------------------------
# device-placement vocabulary (analysis/placement.py, KSL022-KSL024 + KSC105)
#
# The placement pass models, per value, WHERE it computes: `host`,
# `device(slot)`, `slots` (a resolved device tuple), `round-robin`
# (slots indexed by chunk position), `inherited` (a device-resident
# chunk's own committed device) or `top` (conflicting placements met).
# The names below are the ONE vocabulary shared by the static pass, the
# KSL007 compatibility shim and the KSC105 static<->runtime contract.

# -- placement sources ------------------------------------------------------

#: ``jax.device_put`` spellings (the raw transfer primitive — KSL007's
#: historical subject and a KSL023 crossing).
TRANSFER_PUT_CALLS = frozenset({"jax.device_put", "device_put"})
#: Keyword arguments that commit a ``device_put`` to a target.
PUT_TARGET_KWARGS = frozenset({"device", "sharding"})
#: Staging constructors whose result carries its device argument's slot
#: (``stage_device_keys`` inherits the chunk's own committed device).
STAGE_CALLS = frozenset({"stage_keys"})
INHERIT_STAGE_CALLS = frozenset({"stage_device_keys"})
#: The slot-tuple resolver: its result is the abstract ``slots`` value —
#: round-robin staging indexes it by chunk position.
SLOT_RESOLVER_CALLS = frozenset({"resolve_stream_devices"})

# -- dispatch / threading sites ---------------------------------------------

#: Per-bucket device-program dispatches (the KSL014 family): every
#: operand of one dispatch must agree on ONE slot (KSL022).
DISPATCH_CALLS = frozenset(
    {
        "dispatch_chunk_histograms",
        "dispatch_compaction",
        "dispatch_fused_ingest",
        "dispatch_sweep_ingest",
        "fused_ingest_core",
        "sweep_ingest_core",
        "masked_radix_histogram",
        "multi_masked_radix_histogram",
    }
)
#: Calls that accept the resolved device tuple via ``devices=`` and
#: thread it into round-robin staging — the KSL022 drop-site family: a
#: conditional that withholds a resolved tuple from one of these may
#: depend only on placement-independent knobs (pipeline depth, the raw
#: ``devices`` argument), never on the resolved tuple itself.
DEVICE_THREADING_CALLS = frozenset(
    {
        "_key_chunk_stream",
        "ChunkPipeline",
        "streaming_kselect",
        "streaming_kselect_many",
        "update_stream",
    }
)

# -- sanctioned host<->device crossings (KSL023) ----------------------------

#: Host<->device crossing calls the placement pass censuses statically
#: (the AST twin of KSC104's ``_CROSSING_PRIMITIVES``).
CROSSING_CALLS = frozenset(
    {
        "jax.device_put",
        "device_put",
        "jax.device_get",
        "device_get",
        "copy_to_host_async",
    }
)
#: The sanctioned transfer sites: package-relative module path -> why
#: that module may host crossings. A crossing call in `streaming/`,
#: `serve/`, `monitor/`, `ops/` or `parallel/` OUTSIDE this registry is
#: a KSL022-class placement hole (KSL023). Keep reasons current: the
#: placement report exports this table verbatim.
SANCTIONED_TRANSFER_SITES = {
    "streaming/pipeline.py": (
        "THE staging boundary: stage_keys/stage_device_keys commit "
        "buckets to their round-robin slot (KSC104 proves no other "
        "crossing rides a streaming program)"
    ),
    "parallel/mesh.py": (
        "shard_for_mesh — the one sanctioned mesh-sharding helper "
        "(device_put with a NamedSharding)"
    ),
    "parallel/topk.py": "mesh-sharded input registration (NamedSharding put)",
    "parallel/radix.py": "mesh-sharded input registration (NamedSharding put)",
    "parallel/cgm.py": "mesh-sharded input registration (NamedSharding put)",
    "parallel/multihost.py": (
        "the DCN boundary: device_get of cross-process reductions"
    ),
}

# -- placement-nondeterminism sources (KSL024) ------------------------------

#: Calls whose result may never feed a device-target expression: device
#: choice must be a pure function of chunk index, an explicit knob or a
#: recorded slot, or spill replay cannot re-stage deterministically.
NONDET_PLACEMENT_CALLS = frozenset(
    {
        "time.time",
        "time.time_ns",
        "time.monotonic",
        "time.monotonic_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "threading.get_ident",
        "threading.current_thread",
        "get_ident",
        "current_thread",
        "random.random",
        "random.randint",
        "random.randrange",
        "random.choice",
        "random.getrandbits",
        "os.urandom",
        "uuid.uuid1",
        "uuid.uuid4",
        "hash",
        "id",
    }
)
#: Constructors whose iteration order is no contract: a device index
#: drawn from one is nondeterministic placement even without a clock.
UNORDERED_CONSTRUCTORS = frozenset({"set", "frozenset"})
